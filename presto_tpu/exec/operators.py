"""Physical operators over Batches.

Reference parity: ``com.facebook.presto.operator`` — ``Operator`` /
``OperatorFactory``, ``ScanFilterAndProjectOperator``,
``HashAggregationOperator`` (+ GroupByHash / GroupedAccumulator),
``OrderByOperator``, ``TopNOperator``, ``LimitOperator``
[SURVEY §2.1, §3.3; reference tree unavailable, paths reconstructed].

TPU-first execution model (SURVEY §7.1): operators are *push*-style —
``process(batch) -> [Batch]`` then a ``finish() -> [Batch]`` cascade —
and hold their state as device arrays. Each operator family runs one
jit-compiled step per (schema, capacity) signature; batches stay
device-resident between operators, so the Python driver loop is pure
dispatch and XLA overlaps it with device compute. Where the reference
generates per-query JVM bytecode, we trace; where it builds hash
tables, we use the sort/segment kernels in ``presto_tpu.ops``.

Aggregation state is bounded: partial aggregation folds every incoming
batch into a fixed ``max_groups`` device state (direct-addressed when
the key domain is small, merge-by-sort otherwise) — the analog of
``InMemoryHashAggregationBuilder``, with capacity-overflow flags
instead of memory-revoke spilling (spill comes later; SURVEY §5.4).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column, Dictionary
from presto_tpu.expr import Expr, Val, evaluate, evaluate_predicate, param_scope
from presto_tpu.ops.groupby import (
    ValueBitsOverflow,
    fused_small_sums,
    gather_padded,
    group_ids_direct,
    group_ids_sort,
    segment_agg,
)
from presto_tpu.ops.sort import sort_indices, top_n_indices
from presto_tpu.runtime.errors import InternalError, ResourceExhausted
from presto_tpu.runtime.trace import span as trace_span
from presto_tpu.types import BIGINT, DOUBLE, DataType, TypeKind


def null_safe_key(v: "Val") -> "Val":
    """Normalize a group-key Val for NULL-aware grouping: NULL rows'
    stored data is arbitrary, so zero-fill it (all NULLs compare equal)
    — callers ALSO sort/hash on ``v.valid`` so the NULL group stays
    distinct from real zeros. One definition shared by the local sort
    path and the distributed partial/final phases: the tiers must group
    NULLs identically."""
    mask = v.valid[:, None] if v.data.ndim > 1 else v.valid
    return Val(jnp.where(mask, v.data, 0), v.valid, v.dtype, v.dictionary)


class NullGroupKeys(RuntimeError):
    """A direct-addressed grouping met NULL key values at runtime: the
    packed-domain gid has no NULL slot, so the planner must retry with
    the sort strategy (which groups NULL as its own key value)."""


class CapacityOverflow(ResourceExhausted):
    """An operator's static output capacity was exceeded; the host
    re-plans with a larger bucket (SURVEY §7.4 hard part #1).

    Part of the error taxonomy (runtime/errors.py) as a
    ResourceExhausted: NOT lifecycle-retryable — replaying the same
    step hits the same capacity; recovery is the owning operator's
    doubling loop, and exhaustion of THAT is a genuine resource wall."""

    def __init__(self, op: str, capacity: int, needed: int | None = None):
        super().__init__(f"{op}: capacity {capacity} exceeded"
                         + (f" (needed {needed})" if needed else ""))
        self.op, self.capacity, self.needed = op, capacity, needed


class Operator:
    """Push-model operator protocol."""

    def process(self, batch: Batch) -> list[Batch]:
        raise NotImplementedError

    def finish(self) -> list[Batch]:
        return []


# ---------------------------------------------------------------------------
# FilterProject — the fused ScanFilterAndProject body
# ---------------------------------------------------------------------------


class FilterProjectOperator(Operator):
    """Fused filter + projections, one traced step.

    ``projections`` maps output column name -> Expr; a None predicate
    means project-only. Filtering only ANDs the live mask — no data
    movement (selection-vector semantics).
    """

    def __init__(self, predicate: Expr | None, projections: dict[str, Expr] | None,
                 params: Sequence[Any] = ()):
        from presto_tpu.cache.exec_cache import EXEC_CACHE

        self.predicate = predicate
        self.projections = projections
        #: literal-slot values for this query's plan template (traced
        #: step argument, NOT baked into the closure — one compiled
        #: step serves every binding; see expr.param_scope)
        self._params = tuple(params)
        # jitted steps are shared across queries through the compiled-
        # executable cache, keyed by expression CONTENT: the closure
        # bakes in nothing but the exprs (Param slots hash by slot id,
        # never by value), so equal configs trace equal programs
        # (cache/exec_cache.py)
        self._step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("filter_project", predicate, projections),
            lambda: jax.jit(self._make_step()),
        )

    def _make_step(self):
        from presto_tpu.cache.exec_cache import trace_probe

        pred, projs = self.predicate, self.projections

        def step(batch: Batch, params=()) -> Batch:
            trace_probe()
            with param_scope(params):
                return body(batch)

        def body(batch: Batch) -> Batch:
            live = batch.live
            if pred is not None:
                live = live & evaluate_predicate(pred, batch)
            if projs is None:
                return batch.with_live(live)
            cols = {}
            src = batch.with_live(live)
            for name, e in projs.items():
                v = evaluate(e, src)
                if isinstance(v.data, str):
                    # a projected VARCHAR literal: materialize it as a
                    # one-entry dictionary column (literals normally
                    # stay host-side to encode lazily against a peer's
                    # dictionary, but an OUTPUT column must be device
                    # data)
                    from presto_tpu.batch import Dictionary

                    d = Dictionary([v.data])
                    cols[name] = Column(
                        jnp.zeros(batch.capacity, jnp.int32),
                        jnp.ones(batch.capacity, jnp.bool_),
                        e.dtype, d,
                    )
                    continue
                # v.dtype, not e.dtype: evaluate() syncs the physical
                # field to the actual storage, so pass-through narrow
                # columns keep truthful metadata through projections
                cols[name] = Column(v.data, v.valid, v.dtype, v.dictionary)
            return Batch(cols, live)

        return step

    def process(self, batch: Batch) -> list[Batch]:
        # FilterProject usually runs via stream.map closures (never
        # inside a Pipeline), so the jitted-step span lives here
        with trace_span("step:filter_project", "step"):
            return [self._step(batch, self._params)]


# ---------------------------------------------------------------------------
# Aggregation
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AggSpec:
    """One aggregate: kind in {sum,count,min,max,count_star}; ``input``
    evaluated against the input batch (None for count_star)."""

    kind: str
    input: Expr | None
    name: str
    dtype: DataType
    # Static bound on bit-width of |input values| (NOT of the running
    # sum). Lets the scatter-free small-group sum use fewer 15-bit lane
    # passes; 63 is always safe. Only per-batch per-row values see this
    # bound — merge stages aggregate accumulated sums and always use 63.
    value_bits: int = 63
    #: row offset for the lag/lead window kinds (unused elsewhere)
    offset: int = 1

    @property
    def merge_kind(self) -> str:
        """How partial results combine at the FINAL stage."""
        return "sum" if self.kind in ("count", "count_star", "sum") else self.kind


@dataclass(frozen=True)
class DirectStrategy:
    """gid = packed bounded-domain key (BigintGroupByHash-style array
    addressing). mins/strides over the raw key columns."""

    mins: tuple[int, ...]
    strides: tuple[int, ...]
    num_groups: int


@dataclass(frozen=True)
class SortStrategy:
    """Merge-by-sort grouping with a static group capacity."""

    max_groups: int


class HashAggregationOperator(Operator):
    """Streaming grouped aggregation with device-resident state.

    group_keys: list of (name, Expr) producing the key columns.
    Phase 'partial' evaluates agg inputs; phase 'final' consumes
    partial outputs (columns named like the aggs) and merges them.
    """

    def __init__(
        self,
        group_keys: Sequence[tuple[str, Expr]],
        aggs: Sequence[AggSpec],
        strategy: DirectStrategy | SortStrategy,
        phase: str = "single",  # single | partial | final
        passengers: Sequence[tuple[str, Expr]] = (),
        params: Sequence[Any] = (),
    ):
        from presto_tpu.cache.exec_cache import EXEC_CACHE

        self._params = tuple(params)
        self.group_keys = list(group_keys)
        self.aggs = list(aggs)
        self.strategy = strategy
        self.phase = phase
        self.passengers = list(passengers)
        self.state: dict[str, Any] | None = None
        self._key_types: dict[str, DataType] = {n: e.dtype for n, e in self.group_keys}
        if isinstance(strategy, DirectStrategy) and self.passengers:
            raise InternalError("passenger keys need the sort strategy")
        # the jitted update is shared across queries via the executable
        # cache. The traced closure reads only step CONFIG off its
        # operator, so the cache builds a state-less TEMPLATE instance
        # to bind it to (a cached bound method of a live operator would
        # pin that operator's device-resident state forever). The
        # dictionaries the traced update sees ride back in the update's
        # OUTPUT pytree aux (a zero-length Column per key/passenger):
        # jax stores the output treedef per argument signature, so a
        # signature-cache hit hands each operator the dictionaries of
        # ITS trace — a shared side-dict would leak another query's
        # dictionary into finish() whenever a hit skips the body.
        self._dicts: dict[str, Dictionary | None] = {}
        key = EXEC_CACHE.key_of(
            "hash_agg", self.group_keys, self.aggs, strategy, phase,
            self.passengers,
        )
        self._update = EXEC_CACHE.get_or_build(key, self._build_update)

    def _build_update(self):
        tmpl = HashAggregationOperator.__new__(HashAggregationOperator)
        tmpl.group_keys = list(self.group_keys)
        tmpl.aggs = list(self.aggs)
        tmpl.strategy = self.strategy
        tmpl.phase = self.phase
        tmpl.passengers = list(self.passengers)
        tmpl.state = None
        tmpl._dicts = {}
        tmpl._key_types = dict(self._key_types)
        if isinstance(self.strategy, DirectStrategy):
            return jax.jit(tmpl._direct_update)
        return jax.jit(tmpl._sort_update)

    def _dict_carrier(self, kvals, pvals=()):
        """Zero-length Columns whose aux carries each key/passenger
        dictionary out of the traced update (see __init__)."""
        empty = jnp.zeros(0, jnp.int32), jnp.zeros(0, jnp.bool_)
        return {
            name: Column(*empty, e.dtype, v.dictionary)
            for pairs, vals in ((self.group_keys, kvals),
                                (self.passengers, pvals))
            for (name, e), v in zip(pairs, vals)
        }

    @staticmethod
    def _sortable(v):
        """Group-sort surrogate: BYTES(<=7) packs big-endian into int64
        (order-preserving under PAD SPACE collation — zero padding is
        normalized to spaces like bytes_pack); others pass through."""
        data, dtype = v.data, v.dtype
        if dtype.kind is TypeKind.BYTES:
            w = dtype.width
            if w > 7:
                raise InternalError("cannot sort-group wide BYTES keys")
            data = jnp.where(data == 0, jnp.uint8(32), data)
            out = jnp.zeros(data.shape[0], jnp.int64)
            for i in range(w):
                out = (out << np.int64(8)) | data[:, i].astype(jnp.int64)
            return out
        return data

    @staticmethod
    def _sortables(v) -> list:
        """Group-sort surrogate column list: wide BYTES expand into
        big-endian 7-byte int64 chunks (the sort/window convention), so
        any-width keys participate in multi-key grouping; everything
        else is a single surrogate."""
        from presto_tpu.ops.sort import bytes_sort_chunks

        if v.dtype.kind is TypeKind.BYTES:
            return bytes_sort_chunks(v.data)
        return [v.data]

    @staticmethod
    def _key_chunks(e: Expr) -> int:
        if e.dtype.kind is TypeKind.BYTES:
            return -(-e.dtype.width // 7)
        return 1

    # -- shared helpers ---------------------------------------------------

    def _agg_kind(self, a: AggSpec) -> str:
        if self.phase == "final":
            return a.merge_kind
        return "sum" if a.kind in ("count", "count_star") else a.kind

    def _eval_inputs(self, batch: Batch):
        """agg input values + contribution masks for this phase."""
        out = []
        for a in self.aggs:
            if self.phase == "final":
                c = batch[a.name]
                out.append((c.data, batch.live & c.valid))
            elif a.kind == "count_star" or a.input is None:
                out.append((jnp.ones(batch.capacity, jnp.int64), batch.live))
            else:
                v = evaluate(a.input, batch)
                if a.kind == "count":
                    out.append((jnp.ones(batch.capacity, jnp.int64), batch.live & v.valid))
                else:
                    out.append((v.data, batch.live & v.valid))
        return out

    def _eval_keys(self, batch: Batch):
        """Key Vals (dictionaries leave via the update's dict carrier)."""
        return [evaluate(e, batch) for _name, e in self.group_keys]

    def _eval_passengers(self, batch: Batch):
        return [evaluate(e, batch) for _name, e in self.passengers]

    # -- direct-addressed path -------------------------------------------

    def _direct_update(self, state, batch: Batch, params=()):
        # traced entry: the params argument shadows the executor's
        # concrete param scope with this trace's tracers (expr.Param)
        with param_scope(params):
            return self._direct_update_impl(state, batch)

    def _direct_update_impl(self, state, batch: Batch):
        """One-pass direct-addressed update.

        All integer sums, every per-aggregate count, and group presence
        ride a single ``fused_small_sums`` einsum (the MXU one-hot
        segment-sum — one read of the data instead of G x lanes masked
        reductions). Only min/max and float sums take the per-aggregate
        masked-reduction path.
        """
        from presto_tpu.cache.exec_cache import trace_probe

        trace_probe()
        st: DirectStrategy = self.strategy
        kvals = self._eval_keys(batch)
        nk = state["null_key"]
        for v in kvals:
            nk = nk | jnp.any(batch.live & ~v.valid)
        state = dict(state)
        state["null_key"] = nk
        keys = [v.data for v in kvals]
        gids, _ = group_ids_direct(
            keys, st.mins, st.strides, batch.live, st.num_groups
        )
        inputs = self._eval_inputs(batch)
        kinds = [self._agg_kind(a) for a in self.aggs]
        # count-kind partials sum all-ones columns: their sum IS their
        # count — no value lanes needed for them.
        is_count = [
            a.kind in ("count", "count_star") and self.phase != "final"
            for a in self.aggs
        ]
        fused = [
            i
            for i, (k, c) in enumerate(zip(kinds, is_count))
            if k == "sum" and not c
            and not jnp.issubdtype(inputs[i][0].dtype, jnp.floating)
        ]
        # merge stages aggregate accumulated sums, not per-row values:
        # the per-row bound only applies before the final phase
        bits = [
            self.aggs[i].value_bits if self.phase != "final" else 63
            for i in fused
        ]
        rest = [i for i in range(len(self.aggs)) if i not in fused and not is_count[i]]
        unfused = [i for i in range(len(self.aggs)) if i not in fused]
        sums, fcounts, extras, oflow = fused_small_sums(
            [inputs[i][0] for i in fused],
            bits,
            [inputs[i][1] for i in fused],
            gids,
            st.num_groups,
            extra_count_masks=[batch.live] + [inputs[i][1] for i in unfused],
        )
        counts: list = [None] * len(self.aggs)
        for j, i in enumerate(fused):
            counts[i] = fcounts[j]
        for j, i in enumerate(unfused):
            counts[i] = extras[1 + j]
        new = dict(state)
        new["present"] = state["present"] | (extras[0] > 0)
        new["value_overflow"] = state["value_overflow"] | oflow
        for j, i in enumerate(fused):
            new[self.aggs[i].name] = state[self.aggs[i].name] + sums[j]
        for i in range(len(self.aggs)):
            if is_count[i]:
                new[self.aggs[i].name] = state[self.aggs[i].name] + counts[i]
        for i in rest:
            a, kind = self.aggs[i], kinds[i]
            vals, contrib = inputs[i]
            part = segment_agg(vals, contrib, gids, st.num_groups, kind)
            prev = state[a.name]
            if kind == "sum":
                new[a.name] = prev + part
            elif kind == "min":
                new[a.name] = jnp.minimum(prev, part)
            else:
                new[a.name] = jnp.maximum(prev, part)
        for a, cnt in zip(self.aggs, counts):
            new[a.name + "$n"] = state[a.name + "$n"] + cnt
        return new, self._dict_carrier(kvals)

    def _direct_init(self):
        st: DirectStrategy = self.strategy
        g = st.num_groups
        state: dict[str, Any] = {
            "present": jnp.zeros(g, jnp.bool_),
            "value_overflow": jnp.zeros((), jnp.bool_),
            "null_key": jnp.zeros((), jnp.bool_),
        }
        for a in self.aggs:
            kind = self._agg_kind(a)
            dt = _phys_dtype(a)
            from presto_tpu.ops.groupby import _identity

            state[a.name] = jnp.full(g, _identity(kind, dt), dt)
            state[a.name + "$n"] = jnp.zeros(g, jnp.int64)
        return state

    # -- sort-merge path ---------------------------------------------------

    def _sort_update(self, state, batch: Batch, params=()):
        with param_scope(params):
            return self._sort_update_impl(state, batch)

    def _sort_update_impl(self, state, batch: Batch):
        """Fold a batch into the state by concatenating the state rows
        (as a pseudo-batch) with the batch's rows, then re-grouping —
        bounded memory, one multi-key sort per batch."""
        from presto_tpu.cache.exec_cache import trace_probe

        trace_probe()
        st: SortStrategy = self.strategy
        g = st.max_groups
        kvals = self._eval_keys(batch)
        pvals = self._eval_passengers(batch)
        inputs = self._eval_inputs(batch)

        # concat: state group rows [g] + batch rows [cap]; wide BYTES
        # keys contribute one sort column per 7-byte chunk. NULL keys
        # form their OWN group (SQL): data is normalized to the zero
        # fill so all NULLs compare equal, and a per-key validity
        # column joins the sort keys so NULL != any real value.
        cat_sort = []  # ALL sort columns (validity flags + key data)
        cat_data = []  # key data columns only, aligned with sort_names
        sort_names = []
        cat_valids = {}
        for (n, e), v in zip(self.group_keys, kvals):
            valid = v.valid
            cat_v = jnp.concatenate([state["keyv$" + n], valid])
            cat_valids[n] = cat_v
            cat_sort.append(cat_v.astype(jnp.int8))
            if e.dtype.kind is TypeKind.BYTES:
                masked = null_safe_key(v)
                for j, c in enumerate(self._sortables(masked)):
                    key = f"key${n}${j}"
                    cat = jnp.concatenate([state[key], c])
                    cat_sort.append(cat)
                    cat_data.append(cat)
                    sort_names.append(key)
            else:
                key = "key$" + n
                kd = null_safe_key(v).data.astype(state[key].dtype)
                cat = jnp.concatenate([state[key], kd])
                cat_sort.append(cat)
                cat_data.append(cat)
                sort_names.append(key)
        cat_live = jnp.concatenate([state["present"], batch.live])
        gids, rep, ng, ovf = group_ids_sort(cat_sort, cat_live, g)

        def gat(cat, fill=0):
            if cat.ndim > 1:
                safe = jnp.minimum(rep, cat.shape[0] - 1)
                return jnp.where((rep < cat.shape[0])[:, None], cat[safe], fill)
            return gather_padded(cat, rep, fill)

        new = dict(state)
        new["overflow"] = state["overflow"] | ovf
        for (n, _e) in self.group_keys:
            new["keyv$" + n] = gather_padded(cat_valids[n], rep, False)
        for key, cat in zip(sort_names, cat_data):
            new[key] = gat(cat)
        for (n, e), v in zip(self.group_keys, kvals):
            if e.dtype.kind is TypeKind.BYTES:
                cat_raw = jnp.concatenate([state["keyraw$" + n], v.data])
                new["keyraw$" + n] = gat(cat_raw)
        for (n, e), v in zip(self.passengers, pvals):
            cat_p = jnp.concatenate([state["pax$" + n], v.data])
            cat_pv = jnp.concatenate([state["paxv$" + n], v.valid])
            new["pax$" + n] = gat(cat_p)
            new["paxv$" + n] = gather_padded(cat_pv, rep, False)
        present = jnp.arange(g) < ng
        new["present"] = present
        for a, (vals, contrib) in zip(self.aggs, inputs):
            kind = self._agg_kind(a)
            dt = _phys_dtype(a)
            cat_vals = jnp.concatenate([state[a.name], vals.astype(dt)])
            cat_contrib = jnp.concatenate([state[a.name + "$has"], contrib])
            agg = segment_agg(cat_vals, cat_contrib, gids, g, kind)
            cnt = jnp.concatenate(
                [state[a.name + "$n"], contrib.astype(jnp.int64)]
            )
            ncnt = segment_agg(cnt, cat_live, gids, g, "sum")
            new[a.name] = agg
            new[a.name + "$n"] = ncnt
            new[a.name + "$has"] = ncnt > 0
        return new, self._dict_carrier(kvals, pvals)

    def _sort_init(self):
        st: SortStrategy = self.strategy
        g = st.max_groups
        state: dict[str, Any] = {
            "present": jnp.zeros(g, jnp.bool_),
            "overflow": jnp.zeros((), jnp.bool_),
        }
        for name, e in self.group_keys:
            state["keyv$" + name] = jnp.zeros(g, jnp.bool_)
            if e.dtype.kind is TypeKind.BYTES:
                for j in range(self._key_chunks(e)):
                    state[f"key${name}${j}"] = jnp.zeros(g, jnp.int64)
                state["keyraw$" + name] = jnp.zeros((g, e.dtype.width), jnp.uint8)
            else:
                state["key$" + name] = jnp.zeros(g, e.dtype.jnp_dtype)
        for name, e in self.passengers:
            if e.dtype.kind is TypeKind.BYTES:
                state["pax$" + name] = jnp.zeros((g, e.dtype.width), jnp.uint8)
            else:
                state["pax$" + name] = jnp.zeros(g, e.dtype.jnp_dtype)
            state["paxv$" + name] = jnp.zeros(g, jnp.bool_)
        for a in self.aggs:
            dt = _phys_dtype(a)
            from presto_tpu.ops.groupby import _identity

            state[a.name] = jnp.full(g, _identity(self._agg_kind(a), dt), dt)
            state[a.name + "$n"] = jnp.zeros(g, jnp.int64)
            state[a.name + "$has"] = jnp.zeros(g, jnp.bool_)
        return state

    # -- operator protocol -------------------------------------------------

    def process(self, batch: Batch) -> list[Batch]:
        if self.state is None:
            if isinstance(self.strategy, DirectStrategy):
                self.state = self._direct_init()
            else:
                self.state = self._sort_init()
        # the carrier hands back the dictionaries THIS trace signature
        # saw (correct even when jit's signature cache skipped the
        # body — the output treedef is stored per signature)
        self.state, carrier = self._update(self.state, batch, self._params)
        self._dicts = {n: c.dictionary for n, c in carrier.items()}
        return []

    def finish(self) -> list[Batch]:
        if self.state is None:
            if isinstance(self.strategy, DirectStrategy):
                self.state = self._direct_init()
            else:
                self.state = self._sort_init()
        st = self.state
        if isinstance(self.strategy, SortStrategy) and bool(st["overflow"]):
            raise CapacityOverflow("HashAggregation", self.strategy.max_groups)
        if isinstance(self.strategy, DirectStrategy) and bool(st["null_key"]):
            raise NullGroupKeys(
                "direct-addressed grouping met NULL key values "
                f"({[n for n, _ in self.group_keys]}) — replan with the "
                "sort strategy")
        if isinstance(self.strategy, DirectStrategy) and bool(st["value_overflow"]):
            raise ValueBitsOverflow(
                "a declared AggSpec.value_bits bound was exceeded at "
                f"runtime in {[a.name for a in self.aggs]} — the planner "
                "retries with the unbounded 63-bit path"
            )
        cols: dict[str, Column] = {}
        if isinstance(self.strategy, DirectStrategy):
            g = self.strategy.num_groups
            live = st["present"]
            # decode gid -> key values
            gid = jnp.arange(g, dtype=jnp.int32)
            rem = gid
            for (name, e), m, s in zip(
                self.group_keys, self.strategy.mins, self.strategy.strides
            ):
                code = rem // np.int32(s) + np.int32(m)
                rem = rem % np.int32(s)
                cols[name] = Column(
                    code.astype(e.dtype.jnp_dtype),
                    jnp.ones(g, jnp.bool_),
                    e.dtype,
                    self._dicts.get(name),
                )
        else:
            g = self.strategy.max_groups
            live = st["present"]
            for name, e in self.group_keys:
                if e.dtype.kind is TypeKind.BYTES:
                    data = st["keyraw$" + name]
                else:
                    data = st["key$" + name]
                cols[name] = Column(
                    data, st["keyv$" + name], e.dtype, self._dicts.get(name)
                )
            for name, e in self.passengers:
                cols[name] = Column(
                    st["pax$" + name], st["paxv$" + name], e.dtype,
                    self._dicts.get(name),
                )
        for a in self.aggs:
            valid = st[a.name + "$n"] > 0
            data = st[a.name]
            if a.kind in ("count", "count_star") and self.phase != "final":
                valid = jnp.ones(g, jnp.bool_)
            elif a.merge_kind == "sum" and self.phase == "final" and a.kind in (
                "count",
                "count_star",
            ):
                valid = jnp.ones(g, jnp.bool_)
            data = jnp.where(valid, data, 0)
            cols[a.name] = Column(data.astype(a.dtype.jnp_dtype), valid, a.dtype)
        return [Batch(cols, live)]


def _phys_dtype(a: AggSpec):
    if a.kind in ("count", "count_star"):
        return jnp.int64
    return a.dtype.jnp_dtype


# ---------------------------------------------------------------------------
# Global (ungrouped) aggregation — AggregationOperator
# ---------------------------------------------------------------------------


class GlobalAggregationOperator(Operator):
    """Aggregation without GROUP BY (reference: AggregationOperator)."""

    def __init__(self, aggs: Sequence[AggSpec], phase: str = "single",
                 params: Sequence[Any] = ()):
        from presto_tpu.cache.exec_cache import EXEC_CACHE

        self._params = tuple(params)
        self.aggs = list(aggs)
        self.phase = phase
        self.state = None
        # shared across queries via a state-less template (see
        # HashAggregationOperator: a cached bound method of a live
        # operator would pin its final device state)
        self._update = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("global_agg", self.aggs, phase),
            self._build_update,
        )

    def _build_update(self):
        tmpl = GlobalAggregationOperator.__new__(GlobalAggregationOperator)
        tmpl.aggs = list(self.aggs)
        tmpl.phase = self.phase
        tmpl.state = None
        return jax.jit(tmpl._step)

    def _step(self, state, batch: Batch, params=()):
        with param_scope(params):
            return self._step_impl(state, batch)

    def _step_impl(self, state, batch: Batch):
        from presto_tpu.cache.exec_cache import trace_probe

        trace_probe()
        new = dict(state)
        for a in self.aggs:
            if self.phase == "final":
                c = batch[a.name]
                vals, contrib = c.data, batch.live & c.valid
                kind = a.merge_kind
            elif a.kind == "count_star" or a.input is None:
                vals, contrib = jnp.ones(batch.capacity, jnp.int64), batch.live
                kind = "sum"
            else:
                v = evaluate(a.input, batch)
                contrib = batch.live & v.valid
                if a.kind == "count":
                    vals, kind = jnp.ones(batch.capacity, jnp.int64), "sum"
                else:
                    vals, kind = v.data, a.kind
            from presto_tpu.ops.groupby import _identity

            ident = _identity(kind, vals.dtype)
            masked = jnp.where(contrib, vals, ident)
            if kind == "sum":
                # accumulate in the state's (canonical) dtype: narrow
                # physical inputs must widen BEFORE the reduction, or
                # the running sum wraps inside the input width
                masked = masked.astype(state[a.name].dtype)
                new[a.name] = state[a.name] + jnp.sum(masked).astype(state[a.name].dtype)
            elif kind == "min":
                new[a.name] = jnp.minimum(state[a.name], jnp.min(masked))
            else:
                new[a.name] = jnp.maximum(state[a.name], jnp.max(masked))
            new[a.name + "$n"] = state[a.name + "$n"] + jnp.sum(contrib.astype(jnp.int64))
        return new

    def _init(self):
        from presto_tpu.ops.groupby import _identity

        state = {}
        for a in self.aggs:
            kind = (
                a.merge_kind
                if self.phase == "final"
                else ("sum" if a.kind in ("count", "count_star") else a.kind)
            )
            dt = _phys_dtype(a)
            state[a.name] = jnp.asarray(_identity(kind, dt), dt)
            state[a.name + "$n"] = jnp.zeros((), jnp.int64)
        return state

    def process(self, batch: Batch) -> list[Batch]:
        if self.state is None:
            self.state = self._init()
        self.state = self._update(self.state, batch, self._params)
        return []

    def result_batch(self, state) -> Batch:
        """Pure finalize: accumulated state -> the one-row result batch.
        Shared by ``finish()`` (concrete state) and the cross-query
        batched dispatcher (traced, param-stacked state — see
        server/batcher.py), so both paths run IDENTICAL math."""
        cols = {}
        for a in self.aggs:
            n = state[a.name + "$n"]
            valid = (n > 0) | jnp.asarray(a.kind in ("count", "count_star"))
            data = jnp.where(valid, state[a.name], 0)
            cols[a.name] = Column(
                data.astype(a.dtype.jnp_dtype)[None], valid[None], a.dtype
            )
        return Batch(cols, jnp.ones(1, jnp.bool_))

    def finish(self) -> list[Batch]:
        if self.state is None:
            self.state = self._init()
        return [self.result_batch(self.state)]


# ---------------------------------------------------------------------------
# Ordering / limiting
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SortKey:
    expr: Expr
    descending: bool = False
    nulls_first: bool = False


class CollectingOperator(Operator):
    """Base: buffers incoming batches (host list of device batches)."""

    def __init__(self):
        self.batches: list[Batch] = []

    def process(self, batch: Batch) -> list[Batch]:
        self.batches.append(batch)
        return []


def concat_batches(batches: list[Batch]) -> Batch:
    """Concatenate along rows (device op). The output dictionary per
    column is the first non-None one — a NULL-literal union branch
    (grouping-sets subtotal rows) carries none, and taking its None
    would decode every later batch's codes as raw integers."""
    first = batches[0]
    if len(batches) == 1:
        return first
    cols = {}
    for name in first.names:
        t = first[name].dtype
        d = next(
            (b[name].dictionary for b in batches
             if b[name].dictionary is not None),
            None,
        )
        cols[name] = Column(
            jnp.concatenate([b[name].data for b in batches]),
            jnp.concatenate([b[name].valid for b in batches]),
            t,
            d,
        )
    return Batch(cols, jnp.concatenate([b.live for b in batches]))


def union_target_dicts(names, sample_batches):
    """Per-column target dictionaries for a UNION: where children carry
    different dictionaries for the same column, the target is their
    merge; identical/absent dictionaries need no alignment (the common
    case — one dictionary object per source column). ``sample_batches``
    are one representative batch per child (dictionaries are uniform
    within a child's stream); Nones (empty children) are skipped."""
    from presto_tpu.batch import Dictionary

    targets: dict[str, object] = {}
    for n in names:
        dicts = []
        for b in sample_batches:
            if b is None or n not in b:
                continue
            d = b[n].dictionary
            if d is not None and all(d is not x for x in dicts):
                dicts.append(d)
        if len(dicts) > 1:
            merged: list[str] = []
            for d in dicts:
                merged.extend(d.values.tolist())
            targets[n] = Dictionary(merged)
    return targets


def align_batch_dicts(b: Batch, targets: dict, _cache: dict | None = None) -> Batch:
    """Re-encode dictionary columns of ``b`` into the union's target
    dictionaries via a small device-side code mapping table. ``_cache``
    (keyed by (column, source-dictionary identity)) lets a streaming
    caller build each mapping once instead of per batch."""
    if not targets:
        return b
    cols = dict(b.columns)
    for n, target in targets.items():
        c = cols.get(n)
        if c is None or c.dictionary is None or c.dictionary is target:
            continue
        key = (n, id(c.dictionary))
        mapping = None if _cache is None else _cache.get(key)
        if mapping is None:
            mapping = jnp.asarray(
                np.array([target.code_of(v) for v in c.dictionary.values],
                         dtype=np.int32)
            )
            if _cache is not None:
                _cache[key] = mapping
        cols[n] = Column(mapping[c.data], c.valid, c.dtype, target)
    return Batch(cols, b.live)


class OrderByOperator(CollectingOperator):
    """Full sort (reference: OrderByOperator + PagesIndex.sort)."""

    def __init__(self, keys: Sequence[SortKey]):
        super().__init__()
        self.keys = list(keys)

    def result_batch(self, batch: Batch) -> Batch:
        """Pure sort of one concatenated batch (shared by ``finish()``
        and the cross-query batched dispatcher — see finish/result
        split note on GlobalAggregationOperator.result_batch)."""
        vals = [evaluate(k.expr, batch) for k in self.keys]
        order = sort_indices(
            [v.data for v in vals],
            [k.descending for k in self.keys],
            batch.live,
            nulls_first=[k.nulls_first for k in self.keys],
            valids=[v.valid for v in vals],
        )
        cols = {
            n: Column(
                batch[n].data[order], batch[n].valid[order], batch[n].dtype,
                batch[n].dictionary,
            )
            for n in batch.names
        }
        return Batch(cols, batch.live[order])

    def finish(self) -> list[Batch]:
        if not self.batches:
            return []
        return [self.result_batch(concat_batches(self.batches))]


class TopNOperator(CollectingOperator):
    """Sort + limit with bounded output (reference: TopNOperator)."""

    def __init__(self, keys: Sequence[SortKey], n: int):
        super().__init__()
        self.keys = list(keys)
        self.n = n

    def finish(self) -> list[Batch]:
        if not self.batches:
            return []
        return [self.result_batch(concat_batches(self.batches))]

    def result_batch(self, batch: Batch) -> Batch:
        """Pure top-N of one concatenated batch (shared by ``finish()``
        and the cross-query batched dispatcher)."""
        vals = [evaluate(k.expr, batch) for k in self.keys]
        order = sort_indices(
            [v.data for v in vals],
            [k.descending for k in self.keys],
            batch.live,
            nulls_first=[k.nulls_first for k in self.keys],
            valids=[v.valid for v in vals],
        )
        take = order[: self.n]
        live = gather_padded(batch.live, take, False)

        def gat(data):
            if data.ndim > 1:
                safe = jnp.minimum(take, data.shape[0] - 1)
                return jnp.where((take < data.shape[0])[:, None], data[safe], 0)
            return gather_padded(data, take, 0)

        cols = {
            n_: Column(
                gat(batch[n_].data),
                gather_padded(batch[n_].valid, take, False),
                batch[n_].dtype,
                batch[n_].dictionary,
            )
            for n_ in batch.names
        }
        return Batch(cols, live)


class WindowOperator(CollectingOperator):
    """Window functions (reference: WindowOperator + WindowPartition
    row walk; RowNumberOperator / TopNRowNumberOperator fast paths).

    TPU-first: one sort of the whole input by (partition keys, order
    keys), then every function is computed with segmented scans and
    boundary gathers over the sorted rows — no per-partition loop
    (``presto_tpu.ops.window``). Output rows stay in sorted order (SQL
    imposes no output order; a downstream Sort/TopN reorders).

    funcs reuse AggSpec; supported kinds: row_number / rank /
    dense_rank (require order keys) and sum / count / count_star /
    min / max (windowed aggregates honoring ``frame``).
    """

    def __init__(
        self,
        partition_by: Sequence[Expr],
        order_keys: Sequence[SortKey],
        funcs: Sequence[AggSpec],
        frame: str = "range",
        params: Sequence[Any] = (),
    ):
        super().__init__()
        self._params = tuple(params)
        self.partition_by = list(partition_by)
        self.order_keys = list(order_keys)
        self.funcs = list(funcs)
        self.frame = frame
        if frame not in ("range", "rows", "full"):
            raise InternalError(f"unsupported window frame {frame!r}")
        ranked = [
            f for f in funcs
            if f.kind in ("row_number", "rank", "dense_rank",
                          "lag", "lead", "first_value")
        ]
        if ranked and not self.order_keys:
            raise ValueError(f"{ranked[0].kind}() requires ORDER BY in its window")
        from presto_tpu.cache.exec_cache import EXEC_CACHE

        # the step closure reads only window CONFIG off its operator;
        # cache it bound to a state-less template (the buffered batches
        # of a cached live operator must not outlive their query)
        self._step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of(
                "window", self.partition_by, self.order_keys, self.funcs,
                frame,
            ),
            self._build_step,
        )

    def _template(self) -> "WindowOperator":
        """State-less clone for cache-shared traced bodies: a cached
        closure must never pin a live operator (and its buffered
        batches). Also used by the distributed window step builder."""
        tmpl = WindowOperator.__new__(WindowOperator)
        tmpl.batches = []
        tmpl.partition_by = list(self.partition_by)
        tmpl.order_keys = list(self.order_keys)
        tmpl.funcs = list(self.funcs)
        tmpl.frame = self.frame
        return tmpl

    def _build_step(self):
        return jax.jit(self._template()._make_step())

    def _make_step(self):
        from presto_tpu.cache.exec_cache import trace_probe

        from presto_tpu.ops.window import (
            change_flags,
            rank_values,
            windowed_agg,
        )

        sortable = HashAggregationOperator._sortable
        from presto_tpu.ops.sort import bytes_sort_chunks

        def key_parts(v):
            """int64 comparison columns for a key Val: wide BYTES
            expand to big-endian chunk columns (lexicographic), all
            else is a single sortable surrogate."""
            if v.dtype.kind is TypeKind.BYTES and v.dtype.width > 7:
                return bytes_sort_chunks(v.data)
            return [sortable(v)]

        def step(batch: Batch, params=()) -> Batch:
            trace_probe()
            with param_scope(params):
                return body(batch)

        def body(batch: Batch) -> Batch:
            cap = batch.capacity
            # ---- sort keys: partition keys (nulls as a group), then
            # order keys with SQL null placement
            sort_cols, descs, nfs, valids = [], [], [], []
            part_cmp: list = []  # comparison columns (null-normalized)
            for e in self.partition_by:
                v = evaluate(e, batch)
                isnull = (~v.valid).astype(jnp.int32)
                sort_cols.append(isnull)
                descs.append(False)
                nfs.append(False)
                valids.append(None)
                part_cmp.append(isnull)
                for p in key_parts(v):
                    norm = jnp.where(v.valid, p, 0)
                    sort_cols.append(norm)
                    descs.append(False)
                    nfs.append(False)
                    valids.append(None)
                    part_cmp.append(norm)
            peer_cmp: list = []
            for k in self.order_keys:
                v = evaluate(k.expr, batch)
                peer_cmp.append((~v.valid).astype(jnp.int32))
                for j, p in enumerate(key_parts(v)):
                    sort_cols.append(p)
                    descs.append(k.descending)
                    nfs.append(k.nulls_first)
                    valids.append(v.valid if j == 0 else None)
                    peer_cmp.append(jnp.where(v.valid, p, 0))
            order = sort_indices(sort_cols, descs, batch.live,
                                 nulls_first=nfs, valids=valids)

            def gat(data, fill=0):
                if data.ndim > 1:
                    safe = jnp.minimum(order, data.shape[0] - 1)
                    return jnp.where((order < data.shape[0])[:, None], data[safe], fill)
                return gather_padded(data, order, fill)

            cols = {
                n: Column(
                    gat(batch[n].data),
                    gather_padded(batch[n].valid, order, False),
                    batch[n].dtype,
                    batch[n].dictionary,
                )
                for n in batch.names
            }
            live = gather_padded(batch.live, order, False)
            sorted_batch = Batch(cols, live)

            # ---- boundary flags on the sorted layout ----------------
            # liveness participates so the dead tail starts a fresh
            # segment and never extends a live partition's scans
            pcols = [c[order] for c in part_cmp] + [live.astype(jnp.int32)]
            part_change = change_flags(pcols)
            if peer_cmp:
                peer_change = part_change | change_flags(
                    [c[order] for c in peer_cmp]
                )
            else:
                peer_change = part_change

            # ---- functions ------------------------------------------
            row_number, rank, dense = rank_values(part_change, peer_change)
            all_valid = jnp.ones(cap, jnp.bool_)
            idx = jnp.arange(cap)
            seg_start = None  # offset functions' partition fence, lazy
            for f in self.funcs:
                if f.kind in ("lag", "lead", "first_value"):
                    if seg_start is None:
                        from presto_tpu.ops.window import segment_starts

                        seg_start = segment_starts(part_change)
                    v = evaluate(f.input, sorted_batch)
                    cvalid = live & v.valid
                    if f.kind == "first_value":
                        src = seg_start
                        ok = jnp.ones(cap, jnp.bool_)
                    elif f.kind == "lag":
                        src = jnp.maximum(idx - f.offset, 0)
                        ok = (idx - f.offset) >= seg_start
                    else:  # lead: same segment iff its start matches
                        src = jnp.minimum(idx + f.offset, cap - 1)
                        ok = ((idx + f.offset) < cap) & (
                            seg_start[src] == seg_start
                        )
                    data = v.data[src]
                    valid = ok & cvalid[src] & live
                    # v.dtype carries the truthful physical storage of
                    # the shifted column (narrow scan data passes
                    # through the gather unchanged)
                    cols[f.name] = Column(data, valid, v.dtype, v.dictionary)
                    continue
                if f.kind == "row_number":
                    cols[f.name] = Column(row_number, all_valid, f.dtype)
                    continue
                if f.kind == "rank":
                    cols[f.name] = Column(rank, all_valid, f.dtype)
                    continue
                if f.kind == "dense_rank":
                    cols[f.name] = Column(dense, all_valid, f.dtype)
                    continue
                dt = _phys_dtype(f)
                dictionary = None
                if f.kind == "count_star" or f.input is None:
                    vals = jnp.ones(cap, jnp.int64)
                    contrib = live
                else:
                    v = evaluate(f.input, sorted_batch)
                    dictionary = v.dictionary  # min/max on ordered codes
                    if f.kind == "count":
                        vals, contrib = jnp.ones(cap, jnp.int64), live & v.valid
                    else:
                        vals, contrib = v.data.astype(dt), live & v.valid
                kind = "sum" if f.kind in ("count", "count_star") else f.kind
                val, cnt = windowed_agg(vals, contrib, part_change, peer_change,
                                        kind, self.frame)
                if f.kind in ("count", "count_star"):
                    cols[f.name] = Column(
                        val.astype(f.dtype.jnp_dtype), all_valid, f.dtype
                    )
                else:
                    valid = cnt > 0
                    cols[f.name] = Column(
                        jnp.where(valid, val, 0).astype(f.dtype.jnp_dtype),
                        valid, f.dtype, dictionary,
                    )
            return Batch(cols, live)

        return step

    def finish(self) -> list[Batch]:
        if not self.batches:
            return []
        return [self._step(concat_batches(self.batches), self._params)]


def window_operator_from_node(node, scalars, params=()) -> WindowOperator:
    """Lower an ``N.Window`` plan node to a WindowOperator (shared by
    the local and distributed executors)."""
    from presto_tpu.expr import bind_scalars

    part = [bind_scalars(e, scalars) for e in node.partition_by]
    keys = [
        SortKey(bind_scalars(k.expr, scalars), k.descending, k.nulls_first)
        for k in node.order_by
    ]
    aggs = [
        AggSpec(f.kind,
                bind_scalars(f.input, scalars) if f.input is not None else None,
                f.name, f.dtype, offset=f.offset)
        for f in node.funcs
    ]
    return WindowOperator(part, keys, aggs, node.frame, params=params)


class LimitOperator(Operator):
    """Row-count limit across batches (reference: LimitOperator)."""

    def __init__(self, n: int):
        self.remaining = n

    def process(self, batch: Batch) -> list[Batch]:
        if self.remaining <= 0:
            return []
        c = int(batch.count())
        if c <= self.remaining:
            self.remaining -= c
            return [batch]
        # keep only the first `remaining` live rows
        k = self.remaining
        self.remaining = 0
        live_rank = jnp.cumsum(batch.live.astype(jnp.int32))
        return [batch.with_live(batch.live & (live_rank <= k))]
