"""Pipelines and the driver loop.

Reference parity: ``operator.Driver.processFor`` — the inner loop moving
Pages between adjacent operators — and ``DriverFactory``/pipeline
structure from ``LocalExecutionPlanner`` [SURVEY §2.1, §3.2; reference
tree unavailable, paths reconstructed].

TPU-first: the driver is a *push* loop on the host; batches are device
arrays, so each ``process`` call is an async XLA dispatch and the loop
runs ahead of the device (the cooperative time-slicing machinery of
``TaskExecutor`` collapses into Python + the XLA stream). A pipeline is
``source -> transforms... -> sink``; pipeline-breaking operators
(aggregations, sorts, joins' build side) buffer device-side and emit on
``finish()``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, Sequence

from presto_tpu.batch import Batch
from presto_tpu.exec.operators import Operator
from presto_tpu.spi import Connector, Split, batch_capacity


@dataclass
class OperatorStats:
    """Per-operator runtime stats (reference: OperatorStats rollup into
    QueryStats [SURVEY §5.1])."""

    name: str
    input_batches: int = 0
    output_batches: int = 0
    wall_s: float = 0.0


class ScanSource:
    """Pulls splits from a connector and yields device batches
    (reference: ScanFilterAndProjectOperator's page source half +
    SourcePartitionedScheduler's split feed)."""

    def __init__(
        self,
        connector: Connector,
        table: str,
        columns: Sequence[str] | None,
        splits: Sequence[Split] | None = None,
        capacity: int | None = None,
    ):
        self.connector = connector
        self.table = table
        self.columns = list(columns) if columns is not None else None
        self.splits = list(splits) if splits is not None else list(connector.splits(table))
        # one shared capacity bucket across splits keeps a single
        # compiled program per chain
        self.capacity = capacity or batch_capacity(
            max(s.row_hint for s in self.splits)
        )

    def __iter__(self) -> Iterator[Batch]:
        def load(split):
            from presto_tpu.runtime.faults import fault_point

            fault_point("scan")
            return self.connector.scan(split, self.columns, self.capacity)

        return prefetch_iter(load, self.splits)


def prefetch_enabled() -> bool:
    """Default: on when the host has CPU to spare, off on a 1-core
    host — measured on the live chip (notes/PERF.md §8): with one
    host core the worker thread only contends with generation under
    the GIL (sf1 --stream: 439k rows/s prefetched vs 518k serial).
    ``PRESTO_TPU_PREFETCH=1/0`` overrides either way."""
    import os

    v = os.environ.get("PRESTO_TPU_PREFETCH", "").strip().lower()
    if v:
        return v not in ("0", "false", "off", "no")
    try:
        ncpu = len(os.sched_getaffinity(0))  # cgroup/taskset-aware
    except AttributeError:  # non-Linux
        ncpu = os.cpu_count() or 1
    return ncpu > 1


def prefetch_iter(load, items):
    """One-slot prefetch (SURVEY §2.4 PP row, §7.1 double-buffered H2D):
    item k+1 loads (generate + transfer) on a worker thread while the
    consumer holds item k — XLA dispatches are async, so the consumer
    returns to this loop immediately and host-side generation overlaps
    device compute. Exactly one item is in flight (bounded host
    memory). ``PRESTO_TPU_PREFETCH=0`` reverts to a serial loop."""
    if len(items) <= 1 or not prefetch_enabled():
        for it in items:
            yield load(it)
        return
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=1) as ex:
        fut = ex.submit(load, items[0])
        for nxt in items[1:]:
            out = fut.result()
            fut = ex.submit(load, nxt)
            yield out
        yield fut.result()


class BatchSource:
    """A source over in-memory batches (exchange inputs, tests)."""

    def __init__(self, batches: Iterable[Batch]):
        self._batches = batches

    def __iter__(self) -> Iterator[Batch]:
        return iter(self._batches)


class BatchStream:
    """A REPLAYABLE lazy batch stream — the executor's unit of data flow.

    ``make_iter`` returns a fresh iterator on every call, so retry loops
    (capacity-overflow doubling) can re-drain the stream; a plain
    generator would come back empty on the second attempt and silently
    drop rows. Replaying a scan-rooted stream re-generates the data —
    the deliberate trade that keeps memory bounded (SURVEY §7.4 #1:
    overflow retries are rare, whole-table materialization is not).

    Streams rooted at materialized results wrap a list (replay is free).
    """

    def __init__(self, make_iter: Callable[[], Iterator[Batch]]):
        self._make = make_iter

    @classmethod
    def of(cls, batches: Sequence[Batch]) -> "BatchStream":
        return cls(lambda: iter(batches))

    def __iter__(self) -> Iterator[Batch]:
        return self._make()

    def map(self, fn: Callable[[Batch], Batch]) -> "BatchStream":
        return BatchStream(lambda: (fn(b) for b in self))

    def peek(self) -> "Batch | None":
        """First batch, or None when empty (costs one replayed scan of
        the first split — used for trace-time decisions like dictionary
        domains)."""
        return next(iter(self), None)

    def materialize(self) -> list[Batch]:
        return list(self)


class Pipeline:
    """source -> op chain; run() returns the terminal output batches."""

    def __init__(self, source: Iterable[Batch], operators: Sequence[Operator]):
        self.source = source
        self.operators = list(operators)
        self.stats = [OperatorStats(type(op).__name__) for op in self.operators]

    def run(self) -> list[Batch]:
        from presto_tpu.runtime.lifecycle import check_deadline
        from presto_tpu.runtime.trace import span as trace_span

        outputs: list[Batch] = []

        def push(i: int, batch: Batch):
            if i == len(self.operators):
                outputs.append(batch)
                return
            st = self.stats[i]
            st.input_batches += 1
            t0 = time.perf_counter()
            with trace_span(f"step:{st.name}", "step"):
                produced = self.operators[i].process(batch)
            st.wall_s += time.perf_counter() - t0
            for b in produced:
                st.output_batches += 1
                push(i + 1, b)

        # the driver-loop deadline boundary: one check per morsel (a
        # compiled step in flight runs to completion; the NEXT push is
        # what an expired query_max_run_time stops)
        with trace_span("driver:push", "driver"):
            for batch in self.source:
                check_deadline("driver-loop")
                push(0, batch)
        # finish cascade — checked per finish() step, not once: for
        # sort/window/topN plans the heavy work happens HERE, so an
        # expired deadline must stop the remaining collecting operators
        for i, op in enumerate(self.operators):
            check_deadline("driver-finish")
            t0 = time.perf_counter()
            with trace_span(f"finish:{self.stats[i].name}", "step"):
                tail = op.finish()
            self.stats[i].wall_s += time.perf_counter() - t0
            for b in tail:
                self.stats[i].output_batches += 1
                push(i + 1, b)
        return outputs
