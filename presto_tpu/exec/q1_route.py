"""SQL-path routing onto the fully-fused Q1 leaf-fragment kernel.

Reference parity: ``HandTpchQuery1`` in ``presto-benchmark`` [SURVEY
§6] — except the reference keeps the hand-built pipeline *beside* the
SQL engine, while this module recognizes the Q1 leaf fragment (scan ->
shipdate filter -> 6-group partial aggregation) inside a real analyzed
plan and executes it through ``workloads.q1_fused_step``, which on TPU
is the single-pass Pallas kernel (``ops.pallas_q1``, measured 15.6x
baseline). Stats-driven narrow storage (ISSUE-5) is what makes this
fire for real queries: the canonical SQL scan now materializes exactly
the narrow columns the kernel's eligibility check accepts.

Since the leaf-fragment pattern framework landed (exec/leaf_route.py),
this module is its Q1 *specialization*: ``match_leaf_fragment`` tries
``match_q1_fragment`` first — the 3-factor ``charge`` product is
outside the generic 2-term value grammar of ``ops/pallas_agg``, so Q1
keeps its hand-built kernel (bit-identical, same counters) while Q6 /
SSB Q1 / CTAS leaves lower through the parameterized family.

Matching is STRICT and stats-guarded: every structural piece of the
fragment (the shipdate cutoff literal, the ``ep*(1-disc)`` /
``ep*(1-disc)*(1+tax)`` product shapes, decimal scales, the 3x2
returnflag/linestatus dictionary domains) must line up, and every
scanned column's connector stats must prove the kernel's value domains
(qty < 2^13, ep < 2^24, disc in [0, 100], tax in [0, 27], scaled) and
NULL-freedom. Anything else falls through to the generic operator
route; a runtime ``value_overflow`` (violated stats) also falls back —
loud in metrics, never a wrong answer.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from presto_tpu.batch import Batch
from presto_tpu.expr import Call, InputRef, Literal
from presto_tpu.plan import nodes as N
from presto_tpu.spi import batch_capacity, stats_physical_interval
from presto_tpu.types import DataType, TypeKind

#: l_shipdate <= date '1998-12-01' - interval '90' day, the kernel's
#: baked-in cutoff (ops/pallas_q1._CUTOFF)
CUTOFF_DAYS = int(np.datetime64("1998-09-02").astype("datetime64[D]")
                  .astype(np.int64))

#: kernel value-domain guards over the SCALED (physical) values — must
#: match the in-kernel overflow guard (ops/pallas_q1._kernel) exactly:
#: a route admitted here can still trip value_overflow (stats are
#: advisory), but a column whose DECLARED bounds exceed these can never
#: route (the guard would flag every batch)
_DOMAINS = {
    "l_quantity": (0, (1 << 13) - 1),
    "l_extendedprice": (0, (1 << 24) - 1),
    "l_discount": (0, 100),
    "l_tax": (0, 27),
}

#: the seven kernel input columns, canonical names
KERNEL_COLS = ("l_quantity", "l_extendedprice", "l_discount", "l_tax",
               "l_returnflag", "l_linestatus", "l_shipdate")


class Q1Route:
    """A matched Q1 leaf fragment, ready to execute."""

    __slots__ = ("scan", "rename", "outputs", "key_names", "key_dtypes")

    def __init__(self, scan, rename, outputs, key_names, key_dtypes):
        self.scan = scan  # N.TableScan
        #: source column -> kernel canonical name
        self.rename = rename
        #: aggregate output name -> kernel state key
        self.outputs = outputs
        #: (returnflag output name, linestatus output name)
        self.key_names = key_names
        self.key_dtypes = key_dtypes


def _is_one(e) -> bool:
    return (isinstance(e, Literal) and e.value == 1
            and e.dtype.kind in (TypeKind.INTEGER, TypeKind.BIGINT,
                                 TypeKind.DECIMAL))


def _dec2_ref(e) -> Optional[str]:
    """Name of a bare decimal(p,2) column reference, else None."""
    if (isinstance(e, InputRef) and e.dtype.kind is TypeKind.DECIMAL
            and e.dtype.scale == 2):
        return e.name
    return None


def _split_dp(e):
    """mul(ep, sub(1, disc)) at scale 4 -> (ep_name, disc_name)."""
    if not (isinstance(e, Call) and e.fn == "mul"
            and e.dtype.kind is TypeKind.DECIMAL and e.dtype.scale == 4
            and len(e.args) == 2):
        return None
    ep = _dec2_ref(e.args[0])
    b = e.args[1]
    if (ep is None or not isinstance(b, Call) or b.fn != "sub"
            or len(b.args) != 2 or not _is_one(b.args[0])):
        return None
    disc = _dec2_ref(b.args[1])
    return None if disc is None else (ep, disc)


def _split_ch(e):
    """mul(mul(ep, sub(1, disc)), add(1, tax)) -> (ep, disc, tax)."""
    if not (isinstance(e, Call) and e.fn == "mul"
            and e.dtype.kind is TypeKind.DECIMAL and e.dtype.scale == 4
            and len(e.args) == 2):
        return None
    dp = _split_dp(e.args[0])
    t = e.args[1]
    if (dp is None or not isinstance(t, Call) or t.fn != "add"
            or len(t.args) != 2 or not _is_one(t.args[0])):
        return None
    tax = _dec2_ref(t.args[1])
    return None if tax is None else (*dp, tax)


def match_q1_fragment(node: N.Aggregate, catalog) -> Optional[Q1Route]:
    """The strict structural + stats match described in the module
    docstring; None on any mismatch."""
    if not isinstance(node, N.Aggregate) or node.passengers:
        return None
    if len(node.keys) != 2:
        return None
    # ---- fragment shape: Aggregate -> [Filter ->] TableScan ----------
    child = node.child
    if isinstance(child, N.Filter) and isinstance(child.child, N.TableScan):
        scan, pred = child.child, child.predicate
        if scan.predicate is not None:
            return None
    elif isinstance(child, N.TableScan) and child.predicate is not None:
        scan, pred = child, child.predicate
    else:
        return None
    # ---- predicate: ship <= date '1998-09-02' ------------------------
    if not (isinstance(pred, Call) and pred.fn == "le" and len(pred.args) == 2):
        return None
    ship_ref, cutoff = pred.args
    if not (isinstance(ship_ref, InputRef)
            and ship_ref.dtype.kind is TypeKind.DATE
            and isinstance(cutoff, Literal)
            and cutoff.dtype.kind is TypeKind.DATE):
        return None
    try:
        if int(cutoff.dtype.to_physical(cutoff.value)) != CUTOFF_DAYS:
            return None
    except (TypeError, ValueError):
        return None
    # ---- aggregates -> kernel outputs --------------------------------
    roles: dict[str, str] = {}  # kernel name -> aggregate-side name

    def bind(role: str, name: str) -> bool:
        if roles.get(role, name) != name:
            return False
        roles[role] = name
        return True

    outputs: dict[str, str] = {}
    bare_sums: list[str] = []
    counted: list[str] = []
    for a in node.aggs:
        if a.kind == "count_star":
            outputs[a.name] = "count_order"
            continue
        if a.kind == "count" and isinstance(a.input, InputRef):
            counted.append(a.input.name)
            outputs[a.name] = "count_order"
            continue
        if a.kind != "sum" or a.input is None:
            return None
        e = a.input
        name = _dec2_ref(e)
        if name is not None:
            bare_sums.append(a.name)
            continue
        ch = _split_ch(e)
        if ch is not None:
            if not (bind("l_extendedprice", ch[0])
                    and bind("l_discount", ch[1]) and bind("l_tax", ch[2])):
                return None
            outputs[a.name] = "sum_charge"
            continue
        dp = _split_dp(e)
        if dp is not None:
            if not (bind("l_extendedprice", dp[0])
                    and bind("l_discount", dp[1])):
                return None
            outputs[a.name] = "sum_disc_price"
            continue
        return None
    if "l_extendedprice" not in roles or "l_tax" not in roles:
        return None  # both product shapes are required to pin ep/disc/tax
    # bare decimal sums resolve against the product-pinned roles; the
    # one remaining distinct column is quantity
    inv = {v: k for k, v in roles.items()}
    qty_name = None
    for out_name in bare_sums:
        a = next(x for x in node.aggs if x.name == out_name)
        col = a.input.name
        role = inv.get(col)
        if role == "l_extendedprice":
            outputs[out_name] = "sum_base_price"
        elif role == "l_discount":
            outputs[out_name] = "sum_disc"
        elif role == "l_tax":
            return None  # the kernel has no sum(tax) output
        elif qty_name is None or qty_name == col:
            qty_name = col
            outputs[out_name] = "sum_qty"
        else:
            return None  # two distinct unexplained sum columns
    if qty_name is None:
        return None
    roles["l_quantity"] = qty_name
    roles["l_shipdate"] = ship_ref.name
    # ---- keys: returnflag x linestatus dictionaries ------------------
    (rf_out, rf_e), (ls_out, ls_e) = node.keys
    for e in (rf_e, ls_e):
        if not (isinstance(e, InputRef) and e.dtype.kind is TypeKind.VARCHAR):
            return None
    roles["l_returnflag"] = rf_e.name
    roles["l_linestatus"] = ls_e.name
    # counted columns must be kernel columns (proven NULL-free below)
    if any(c not in roles.values() for c in counted):
        return None
    # ---- resolve to scan source columns + stats guards ---------------
    out_to_src = dict(scan.columns)
    conn = catalog.connectors.get(scan.connector)
    if conn is None:
        return None
    try:
        dicts = conn.dictionaries(scan.table)
        schema = conn.schema(scan.table)
    except (KeyError, AttributeError):
        return None
    rename: dict[str, str] = {}
    for kname, aggname in roles.items():
        src = out_to_src.get(aggname)
        if src is None:
            return None
        rename[src] = kname
        stats = catalog.stats(scan.connector, scan.table, src)
        if stats is None or getattr(stats, "null_fraction", 1.0):
            return None  # NULL-freedom and bounds must be DECLARED
        if kname in _DOMAINS:
            iv = stats_physical_interval(stats, schema[src])
            lo, hi = _DOMAINS[kname]
            if iv is None or iv[0] < lo or iv[1] > hi:
                return None
        if kname == "l_shipdate":
            iv = stats_physical_interval(stats, schema[src])
            if iv is None or iv[0] < -(1 << 31) or iv[1] >= (1 << 31):
                return None  # the kernel compares shipdate as int32
    if len(rename) != 7:
        return None  # two roles share one source column: not Q1's shape
    d_rf = dicts.get(out_to_src[rf_e.name])
    d_ls = dicts.get(out_to_src[ls_e.name])
    if d_rf is None or d_ls is None or len(d_rf) != 3 or len(d_ls) != 2:
        return None  # gid = rf*2 + ls needs exactly the 3x2 domain
    return Q1Route(scan, rename, outputs, (rf_out, ls_out),
                   (rf_e.dtype, ls_e.dtype))


def execute_q1_route(route: Q1Route, catalog, aggs) -> Optional[list[Batch]]:
    """Run the matched fragment: stream scan splits through the fused
    step (Pallas on TPU when eligible, the generic one-pass einsum
    otherwise), combine states, decode the 6-group output batch.
    Returns None when ``value_overflow`` tripped (violated advisory
    stats) — the caller falls back to the generic operator route."""
    import jax.numpy as jnp

    from presto_tpu.cache.exec_cache import EXEC_CACHE, trace_probe
    from presto_tpu.runtime.faults import fault_point
    from presto_tpu.runtime.lifecycle import check_deadline
    from presto_tpu.runtime.metrics import REGISTRY
    from presto_tpu.workloads import combine_q1_states, q1_fused_step

    fault_point("aggregation")
    fault_point("step.agg")
    scan = route.scan
    conn = catalog.connector(scan.connector)
    src_cols = list(route.rename)
    splits = list(conn.splits(scan.table))
    if not splits:
        return None
    cap = batch_capacity(max(s.row_hint for s in splits))

    def _build(pallas_ok: bool):
        from presto_tpu.ops.pallas_agg import null_violation

        def step(batch: Batch):
            trace_probe()
            nulls = null_violation(batch)
            state = q1_fused_step(batch, pallas_ok=pallas_ok)
            state["value_overflow"] = state["value_overflow"] | nulls
            return state

        return jax.jit(step)

    fold = EXEC_CACHE.get_or_build(
        EXEC_CACHE.key_of("q1_route_fold"),
        lambda: jax.jit(combine_q1_states),
    )
    state = None
    step = None
    for split in splits:
        fault_point("scan")
        check_deadline("scan")
        b = conn.scan(split, src_cols, cap).rename(route.rename)
        if step is None:
            # hoisted Pallas decision on the first CONCRETE batch —
            # pallas_q1.supported's shared-mask identity check breaks
            # on tracers, so deciding inside the jitted step would
            # silently pin the route to the XLA twin on TPU
            from presto_tpu.ops import pallas_q1
            from presto_tpu.ops.strings import use_pallas

            pallas_ok = (use_pallas() and jax.default_backend() == "tpu"
                         and pallas_q1.supported(b)
                         and pallas_q1.probe_supported(cap))
            step = EXEC_CACHE.get_or_build(
                EXEC_CACHE.key_of("q1_route_step", pallas_ok,
                                  jax.default_backend()),
                lambda: _build(pallas_ok),
            )
        s = step(b)
        state = s if state is None else fold(state, s)
    if state is None or bool(state["value_overflow"]):
        REGISTRY.counter("exec.q1_route_fallback").add()
        return None
    REGISTRY.counter("exec.q1_fused_route").add()
    return [decode_q1_state(route, conn, aggs, state)]


def decode_q1_state(route: Q1Route, conn, aggs, state) -> Batch:
    """Decode a combined ``q1_fused_step`` [6]-group state into the
    Aggregate's output batch (shared by the local split loop above and
    the distributed leaf route's psum path)."""
    import jax.numpy as jnp

    from presto_tpu.batch import Column

    scan = route.scan
    G = 6
    dicts = conn.dictionaries(scan.table)
    out_to_src = dict(scan.columns)
    gid = jnp.arange(G, dtype=jnp.int32)
    present = state["present"]
    all_true = jnp.ones(G, jnp.bool_)
    rf_out, ls_out = route.key_names
    cols = {
        rf_out: Column(gid // 2, all_true, route.key_dtypes[0],
                       dicts.get(out_to_src[rf_out])),
        ls_out: Column(gid % 2, all_true, route.key_dtypes[1],
                       dicts.get(out_to_src[ls_out])),
    }
    for a in aggs:
        kkey = route.outputs[a.name]
        data = state[kkey]
        if kkey == "count_order":
            valid = all_true  # counts are 0, not NULL, for empty groups
        else:
            valid = present
            data = jnp.where(valid, data, 0)
        cols[a.name] = Column(data.astype(a.dtype.jnp_dtype), valid, a.dtype)
    return Batch(cols, present)
