"""Planned hybrid-spill tier: out-of-core joins/aggs as a PLAN choice.

Reference parity: hybrid hash join policy space ("Design Trade-offs for
a Robust Dynamic Hybrid Hash Join"): keep the K hottest build
partitions device-resident, stream the cold ones, and adapt partition
counts to the real memory budget instead of discovering it by crashing.
Before this tier, larger-than-HBM execution was an ERROR path — a
backend OOM walked the degradation ladder (exec/ladder.py), paying a
failed compile + OOM round trip per rung. Here the byte budget
(`runtime/memory.node_row_bytes` widths x stats rows) picks
``resident | hybrid | grouped`` at plan time, so a 4x-over-budget build
runs with ZERO ladder rungs.

Three pieces, shared by both executors:

- :func:`plan_spill` — the decision function. ``hybrid`` keeps K
  resident buckets (hot-first when exchange-skew history names a hot
  partition for this plan fingerprint) and streams the rest; the
  resident share of the budget SHRINKS with the OOM-ladder rung, so
  rung 1 is a cheap re-bucket into a smaller resident set, not a jump
  to fully-grouped.
- :func:`transfer_iter` — a TWO-slot double-buffered host->device
  transfer pipeline (generalizing ``exec/pipeline.prefetch_iter``'s
  one-slot loop): bucket k+1 (and k+2) transfer on worker threads
  while the device joins bucket k. Transfer timings are re-recorded on
  the driver's trace recorder (``trace.add_complete``) so the overlap
  is visible in exported traces.
- :func:`expand_units` — bounded-depth recursive re-partitioning for
  cold buckets that STILL exceed the budget (skew): bucket ``b`` under
  modulus ``N`` splits exactly into residues ``{b, b+N}`` under ``2N``
  (``ops/hashing.partition_ids`` is ``hash % N``), each split is loud
  (``spill.partition_overflow`` + the ``step.spill_partition`` fault
  site), and depth caps at :data:`MAX_SPILL_RECURSION` with a typed
  failure — a bucket that cannot be split is one key's duplicates, not
  a partitioning problem.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Sequence

import numpy as np

#: recursion bound on cold-partition re-splitting: 4 doublings = 16x
#: the planned per-bucket size absorbed before the typed refusal
MAX_SPILL_RECURSION = 4

#: above this est/budget ratio hybrid keeps nothing resident — the
#: resident set would be a rounding error of the relation
HYBRID_MAX_RATIO = 64

#: partition-count ceiling (matches the ladder's grouped cap)
MAX_BUCKETS = 1 << 12


@dataclasses.dataclass(frozen=True)
class SpillDecision:
    """The plan-time out-of-core choice for one join build / agg state.

    ``resident`` lists the bucket ids kept device-resident (hot-first);
    ``resident_budget`` is the byte share reserved for them — both
    advisory until :func:`fit_resident` clamps against ACTUAL bucket
    sizes after partitioning."""

    mode: str  # "resident" | "hybrid" | "grouped"
    nbuckets: int = 1
    resident: tuple = ()
    est_bytes: int = 0
    budget: int = 0
    resident_budget: int = 0

    def explain(self) -> str:
        """The EXPLAIN detail: ``hybrid(2/8 resident)``."""
        if self.mode == "hybrid":
            return f"hybrid({len(self.resident)}/{self.nbuckets} resident)"
        if self.mode == "grouped":
            return f"grouped({self.nbuckets} buckets)"
        return "resident"


def _resident_ids(nbuckets: int, k: int, hot) -> tuple:
    """First-K bucket ids with the skew-history hot partition (when a
    recurring fingerprint recorded one) promoted to the front."""
    order = list(range(nbuckets))
    if hot is not None:
        h = int(hot) % nbuckets
        order.remove(h)
        order.insert(0, h)
    return tuple(order[:k])


def plan_spill(est_bytes: int, budget: int, hot_partition=None,
               oom_rung: int = 0) -> SpillDecision:
    """resident | hybrid | grouped for an estimated build/state size.

    Buckets are sized to ~half the budget each (so a streamed bucket
    plus the in-flight transfer slots fit beside the resident set) and
    double per ladder rung; the resident share is half the budget at
    rung 0 and HALVES per rung — rung 1 re-plans into hybrid with a
    shrunk resident set instead of jumping to fully-grouped. A rung>0
    re-plan with an under-budget estimate means the stats lied: the
    build is treated as at least 2x budget so the re-bucket is real.
    """
    budget = max(int(budget), 1)
    est = max(int(est_bytes), 0)
    if est <= budget and oom_rung == 0:
        return SpillDecision("resident", 1, (), est, budget, budget)
    est = max(est, 2 * budget)
    ratio = -(-est // budget)
    nbuckets = min(max(2, 2 * ratio) << oom_rung, MAX_BUCKETS)
    per_bucket = max(est // nbuckets, 1)
    resident_budget = budget >> (1 + oom_rung)
    k = min(resident_budget // per_bucket, nbuckets - 1)
    if oom_rung >= 3 or ratio > HYBRID_MAX_RATIO or k < 1:
        return SpillDecision("grouped", nbuckets, (), est, budget, 0)
    return SpillDecision(
        "hybrid", nbuckets, _resident_ids(nbuckets, k, hot_partition),
        est, budget, resident_budget,
    )


def fit_resident(decision: SpillDecision, bucket_rows: Callable[[int], int],
                 row_bytes: int) -> tuple[tuple, int]:
    """Clamp the planned resident set against ACTUAL partition sizes:
    residents stay resident only while their cumulative bytes fit the
    resident share of the budget (hot-first order preserved); oversized
    ones demote to the streamed tier instead of blowing the device.
    Returns ``(resident_ids, resident_bytes)``."""
    out: list[int] = []
    acc = 0
    cap = max(decision.resident_budget, 1)
    for b in decision.resident:
        nb = bucket_rows(b) * row_bytes
        if acc + nb > cap and acc > 0:
            continue
        if nb > cap:
            continue
        acc += nb
        out.append(b)
    return tuple(out), acc


# ---------------------------------------------------------------------------
# Two-slot double-buffered transfer pipeline
# ---------------------------------------------------------------------------


def transfer_iter(load, items: Sequence, label: str = "spill:transfer"):
    """Yield ``(item, load(item))`` with TWO transfers in flight.

    The device-transfer generalization of ``pipeline.prefetch_iter``:
    two worker slots keep a transfer running while the driver holds one
    loaded bucket and the device computes — transfer k+2 overlaps the
    compute of bucket k. Each worker call is timed and re-recorded on
    the DRIVER's trace recorder as a complete span (ContextVars don't
    cross the pool threads), so exported traces show the overlap.

    The ``step.spill_transfer`` fault site fires on the driver thread
    before each submit — a mid-spill backend OOM propagates exactly
    like a compute-site OOM (typed, ladder-eligible), with no worker
    thread holding a half-transferred bucket. Each submit slot is also
    a cancel/deadline checkpoint (``runtime/overload.CancelScope``): a
    cancelled spilling query stops transferring within one bucket and
    its host-spill reservation releases through the ordinary unwind.
    """
    from presto_tpu.exec.pipeline import prefetch_enabled
    from presto_tpu.runtime import trace
    from presto_tpu.runtime.faults import fault_point
    from presto_tpu.runtime.lifecycle import check_deadline

    items = list(items)
    if len(items) <= 1 or not prefetch_enabled():
        for it in items:
            check_deadline("spill-transfer")
            fault_point("step.spill_transfer")
            t0 = time.perf_counter()
            out = load(it)
            trace.add_complete(label, "step", t0,
                               time.perf_counter() - t0, {"slot": "serial"})
            yield it, out
        return

    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    def timed(it):
        t0 = time.perf_counter()
        out = load(it)
        return t0, time.perf_counter() - t0, out

    with ThreadPoolExecutor(max_workers=2) as ex:
        pending: deque = deque()
        idx = 0
        while idx < len(items) and len(pending) < 2:
            check_deadline("spill-transfer")
            fault_point("step.spill_transfer")
            pending.append((items[idx], ex.submit(timed, items[idx])))
            idx += 1
        while pending:
            it, fut = pending.popleft()
            t0, dur, out = fut.result()
            trace.add_complete(label, "step", t0, dur, {"slot": "worker"})
            if idx < len(items):
                check_deadline("spill-transfer")
                fault_point("step.spill_transfer")
                pending.append((items[idx], ex.submit(timed, items[idx])))
                idx += 1
            yield it, out


# ---------------------------------------------------------------------------
# Bounded recursive re-partitioning (cold-partition overflow)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SpillUnit:
    """One streamed unit of work: bucket ``bucket`` of the build (and
    optionally probe) spill, restricted to hash residue ``residue``
    under ``modulus`` (depth 0: the whole planned bucket)."""

    build: "HostSpill"  # noqa: F821 — exec/grouped.HostSpill
    probe: "Optional[HostSpill]"  # noqa: F821
    bucket: int
    modulus: int
    residue: int
    depth: int = 0


def _split_side(spill, bucket: int, ids_for, residue: int, modulus: int,
                make_spill):
    """Re-hash one side's bucket under the doubled modulus into two
    child stores (residues ``residue`` and ``residue + modulus``).
    ``hash % N == b`` implies ``hash % 2N in {b, b+N}``, so the split
    is exact and loses no rows."""
    lo, hi = make_spill(), make_spill()
    for chunk in spill.chunks[bucket]:
        batch = spill._to_batch([chunk], None)
        ids = np.asarray(ids_for(batch, 2 * modulus))
        lo.append(batch, np.where(ids == residue, 0, -1))
        hi.append(batch, np.where(ids == residue + modulus, 0, -1))
    return lo, hi


def split_unit(unit: SpillUnit, build_ids, probe_ids, make_spill):
    """Split one oversized unit into its two children (both sides split
    under the SAME doubled modulus, so probe rows stay with exactly the
    build rows they could match — outer/anti null-extension decisions
    remain per-unit-correct)."""
    blo, bhi = _split_side(unit.build, unit.bucket, build_ids,
                           unit.residue, unit.modulus, make_spill)
    plo = phi = None
    if unit.probe is not None:
        plo, phi = _split_side(unit.probe, unit.bucket, probe_ids,
                               unit.residue, unit.modulus, make_spill)
    m2 = unit.modulus * 2
    return (
        SpillUnit(blo, plo, 0, m2, unit.residue, unit.depth + 1),
        SpillUnit(bhi, phi, 0, m2, unit.residue + unit.modulus,
                  unit.depth + 1),
    )


def expand_units(build_spill, probe_spill, buckets: Sequence[int],
                 unit_budget: int, row_bytes: int, build_ids,
                 probe_ids=None, make_spill=None) -> list[SpillUnit]:
    """The streamed work list for the cold buckets, recursively
    splitting any whose build rows exceed ``unit_budget`` bytes.

    ``build_ids(batch, modulus) -> ids`` recomputes bucket ids at a
    doubled modulus (the same hash the original partitioning used).
    Every split fires the ``step.spill_partition`` fault site and the
    ``spill.partition_overflow`` counter; depth > MAX_SPILL_RECURSION
    raises the typed ``SpillPartitionOverflow`` — loud, never a silent
    device blowup."""
    from presto_tpu.runtime.errors import SpillPartitionOverflow
    from presto_tpu.runtime.faults import fault_point
    from presto_tpu.runtime.metrics import REGISTRY

    if make_spill is None:
        from presto_tpu.exec.grouped import HostSpill

        make_spill = lambda: HostSpill(1)  # noqa: E731
    row_bytes = max(int(row_bytes), 1)
    out: list[SpillUnit] = []
    stack = [
        SpillUnit(build_spill, probe_spill, b, build_spill.nbuckets, b, 0)
        for b in reversed(list(buckets))
    ]
    while stack:
        u = stack.pop()
        rows = u.build.bucket_rows(u.bucket)
        if rows * row_bytes <= unit_budget or rows <= 16:
            out.append(u)
            continue
        if u.depth >= MAX_SPILL_RECURSION:
            raise SpillPartitionOverflow(
                f"spill partition (residue {u.residue} mod {u.modulus}) "
                f"still holds ~{rows * row_bytes} bytes over the "
                f"{unit_budget}-byte unit budget after "
                f"{MAX_SPILL_RECURSION} recursive splits — one key's "
                "duplicate run cannot be partitioned further"
            )
        fault_point("step.spill_partition")
        REGISTRY.counter("spill.partition_overflow").add()
        lo, hi = split_unit(u, build_ids, probe_ids, make_spill)
        u.build.release_bucket(u.bucket)
        if u.probe is not None:
            u.probe.release_bucket(u.bucket)
        stack.append(hi)
        stack.append(lo)
    return out
