"""Row expression IR + vectorized evaluator.

Reference parity: ``com.facebook.presto.spi.relation.RowExpression``
(``CallExpression``, ``ConstantExpression``, ``InputReferenceExpression``,
``SpecialFormExpression``) and ``sql.gen.PageFunctionCompiler`` /
``ExpressionCompiler`` which bytecode-compile them per query
[SURVEY §2.1; reference tree unavailable, paths reconstructed].

TPU-first replacement: expressions are a tiny immutable IR evaluated by
tracing over ``Batch`` columns — ``jax.jit`` of the enclosing operator
chain *is* the per-query compiler. Two idioms matter:

- **Null semantics without branches**: every evaluation returns
  ``Val(data, valid)``; functions combine validity masks (Kleene logic
  for AND/OR) so NULL handling is branch-free vector math.
- **String predicates via the dictionary**: LIKE / substr / prefix tests
  on dictionary-encoded columns are computed once on the (small) host
  dictionary into a lookup table, then applied on-device as a gather by
  code — a scan over *distinct values*, not rows. Raw ``BYTES`` columns
  fall back to device byte-tensor kernels (Pallas for the hot ones).
"""

from __future__ import annotations

from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column, Dictionary
from presto_tpu.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    TIMESTAMP,
    DataType,
    TypeKind,
    common_super_type,
    decimal,
)

# ---------------------------------------------------------------------------
# IR nodes
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Expr:
    dtype: DataType

    def __and__(self, other: "Expr") -> "Expr":
        return Call(BOOLEAN, "and", (self, other))

    def __or__(self, other: "Expr") -> "Expr":
        return Call(BOOLEAN, "or", (self, other))


@dataclass(frozen=True)
class InputRef(Expr):
    """Reference to a named column of the input batch."""

    name: str = ""

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class Literal(Expr):
    """A constant. ``value`` is the *logical* Python value."""

    value: Any = None

    def __str__(self) -> str:
        return repr(self.value)


@dataclass(frozen=True)
class Call(Expr):
    """Function call (covers operators, special forms, casts)."""

    fn: str = ""
    args: tuple[Expr, ...] = ()

    def __str__(self) -> str:
        return f"{self.fn}({', '.join(map(str, self.args))})"


@dataclass(frozen=True)
class Param(Expr):
    """A typed literal slot (plan-template parameterization): the VALUE
    lives outside the expression tree and arrives at evaluation time
    through the ambient parameter scope (:func:`param_scope`). Two
    queries differing only in literals share one Param-bearing plan
    *template*, so every content-keyed cache (compiled executables,
    jit signatures) hits across the differing constants. Hashes by
    (slot, dtype) — never by value — which is exactly what makes the
    template the cache identity."""

    slot: int = 0

    def __str__(self) -> str:
        return f"?{self.slot}"


#: the ambient parameter-slot values. Two nesting levels cooperate:
#: executors install the CONCRETE device scalars for the whole plan run
#: (eager evaluation sites — sort keys, runtime min/max probes, spill
#: bucketing — read them directly), and every traced step body shadows
#: them with its own TRACED params argument for the duration of the
#: trace, so compiled programs close over tracers, never over one
#: binding's constants (which a jit signature-cache hit would silently
#: replay for the next binding).
_PARAM_VALUES: ContextVar[Optional[tuple]] = ContextVar(
    "presto_tpu_param_values", default=None
)


@contextmanager
def param_scope(values):
    """Install parameter-slot values for evaluate() (see _PARAM_VALUES)."""
    token = _PARAM_VALUES.set(tuple(values) if values is not None else None)
    try:
        yield
    finally:
        _PARAM_VALUES.reset(token)


@dataclass(frozen=True)
class Unbound(Expr):
    """A runtime-scalar slot (uncorrelated scalar subquery result).
    The executor substitutes a Literal before compiling the consuming
    pipeline; evaluating an Unbound directly is an error."""

    name: str = ""

    def __str__(self) -> str:
        return f"?{self.name}"


def bind_scalars(e: Expr, values: dict[str, Any]) -> Expr:
    """Replace Unbound slots with Literals (executor-side)."""
    if isinstance(e, Unbound):
        if e.name not in values:
            raise KeyError(f"unbound scalar {e.name}")
        return Literal(e.dtype, values[e.name])
    if isinstance(e, Call):
        return Call(e.dtype, e.fn, tuple(bind_scalars(a, values) for a in e.args))
    return e


def col(name: str, dtype: DataType) -> InputRef:
    return InputRef(dtype, name)


def lit(value: Any, dtype: DataType) -> Literal:
    return Literal(dtype, value)


# ---------------------------------------------------------------------------
# Evaluation values
# ---------------------------------------------------------------------------


@dataclass
class Val:
    """An evaluated vector: device data + validity + metadata."""

    data: Any
    valid: Any
    dtype: DataType
    dictionary: Dictionary | None = None


def _all_valid(template) -> Any:
    return jnp.ones(template.shape[0], dtype=jnp.bool_)


# ---------------------------------------------------------------------------
# Scalar function registry
# ---------------------------------------------------------------------------
# impl(args: list[Val], out_type) -> (data, valid_override|None)
# type_rule(arg_types) -> DataType

_REGISTRY: dict[str, tuple[Callable, Callable]] = {}


def register(name: str, type_rule: Callable):
    def deco(impl):
        _REGISTRY[name] = (impl, type_rule)
        return impl

    return deco


def result_type(fn: str, arg_types: Sequence[DataType]) -> DataType:
    if fn not in _REGISTRY:
        raise KeyError(f"unknown function {fn!r}")
    return _REGISTRY[fn][1](list(arg_types))


# ---- type rules -----------------------------------------------------------


def _t_bool(_):
    return BOOLEAN


def _t_same(args):
    t = args[0]
    for u in args[1:]:
        t = common_super_type(t, u)
    return t


def _t_add(args):
    a, b = args
    # DATE +/- integer days -> DATE (TPC-DS `d_date + 5` interval
    # arithmetic; dates are physically days-since-epoch)
    if a.kind is TypeKind.DATE and b.kind in (TypeKind.INTEGER, TypeKind.BIGINT):
        return a
    if b.kind is TypeKind.DATE and a.kind in (TypeKind.INTEGER, TypeKind.BIGINT):
        return b
    return _t_same(args)


def _t_mul(args):
    a, b = args
    if a.kind is TypeKind.DECIMAL or b.kind is TypeKind.DECIMAL:
        sa = a.scale if a.kind is TypeKind.DECIMAL else 0
        sb = b.scale if b.kind is TypeKind.DECIMAL else 0
        if a.kind is TypeKind.DOUBLE or b.kind is TypeKind.DOUBLE:
            return DOUBLE
        # Engine-defined: product scale capped at 4 (documented divergence
        # from ANSI sa+sb; keeps SF1000 64-bit sums exact — see SURVEY §7.4).
        return decimal(38, min(sa + sb, 4))
    return _t_same(args)


def _t_div(args):
    a, b = args
    if a.kind is TypeKind.DECIMAL or b.kind is TypeKind.DECIMAL:
        return DOUBLE
    if a.kind is TypeKind.DOUBLE or b.kind is TypeKind.DOUBLE:
        return DOUBLE
    return DOUBLE


def _t_first(args):
    return args[0]


def _t_double(_):
    return DOUBLE


def _t_int(_):
    return INTEGER


def _t_bigint(_):
    return BIGINT


# ---- numeric helpers ------------------------------------------------------


def _round_half_away(d, f):
    """Divide int64 ``d`` by positive ``f`` rounding half away from zero.

    jnp ``//`` floors (unlike C truncation), so negatives need their own
    branch: |d| is rounded, then the sign is reapplied.
    """
    a = jnp.abs(d)
    q = (a + f // 2) // f
    return jnp.where(d >= 0, q, -q)


def _to_physical(v: Val, target: DataType):
    """Rescale/convert v.data to target's physical representation."""
    src = v.dtype
    data = v.data
    if src == target:
        return data
    if target.kind is TypeKind.DOUBLE:
        if src.kind is TypeKind.DECIMAL:
            return data.astype(jnp.float32) / np.float32(10**src.scale)
        return data.astype(jnp.float32)
    if target.kind is TypeKind.DECIMAL:
        if src.kind is TypeKind.DECIMAL:
            if src.scale == target.scale:
                return data.astype(jnp.int64)
            if src.scale < target.scale:
                return data.astype(jnp.int64) * np.int64(10 ** (target.scale - src.scale))
            f = np.int64(10 ** (src.scale - target.scale))
            return _round_half_away(data.astype(jnp.int64), f)
        return data.astype(jnp.int64) * np.int64(10**target.scale)
    if target.kind is TypeKind.TIMESTAMP:
        if src.kind is TypeKind.DATE:
            return data.astype(jnp.int64) * np.int64(86_400_000_000)
        return data.astype(jnp.int64)
    if target.kind in (TypeKind.BIGINT, TypeKind.INTEGER, TypeKind.DATE):
        return data.astype(target.jnp_dtype)
    if target.kind is TypeKind.BOOLEAN:
        return data.astype(jnp.bool_)
    if (target.kind is TypeKind.BYTES and src.kind is TypeKind.BYTES
            and src.width == target.width):
        return data
    if target.kind is TypeKind.VARCHAR and src.kind is TypeKind.VARCHAR:
        # dictionary codes pass through regardless of physical width
        # (narrowed int8/int16 codes promote wherever they mix with
        # canonical int32 ones; code spaces are the caller's concern)
        return data
    raise TypeError(f"cannot convert {src} -> {target}")


def _binary_numeric(op):
    def impl(args: list[Val], out: DataType):
        a, b = args
        if out.kind is TypeKind.DECIMAL:
            x = _to_physical(a, decimal(38, out.scale))
            y = _to_physical(b, decimal(38, out.scale))
        else:
            x = _to_physical(a, out)
            y = _to_physical(b, out)
        return op(x, y), None

    return impl


def _mul_impl(args: list[Val], out: DataType):
    a, b = args
    if out.kind is TypeKind.DECIMAL:
        sa = a.dtype.scale if a.dtype.kind is TypeKind.DECIMAL else 0
        sb = b.dtype.scale if b.dtype.kind is TypeKind.DECIMAL else 0
        x = a.data.astype(jnp.int64) if a.dtype.kind is TypeKind.DECIMAL else _to_physical(a, decimal(38, 0))
        y = b.data.astype(jnp.int64) if b.dtype.kind is TypeKind.DECIMAL else _to_physical(b, decimal(38, 0))
        prod = x * y  # scale sa+sb
        excess = sa + sb - out.scale
        if excess > 0:
            prod = _round_half_away(prod, np.int64(10**excess))
        return prod, None
    x = _to_physical(a, out)
    y = _to_physical(b, out)
    return x * y, None


def _div_impl(args: list[Val], out: DataType):
    a, b = args
    x = _to_physical(a, DOUBLE)
    y = _to_physical(b, DOUBLE)
    bad = y == 0
    res = x / jnp.where(bad, jnp.float32(1), y)
    return res, ~bad & a.valid & b.valid


register("add", _t_add)(_binary_numeric(lambda x, y: x + y))
register("sub", _t_add)(_binary_numeric(lambda x, y: x - y))
register("mul", _t_mul)(_mul_impl)
register("div", _t_div)(_div_impl)


@register("mod", _t_same)
def _mod_impl(args, out):
    x = _to_physical(args[0], out)
    y = _to_physical(args[1], out)
    bad = y == 0
    return jnp.where(bad, 0, x % jnp.where(bad, 1, y)), ~bad & args[0].valid & args[1].valid


@register("neg", _t_first)
def _neg(args, out):
    return -args[0].data, None


@register("upper", _t_first)
def _upper(args, out):
    a = args[0]
    if a.dtype.kind is not TypeKind.BYTES and a.dictionary is not None:
        data, nd = _dict_value_transform(a, "upper", str.upper)
        return data, None, nd
    d = a.data  # [rows, width] uint8 (BYTES)
    return jnp.where((d >= 97) & (d <= 122), d - 32, d), None


@register("lower", _t_first)
def _lower(args, out):
    a = args[0]
    if a.dtype.kind is not TypeKind.BYTES and a.dictionary is not None:
        data, nd = _dict_value_transform(a, "lower", str.lower)
        return data, None, nd
    d = a.data
    return jnp.where((d >= 65) & (d <= 90), d + 32, d), None


@register("concat", _t_first)
def _concat(args, out):
    """BYTES/string-literal concatenation (SQL ``||``): output width is
    the sum of part widths (analyzer-computed); literals broadcast."""
    cap = next(a.data.shape[0] for a in args if not isinstance(a.data, str))
    parts = []
    for a in args:
        if isinstance(a.data, str):
            arr = np.frombuffer(a.data.encode(), np.uint8)
            parts.append(jnp.broadcast_to(jnp.asarray(arr), (cap, len(arr))))
        else:
            # CHAR semantics: each part occupies its full declared
            # width space-padded (zero tails become spaces)
            parts.append(_pad_space(a.data))
    return jnp.concatenate(parts, axis=1), None


def _t_dict_bytes(args):
    raise NotImplementedError(
        "dict_bytes width is planner-assigned (construct the Call with "
        "an explicit fixed_bytes dtype)"
    )


@register("dict_bytes", _t_dict_bytes)
def _dict_bytes(args, out):
    """Dictionary-encoded VARCHAR -> fixed-width BYTES: materialize
    codes through the dictionary's decode table. The join planner uses
    this to compare keys from DIFFERENT dictionaries by value (codes
    are only comparable within one dictionary; cross-dictionary code
    joins would be silently wrong)."""
    a = args[0]
    if a.dictionary is None:
        raise NotImplementedError("dict_bytes on dictionary-less VARCHAR")
    mat = jnp.asarray(a.dictionary.bytes_matrix(out.width))
    codes = jnp.clip(a.data.astype(jnp.int32), 0, len(a.dictionary) - 1)
    return mat[codes], None


@register("bytes_pack", lambda args: BIGINT)
def _bytes_pack(args, out):
    """BYTES(w<=7) -> exact big-endian int64 (order-preserving,
    non-negative, < 2^56): narrow string join/group keys become plain
    integer keys for the sorted kernels. Padding is normalized to
    spaces first so packs agree with PAD SPACE comparison semantics
    (a space-padded concat result equals zero-padded storage)."""
    d = _pad_space(args[0].data).astype(jnp.int64)
    h = jnp.zeros(d.shape[0], jnp.int64)
    for i in range(d.shape[1]):
        h = h * 256 + d[:, i]
    return h, None


def _fnv63_fold(columns):
    """Order-sensitive FNV fold of int64 column vectors into [0, 2^63),
    never yielding the int64-max lookup sentinel (a hash landing there
    would silently drop the row from the sorted lookup source). The ONE
    definition of the join-hash contract — bytes_hash and hash63_mix
    must agree on mask and sentinel scheme."""
    h = columns[0].astype(jnp.int64)
    for c in columns[1:]:
        h = h * jnp.int64(1099511628211) + c.astype(jnp.int64)
    h = h & jnp.int64((1 << 63) - 1)
    sentinel = jnp.int64(np.iinfo(np.int64).max)
    return jnp.where(h == sentinel, 0, h)


@register("bytes_hash", lambda args: BIGINT)
def _bytes_hash(args, out):
    """BYTES(w>7) -> 63-bit polynomial hash (FNV fold). NOT injective:
    callers must verify candidate matches on the original bytes
    (LookupJoinOperator ``verify`` pairs). Hashes over space-normalized
    padding (PAD SPACE, like _bytes_pack)."""
    d = _pad_space(args[0].data).astype(jnp.int64)
    cols = [jnp.zeros(d.shape[0], jnp.int64)] + [
        d[:, i] for i in range(d.shape[1])]
    return _fnv63_fold(cols), None


@register("hash63_mix", lambda args: BIGINT)
def _hash63_mix(args, out):
    """Order-sensitive 63-bit FNV mix of N integer key columns — the
    multi-key join fallback when bit-packed widths exceed 63 (e.g. a
    string-hash component is itself 63 bits). NOT injective: callers
    must verify candidates on the original key pairs. Handles negative
    components (the mask maps any int64 into [0, 2^63))."""
    return _fnv63_fold([a.data for a in args]), None


# ---- comparisons ----------------------------------------------------------


def _pad_space(d):
    """SQL CHAR PAD SPACE comparison semantics: the zero padding behind
    fixed-width values compares as spaces, so 'after' (zero-padded)
    equals 'after      ' (space-then-zero-padded) and ordering matches
    space-extended collation. Data never contains real NULs."""
    return jnp.where(d == 0, jnp.uint8(32), d)


def _bytes_sign(a: Val, b: Val):
    """3-way lexicographic compare involving a BYTES side: returns an
    int32 sign array; comparisons test it against 0."""
    from presto_tpu.ops import strings as ops_strings

    if a.dtype.kind is TypeKind.BYTES and isinstance(b.data, str):
        lit = ops_strings.pad_literal(b.data, a.data.shape[1])
        return ops_strings.bytes_compare(
            _pad_space(a.data),
            jnp.broadcast_to(_pad_space(jnp.asarray(lit)), a.data.shape),
        )
    if b.dtype.kind is TypeKind.BYTES and isinstance(a.data, str):
        lit = ops_strings.pad_literal(a.data, b.data.shape[1])
        return -ops_strings.bytes_compare(
            _pad_space(b.data),
            jnp.broadcast_to(_pad_space(jnp.asarray(lit)), b.data.shape),
        )
    if a.dtype.kind is TypeKind.BYTES and b.dtype.kind is TypeKind.BYTES:
        from presto_tpu.ops.strings import bytes_compare

        w = max(a.data.shape[1], b.data.shape[1])

        def widen(d):
            if d.shape[1] == w:
                return d
            pad = jnp.zeros((d.shape[0], w - d.shape[1]), d.dtype)
            return jnp.concatenate([d, pad], axis=1)

        return bytes_compare(_pad_space(widen(a.data)), _pad_space(widen(b.data)))
    raise TypeError("not a BYTES comparison")


def _is_bytes_cmp(a: Val, b: Val) -> bool:
    return a.dtype.kind is TypeKind.BYTES or b.dtype.kind is TypeKind.BYTES


def _cmp_physicals(a: Val, b: Val):
    """Bring two comparable Vals to a common physical domain."""
    ta, tb = a.dtype, b.dtype
    if ta.kind is TypeKind.VARCHAR or tb.kind is TypeKind.VARCHAR:
        # codes compare lexicographically within ONE ordered dictionary;
        # literals are encoded against the column's dictionary upstream.
        if (
            a.dictionary is not None
            and b.dictionary is not None
            and a.dictionary is not b.dictionary
        ):
            raise ValueError(
                "comparing VARCHAR columns from different dictionaries; "
                "re-encode to a shared dictionary first"
            )
        return a.data, b.data
    t = common_super_type(ta, tb) if ta != tb else ta
    if t.kind is TypeKind.DECIMAL:
        s = max(ta.scale if ta.kind is TypeKind.DECIMAL else 0,
                tb.scale if tb.kind is TypeKind.DECIMAL else 0)
        t = decimal(38, s)
    return _to_physical(a, t), _to_physical(b, t)


def _cmp(op):
    def impl(args: list[Val], out: DataType):
        if _is_bytes_cmp(args[0], args[1]):
            sign = _bytes_sign(args[0], args[1])
            return op(sign, jnp.zeros_like(sign)), None
        x, y = _cmp_physicals(args[0], args[1])
        return op(x, y), None

    return impl


register("eq", _t_bool)(_cmp(lambda x, y: x == y))
register("ne", _t_bool)(_cmp(lambda x, y: x != y))
register("lt", _t_bool)(_cmp(lambda x, y: x < y))
register("le", _t_bool)(_cmp(lambda x, y: x <= y))
register("gt", _t_bool)(_cmp(lambda x, y: x > y))
register("ge", _t_bool)(_cmp(lambda x, y: x >= y))


@register("between", _t_bool)
def _between(args, out):
    lo = _cmp(lambda x, y: x >= y)([args[0], args[1]], out)[0]
    hi = _cmp(lambda x, y: x <= y)([args[0], args[2]], out)[0]
    return lo & hi, None


# ---- boolean special forms (Kleene) --------------------------------------


@register("and", _t_bool)
def _and(args, out):
    a, b = args
    # Kleene: FALSE dominates NULL; data is "definitely true"
    true_a = a.valid & a.data
    true_b = b.valid & b.data
    false_a = a.valid & ~a.data
    false_b = b.valid & ~b.data
    valid = (a.valid & b.valid) | false_a | false_b
    return true_a & true_b, valid


@register("or", _t_bool)
def _or(args, out):
    a, b = args
    true_a = a.valid & a.data
    true_b = b.valid & b.data
    data = true_a | true_b
    valid = (a.valid & b.valid) | true_a | true_b
    return data, valid


@register("not", _t_bool)
def _not(args, out):
    return ~args[0].data, None


@register("is_null", _t_bool)
def _is_null(args, out):
    return ~args[0].valid, _all_valid(args[0].valid)


@register("is_not_null", _t_bool)
def _is_not_null(args, out):
    return args[0].valid, _all_valid(args[0].valid)


@register("abs", _t_same)
def _abs(args, out):
    return jnp.abs(_to_physical(args[0], out)), None


@register("sqrt", _t_double)
def _sqrt(args, out):
    x = _to_physical(args[0], out)
    bad = x < 0
    return jnp.sqrt(jnp.where(bad, 0.0, x)), ~bad & args[0].valid


@register("floor", _t_double)
def _floor(args, out):
    return jnp.floor(_to_physical(args[0], out)), None


@register("ceil", _t_double)
def _ceil(args, out):
    return jnp.ceil(_to_physical(args[0], out)), None


@register("round", _t_double)
def _round(args, out):
    """SQL ROUND: half away from zero (jnp.round is half-even)."""
    x = _to_physical(args[0], out)
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5), None


def _bytes_literal_matrix(s: str, width: int, cap: int):
    """A VARCHAR literal as a broadcast [cap, width] BYTES matrix
    (space-padded/truncated to the fixed width)."""
    raw = s.encode()[:width].ljust(width, b" ")
    return jnp.broadcast_to(jnp.asarray(np.frombuffer(raw, np.uint8)), (cap, width))


@register("coalesce", _t_same)
def _coalesce(args, out):
    if out.kind is TypeKind.BYTES:
        cap = next(a.data.shape[0] for a in args if not isinstance(a.data, str))
        args = [
            Val(_bytes_literal_matrix(a.data, out.width, cap),
                jnp.ones(cap, dtype=jnp.bool_), out)
            if isinstance(a.data, str) else a
            for a in args
        ]
    data = _to_physical(args[-1], out)
    valid = args[-1].valid
    for v in reversed(args[:-1]):
        d = _to_physical(v, out)
        data = jnp.where(v.valid[:, None] if data.ndim > 1 else v.valid, d, data)
        valid = v.valid | valid
    return data, valid


@register("if", lambda args: _t_same(args[1:]))
def _if(args, out):
    c, t, f = args
    cond = c.data & c.valid
    data = jnp.where(cond, _to_physical(t, out), _to_physical(f, out))
    valid = jnp.where(cond, t.valid, f.valid)
    return data, valid


def _t_case(args):
    return _t_same([args[i] for i in range(1, len(args), 2)] + ([args[-1]] if len(args) % 2 else []))


@register("case", _t_case)
def _case(args, out):
    """case(when1, then1, when2, then2, ..., [else])."""
    pairs = list(zip(args[0::2], args[1::2]))
    has_else = len(args) % 2 == 1
    if has_else:
        data = _to_physical(args[-1], out)
        valid = args[-1].valid
    else:
        data = jnp.zeros_like(_to_physical(pairs[0][1], out))
        valid = jnp.zeros_like(pairs[0][0].valid)
    for c, t in reversed(pairs):
        cond = c.data & c.valid
        data = jnp.where(cond, _to_physical(t, out), data)
        valid = jnp.where(cond, t.valid, valid)
    return data, valid


@register("in", _t_bool)
def _in(args, out):
    """in(needle, v1, v2, ...) — small literal lists."""
    needle = args[0]
    hit = None
    for v in args[1:]:
        if _is_bytes_cmp(needle, v):
            h = _bytes_sign(needle, v) == 0
        else:
            x, y = _cmp_physicals(needle, v)
            h = x == y
        hit = h if hit is None else (hit | h)
    return hit, needle.valid if needle.valid is not None else None


# ---- dates ----------------------------------------------------------------


def civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day); branch-free int32 math.

    Standard civil-calendar algorithm (Hinnant), adapted to floor
    division (jnp ``//`` floors, so no negative-era correction is
    needed); vectorizes onto the VPU.
    """
    z = days.astype(jnp.int32) + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


_MICROS_PER_DAY = np.int64(86_400_000_000)


def _days_of(v: Val):
    """Days-since-epoch view of a DATE or TIMESTAMP Val (micros floor
    to days, correct for pre-epoch instants)."""
    if v.dtype.kind is TypeKind.TIMESTAMP:
        return (v.data.astype(jnp.int64) // _MICROS_PER_DAY).astype(jnp.int32)
    return v.data


def _time_of_day_us(v: Val):
    return v.data.astype(jnp.int64) % _MICROS_PER_DAY


@register("year", _t_int)
def _year(args, out):
    y, _, _ = civil_from_days(_days_of(args[0]))
    return y, None


@register("hour", _t_int)
def _hour(args, out):
    return (_time_of_day_us(args[0]) // 3_600_000_000).astype(jnp.int32), None


@register("minute", _t_int)
def _minute(args, out):
    return ((_time_of_day_us(args[0]) // 60_000_000) % 60).astype(jnp.int32), None


@register("second", _t_int)
def _second(args, out):
    return ((_time_of_day_us(args[0]) // 1_000_000) % 60).astype(jnp.int32), None


@register("cast_timestamp", lambda args: TIMESTAMP)
def _cast_timestamp(args, out):
    return _to_physical(args[0], out), None


def parse_timestamp_fn() -> str:
    """cast(varchar AS timestamp) over a dictionary column (host parse;
    ISO 'YYYY-MM-DD[ HH:MM:SS[.ffffff]]')."""
    name = "parse_timestamp"
    if name not in _REGISTRY:

        def rule(args):
            return TIMESTAMP

        @register(name, rule)
        def impl(args, out):
            a = args[0]
            if a.dictionary is None:
                raise NotImplementedError(
                    "cast to timestamp on dictionary-less VARCHAR")
            bad_v = -(2**63)

            def f(v):
                try:
                    return int((np.datetime64(v.strip().replace(" ", "T"), "us")
                                - np.datetime64("1970-01-01T00:00:00", "us"))
                               .astype(np.int64))
                except ValueError:
                    return bad_v

            t = _dict_int_table(a.dictionary, "parse_timestamp", f,
                                dtype=np.int64)
            d = _gather_dict(a, t)
            bad = d == bad_v
            return jnp.where(bad, 0, d), ~bad & a.valid

    return name


@register("month", _t_int)
def _month(args, out):
    _, m, _ = civil_from_days(_days_of(args[0]))
    return m, None


@register("day", _t_int)
def _day(args, out):
    _, _, d = civil_from_days(_days_of(args[0]))
    return d, None


# ---- casts ----------------------------------------------------------------


@register("cast_double", _t_double)
def _cast_double(args, out):
    return _to_physical(args[0], DOUBLE), None


@register("cast_bigint", _t_bigint)
def _cast_bigint(args, out):
    v = args[0]
    if v.dtype.kind is TypeKind.DECIMAL:
        f = np.int64(10**v.dtype.scale)
        return v.data.astype(jnp.int64) // f, None
    return v.data.astype(jnp.int64), None


def rescale_decimal(target_scale: int):
    name = f"rescale_{target_scale}"
    if name not in _REGISTRY:
        def rule(args, _s=target_scale):
            return decimal(38, _s)

        @register(name, rule)
        def impl(args, out, _s=target_scale):
            return _to_physical(args[0], decimal(38, _s)), None

    return name


# ---- string predicates on dictionary / bytes columns ----------------------


def _like_to_regex(pattern: str) -> str:
    import re as _re

    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(_re.escape(ch))
    return "^" + "".join(out) + "$"


def _dict_predicate_table(dictionary: Dictionary, pred) -> np.ndarray:
    return np.fromiter(
        (pred(v) for v in dictionary.values), dtype=np.bool_, count=len(dictionary)
    )


@register("like", _t_bool)
def _like(args, out):
    """like(col, pattern_literal). Dictionary path: host regex over the
    dictionary -> device gather by code (a scan over distinct values).
    BYTES path: vectorized sliding-window segment matching on device."""
    import re

    target, pat = args
    if target.dtype.kind is TypeKind.BYTES:
        from presto_tpu.ops.strings import like_mask, use_pallas

        if use_pallas():
            from presto_tpu.ops.pallas_strings import (
                like_mask_pallas,
                like_supported,
            )

            if like_supported(pat.data, target.data.shape[1]):
                return like_mask_pallas(target.data, pat.data), None
        return like_mask(target.data, pat.data), None
    if target.dictionary is None:
        raise NotImplementedError("LIKE on dictionary-less VARCHAR")
    rx = re.compile(_like_to_regex(pat.data))
    table = _dict_predicate_table(target.dictionary, lambda v: rx.match(v) is not None)
    return jnp.asarray(table)[target.data], None


@register("starts_with", _t_bool)
def _starts_with(args, out):
    target, pref = args
    if target.dtype.kind is TypeKind.BYTES:
        from presto_tpu.ops.strings import starts_with_mask, use_pallas

        if use_pallas():
            from presto_tpu.ops.pallas_strings import (
                starts_with_pallas,
                starts_with_supported,
            )

            if starts_with_supported(pref.data, target.data.shape[1]):
                return starts_with_pallas(target.data, pref.data), None
        return starts_with_mask(target.data, pref.data), None
    if target.dictionary is None:
        raise NotImplementedError("starts_with on dictionary-less VARCHAR")
    table = _dict_predicate_table(target.dictionary, lambda v: v.startswith(pref.data))
    return jnp.asarray(table)[target.data], None


def substr_fn(start: int, length: int) -> str:
    """Register (once) and return the name of a static-bound substr:
    BYTES(w) -> BYTES(length). SQL is 1-based."""
    from presto_tpu.types import fixed_bytes

    name = f"substr_{start}_{length}"
    if name not in _REGISTRY:

        def rule(args, _l=length):
            return fixed_bytes(_l)

        @register(name, rule)
        def impl(args, out, _s=start, _l=length):
            from presto_tpu.ops.strings import substr

            return substr(args[0].data, _s, _l), None

    return name


# ---- round-5 breadth: math / string / date scalar family ------------------
# Reference parity: the operator.scalar function catalog [SURVEY §2.1
# metadata/functions row]. Implementations follow the engine's two string
# representations: dictionary-coded VARCHAR uses host-side per-dictionary
# transform tables (one gather on device — the scan-over-distinct-values
# trick _like already uses), fixed-width BYTES uses vectorized [rows, w]
# kernels from ops.strings.


@register("sign", _t_int)
def _sign(args, out):
    # engine-defined: INTEGER for all inputs (Presto types sign(double)
    # as double; the -1/0/1 value domain is identical)
    return jnp.sign(args[0].data).astype(jnp.int32), None


def _unary_double(name, f):
    @register(name, _t_double)
    def impl(args, out, _f=f):
        return _f(_to_physical(args[0], DOUBLE)), None

    return impl


_unary_double("exp", jnp.exp)
_unary_double("log2", jnp.log2)


@register("ln", _t_double)
def _ln(args, out):
    # ln(0) = -Infinity, ln(<0) = NaN (IEEE, matching Presto)
    return jnp.log(_to_physical(args[0], DOUBLE)), None


@register("log10", _t_double)
def _log10(args, out):
    return jnp.log10(_to_physical(args[0], DOUBLE)), None


@register("power", _t_double)
def _power(args, out):
    x = _to_physical(args[0], DOUBLE)
    y = _to_physical(args[1], DOUBLE)
    return jnp.power(x, y), None


@register("truncate", _t_double)
def _truncate(args, out):
    x = _to_physical(args[0], DOUBLE)
    return jnp.trunc(x), None


def _t_greatest(args):
    return _t_same(args)


def _check_comparable_dicts(args, what):
    if any(a.dtype.kind is TypeKind.VARCHAR and isinstance(a.data, str)
           for a in args):
        raise NotImplementedError(
            f"{what} with a string literal: the winning literal may be "
            "absent from the column dictionary (unrepresentable result)")
    dicts = [a.dictionary for a in args
             if a.dtype.kind is TypeKind.VARCHAR and a.dictionary is not None]
    if dicts and any(d is not dicts[0] for d in dicts[1:]):
        raise NotImplementedError(
            f"{what} across different dictionaries: codes are only "
            "ordered within one dictionary")


@register("greatest", _t_greatest)
def _greatest(args, out):
    _check_comparable_dicts(args, "greatest")
    data = _to_physical(args[0], out)
    valid = args[0].valid
    for a in args[1:]:
        data = jnp.maximum(data, _to_physical(a, out))
        valid = valid & a.valid  # SQL: NULL if ANY argument is NULL
    return data, valid


@register("least", _t_greatest)
def _least(args, out):
    _check_comparable_dicts(args, "least")
    data = _to_physical(args[0], out)
    valid = args[0].valid
    for a in args[1:]:
        data = jnp.minimum(data, _to_physical(a, out))
        valid = valid & a.valid
    return data, valid


# ---- string breadth -------------------------------------------------------


def _dict_int_table(dictionary: Dictionary, key, fn,
                    dtype=np.int32) -> np.ndarray:
    """Host integer table over a dictionary's values, cached per (key)."""
    cache = dictionary._bytes_mats
    k = ("int_table", key)
    if k not in cache:
        cache[k] = np.fromiter(
            (fn(v) for v in dictionary.values), dtype=dtype,
            count=len(dictionary),
        )
    return cache[k]


def _dict_transform_matrix(dictionary: Dictionary, key, fn, width) -> np.ndarray:
    """Host [dict_size, width] uint8 matrix of fn(value) strings,
    zero-padded/truncated — a string-to-string dictionary transform
    becomes one device gather by code."""
    cache = dictionary._bytes_mats
    k = ("xform", key, width)
    if k not in cache:
        mat = np.zeros((len(dictionary), width), dtype=np.uint8)
        for i, v in enumerate(dictionary.values):
            b = str(fn(v)).encode("latin1", "replace")[:width]
            mat[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        cache[k] = mat
    return cache[k]


def _gather_dict(a: Val, table):
    codes = jnp.clip(a.data.astype(jnp.int32), 0, table.shape[0] - 1)
    return jnp.asarray(table)[codes]


@register("length", _t_int)
def _length(args, out):
    a = args[0]
    if a.dtype.kind is TypeKind.BYTES:
        from presto_tpu.ops.strings import row_lengths

        # PAD SPACE storage: trailing spaces before the zero padding do
        # count in Presto's length() of the underlying VARCHAR value,
        # but fixed-width storage can't distinguish stored trailing
        # spaces from padding — report content length (rtrim'd), the
        # generator-side convention.
        from presto_tpu.ops.strings import rtrim_bytes

        return row_lengths(rtrim_bytes(a.data)), None
    if a.dictionary is None:
        raise NotImplementedError("length() on dictionary-less VARCHAR")
    t = _dict_int_table(a.dictionary, "length", len)
    return _gather_dict(a, t), None


def _dict_value_transform(a: Val, key, fn):
    """String->string transform over a dictionary column: build the
    transformed Dictionary host-side once, remap codes with one device
    gather. Returns (codes, derived_dictionary)."""
    cache = a.dictionary._bytes_mats
    k = ("remap", key)
    if k not in cache:
        from presto_tpu.batch import Dictionary as _Dict

        xs = [fn(v) for v in a.dictionary.values]
        nd = _Dict(xs)
        cache[k] = (nd, nd.encode(xs))
    nd, table = cache[k]
    return _gather_dict(a, table), nd


def _string_transform(key, host_fn, bytes_fn_name):
    """Register a same-type string transform: BYTES rows go through the
    ops.strings kernel; dictionary VARCHAR derives a new dictionary."""

    @register(key, _t_first)
    def impl(args, out, _key=key, _h=host_fn, _b=bytes_fn_name):
        a = args[0]
        if a.dtype.kind is TypeKind.BYTES:
            from presto_tpu.ops import strings as S

            return getattr(S, _b)(a.data), None
        if a.dictionary is None:
            raise NotImplementedError(f"{_key} on dictionary-less VARCHAR")
        data, nd = _dict_value_transform(a, _key, _h)
        return data, None, nd

    return impl


# ASCII space only, on BOTH representations (the BYTES kernels strip
# 0x20) — one semantic regardless of storage
_string_transform("trim", lambda s: s.strip(" "), "trim_bytes")
_string_transform("ltrim", lambda s: s.lstrip(" "), "ltrim_bytes")
_string_transform("rtrim", lambda s: s.rstrip(" "), "rtrim_bytes")
_string_transform("reverse", lambda s: s[::-1], "reverse_bytes")


@register("strpos", _t_int)
def _strpos(args, out):
    """strpos(haystack, needle_literal): 1-based, 0 when absent."""
    a, b = args
    if not isinstance(b.data, str):
        raise NotImplementedError("strpos needle must be a literal")
    if a.dtype.kind is TypeKind.BYTES:
        from presto_tpu.ops.strings import position_in

        return position_in(a.data, b.data), None
    if a.dictionary is None:
        raise NotImplementedError("strpos on dictionary-less VARCHAR")
    t = _dict_int_table(a.dictionary, ("strpos", b.data),
                        lambda v: v.find(b.data) + 1)
    return _gather_dict(a, t), None


@register("replace", _t_first)
def _replace(args, out):
    """replace(col, from_lit, to_lit) — dictionary path only (BYTES
    replace has data-dependent widths)."""
    a, frm, to = args
    if not (isinstance(frm.data, str) and isinstance(to.data, str)):
        raise NotImplementedError("replace() arguments must be literals")
    if a.dictionary is None:
        raise NotImplementedError("replace() requires a dictionary VARCHAR")
    data, nd = _dict_value_transform(
        a, ("replace", frm.data, to.data),
        lambda v: v.replace(frm.data, to.data),
    )
    return data, None, nd


def split_part_fn(sep: str, n: int) -> str:
    """Static-bound split_part(col, sep_literal, n_literal) — dictionary
    path only (like substr_fn, the literal args live in the name)."""
    name = f"split_part_{sep!r}_{n}"
    if name not in _REGISTRY:

        @register(name, _t_first)
        def impl(args, out, _s=sep, _n=n):
            a = args[0]
            if a.dictionary is None:
                raise NotImplementedError(
                    "split_part() requires a dictionary VARCHAR")

            def f(v):
                parts = v.split(_s)
                return parts[_n - 1] if 1 <= _n <= len(parts) else ""

            data, nd = _dict_value_transform(a, ("split_part", _s, _n), f)
            return data, None, nd

    return name


def substr_dict_fn(start: int, length: int) -> str:
    """General 1-based substr over a dictionary VARCHAR (derived
    dictionary; negative start counts from the end, SQL-style)."""
    name = f"substr_dict_{start}_{length}"
    if name not in _REGISTRY:

        @register(name, _t_first)
        def impl(args, out, _s=start, _l=length):
            a = args[0]
            if a.dictionary is None:
                raise NotImplementedError("substr on dictionary-less VARCHAR")

            def f(v):
                if _s >= 1:
                    return v[_s - 1:_s - 1 + _l]
                if _s < 0:
                    b = len(v) + _s
                    # start before the beginning -> empty (SQL)
                    return v[b:b + _l] if b >= 0 else ""
                return ""  # start 0 is out of range in SQL

            data, nd = _dict_value_transform(a, ("substr", _s, _l), f)
            return data, None, nd

    return name


@register("regexp_like", _t_bool)
def _regexp_like(args, out):
    import re

    a, pat = args
    if not isinstance(pat.data, str):
        raise NotImplementedError("regexp_like pattern must be a literal")
    if a.dictionary is None:
        raise NotImplementedError("regexp_like requires a dictionary VARCHAR")
    rx = re.compile(pat.data)
    table = _dict_predicate_table(a.dictionary,
                                  lambda v: rx.search(v) is not None)
    return _gather_dict(a, table), None


# ---- date breadth ---------------------------------------------------------


def days_from_civil(y, m, d):
    """(year, month, day) -> days since 1970-01-01 (Hinnant inverse of
    ``civil_from_days``); floor-division form, vectorizes on the VPU."""
    y = y - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


@register("quarter", _t_int)
def _quarter(args, out):
    _, m, _ = civil_from_days(_days_of(args[0]))
    return (m + 2) // 3, None


@register("day_of_week", _t_int)
def _day_of_week(args, out):
    """ISO: Monday=1 .. Sunday=7 (1970-01-01 was a Thursday)."""
    d = _days_of(args[0]).astype(jnp.int32)
    return (d + 3) % 7 + 1, None


@register("day_of_year", _t_int)
def _day_of_year(args, out):
    d = _days_of(args[0])
    y, _, _ = civil_from_days(d)
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return (d.astype(jnp.int32) - jan1 + 1).astype(jnp.int32), None


def date_trunc_fn(unit: str) -> str:
    name = f"date_trunc_{unit}"
    if name not in _REGISTRY:
        if unit not in ("second", "minute", "hour", "day", "week", "month",
                       "quarter", "year"):
            raise NotImplementedError(f"date_trunc unit {unit!r}")

        def rule(args):
            return args[0]  # DATE stays DATE, TIMESTAMP stays TIMESTAMP

        @register(name, rule)
        def impl(args, out, _u=unit):
            is_ts = args[0].dtype.kind is TypeKind.TIMESTAMP
            if _u in ("hour", "minute", "second"):
                if not is_ts:  # sub-day truncation of a DATE: identity
                    return args[0].data, None
                us = _time_of_day_us(args[0])
                per = {"hour": 3_600_000_000, "minute": 60_000_000,
                       "second": 1_000_000}[_u]
                return args[0].data - us % per, None
            d = _days_of(args[0]).astype(jnp.int32)
            if _u == "day":
                days = d
            elif _u == "week":  # ISO week starts Monday
                days = d - (d + 3) % 7
            else:
                y, m, _day = civil_from_days(d)
                if _u == "month":
                    days = days_from_civil(y, m, jnp.ones_like(y))
                elif _u == "quarter":
                    qm = ((m - 1) // 3) * 3 + 1
                    days = days_from_civil(y, qm, jnp.ones_like(y))
                else:
                    days = days_from_civil(y, jnp.ones_like(y),
                                           jnp.ones_like(y))
            if is_ts:
                return days.astype(jnp.int64) * _MICROS_PER_DAY, None
            return days, None

    return name


def _add_months(d, n):
    """Calendar month addition with end-of-month clamping."""
    y, m, day = civil_from_days(d)
    tot = y * 12 + (m - 1) + n
    y2 = tot // 12
    m2 = tot % 12 + 1
    first = days_from_civil(y2, m2, jnp.ones_like(y2))
    nxt = days_from_civil(y2 + (m2 == 12), m2 % 12 + 1, jnp.ones_like(y2))
    dim = nxt - first
    return first + jnp.minimum(day, dim) - 1


def date_add_fn(unit: str) -> str:
    name = f"date_add_{unit}"
    if name not in _REGISTRY:
        if unit not in ("day", "week", "month", "quarter", "year"):
            raise NotImplementedError(f"date_add unit {unit!r}")

        def rule(args):
            return DATE

        @register(name, rule)
        def impl(args, out, _u=unit):
            n = args[0].data.astype(jnp.int32)
            d = args[1].data.astype(jnp.int32)
            if _u == "day":
                return d + n, None
            if _u == "week":
                return d + 7 * n, None
            months = {"month": 1, "quarter": 3, "year": 12}[_u]
            return _add_months(d, n * months), None

    return name


def date_diff_fn(unit: str) -> str:
    name = f"date_diff_{unit}"
    if name not in _REGISTRY:
        if unit not in ("day", "week", "month", "quarter", "year"):
            raise NotImplementedError(f"date_diff unit {unit!r}")

        def rule(args):
            return BIGINT

        @register(name, rule)
        def impl(args, out, _u=unit):
            a = args[0].data.astype(jnp.int32)
            b = args[1].data.astype(jnp.int32)
            if _u == "day":
                return (b - a).astype(jnp.int64), None

            def trunc_div(x, d):
                # SQL date_diff counts COMPLETE units toward zero
                # (jnp // floors, wrong for negative spans)
                q = jnp.abs(x) // d
                return jnp.where(x >= 0, q, -q)

            if _u == "week":
                return trunc_div(b - a, 7).astype(jnp.int64), None
            ya, ma, da = civil_from_days(a)
            yb, mb, db = civil_from_days(b)
            raw = (yb * 12 + mb) - (ya * 12 + ma)
            months = jnp.where(b >= a, raw - (db < da), raw + (db > da))
            per = {"month": 1, "quarter": 3, "year": 12}[_u]
            return trunc_div(months, per).astype(jnp.int64), None

    return name


@register("last_day_of_month", lambda args: DATE)
def _last_day_of_month(args, out):
    d = args[0].data.astype(jnp.int32)
    y, m, _day = civil_from_days(d)
    nxt = days_from_civil(y + (m == 12), m % 12 + 1, jnp.ones_like(y))
    return nxt - 1, None


# ---- cast to varchar ------------------------------------------------------

_POW10_I64 = np.array([10**k for k in range(19)] + [np.iinfo(np.int64).max],
                      dtype=np.int64)


def _render_int_bytes(v, width: int, neg=None):
    """Left-aligned decimal text of int64 ``v`` into [rows, width] uint8.
    ``neg`` overrides the sign (the decimal renderer needs '-0.50')."""
    neg = (v < 0) if neg is None else neg
    a = jnp.abs(v)
    nd = jnp.ones(v.shape[0], jnp.int32)
    for k in range(1, 19):
        nd = nd + (a >= np.int64(10**k)).astype(jnp.int32)
    j = jnp.arange(width, dtype=jnp.int32)[None, :]
    je = j - neg[:, None].astype(jnp.int32)  # shift past the '-' sign
    place = nd[:, None] - 1 - je
    pw = jnp.asarray(_POW10_I64)[jnp.clip(place, 0, 19)]
    dig = (a[:, None] // pw) % 10
    in_digits = (je >= 0) & (je < nd[:, None])
    out = jnp.where(in_digits, 48 + dig.astype(jnp.int32), 0)
    out = jnp.where((j == 0) & neg[:, None], 45, out)  # '-'
    return out.astype(jnp.uint8)


def cast_varchar_fn(width: int) -> str:
    """cast(x AS varchar) rendered into fixed BYTES(width); supports
    integer kinds, DATE ('yyyy-mm-dd'), decimals, and passthrough for
    BYTES / dictionary VARCHAR."""
    from presto_tpu.types import fixed_bytes

    name = f"cast_varchar_{width}"
    if name not in _REGISTRY:

        def rule(args, _w=width):
            return fixed_bytes(_w)

        @register(name, rule)
        def impl(args, out, _w=width):
            a = args[0]
            k = a.dtype.kind
            if k is TypeKind.BYTES:
                d = a.data
                if d.shape[1] == _w:
                    return d, None
                if d.shape[1] > _w:
                    return d[:, :_w], None
                pad = jnp.zeros((d.shape[0], _w - d.shape[1]), d.dtype)
                return jnp.concatenate([d, pad], axis=1), None
            if k is TypeKind.VARCHAR:
                if a.dictionary is None:
                    raise NotImplementedError("cast on dictionary-less VARCHAR")
                return _gather_dict(a, a.dictionary.bytes_matrix(_w)), None
            if k is TypeKind.TIMESTAMP:
                days = (a.data.astype(jnp.int64) // _MICROS_PER_DAY)
                us = a.data.astype(jnp.int64) % _MICROS_PER_DAY
                y, m, d = civil_from_days(days.astype(jnp.int32))
                hh = us // 3_600_000_000
                mi = (us // 60_000_000) % 60
                ss = (us // 1_000_000) % 60
                dash = jnp.full_like(y, 45)
                colon = jnp.full_like(y, 58)
                space = jnp.full_like(y, 32)
                cols = [48 + (y // 1000) % 10, 48 + (y // 100) % 10,
                        48 + (y // 10) % 10, 48 + y % 10, dash,
                        48 + m // 10, 48 + m % 10, dash,
                        48 + d // 10, 48 + d % 10, space,
                        48 + hh // 10, 48 + hh % 10, colon,
                        48 + mi // 10, 48 + mi % 10, colon,
                        48 + ss // 10, 48 + ss % 10]
                txt = jnp.stack(cols, axis=1).astype(jnp.uint8)
                if _w <= 19:
                    return txt[:, :_w], None
                pad = jnp.zeros((txt.shape[0], _w - 19), jnp.uint8)
                return jnp.concatenate([txt, pad], axis=1), None
            if k is TypeKind.DATE:
                y, m, d = civil_from_days(a.data)
                dash = jnp.full_like(y, 45)  # '-'
                cols = [48 + (y // 1000) % 10, 48 + (y // 100) % 10,
                        48 + (y // 10) % 10, 48 + y % 10, dash,
                        48 + m // 10, 48 + m % 10, dash,
                        48 + d // 10, 48 + d % 10]
                txt = jnp.stack(cols, axis=1).astype(jnp.uint8)
                if _w <= 10:
                    return txt[:, :_w], None
                pad = jnp.zeros((txt.shape[0], _w - 10), jnp.uint8)
                return jnp.concatenate([txt, pad], axis=1), None
            if k is TypeKind.DECIMAL and a.dtype.scale > 0:
                s = a.dtype.scale
                f = np.int64(10**s)
                v = a.data.astype(jnp.int64)
                ip = jnp.abs(v) // f  # sign rendered separately: '-0.50'
                frac = jnp.abs(v) % f
                ip_txt = _render_int_bytes(ip, _w, neg=v < 0)
                # place '.' + zero-padded fraction right after the int part
                from presto_tpu.ops.strings import row_lengths

                ip_len = row_lengths(ip_txt)
                j = jnp.arange(_w, dtype=jnp.int32)[None, :]
                rel = j - ip_len[:, None]  # 0 -> '.', 1..s -> frac digits
                fd = (frac[:, None] //
                      jnp.asarray(_POW10_I64)[jnp.clip(s - 1 - (rel - 1), 0, 19)]) % 10
                out_b = jnp.where(rel == 0, 46, 0)
                out_b = jnp.where((rel >= 1) & (rel <= s),
                                  48 + fd.astype(jnp.int32), out_b)
                return jnp.where(rel < 0, ip_txt.astype(jnp.int32),
                                 out_b).astype(jnp.uint8), None
            return _render_int_bytes(a.data.astype(jnp.int64), _w), None

    return name


def parse_date_fn() -> str:
    """cast(varchar AS date) over a dictionary column (host parse)."""
    name = "parse_date"
    if name not in _REGISTRY:

        def rule(args):
            return DATE

        @register(name, rule)
        def impl(args, out):
            import datetime

            a = args[0]
            if a.dictionary is None:
                raise NotImplementedError("cast to date on dictionary-less VARCHAR")
            epoch = datetime.date(1970, 1, 1)

            def f(v):
                try:
                    return (datetime.date.fromisoformat(v.strip()) - epoch).days
                except ValueError:
                    return -(2**31)  # poisoned; validity cleared below

            t = _dict_int_table(a.dictionary, "parse_date", f)
            d = _gather_dict(a, t)
            bad = d == -(2**31)
            return jnp.where(bad, 0, d), ~bad & a.valid

    return name


# ---------------------------------------------------------------------------
# Evaluator
# ---------------------------------------------------------------------------


def evaluate(expr: Expr, batch: Batch) -> Val:
    """Evaluate ``expr`` over a batch; returns a full-capacity ``Val``.

    Dead rows (``~batch.live``) produce garbage-but-well-defined values;
    consumers mask with ``batch.live``.
    """
    if isinstance(expr, InputRef):
        c = batch[expr.name]
        return Val(c.data, c.valid, c.dtype, c.dictionary)
    if isinstance(expr, Param):
        vals = _PARAM_VALUES.get()
        if vals is None or expr.slot >= len(vals):
            raise KeyError(
                f"unbound literal slot ?{expr.slot}: evaluation outside a "
                "param_scope (executor run scope or traced step body)"
            )
        cap = batch.capacity
        data = jnp.broadcast_to(
            jnp.asarray(vals[expr.slot], expr.dtype.jnp_dtype), (cap,)
        )
        return Val(data, jnp.ones(cap, dtype=jnp.bool_), expr.dtype)
    if isinstance(expr, Literal):
        cap = batch.capacity
        if expr.value is None:
            t = expr.dtype
            shape = (cap, t.width) if t.kind is TypeKind.BYTES else (cap,)
            return Val(
                jnp.zeros(shape, dtype=t.jnp_dtype),
                jnp.zeros(cap, dtype=jnp.bool_),
                t,
            )
        if expr.dtype.kind is TypeKind.VARCHAR:
            # stays host-side; encoded lazily against the peer dictionary
            return Val(expr.value, None, expr.dtype, None)
        phys = expr.dtype.to_physical(expr.value)
        data = jnp.full(cap, phys, dtype=expr.dtype.jnp_dtype)
        return Val(data, jnp.ones(cap, dtype=jnp.bool_), expr.dtype)
    if isinstance(expr, Call):
        args = [evaluate(a, batch) for a in expr.args]
        args = _encode_string_literals(expr.fn, args)
        impl, _rule = _REGISTRY[expr.fn]
        res = impl(args, expr.dtype)
        # impls may return (data, valid) or (data, valid, derived_dict)
        # — dictionary transforms produce NEW dictionaries (trim et al.)
        out_dict = None
        if len(res) == 3:
            data, valid, out_dict = res
        else:
            data, valid = res
        if valid is None:
            valid = None
            for a in args:
                if a.valid is not None:
                    valid = a.valid if valid is None else (valid & a.valid)
            if valid is None:
                valid = jnp.ones(batch.capacity, dtype=jnp.bool_)
        dictionary = out_dict
        if dictionary is None and expr.dtype.kind is TypeKind.VARCHAR:
            for a in args:
                if a.dictionary is not None:
                    dictionary = a.dictionary
                    break
        return Val(data, valid, _sync_physical(expr.dtype, data), dictionary)
    raise TypeError(f"unknown expr node {type(expr)}")


def _sync_physical(dtype: DataType, data) -> DataType:
    """Metadata must tell the truth about storage: pass-through impls
    (trim, min/max-style selections, identity projections) hand narrow
    column data onward under the expr's canonical claimed type — sync
    the physical field to the actual device dtype so downstream
    ``_to_physical`` widening keys on reality, not on the claim.
    Host-side values (string literals) and non-narrowable kinds pass
    through unchanged."""
    if not hasattr(data, "dtype") or dtype.kind in (
        TypeKind.BYTES, TypeKind.BOOLEAN, TypeKind.DOUBLE
    ):
        return dtype
    if data.dtype == dtype.np_dtype:
        return dtype
    return dtype.with_physical(data.dtype)


def _encode_string_literals(fn: str, args: list[Val]) -> list[Val]:
    """Encode host-side VARCHAR literals against a sibling dictionary."""
    if fn in ("like", "starts_with", "strpos", "replace", "regexp_like",
              "greatest", "least"):
        return args  # patterns/needles stay as raw strings
    dictionary = next((a.dictionary for a in args if a.dictionary is not None), None)
    if dictionary is None:
        return args
    out = []
    for pos, a in enumerate(args):
        if a.dtype.kind is TypeKind.VARCHAR and isinstance(a.data, str):
            s = a.data
            if s in dictionary._index:
                code = dictionary._index[s]
            elif fn in ("lt", "ge") or (fn == "between" and pos == 1):
                # x < s  ==  code < lb(s); x >= s  ==  code >= lb(s)
                code = dictionary.lower_bound(s)
            elif fn in ("le", "gt") or (fn == "between" and pos == 2):
                # x <= s with s absent  ==  code <= lb(s)-1 (may be -1:
                # constant-false for le, constant-true for gt)
                code = dictionary.lower_bound(s) - 1
            else:
                # eq/ne/in with an absent value: impossible code
                code = len(dictionary)
            cap = next(x.data.shape[0] for x in args if x.dictionary is not None)
            out.append(
                Val(
                    jnp.full(cap, np.int32(code), dtype=jnp.int32),
                    jnp.ones(cap, dtype=jnp.bool_),
                    a.dtype,
                    dictionary,
                )
            )
        else:
            out.append(a)
    return out


def evaluate_predicate(expr: Expr, batch: Batch):
    """Evaluate a boolean expr to a device mask (NULL -> False)."""
    v = evaluate(expr, batch)
    return v.data & v.valid
