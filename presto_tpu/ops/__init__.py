"""Relational kernels over fixed-capacity device arrays.

These are the TPU-native replacements for the reference's hot operator
internals (``GroupByHash``, ``PagesHash``/``JoinProbe``,
``PagePartitioner`` ... [SURVEY §2.1]): sort/segment/gather idioms with
static shapes instead of scatter-heavy open-addressing hash tables
(SURVEY §7.1 design stance).
"""

from presto_tpu.ops.compact import compact_indices, compact_mask_overflow
from presto_tpu.ops.hashing import hash_columns, mix64
