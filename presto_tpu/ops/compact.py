"""Row compaction: mask -> packed row indices.

Reference parity: the positions-list/selected-positions machinery inside
``PageProcessor`` and ``PartitionedOutputOperator``'s row gathering
[SURVEY §2.1; reference tree unavailable]. TPU-first: compaction is the
*only* data-movement primitive — filters just AND masks; rows physically
move only at shuffle/build/output boundaries, and then via a single
``nonzero``+gather with a static output capacity.
"""

from __future__ import annotations

import jax.numpy as jnp


def compact_indices(mask, out_capacity: int):
    """Packed indices of True positions, padded with ``cap`` (an
    out-of-range sentinel safe for ``.at[].set`` with drop semantics /
    gathers with fill).

    Returns (indices[out_capacity], n_selected, overflowed).
    ``overflowed`` is a traced bool: True when more rows were selected
    than ``out_capacity`` — the host must retry at a larger bucket
    (SURVEY §7.4 hard part #1).
    """
    cap = mask.shape[0]
    n = jnp.sum(mask.astype(jnp.int32))
    idx = jnp.nonzero(mask, size=out_capacity, fill_value=cap)[0]
    return idx, n, n > out_capacity


def compact_mask_overflow(mask, out_capacity: int):
    """Just the overflow flag for a planned compaction."""
    return jnp.sum(mask.astype(jnp.int32)) > out_capacity
