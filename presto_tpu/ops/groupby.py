"""Grouping kernels: row -> group-id assignment + segment aggregation.

Reference parity: ``GroupByHash`` (``BigintGroupByHash`` fast path,
``MultiChannelGroupByHash``) + ``InMemoryHashAggregationBuilder`` /
``GroupedAccumulator`` [SURVEY §2.1, §3.3; reference tree unavailable].

TPU-first (SURVEY §7.1): open-addressing hash tables are
scatter-serialized on TPU, so grouping is

- **direct addressing** when the composite key domain is small and
  known (dictionary codes, bounded ints): gid = bit-packed key. The
  analog of BigintGroupByHash's array-based fast path — Q1's
  returnflag x linestatus lands here, zero sorting.
- **sort-based** otherwise: stable multi-key argsort, adjacent-diff
  boundaries, cumsum group ids — O(n log n) but built entirely from
  TPU-friendly sort/gather/scan primitives.

Aggregation is ``jax.ops.segment_*`` over the group ids with one extra
"trash" segment that absorbs dead rows; outputs have a static
``max_groups`` capacity with an overflow flag (SURVEY §7.4 #1).
"""

from __future__ import annotations

from functools import reduce

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.runtime.errors import InternalError


def gather_padded(arr, idx, fill):
    """arr[idx] with out-of-range idx (>= len) producing ``fill``."""
    cap = arr.shape[0]
    safe = jnp.minimum(idx, cap - 1)
    return jnp.where(idx < cap, arr[safe], fill)


# ---------------------------------------------------------------------------
# group-id assignment
# ---------------------------------------------------------------------------


def group_ids_direct(key_cols, mins, strides, live, num_groups: int):
    """Direct-addressed gids: gid = sum_i (k_i - min_i) * stride_i.

    Caller guarantees the packed domain is exactly ``num_groups``.
    Dead rows get gid == num_groups (the trash segment).
    Returns (gids, rep_valid) where rep_valid[g] marks groups with >=1
    live row.
    """
    gid = None
    for k, m, s in zip(key_cols, mins, strides):
        t = (k.astype(jnp.int32) - np.int32(m)) * np.int32(s)
        gid = t if gid is None else gid + t
    gid = jnp.clip(gid, 0, num_groups - 1)
    gid = jnp.where(live, gid, num_groups)
    if num_groups <= SMALL_GROUP_LIMIT:
        # scatter-free presence: one any-reduction per group
        present = jnp.stack([jnp.any(gid == g) for g in range(num_groups)])
    else:
        present = (
            jnp.zeros(num_groups + 1, dtype=jnp.bool_).at[gid].set(True)[:num_groups]
        )
    return gid, present


def group_ids_sort(key_cols, live, max_groups: int):
    """Sort-based gids for arbitrary keys.

    Returns (gids[cap], rep_idx[max_groups], ngroups, overflow):
    - gids: per-row group id in [0, max_groups) for live rows,
      ``max_groups`` (trash) for dead rows;
    - rep_idx: original row index of each group's first member
      (sentinel ``cap`` for unused slots) — gather key columns through
      it to materialize group keys;
    - overflow: True when distinct live keys exceeded max_groups.
    """
    cap = live.shape[0]
    order = jnp.arange(cap)
    for k in reversed(list(key_cols)):
        order = order[jnp.argsort(k[order], stable=True)]
    # liveness is the most significant key: live rows first
    order = order[jnp.argsort(~live[order], stable=True)]

    sl = live[order]
    diffs = [k[order][1:] != k[order][:-1] for k in key_cols]
    any_diff = reduce(jnp.logical_or, diffs) if diffs else jnp.zeros(cap - 1, bool)
    boundary = any_diff | ~sl[:-1]
    newgrp = jnp.concatenate([sl[:1], boundary & sl[1:]])
    ngroups = jnp.sum(newgrp.astype(jnp.int32))
    gid_sorted = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
    gid_sorted = jnp.where(sl, jnp.minimum(gid_sorted, max_groups), max_groups)
    gids = jnp.zeros(cap, dtype=jnp.int32).at[order].set(gid_sorted)

    rep_sorted = jnp.nonzero(newgrp, size=max_groups, fill_value=cap)[0]
    rep_idx = gather_padded(order, rep_sorted, cap)
    return gids, rep_idx, ngroups, ngroups > max_groups


# ---------------------------------------------------------------------------
# segment aggregation
# ---------------------------------------------------------------------------

_I64_MIN = np.int64(np.iinfo(np.int64).min)
_I64_MAX = np.int64(np.iinfo(np.int64).max)


def _identity(kind: str, dtype):
    if kind == "min":
        return (
            jnp.asarray(np.inf, dtype)
            if jnp.issubdtype(dtype, jnp.floating)
            else jnp.asarray(jnp.iinfo(dtype).max, dtype)
        )
    if kind == "max":
        return (
            jnp.asarray(-np.inf, dtype)
            if jnp.issubdtype(dtype, jnp.floating)
            else jnp.asarray(jnp.iinfo(dtype).min, dtype)
        )
    return jnp.asarray(0, dtype)


# Below this group count, aggregation avoids scatters entirely (measured
# ~25x faster on TPU: scatter-add serializes, masked reductions ride the
# VPU at memory bandwidth — notes/perf_q1_probe.py variant C).
SMALL_GROUP_LIMIT = 32

# Chunk length for the lane-split accumulators: 15-bit lanes x 2^16-row
# chunks keep every in-chunk partial sum < 2^31 (32767 * 65536 < 2^31),
# so the hot loop runs entirely in native int32; only the [nchunks,
# groups] combine widens to int64.
_LANE_BITS = 15
_LANE_CHUNK = 1 << 16


def _chunked(x, cap: int, fill):
    """Reshape [cap] -> [nchunks, <=2^16] (zero-padding to a chunk
    multiple when needed, so per-chunk int32 sums can never overflow)."""
    if cap <= _LANE_CHUNK:
        return x.reshape(1, cap)
    if cap % _LANE_CHUNK:
        pad = _LANE_CHUNK - cap % _LANE_CHUNK
        x = jnp.concatenate([x, jnp.full(pad, fill, dtype=x.dtype)])
        cap = cap + pad
    return x.reshape(cap // _LANE_CHUNK, _LANE_CHUNK)


def _masked_group_sums(vals2d, gids2d, num_groups: int):
    """[nch, chunk] int32 values -> [num_groups] int32 per-chunk-summed.

    Scatter-free: one masked reduction per group (VPU-native). Caller
    guarantees per-chunk sums cannot overflow int32.
    """
    per_chunk = jnp.stack(
        [
            jnp.sum(jnp.where(gids2d == g, vals2d, 0), axis=1, dtype=jnp.int32)
            for g in range(num_groups)
        ],
        axis=1,
    )  # [nch, G] int32
    return per_chunk


def _small_sum_int(values, contrib, gids, max_groups: int, value_bits: int):
    """Exact integer sum per group without scatters.

    Splits each value into ceil(value_bits/15)-many 15-bit lanes,
    accumulates each lane per 2^16-row chunk in int32 (provably no
    overflow), then recombines in int64 over the tiny [nch, G] partials.
    """
    cap = values.shape[0]
    v = jnp.where(contrib, values, 0)
    neg = v < 0
    mag = jnp.abs(v)
    g2 = _chunked(jnp.where(contrib, gids, max_groups), cap, max_groups)
    # lanes never exceed what the value dtype can hold (shift >= width
    # is undefined); int32 inputs cap at 31 bits -> 3 lanes
    value_bits = min(value_bits, jnp.iinfo(values.dtype).bits - 1)
    nlanes = max(1, -(-value_bits // _LANE_BITS))
    total = jnp.zeros(max_groups, dtype=jnp.int64)
    for lane in range(nlanes):
        lane_vals = ((mag >> (lane * _LANE_BITS)) & ((1 << _LANE_BITS) - 1)).astype(
            jnp.int32
        )
        lane_vals = jnp.where(neg, -lane_vals, lane_vals)
        per_chunk = _masked_group_sums(_chunked(lane_vals, cap, 0), g2, max_groups)
        total = total + (per_chunk.astype(jnp.int64).sum(axis=0) << (lane * _LANE_BITS))
    return total


def _small_agg(values, contrib, gids, max_groups: int, kind: str, value_bits: int):
    cap = contrib.shape[0]
    g2 = _chunked(jnp.where(contrib, gids, max_groups), cap, max_groups)
    if kind == "count":
        per_chunk = _masked_group_sums(
            _chunked(contrib.astype(jnp.int32), cap, 0), g2, max_groups
        )
        return per_chunk.astype(jnp.int64).sum(axis=0)
    if kind == "sum":
        if jnp.issubdtype(values.dtype, jnp.floating):
            v = _chunked(jnp.where(contrib, values, 0), cap, 0)
            per_chunk = jnp.stack(
                [jnp.sum(jnp.where(g2 == g, v, 0), axis=1) for g in range(max_groups)],
                axis=1,
            )
            return per_chunk.sum(axis=0)
        # int64 always: running sums outgrow narrow input dtypes
        return _small_sum_int(values, contrib, gids, max_groups, value_bits)
    # min/max: plain masked reductions per group (no overflow concern).
    ident = _identity(kind, values.dtype)
    v = _chunked(jnp.where(contrib, values, ident), cap, ident)
    red = jnp.min if kind == "min" else jnp.max
    return jnp.stack(
        [red(jnp.where(g2 == g, v, ident)) for g in range(max_groups)]
    )


# ---------------------------------------------------------------------------
# Fused multi-aggregate segment sums: the MXU one-hot matmul path.
#
# The canonical TPU segment-sum for small group counts: pack every
# integer sum's 7-bit signed lanes (plus one int8 count column per
# aggregate) into one X[rows, L] int8 matrix and contract it against a
# one-hot [rows, G] int8 matrix with int32 accumulation — a single
# MXU-friendly einsum reads the data ONCE, replacing the G x lanes
# masked-reduction passes of ``_small_agg`` (VERDICT r2 weak #2: the
# old path read the data ~50x for Q1's 4 sums + count).
# ---------------------------------------------------------------------------

_MM_LANE_BITS = 7  # signed int8 lanes: values in [-127, 127]
_MM_CHUNK = 1 << 23  # 127 * 2^23 < 2^31 — per-chunk int32 sums cannot overflow


def _mm_chunked(x, fill):
    cap = x.shape[0]
    if cap <= _MM_CHUNK:
        return x.reshape(1, *x.shape)
    if cap % _MM_CHUNK:
        pad = _MM_CHUNK - cap % _MM_CHUNK
        x = jnp.concatenate([x, jnp.full((pad,) + x.shape[1:], fill, dtype=x.dtype)])
    return x.reshape(-1, _MM_CHUNK, *x.shape[1:])


def fused_small_sums(values, bits_list, contribs, gids, max_groups: int,
                     extra_count_masks=()):
    """Exact integer segment sums for many aggregates in ONE data pass.

    values/bits_list/contribs: per-aggregate integer value arrays, static
    |value| bit bounds, and contribution masks. gids: per-row group id
    (``max_groups`` = trash). extra_count_masks: additional bool masks to
    count per group (e.g. ``live`` for group presence).

    Returns (sums, counts, extra_counts, value_overflow):
    - sums[i]: int64-exact per-group sum of values[i] (in values[i].dtype
      when narrower);
    - counts[i]: int64 per-group count of contribs[i];
    - value_overflow: scalar bool — True when any contributing |value|
      exceeded its declared bits bound (the declared-stats runtime guard:
      a violated bound would otherwise silently truncate high lanes).

    Fast path: on TPU, when every bound fits int32 and the capacity is
    lane-chunk aligned, the whole computation runs as ONE Pallas pass
    (ops.pallas_groupby) — the XLA einsum below materializes the lane
    matrix + one-hot in HBM (~6 round trips; measured 73 ms vs ~20 ms
    for 60M rows). Falls back here when the compile probe fails.
    """
    # identical mask objects (e.g. one ``live`` reused for every
    # aggregate) get ONE count column — slots map back through uniq
    all_masks = list(contribs) + list(extra_count_masks)
    uniq: dict[int, int] = {}
    slot = []
    mask_cols = []
    for m in all_masks:
        if id(m) not in uniq:
            uniq[id(m)] = len(mask_cols)
            mask_cols.append(m)
        slot.append(uniq[id(m)])

    pallas_ok = (
        all(not jnp.issubdtype(v.dtype, jnp.floating) for v in values)
        and all(b <= 31 for b in bits_list)
    )
    if pallas_ok:
        from presto_tpu.ops.strings import use_pallas

        pallas_ok = use_pallas()
    if pallas_ok:
        from presto_tpu.ops import pallas_groupby as PG

        eff_bits = [
            min(b, jnp.iinfo(v.dtype).bits - 1)
            for v, b in zip(values, bits_list)
        ]
        if PG.probe_supported(eff_bits, len(mask_cols), max_groups,
                              gids.shape[0]):
            # bound check on the ORIGINAL dtype, before the int32 cast
            # (a wide value would wrap and dodge the in-kernel check);
            # XLA fuses this into the zeroing pass below
            oflow = jnp.zeros((), jnp.bool_)
            for v, c, eb in zip(values, contribs, eff_bits):
                if eb < jnp.iinfo(v.dtype).bits - 1:
                    oflow = oflow | jnp.any(
                        jnp.where(c, jnp.abs(v) >> eb, 0) != 0)
            zeroed = [
                jnp.where(c, v, 0).astype(jnp.int32)
                for v, c in zip(values, contribs)
            ]
            sums, counts_all, k_oflow = PG.fused_lane_sums(
                zeroed, eff_bits, mask_cols, gids.astype(jnp.int32),
                max_groups,
            )
            counts = [counts_all[slot[i]] for i in range(len(contribs))]
            extra = [counts_all[slot[len(contribs) + i]]
                     for i in range(len(extra_count_masks))]
            return sums, counts, extra, oflow | k_oflow

    lane_cols = []
    spans = []
    oflow = jnp.zeros((), jnp.bool_)
    for v, bits, contrib in zip(values, bits_list, contribs):
        width = jnp.iinfo(v.dtype).bits - 1
        vv = jnp.where(contrib, v, 0)
        neg = vv < 0
        mag = jnp.abs(vv)
        if bits < width:
            oflow = oflow | jnp.any((mag >> bits) != 0)
        eff = min(bits, width)
        nlanes = max(1, -(-eff // _MM_LANE_BITS))
        spans.append((len(lane_cols), nlanes))
        for k in range(nlanes):
            lane = ((mag >> (_MM_LANE_BITS * k)) & 127).astype(jnp.int8)
            lane_cols.append(jnp.where(neg, -lane, lane))
    count_cols = [m.astype(jnp.int8) for m in mask_cols]
    X = jnp.stack(lane_cols + count_cols, axis=1)  # [rows, L] int8
    x3 = _mm_chunked(X, 0)  # [nch, chunk, L]
    g3 = _mm_chunked(gids, max_groups)  # [nch, chunk]
    onehot = (g3[..., None] == jnp.arange(max_groups, dtype=gids.dtype)).astype(
        jnp.int8
    )  # [nch, chunk, G]
    partials = jnp.einsum(
        "ncl,ncg->ngl", x3, onehot, preferred_element_type=jnp.int32
    )
    tot = partials.astype(jnp.int64).sum(axis=0)  # [G, L]
    sums = []
    for (start, nlanes), v in zip(spans, values):
        s = jnp.zeros(max_groups, jnp.int64)
        for k in range(nlanes):
            s = s + (tot[:, start + k] << (_MM_LANE_BITS * k))
        # always int64: a running sum of narrow ints overflows its input
        # dtype long before int64 (SQL types sum(int) as bigint)
        sums.append(s)
    base = len(lane_cols)
    counts = [tot[:, base + slot[i]] for i in range(len(contribs))]
    extra = [
        tot[:, base + slot[len(contribs) + i]]
        for i in range(len(extra_count_masks))
    ]
    return sums, counts, extra, oflow


class ValueBitsOverflow(Exception):
    """A declared AggSpec.value_bits bound was violated at runtime."""


def segment_agg(
    values, contrib, gids, max_groups: int, kind: str, value_bits: int = 63
):
    """Aggregate ``values`` per group.

    contrib: bool mask of rows that contribute (live AND value-valid).
    kind: 'sum' | 'count' | 'min' | 'max'.
    value_bits: static bound on bit-width of |values| (callers with
    typed columns can pass a tighter bound to cut lane passes; 63 is
    always safe for int64).
    Returns array [max_groups] (trash segment sliced off). Integer sums
    come back int64 regardless of input dtype (running sums outgrow
    narrow inputs; SQL types sum(int) as bigint). Groups with no
    contributing rows yield the kind's identity — pair with a count to
    rebuild SQL NULL semantics.
    """
    if max_groups <= SMALL_GROUP_LIMIT:
        return _small_agg(values, contrib, gids, max_groups, kind, value_bits)
    nseg = max_groups + 1
    g = jnp.where(contrib, gids, max_groups)
    if kind == "count":
        return jax.ops.segment_sum(
            contrib.astype(jnp.int64), g, num_segments=nseg
        )[:max_groups]
    if kind == "sum":
        vals = jnp.where(contrib, values, _identity("sum", values.dtype))
        if not jnp.issubdtype(values.dtype, jnp.floating):
            vals = vals.astype(jnp.int64)  # running sums outgrow int32
        return jax.ops.segment_sum(vals, g, num_segments=nseg)[:max_groups]
    if kind == "min":
        vals = jnp.where(contrib, values, _identity("min", values.dtype))
        return jax.ops.segment_min(vals, g, num_segments=nseg)[:max_groups]
    if kind == "max":
        vals = jnp.where(contrib, values, _identity("max", values.dtype))
        return jax.ops.segment_max(vals, g, num_segments=nseg)[:max_groups]
    raise InternalError(f"unknown aggregate kind {kind!r}")
