"""Grouping kernels: row -> group-id assignment + segment aggregation.

Reference parity: ``GroupByHash`` (``BigintGroupByHash`` fast path,
``MultiChannelGroupByHash``) + ``InMemoryHashAggregationBuilder`` /
``GroupedAccumulator`` [SURVEY §2.1, §3.3; reference tree unavailable].

TPU-first (SURVEY §7.1): open-addressing hash tables are
scatter-serialized on TPU, so grouping is

- **direct addressing** when the composite key domain is small and
  known (dictionary codes, bounded ints): gid = bit-packed key. The
  analog of BigintGroupByHash's array-based fast path — Q1's
  returnflag x linestatus lands here, zero sorting.
- **sort-based** otherwise: stable multi-key argsort, adjacent-diff
  boundaries, cumsum group ids — O(n log n) but built entirely from
  TPU-friendly sort/gather/scan primitives.

Aggregation is ``jax.ops.segment_*`` over the group ids with one extra
"trash" segment that absorbs dead rows; outputs have a static
``max_groups`` capacity with an overflow flag (SURVEY §7.4 #1).
"""

from __future__ import annotations

from functools import reduce

import jax
import jax.numpy as jnp
import numpy as np


def gather_padded(arr, idx, fill):
    """arr[idx] with out-of-range idx (>= len) producing ``fill``."""
    cap = arr.shape[0]
    safe = jnp.minimum(idx, cap - 1)
    return jnp.where(idx < cap, arr[safe], fill)


# ---------------------------------------------------------------------------
# group-id assignment
# ---------------------------------------------------------------------------


def group_ids_direct(key_cols, mins, strides, live, num_groups: int):
    """Direct-addressed gids: gid = sum_i (k_i - min_i) * stride_i.

    Caller guarantees the packed domain is exactly ``num_groups``.
    Dead rows get gid == num_groups (the trash segment).
    Returns (gids, rep_valid) where rep_valid[g] marks groups with >=1
    live row.
    """
    gid = None
    for k, m, s in zip(key_cols, mins, strides):
        t = (k.astype(jnp.int32) - np.int32(m)) * np.int32(s)
        gid = t if gid is None else gid + t
    gid = jnp.clip(gid, 0, num_groups - 1)
    gid = jnp.where(live, gid, num_groups)
    present = jnp.zeros(num_groups + 1, dtype=jnp.bool_).at[gid].set(True)[:num_groups]
    return gid, present


def group_ids_sort(key_cols, live, max_groups: int):
    """Sort-based gids for arbitrary keys.

    Returns (gids[cap], rep_idx[max_groups], ngroups, overflow):
    - gids: per-row group id in [0, max_groups) for live rows,
      ``max_groups`` (trash) for dead rows;
    - rep_idx: original row index of each group's first member
      (sentinel ``cap`` for unused slots) — gather key columns through
      it to materialize group keys;
    - overflow: True when distinct live keys exceeded max_groups.
    """
    cap = live.shape[0]
    order = jnp.arange(cap)
    for k in reversed(list(key_cols)):
        order = order[jnp.argsort(k[order], stable=True)]
    # liveness is the most significant key: live rows first
    order = order[jnp.argsort(~live[order], stable=True)]

    sl = live[order]
    diffs = [k[order][1:] != k[order][:-1] for k in key_cols]
    any_diff = reduce(jnp.logical_or, diffs) if diffs else jnp.zeros(cap - 1, bool)
    boundary = any_diff | ~sl[:-1]
    newgrp = jnp.concatenate([sl[:1], boundary & sl[1:]])
    ngroups = jnp.sum(newgrp.astype(jnp.int32))
    gid_sorted = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
    gid_sorted = jnp.where(sl, jnp.minimum(gid_sorted, max_groups), max_groups)
    gids = jnp.zeros(cap, dtype=jnp.int32).at[order].set(gid_sorted)

    rep_sorted = jnp.nonzero(newgrp, size=max_groups, fill_value=cap)[0]
    rep_idx = gather_padded(order, rep_sorted, cap)
    return gids, rep_idx, ngroups, ngroups > max_groups


# ---------------------------------------------------------------------------
# segment aggregation
# ---------------------------------------------------------------------------

_I64_MIN = np.int64(np.iinfo(np.int64).min)
_I64_MAX = np.int64(np.iinfo(np.int64).max)


def _identity(kind: str, dtype):
    if kind == "min":
        return (
            jnp.asarray(np.inf, dtype)
            if jnp.issubdtype(dtype, jnp.floating)
            else jnp.asarray(jnp.iinfo(dtype).max, dtype)
        )
    if kind == "max":
        return (
            jnp.asarray(-np.inf, dtype)
            if jnp.issubdtype(dtype, jnp.floating)
            else jnp.asarray(jnp.iinfo(dtype).min, dtype)
        )
    return jnp.asarray(0, dtype)


def segment_agg(values, contrib, gids, max_groups: int, kind: str):
    """Aggregate ``values`` per group.

    contrib: bool mask of rows that contribute (live AND value-valid).
    kind: 'sum' | 'count' | 'min' | 'max'.
    Returns array [max_groups] (trash segment sliced off). Groups with
    no contributing rows yield the kind's identity — pair with a count
    to rebuild SQL NULL semantics.
    """
    nseg = max_groups + 1
    g = jnp.where(contrib, gids, max_groups)
    if kind == "count":
        return jax.ops.segment_sum(
            contrib.astype(jnp.int64), g, num_segments=nseg
        )[:max_groups]
    if kind == "sum":
        vals = jnp.where(contrib, values, _identity("sum", values.dtype))
        return jax.ops.segment_sum(vals, g, num_segments=nseg)[:max_groups]
    if kind == "min":
        vals = jnp.where(contrib, values, _identity("min", values.dtype))
        return jax.ops.segment_min(vals, g, num_segments=nseg)[:max_groups]
    if kind == "max":
        vals = jnp.where(contrib, values, _identity("max", values.dtype))
        return jax.ops.segment_max(vals, g, num_segments=nseg)[:max_groups]
    raise ValueError(f"unknown aggregate kind {kind!r}")
