"""Vectorized 64-bit key hashing.

Reference parity: ``InterpretedHashGenerator`` / the XxHash64-based
``CombineHashFunction`` used by ``GroupByHash`` and the
``LocalPartitionGenerator`` [SURVEY §2.1; reference tree unavailable].
TPU-first: a splitmix64 finalizer chain over int64 lanes — pure VPU
bit-math, no lookup tables. The same function must be used engine-wide:
partitioned exchanges rely on every device computing identical
partition ids for a key.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def mix64(x):
    """splitmix64 finalizer: uint64 -> uint64, good avalanche."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> np.uint64(30))) * _M1
    x = (x ^ (x >> np.uint64(27))) * _M2
    return x ^ (x >> np.uint64(31))


def hash_columns(columns) -> jnp.ndarray:
    """Combined uint64 hash of one or more key arrays (int-like).

    Combine rule: h = mix(h*GOLDEN ^ mix(col)) — order-sensitive, so
    (a, b) and (b, a) hash differently.
    """
    h = None
    for c in columns:
        hc = mix64(c.astype(jnp.int64).view(jnp.uint64) if c.dtype == jnp.int64 else c.astype(jnp.uint64))
        h = hc if h is None else mix64(h * _GOLDEN ^ hc)
    return h


def partition_ids(columns, num_partitions: int) -> jnp.ndarray:
    """Hash-partition assignment in [0, num_partitions): the exchange's
    row->consumer map (reference: PagePartitioner)."""
    h = hash_columns(columns)
    return (h % np.uint64(num_partitions)).astype(jnp.int32)


_BUCKET_SEED = np.uint64(0xA24BAED4963EE407)


def bucket_ids(columns, num_buckets: int) -> jnp.ndarray:
    """Grouped-execution bucket assignment in [0, num_buckets).

    Applies one extra seeded mix on top of ``hash_columns`` so bucket
    ids are DECORRELATED from ``partition_ids`` over the same key:
    ``h % B`` and ``h % P`` share low-bit structure whenever B and P
    share factors, which would route each bucket's rows onto a subset
    of the mesh during the in-bucket repartition exchange."""
    h = mix64(hash_columns(columns) ^ _BUCKET_SEED)
    return (h % np.uint64(num_buckets)).astype(jnp.int32)
