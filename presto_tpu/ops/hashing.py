"""Vectorized 64-bit key hashing.

Reference parity: ``InterpretedHashGenerator`` / the XxHash64-based
``CombineHashFunction`` used by ``GroupByHash`` and the
``LocalPartitionGenerator`` [SURVEY §2.1; reference tree unavailable].
TPU-first: a splitmix64 finalizer chain over int64 lanes — pure VPU
bit-math, no lookup tables. The same function must be used engine-wide:
partitioned exchanges rely on every device computing identical
partition ids for a key.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def mix64(x):
    """splitmix64 finalizer: uint64 -> uint64, good avalanche."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> np.uint64(30))) * _M1
    x = (x ^ (x >> np.uint64(27))) * _M2
    return x ^ (x >> np.uint64(31))


def hash_columns(columns) -> jnp.ndarray:
    """Combined uint64 hash of one or more key arrays (int-like).

    Combine rule: h = mix(h*GOLDEN ^ mix(col)) — order-sensitive, so
    (a, b) and (b, a) hash differently.
    """
    h = None
    for c in columns:
        hc = mix64(c.astype(jnp.int64).view(jnp.uint64) if c.dtype == jnp.int64 else c.astype(jnp.uint64))
        h = hc if h is None else mix64(h * _GOLDEN ^ hc)
    return h


def partition_ids(columns, num_partitions: int) -> jnp.ndarray:
    """Hash-partition assignment in [0, num_partitions): the exchange's
    row->consumer map (reference: PagePartitioner)."""
    h = hash_columns(columns)
    return (h % np.uint64(num_partitions)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# 32-bit mixing for the join-sketch / runtime-filter Bloom bitmasks.
# Everything below must trace under BOTH XLA and Mosaic (Pallas): int32
# arithmetic only, arithmetic shifts masked back to logical, np.int32
# literals (weak Python ints trace as i64 scalars Mosaic rejects — see
# ops/pallas_groupby.py). Build (XLA scatter) and probe (in-kernel)
# MUST use the same functions or bits and tests would disagree.
# ---------------------------------------------------------------------------

_M32A = np.int32(np.uint32(0x85EBCA6B).view(np.int32))
_M32B = np.int32(np.uint32(0xC2B2AE35).view(np.int32))
#: second-hash input perturbation for the two-bit Bloom
SKETCH_SEED = np.int32(np.uint32(0x9E3779B9).view(np.int32))


def mix32(x):
    """murmur3 finalizer on int32 lanes (wrapping int32 multiplies;
    logical shifts emulated as arithmetic-shift-then-mask). Keys wider
    than 32 bits are truncated first — fine for membership sketches
    (an aliased wide key can only add a false positive)."""
    x = x.astype(jnp.int32)
    x = x ^ ((x >> np.int32(16)) & np.int32(0xFFFF))
    x = x * _M32A
    x = x ^ ((x >> np.int32(13)) & np.int32((1 << 19) - 1))
    x = x * _M32B
    return x ^ ((x >> np.int32(16)) & np.int32(0xFFFF))


def mix32_slots(keys, nbits: int):
    """The two Bloom bit slots of each key in [0, nbits); ``nbits``
    must be a power of two (the mask keeps slots non-negative)."""
    assert nbits & (nbits - 1) == 0, "nbits must be a power of two"
    mask = np.int32(nbits - 1)
    k = keys.astype(jnp.int32)
    return mix32(k) & mask, mix32(k ^ SKETCH_SEED) & mask


def bloom_build(keys, live, nbits: int):
    """[nbits/32] int32 packed two-hash Bloom words over the live keys
    (XLA side: the runtime-join-filter build product). Bit packing
    goes through a byte-per-bit scatter so duplicate keys OR cleanly."""
    s1, s2 = mix32_slots(keys, nbits)
    p = jnp.zeros(nbits, jnp.int8)
    p = p.at[jnp.where(live, s1, nbits)].set(1, mode="drop")
    p = p.at[jnp.where(live, s2, nbits)].set(1, mode="drop")
    p = p.reshape(nbits // 32, 32).astype(jnp.int64)
    return (p << jnp.arange(32, dtype=jnp.int64)).sum(
        axis=1, dtype=jnp.int64).astype(jnp.int32)


def bloom_test(words, keys):
    """bool [n]: Bloom membership (false positives possible, never
    false negatives). ``words`` from ``bloom_build``."""
    nbits = words.shape[0] * 32
    s1, s2 = mix32_slots(keys, nbits)

    def bit(s):
        w = words[(s >> np.int32(5)).astype(jnp.int32)]
        return ((w >> (s & np.int32(31))) & np.int32(1)) != 0

    return bit(s1) & bit(s2)


_BUCKET_SEED = np.uint64(0xA24BAED4963EE407)


def bucket_ids(columns, num_buckets: int) -> jnp.ndarray:
    """Grouped-execution bucket assignment in [0, num_buckets).

    Applies one extra seeded mix on top of ``hash_columns`` so bucket
    ids are DECORRELATED from ``partition_ids`` over the same key:
    ``h % B`` and ``h % P`` share low-bit structure whenever B and P
    share factors, which would route each bucket's rows onto a subset
    of the mesh during the in-bucket repartition exchange."""
    h = mix64(hash_columns(columns) ^ _BUCKET_SEED)
    return (h % np.uint64(num_buckets)).astype(jnp.int32)
