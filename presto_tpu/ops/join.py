"""Join kernels: sorted build + vectorized binary-search probe.

Reference parity: ``HashBuilderOperator`` (``PagesIndex``/``PagesHash``)
and ``LookupJoinOperator`` (compiled ``JoinProbe``) [SURVEY §2.1, §3.4;
reference tree unavailable].

TPU-first (SURVEY §7.1): the "hash table" is a *sorted key array* —
build compacts live rows and sorts them by key; probe is
``searchsorted(method="sort")``, i.e. sort-merge: the probe keys are
sorted and merged against the build keys (binary-search probing is
~17x slower on TPU — its log2(B) dependent gathers serialize, while
sorts ride the native sort unit; measured in notes/PERF.md).
Duplicate build keys are handled by (lo, hi) range probes plus a
prefix-sum expansion with a static output capacity and an overflow
flag. FK->PK joins (unique build keys: most TPC-H joins) take the
1-gather fast path.

Composite keys are packed into one int64 when the domains allow
(planner guarantees it via connector stats); otherwise pre-hashed with
collision verification on the payload equality mask.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from presto_tpu.ops.groupby import gather_padded


class BuildSide(NamedTuple):
    """A sorted, compacted build side (the 'LookupSource')."""

    sorted_keys: jnp.ndarray  # [build_cap] int64, dead slots = I64_MAX
    row_idx: jnp.ndarray  # [build_cap] original row index (cap = dead)
    n_rows: jnp.ndarray  # traced scalar
    overflow: jnp.ndarray  # traced bool
    #: a LIVE build key equals the reserved I64_MAX dead-slot sentinel:
    #: such a row is indistinguishable from a dead slot, so its matches
    #: would silently vanish — builders surface this flag and the host
    #: refuses loudly instead (bytes_hash already avoids the sentinel
    #: by construction; this guards plain integer keys)
    sentinel_hit: jnp.ndarray
    #: (key << pack_bits) | row packed int64, key-sorted, dead = I64_MAX
    #: — present when the planner proved key_bits + pack_bits <= 62
    #: (non-negative keys); the unique probe then needs ONE gather per
    #: probe row instead of two (key check + row fetch). [SURVEY §6
    #: BenchmarkHashBuildAndJoinOperators analog; VERDICT r4 ask #4]
    packed: jnp.ndarray | None = None


_I64_MAX = np.int64(np.iinfo(np.int64).max)


def build_lookup(keys, live, build_capacity: int,
                 pack_bits: int | None = None) -> BuildSide:
    """Compact live rows and sort them by key.

    ``pack_bits``: when the caller proves 0 <= key < 2^(62 - pack_bits)
    and capacity <= 2^pack_bits, rows sort as ONE packed
    (key << pack_bits | row) int64 — the sort needs no payload gathers
    and the unique probe one gather total. Violating keys fall back
    safely: they set ``sentinel_hit`` (checked by every builder host-
    side) rather than mispacking.
    """
    cap = keys.shape[0]
    k0 = keys.astype(jnp.int64)
    if pack_bits is not None:
        bad = (k0 < 0) | (k0 >= (np.int64(1) << np.int64(62 - pack_bits)))
        sentinel_hit = jnp.any(live & bad)
        packed = jnp.where(
            live & ~bad,
            (k0 << np.int64(pack_bits)) | jnp.arange(cap, dtype=jnp.int64),
            _I64_MAX,
        )
        sp = jnp.sort(packed)[:build_capacity]
        if build_capacity > cap:
            sp = jnp.concatenate(
                [sp, jnp.full(build_capacity - cap, _I64_MAX)])
        dead = sp == _I64_MAX
        sorted_keys = jnp.where(dead, _I64_MAX, sp >> np.int64(pack_bits))
        mask = (np.int64(1) << np.int64(pack_bits)) - np.int64(1)
        row_idx = jnp.where(dead, cap, (sp & mask).astype(jnp.int32))
        n_live = jnp.sum(live.astype(jnp.int32))
        return BuildSide(sorted_keys, row_idx, n_live,
                         n_live > build_capacity, sentinel_hit, sp)
    sentinel_hit = jnp.any(live & (k0 == _I64_MAX))
    k = jnp.where(live, k0, _I64_MAX)
    order = jnp.argsort(k, stable=True)
    sk = k[order]
    # take the first build_capacity sorted slots (live rows sort first,
    # dead rows carry the sentinel key)
    take = jnp.arange(build_capacity)
    sorted_keys = gather_padded(sk, take, _I64_MAX)
    row_idx = gather_padded(order, take, cap)
    row_idx = jnp.where(sorted_keys == _I64_MAX, cap, row_idx)
    n_live = jnp.sum(live.astype(jnp.int32))
    return BuildSide(sorted_keys, row_idx, n_live, n_live > build_capacity,
                     sentinel_hit)


class UniqueProbe(NamedTuple):
    build_row: jnp.ndarray  # [probe_cap] build-side original row idx (cap = miss)
    matched: jnp.ndarray  # [probe_cap] bool


def probe_unique(build: BuildSide, probe_keys, probe_live,
                 pack_bits: int | None = None) -> UniqueProbe:
    """FK->PK probe: each probe row matches <= 1 build row.

    Output is aligned with the probe batch (no expansion): the join
    operator gathers build payload columns through ``build_row`` and
    ANDs ``matched`` into the live mask (inner) or into validity
    (left outer). With a packed build (``pack_bits``), key check and
    row fetch ride ONE latency-bound gather instead of two.
    """
    pk = probe_keys.astype(jnp.int64)
    if pack_bits is not None and build.packed is not None:
        target = pk << np.int64(pack_bits)
        pos = jnp.searchsorted(build.packed, target, side="left",
                               method="sort")
        hit = gather_padded(build.packed, pos, _I64_MAX)
        in_range = (pk >= 0) & (pk < (np.int64(1) << np.int64(62 - pack_bits)))
        matched = ((hit >> np.int64(pack_bits)) == pk) & probe_live & (
            hit != _I64_MAX) & in_range
        mask = (np.int64(1) << np.int64(pack_bits)) - np.int64(1)
        build_row = jnp.where(matched, (hit & mask).astype(jnp.int32),
                              build.row_idx.shape[0])
        return UniqueProbe(build_row, matched)
    pos = jnp.searchsorted(build.sorted_keys, pk, method="sort")
    hit_key = gather_padded(build.sorted_keys, pos, _I64_MAX)
    matched = (hit_key == pk) & probe_live & (pk != _I64_MAX)
    build_row = jnp.where(matched, gather_padded(build.row_idx, pos, 0), build.row_idx.shape[0])
    return UniqueProbe(build_row, matched)


class ExpandedProbe(NamedTuple):
    probe_row: jnp.ndarray  # [out_cap] probe-side row idx (sentinel probe_cap)
    build_row: jnp.ndarray  # [out_cap] build-side original row idx
    live: jnp.ndarray  # [out_cap]
    n_out: jnp.ndarray  # traced scalar
    overflow: jnp.ndarray  # traced bool


def probe_expand(
    build: BuildSide, probe_keys, probe_live, out_capacity: int,
    left: bool = False, emit_live=None,
) -> ExpandedProbe:
    """General join probe with duplicate build keys.

    For each probe row: match range [lo, hi) in the sorted build keys;
    outputs one row per (probe, build-match) pair, laid out by a
    prefix-sum expansion into a static out_capacity. With ``left=True``
    (probe-outer), match-less probe rows emit one row whose build_row is
    the miss sentinel (build payload gathers yield invalid/null).

    ``emit_live`` (left only): rows that must emit a null-extended
    output row even though their key cannot match — a live probe row
    with a NULL join key is excluded from ``probe_live`` (NULL matches
    nothing) but still appears in a LEFT/FULL OUTER result. Defaults
    to ``probe_live``.
    """
    probe_cap = probe_keys.shape[0]
    pk = jnp.where(probe_live, probe_keys.astype(jnp.int64), _I64_MAX)
    lo = jnp.searchsorted(build.sorted_keys, pk, side="left", method="sort")
    hi = jnp.searchsorted(build.sorted_keys, pk, side="right", method="sort")
    matches = jnp.where(probe_live & (pk != _I64_MAX), hi - lo, 0)
    el = probe_live if emit_live is None else emit_live
    counts = jnp.where(el & (matches == 0), 1, matches) if left else matches
    offsets = jnp.cumsum(counts) - counts  # exclusive prefix
    total = jnp.sum(counts)

    j = jnp.arange(out_capacity)
    # probe row owning output slot j: last i with offsets[i] <= j
    probe_row = jnp.searchsorted(offsets, j, side="right", method="sort") - 1
    probe_row = jnp.clip(probe_row, 0, probe_cap - 1)
    rank = j - offsets[probe_row]
    valid = (j < total) & (rank >= 0) & (rank < counts[probe_row])
    is_match = valid & (rank < matches[probe_row])
    bpos = lo[probe_row] + rank
    build_row = jnp.where(
        is_match, gather_padded(build.row_idx, bpos, 0), build.row_idx.shape[0]
    )
    probe_row = jnp.where(valid, probe_row, probe_cap)
    return ExpandedProbe(probe_row, build_row, valid, total, total > out_capacity)


# ---------------------------------------------------------------------------
# Dense-domain direct lookup: when connector stats bound the build key
# domain [key_min, key_min + domain), the "hash table" is a dense
# row-index array — probe is ONE gather (no probe-side sort at all).
# The TPU trade: one build-time scatter (build side is the small side)
# buys gather-only probes; measured on the sorted path, probe cost was
# dominated by the probe sort + two gathers (notes/PERF.md §5).
# ---------------------------------------------------------------------------


class DenseSide(NamedTuple):
    """Dense direct-address lookup table over a bounded key domain."""

    table: jnp.ndarray  # [domain] int32: build row idx, sentinel = miss
    key_min: jnp.ndarray  # 0-d int64
    sentinel: jnp.ndarray  # 0-d int32 (the build batch capacity)
    n_rows: jnp.ndarray  # traced scalar
    overflow: jnp.ndarray  # traced bool: a live key fell outside the domain


def build_dense(keys, live, key_min: int, domain: int) -> DenseSide:
    """One scatter builds the table; duplicate keys keep one row
    (callers must only use the row payload when build keys are unique —
    existence tests are correct regardless)."""
    cap = keys.shape[0]
    k = keys.astype(jnp.int64)
    slot = k - jnp.int64(key_min)
    in_range = (slot >= 0) & (slot < domain)
    ok = live & in_range
    table = (
        jnp.full(domain, cap, jnp.int32)
        .at[jnp.where(ok, slot, domain)]
        .set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
    )
    oob = jnp.any(live & ~in_range)
    return DenseSide(
        table,
        jnp.asarray(key_min, jnp.int64),
        jnp.asarray(cap, jnp.int32),
        jnp.sum(live.astype(jnp.int32)),
        oob,
    )


def probe_unique_dense(dense: DenseSide, probe_keys, probe_live) -> UniqueProbe:
    """FK->PK probe against a dense table: one gather, no sort.

    The gather index is int32: the table materialized, so domain <
    2^31, and int64 indices measurably slow the TPU gather (~12% on
    the 60M-row Q3 probe — notes/perf_q3_r5.py; the gather itself is
    the wall at ~11 ns/element regardless of table size)."""
    domain = dense.table.shape[0]
    assert domain < (1 << 31), "dense domain must fit int32 gather indices"
    slot = probe_keys.astype(jnp.int64) - dense.key_min
    inr = (slot >= 0) & (slot < domain) & probe_live
    idx = jnp.clip(slot, 0, domain - 1).astype(jnp.int32)
    row = jnp.where(inr, dense.table[idx], dense.sentinel)
    matched = row != dense.sentinel
    return UniqueProbe(jnp.where(matched, row, dense.sentinel), matched)


def probe_exists_dense(dense: DenseSide, probe_keys, probe_live):
    """Semi-join membership via the dense table (duplicate-safe)."""
    return probe_unique_dense(dense, probe_keys, probe_live).matched


def probe_exists(build: BuildSide, probe_keys, probe_live):
    """Semi-join membership: True where the probe key exists in build.
    (reference: SetBuilderOperator / HashSemiJoinOperator)."""
    pk = probe_keys.astype(jnp.int64)
    pos = jnp.searchsorted(build.sorted_keys, pk, method="sort")
    hit_key = gather_padded(build.sorted_keys, pos, _I64_MAX)
    return (hit_key == pk) & probe_live & (pk != _I64_MAX)


def pack_key_columns(cols, bit_widths):
    """Bit-pack multiple bounded-domain int key columns into one int64.

    ``bit_widths[i]`` must satisfy sum <= 63 and col_i in [0, 2^w_i)
    (the planner normalizes by subtracting mins first).
    """
    assert sum(bit_widths) <= 63, "packed key exceeds 63 bits"
    out = None
    for c, w in zip(cols, bit_widths):
        c = c.astype(jnp.int64)
        out = c if out is None else (out << np.int64(w)) | c
    return out
