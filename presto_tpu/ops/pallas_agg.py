"""Parameterized fused leaf-aggregation kernel family.

The ``ops/pallas_q1`` trick, generalized: a scan -> filter ->
partial-agg leaf fragment over narrowed, NULL-free columns runs as ONE
Pallas pass — predicate (interval tests), flat group id (k small key
domains packed by stride), derived decimal products, the signed 8-bit
lane split, and the per-(group, lane) partial sums all in VMEM and
registers, touching each input byte exactly once. ``ops/pallas_q1``
remains the hand-built specialization of this family (its 3-factor
``charge`` product is outside the 2-term grammar here); everything the
grammar covers — TPC-H Q6, the SSB Q1 flight, CTAS-narrowed GROUP BYs —
is lowered through :func:`agg_step` instead of a bespoke kernel.

The fragment is described by a static :class:`LeafAggSpec`:

- ``filters``: closed physical intervals per column (``lo <= c <= hi``;
  one-sided allowed) — the executor's planner converts every admitted
  comparison/BETWEEN conjunct into this form *in the column's own
  physical scale*, so the in-kernel test is exact integer comparison.
- ``keys``: ``gid = sum_i (c_i - lo_i) * stride_i`` over small declared
  domains (dictionary codes or stats-bounded ints); ``groups == 1``
  with no keys is the keyless/global specialization (TPC-H Q6 shape).
- ``values``: per aggregate, a product of at most two *linear terms*
  ``c0 + c1 * col`` over physical int values, with a declared |value|
  bit bound. Admission (exec/leaf_route.py) proves from the declared
  column intervals that every in-range product fits int32, the same
  int32-exactness discipline as pallas_q1's proof block.
- ``guards``: the declared column intervals themselves. A live row
  outside its declared interval is flagged (``value_overflow``) and the
  caller falls back to the generic operator route — advisory stats can
  cost a recompile/re-run, never a wrong answer. Out-of-domain KEY
  codes are guarded the same way: gid is neither clipped nor
  range-checked in-kernel (a wild code would silently vanish from
  every group), so the guard flags it loudly instead.

Exactness: every slot sums a signed 8-bit lane over <= 2^23 rows per
output major (255 * 2^23 < 2^31), majors recombine in int64 outside —
the scaffolding (``rsum32``, ``emit_slots``, ``slots_pallas_call``) is
shared with ops/pallas_groupby.py, which documents each Mosaic/x64
workaround. Off-TPU (and for fragments with min/max aggregates, which
need non-additive cross-block accumulation) the SAME spec executes as
one fused XLA step built on ``fused_small_sums``/``segment_agg`` —
bit-identical by integer exactness, so routed results never depend on
which backend fired.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from presto_tpu.ops.pallas_groupby import (
    _I0,
    _SLOTS,
    _VMEM_BUDGET,
    emit_slots,
    rsum32,
    slots_pallas_call,
)

#: slot budget: groups * (total value lanes + 1 count) + 1 overflow
#: must fit the shared (1, 1, 1024) output tile
MAX_GROUPS = 512


@dataclass(frozen=True)
class Term:
    """One linear term ``c0 + c1 * col`` over a column's physical
    values (``col == -1``: the constant ``c0``)."""

    col: int
    c0: int = 0
    c1: int = 1


@dataclass(frozen=True)
class ValueAgg:
    """One aggregate over a derived value: ``op`` in sum|min|max,
    value = ``a`` or ``a * b``, |value| < 2^bits proven by admission."""

    op: str
    a: Term
    b: Optional[Term] = None
    bits: int = 31


@dataclass(frozen=True)
class LeafAggSpec:
    """Static description of one scan->filter->partial-agg fragment."""

    cols: tuple[str, ...]
    #: (col index, lo|None, hi|None) closed physical bounds
    filters: tuple[tuple[int, Optional[int], Optional[int]], ...]
    #: (col index, domain lo, stride); gid = sum (c - lo) * stride
    keys: tuple[tuple[int, int, int], ...]
    groups: int
    values: tuple[ValueAgg, ...]
    #: (col index, declared lo, declared hi) — violation flags loudly
    guards: tuple[tuple[int, int, int], ...]

    @property
    def nlanes(self) -> tuple[int, ...]:
        return tuple(max(1, -(-min(v.bits, 31) // 8)) for v in self.values)


def state_keys(spec: LeafAggSpec) -> list[str]:
    """The value-state keys of :func:`agg_step`'s output, in
    ``spec.values`` order (``{op}_{i}``)."""
    return [f"{v.op}_{i}" for i, v in enumerate(spec.values)]


# ---------------------------------------------------------------------------
# eligibility
# ---------------------------------------------------------------------------


def _row_bytes(spec: LeafAggSpec) -> int:
    """Conservative per-row scoped-VMEM estimate: double-buffered
    narrow inputs (counted at 4 B worst case) + int32 lane arrays +
    int32 temporaries (gid, live, per-value mag/neg)."""
    nl_total = sum(spec.nlanes)
    n_in = len(spec.cols) + 1  # + live mask
    return 2 * 4 * n_in + 4 * (nl_total + 2) + 8 * max(len(spec.values), 1)


def _block_rows(spec: LeafAggSpec, cap: int) -> int | None:
    per_row = _row_bytes(spec)
    for b in (1 << 17, 1 << 16):
        if cap % b == 0 and b * per_row <= _VMEM_BUDGET:
            return b
    return None


def _num_slots(spec: LeafAggSpec) -> int:
    return spec.groups * (sum(spec.nlanes) + 1) + 1


def kernel_supported(spec: LeafAggSpec, batch, cap: int | None = None) -> bool:
    """Static Pallas eligibility for this (spec, batch): sum-only
    aggregates with int32-provable bounds, narrow integer columns that
    are NULL-free over live rows (validity shares the live mask — the
    ``Batch.from_numpy`` identity pallas_q1.supported also keys on),
    aligned capacity, slots within the output tile.

    MUST be evaluated on a CONCRETE batch, never inside a jit trace:
    pytree flattening gives ``live`` and each ``valid`` distinct tracer
    objects, so the shared-mask identity check always fails in-trace
    (callers hoist the decision and bake it into the built step via
    ``agg_step(..., pallas_ok=)``). ``cap``: capacity override for
    sharded execution, where the per-device block is ``capacity / n``."""
    if any(v.op != "sum" for v in spec.values):
        return False
    if any(v.bits > 31 for v in spec.values):
        return False
    if spec.groups > MAX_GROUPS or _num_slots(spec) > _SLOTS:
        return False
    for c in spec.cols:
        if c not in batch.columns:
            return False
        col = batch[c]
        dt = col.data.dtype
        if not (jnp.issubdtype(dt, jnp.integer) and jnp.iinfo(dt).bits <= 32):
            return False
        if col.valid is not None and col.valid is not batch.live:
            return False
    return _block_rows(spec, cap if cap is not None else batch.capacity) \
        is not None


# ---------------------------------------------------------------------------
# the Pallas kernel
# ---------------------------------------------------------------------------


def _kernel(spec: LeafAggSpec, spm, *refs):
    """Grid body: refs = [col_0..col_{n-1}, live, out]."""
    i = pl.program_id(0)
    zero = _I0
    cols = [r[...].astype(jnp.int32) for r in refs[: len(spec.cols)]]
    live = refs[len(spec.cols)][...] != 0
    o_ref = refs[-1]

    for ci, lo, hi in spec.filters:
        c = cols[ci]
        if lo is not None:
            live = live & (c >= np.int32(lo))
        if hi is not None:
            live = live & (c <= np.int32(hi))

    G = np.int32(spec.groups)
    gid = jnp.zeros_like(cols[0]) if not spec.keys else None
    for ci, lo, stride in spec.keys:
        t = (cols[ci] - np.int32(lo)) * np.int32(stride)
        gid = t if gid is None else gid + t
    gid = jnp.where(live, gid, G)

    # declared-bounds guard (advisory stats' runtime check): a live row
    # outside its declared interval could wrap the int32 products the
    # admission proof relies on — flag, never risk a silent wrap
    badrow = jnp.zeros_like(cols[0])
    for ci, lo, hi in spec.guards:
        c = cols[ci]
        badrow = badrow | ((c < np.int32(lo)) | (c > np.int32(hi))).astype(
            jnp.int32)

    def term(t: Term):
        if t.col < 0:
            return jnp.full_like(cols[0], np.int32(t.c0))
        v = cols[t.col]
        if t.c1 != 1:
            v = v * np.int32(t.c1)
        if t.c0 != 0:
            v = np.int32(t.c0) + v
        return v

    lanes = []
    for v in spec.values:
        val = term(v.a)
        if v.b is not None:
            val = val * term(v.b)
        val = jnp.where(live, val, zero)
        neg = val < 0
        mag = jnp.abs(val)
        bits = min(v.bits, 31)
        if bits < 31:
            badrow = badrow | ((mag >> np.int32(bits)) != 0).astype(jnp.int32)
        for k in range(max(1, -(-bits // 8))):
            lane = (mag >> np.int32(8 * k)) & np.int32(255)
            lanes.append(jnp.where(neg, -lane, lane))

    scalars = []
    for g in range(spec.groups):
        m = gid == np.int32(g)
        for lane in lanes:
            scalars.append(rsum32(jnp.where(m, lane, zero)))
        scalars.append(rsum32(m.astype(jnp.int32)))
    scalars.append(rsum32(jnp.where(live, badrow, zero)))
    emit_slots(o_ref, i, spm, scalars)


def _pallas_step(spec: LeafAggSpec, batch, interpret: bool | None = None):
    from functools import partial

    cap = batch.capacity
    B = _block_rows(spec, cap)
    args = [batch[c].data for c in spec.cols]
    args.append(batch.live.astype(jnp.int8))
    o = slots_pallas_call(
        partial(_kernel, spec), args, cap, B,
        interpret=(jax.default_backend() != "tpu"
                   if interpret is None else interpret))
    G = spec.groups
    nl = spec.nlanes
    per_g = o[: G * (sum(nl) + 1)].reshape(G, sum(nl) + 1)
    res = {}
    idx = 0
    for key, n in zip(state_keys(spec), nl):
        s = jnp.zeros(G, jnp.int64)
        for k in range(n):
            s = s + (per_g[:, idx + k] << (8 * k))
        res[key] = s
        idx += n
    res["count"] = per_g[:, sum(nl)].astype(jnp.int64)
    res["present"] = res["count"] > 0
    res["value_overflow"] = o[G * (sum(nl) + 1)] != 0
    return res


# ---------------------------------------------------------------------------
# the XLA twin (off-TPU, and fragments with min/max aggregates)
# ---------------------------------------------------------------------------


def _xla_step(spec: LeafAggSpec, batch):
    """The same fragment as one fused XLA computation: exact integer
    results, so Pallas/XLA agree bit-for-bit wherever both fire."""
    from presto_tpu.ops.groupby import fused_small_sums, segment_agg

    cols = [batch[c].data for c in spec.cols]
    live = batch.live
    for ci, lo, hi in spec.filters:
        c = cols[ci].astype(jnp.int64)
        if lo is not None:
            live = live & (c >= lo)
        if hi is not None:
            live = live & (c <= hi)
    oflow = jnp.zeros((), jnp.bool_)
    for ci, lo, hi in spec.guards:
        c = cols[ci].astype(jnp.int64)
        oflow = oflow | jnp.any(live & ((c < lo) | (c > hi)))
    gid = jnp.zeros(batch.capacity, jnp.int32)
    for ci, lo, stride in spec.keys:
        gid = gid + (cols[ci].astype(jnp.int32) - np.int32(lo)) * np.int32(
            stride)
    gid = jnp.where(live, gid, np.int32(spec.groups))

    def value(v: ValueAgg):
        def term(t: Term):
            if t.col < 0:
                return jnp.full(batch.capacity, t.c0, jnp.int64)
            return t.c0 + t.c1 * cols[t.col].astype(jnp.int64)

        val = term(v.a)
        if v.b is not None:
            val = val * term(v.b)
        return val

    res: dict = {}
    sums = [(i, v) for i, v in enumerate(spec.values) if v.op == "sum"]
    minmax = [(i, v) for i, v in enumerate(spec.values) if v.op != "sum"]
    keys = state_keys(spec)
    if sums:
        svals, _scounts, extra, s_oflow = fused_small_sums(
            [value(v) for _i, v in sums],
            [min(v.bits, 63) for _i, v in sums],
            [live] * len(sums),
            gid,
            spec.groups,
            extra_count_masks=[live],
        )
        for (i, _v), s in zip(sums, svals):
            res[keys[i]] = s
        res["count"] = extra[0]
        oflow = oflow | s_oflow
    else:
        res["count"] = segment_agg(
            jnp.ones(batch.capacity, jnp.int64), live, gid, spec.groups,
            "count")
    for i, v in minmax:
        res[keys[i]] = segment_agg(value(v), live, gid, spec.groups, v.op)
    res["present"] = res["count"] > 0
    res["value_overflow"] = oflow
    return res


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def agg_step(spec: LeafAggSpec, batch, pallas_ok: bool | None = None):
    """One fused partial-aggregation step over ``batch``: the Pallas
    kernel on TPU when eligible (sum-only, narrow NULL-free columns,
    aligned capacity, compile probe green), the fused XLA twin
    otherwise. Returns a dict of [groups] states: one ``{op}_{i}`` per
    value aggregate, ``count`` (live rows per group), ``present``, and
    the ``value_overflow`` flag callers MUST honor by falling back.

    ``pallas_ok``: the hoisted eligibility decision (see
    :func:`pallas_eligible`). Callers tracing this inside jit/shard_map
    MUST pass it — the default in-line check is only sound on concrete
    batches (tracer identity breaks the shared-mask test)."""
    if pallas_ok is None:
        pallas_ok = pallas_eligible(spec, batch)
    if pallas_ok:
        return _pallas_step(spec, batch)
    return _xla_step(spec, batch)


def null_violation(batch):
    """Traced scalar: any live NULL in any column of ``batch`` — the
    runtime check of the DECLARED NULL-freedom every routed column
    admits on. Identity checks (``valid is live``) do not survive jit
    flattening and the Pallas kernel never sees validity masks, so
    this device-computed reduction is the ONE guard; callers fold it
    into ``value_overflow`` (lying stats fall back loudly, never
    aggregate NULL slots' fill values)."""
    bad = jnp.zeros((), jnp.bool_)
    for col in batch.columns.values():
        if col.valid is not None:
            bad = bad | jnp.any(batch.live & ~col.valid)
    return bad


def pallas_eligible(spec: LeafAggSpec, batch, cap: int | None = None) -> bool:
    """The full hoisted Pallas decision for a CONCRETE batch: toggle,
    backend, static spec/batch eligibility, and the compile probe.
    ``cap``: per-device capacity for sharded execution."""
    from presto_tpu.ops.strings import use_pallas

    return (use_pallas() and jax.default_backend() == "tpu"
            and kernel_supported(spec, batch, cap)
            and probe_supported(spec,
                                cap if cap is not None else batch.capacity))


def combine_states(spec: LeafAggSpec, a: dict, b: dict) -> dict:
    """Fold two split states (sums/counts add, min/max reduce, flags
    OR) — the cross-split merge of the streamed scan loop."""
    out = {}
    for key in state_keys(spec):
        if key.startswith("min"):
            out[key] = jnp.minimum(a[key], b[key])
        elif key.startswith("max"):
            out[key] = jnp.maximum(a[key], b[key])
        else:
            out[key] = a[key] + b[key]
    out["count"] = a["count"] + b["count"]
    out["present"] = a["present"] | b["present"]
    out["value_overflow"] = a["value_overflow"] | b["value_overflow"]
    return out


# -- compile probe (contract shared with ops.pallas_groupby's): the
# remote Mosaic helper can reject valid programs; callers fall back to
# the XLA twin visibly, never silently -------------------------------------

_PROBE: dict = {}


def probe_supported(spec: LeafAggSpec, cap: int) -> bool:
    if jax.default_backend() != "tpu":
        return True
    B = _block_rows(spec, cap)
    if B is None:
        return False
    key = (spec, B)
    if key not in _PROBE:
        try:
            from presto_tpu.batch import Batch, Column
            from presto_tpu.types import BIGINT

            c = 2 * B  # two blocks: the accumulate branch compiles too
            cols = {name: Column(jnp.ones(c, jnp.int32), None, BIGINT)
                    for name in spec.cols}
            bt = Batch(cols, jnp.ones(c, jnp.bool_))
            jax.block_until_ready(_pallas_step(spec, bt))
            _PROBE[key] = True
        except Exception as e:  # noqa: BLE001 — fallback must be visible
            import logging

            logging.getLogger(__name__).warning(
                "pallas leaf-agg kernel probe failed (falling back to the "
                "fused XLA step): %s: %s", type(e).__name__, e)
            _PROBE[key] = False
    return _PROBE[key]
