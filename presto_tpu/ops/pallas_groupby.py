"""Fused small-group segment sums as a single-pass Pallas kernel.

Reference parity: the hot loop of ``InMemoryHashAggregationBuilder``
for tiny group counts (Q1's 6 groups) [SURVEY §2.1, §6]. The XLA path
(``ops.groupby.fused_small_sums``) packs 8-bit lanes into an [rows, L]
int8 matrix and contracts it against a one-hot matrix on the MXU — but
the lane matrix + one-hot materialization costs ~6 HBM round trips
(measured round 5: 73 ms for 60M rows where the read floor is ~16 ms).

This kernel does the whole thing in ONE pass: a sequential grid over
row blocks loads the int32 value columns once, splits signed 8-bit
lanes in registers, and accumulates per-(lane, group) partial sums into
a [128-slot] int32 vector in VMEM. Exactness: every output slot sums
|lane| <= 255 over at most 2^23 rows per output *major* (255 * 2^23 <
2^31), majors recombine in int64 outside the kernel. The f32-reciprocal
trick is NOT needed here — callers pass precomputed int32 values.

Eligibility (callers check ``supported(...)``): integer values whose
declared |value| bit bound <= 31 (fits int32), slot count <= 1024, and
capacity divisible by 2^16 (the groupby lane-chunk, which put_table and
the executors already align to).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.runtime.errors import InternalError
from jax.experimental import pallas as pl

LANE_BITS = 8
_MAJOR_ROWS = 1 << 23  # 255 * 2^23 < 2^31: int32-exact per major
_SLOTS = 1024  # [8, 128] int32 output tile per major
_I0 = np.int32(0)  # int32 index-map constant (x64: bare 0 would be i64)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _nlanes(bits: int) -> int:
    return max(1, -(-min(bits, 31) // LANE_BITS))


_VMEM_BUDGET = 14 << 20  # scoped VMEM is 16M; leave headroom


def _vmem_row_bytes(nl_total: int, nval: int, nmask: int) -> int:
    """Per-row scoped-VMEM estimate: double-buffered input blocks plus
    the int32 lane/mask intermediates the kernel materializes (measured
    on v5e: a 13-lane block came to ~88 B/row; a 2^18 block OOM'd the
    16M scoped limit)."""
    in_bytes = 4 * nval + nmask + 4  # int32 values, int8 masks, gid
    return 2 * in_bytes + 4 * (nl_total + nmask) + 8


def _block_rows(cap: int, nl_total: int = 13, nval: int = 4,
                nmask: int = 1) -> int | None:
    per_row = _vmem_row_bytes(nl_total, nval, nmask)
    for b in (1 << 18, 1 << 17, 1 << 16):
        if cap % b == 0 and b * per_row <= _VMEM_BUDGET:
            return b
    return None


def supported(bits_list, num_slots: int, cap: int,
              nval: int = 4, nmask: int = 1) -> bool:
    """Static eligibility for the fused kernel."""
    nl_total = sum(_nlanes(b) for b in bits_list)
    return (
        all(b <= 31 for b in bits_list)
        and num_slots <= _SLOTS
        and _block_rows(cap, nl_total, nval, nmask) is not None
    )


# ---------------------------------------------------------------------------
# Shared Mosaic/x64 scaffolding, used by this kernel and ops.pallas_q1.
# Each workaround here was found on the live chip: weak Python-int
# literals trace as i64 scalars whose rank-0 converts infinitely
# recurse Mosaic's _convert_helper; jnp.sum to a scalar re-enters
# jnp.sum without the dtype pin and promotes int32 -> int64; index
# maps returning bare 0 emit i64 func.returns Mosaic rejects.
# ---------------------------------------------------------------------------


def rsum32(x):
    """Full reduction of a (1, 8, B//8) block to (1, 1, 1) int32 via
    per-axis keepdims sums — never a rank-0 reduce primitive."""
    s = jnp.sum(x, axis=2, dtype=jnp.int32, keepdims=True)
    return jnp.sum(s, axis=1, dtype=jnp.int32, keepdims=True)


def emit_slots(o_ref, i, spm, scalars):
    """Write the per-block (1,1,1) partials into the (1, 1, _SLOTS)
    output tile: initialize on the first block of each output major,
    accumulate otherwise."""
    zero = _I0
    vec = jnp.concatenate(scalars, axis=2)
    vec = jnp.pad(vec, ((0, 0), (0, 0), (0, _SLOTS - vec.shape[2])),
                  constant_values=zero)
    spm = np.int32(spm)

    @pl.when(i % spm == 0)
    def _init():
        o_ref[...] = vec

    @pl.when(i % spm != 0)
    def _acc():
        o_ref[...] = o_ref[...] + vec


def slots_pallas_call(kernel, args, cap, B, interpret=None):
    """Run ``kernel`` on a (nblk,) grid over 1-D [cap] arrays reshaped
    to (1, 8, B//8) blocks, accumulating (1, 1, _SLOTS) int32 tiles per
    <= 2^23-row major; returns the int64 [_SLOTS] recombined totals."""
    nblk = cap // B
    spm = max(1, _MAJOR_ROWS // B)
    nmajor = -(-nblk // spm)
    args3d = [a.reshape(nblk, 8, B // 8) for a in args]
    out = pl.pallas_call(
        partial(kernel, spm),
        grid=(nblk,),
        in_specs=[pl.BlockSpec((1, 8, B // 8), lambda i: (i, _I0, _I0))
                  for _ in args3d],
        out_specs=pl.BlockSpec(
            (1, 1, _SLOTS), lambda i: (i // np.int32(spm), _I0, _I0)),
        out_shape=jax.ShapeDtypeStruct((nmajor, 1, _SLOTS), jnp.int32),
        interpret=_interpret() if interpret is None else interpret,
    )(*args3d)
    return out.astype(jnp.int64).sum(axis=(0, 1)).reshape(_SLOTS)


def _kernel(nlanes_list, max_groups, nval, nmask, spm, *refs):
    """Grid body: refs = [v_0..v_{nval-1}, m_0..m_{nmask-1}, gids, out].

    Values are int32 (dead rows already zeroed by the caller), masks
    int8, gids int32 with >= max_groups meaning "no group" (trash).
    """
    i = pl.program_id(0)
    zero = _I0
    vals = [r[...] for r in refs[:nval]]
    masks = [r[...].astype(jnp.int32) for r in refs[nval:nval + nmask]]
    gid = refs[nval + nmask][...]
    o_ref = refs[-1]

    lanes = []
    oflow = None
    for v, (nl, bits) in zip(vals, nlanes_list):
        neg = v < 0
        mag = jnp.abs(v)
        if bits < 31:
            # count violating rows (NOT sum of excess bits — that sum
            # could itself overflow int32 across a block)
            viol = rsum32(((mag >> bits) != 0).astype(jnp.int32))
            oflow = viol if oflow is None else oflow + viol
        for k in range(nl):
            lane = (mag >> (LANE_BITS * k)) & 255
            lanes.append(jnp.where(neg, -lane, lane))

    scalars = []
    for g in range(max_groups):
        m = gid == np.int32(g)
        for lane in lanes:
            scalars.append(rsum32(jnp.where(m, lane, zero)))
        for mk in masks:
            scalars.append(rsum32(jnp.where(m, mk, zero)))
    scalars.append(oflow if oflow is not None
                   else jnp.zeros((1, 1, 1), jnp.int32))
    emit_slots(o_ref, i, spm, scalars)


def fused_lane_sums(values, bits_list, count_masks, gids, max_groups: int,
                    block_rows: int | None = None):
    """Exact per-group integer sums + mask counts in one device pass.

    values: list of int32 [cap] arrays, dead rows ZEROED by the caller.
    bits_list: static |value| bit bounds (<= 31 each).
    count_masks: list of bool [cap] arrays counted per group.
    gids: int32 [cap], group id in [0, max_groups) or >= max_groups for
    dead rows.

    Returns (sums, counts, overflow): int64 [max_groups] per value /
    mask; overflow True when a declared bound was violated.
    """
    cap = gids.shape[0]
    nlanes_list = [(_nlanes(b), min(b, 31)) for b in bits_list]
    nl_total = sum(n for n, _ in nlanes_list)
    nval, nmask = len(values), len(count_masks)
    B = (block_rows if block_rows is not None
         else _block_rows(cap, nl_total, nval, nmask))
    num_slots = max_groups * (nl_total + nmask) + 1
    if not supported(bits_list, num_slots, cap, nval, nmask):
        raise InternalError("fused_lane_sums: ineligible shapes/bounds")
    args = ([v.astype(jnp.int32) for v in values]
            + [m.astype(jnp.int8) for m in count_masks]
            + [jnp.minimum(gids, max_groups).astype(jnp.int32)])
    o = slots_pallas_call(
        partial(_kernel, nlanes_list, max_groups, nval, nmask),
        args, cap, B)

    per_g = o[: max_groups * (nl_total + len(count_masks))].reshape(
        max_groups, nl_total + len(count_masks))
    sums = []
    idx = 0
    for nl, _bits in nlanes_list:
        s = jnp.zeros(max_groups, jnp.int64)
        for k in range(nl):
            s = s + (per_g[:, idx + k] << (LANE_BITS * k))
        sums.append(s)
        idx += nl
    counts = [per_g[:, idx + j] for j in range(len(count_masks))]
    oflow = o[max_groups * (nl_total + len(count_masks))] != 0
    return sums, counts, oflow


# ---------------------------------------------------------------------------
# Compile probe: the tunnel's remote Mosaic compile helper can reject
# valid programs; callers fall back to the XLA einsum path (visible in
# the log, never silent). Keyed per (nval, nmask, groups, lane config,
# block) — the compiled artifact is shape-generic beyond that.
# ---------------------------------------------------------------------------

_PROBE_CACHE: dict = {}


def probe_supported(bits_list, nmasks: int, max_groups: int, cap: int) -> bool:
    nlanes_list = tuple((_nlanes(b), min(b, 31)) for b in bits_list)
    nl_total = sum(n for n, _ in nlanes_list)
    nval = len(bits_list)
    num_slots = max_groups * (nl_total + nmasks) + 1
    if not supported(bits_list, num_slots, cap, nval, nmasks):
        return False
    B = _block_rows(cap, nl_total, nval, nmasks)
    key = (nlanes_list, nmasks, max_groups, B)
    if key not in _PROBE_CACHE:
        if _interpret():
            _PROBE_CACHE[key] = True
        else:
            try:
                # probe with the SAME block size the real call will use
                # (VMEM pressure scales with the block; a 2^16 probe
                # proving a 2^18-block program would be vacuous) and two
                # blocks so the accumulate branch compiles too — the
                # block is pinned explicitly, since _block_rows(2B)
                # would otherwise pick a LARGER block for small B
                c = 2 * B
                vals = [jnp.ones(c, jnp.int32) for _ in bits_list]
                masks = [jnp.ones(c, jnp.bool_) for _ in range(nmasks)]
                g = jnp.zeros(c, jnp.int32)
                jax.block_until_ready(
                    fused_lane_sums(vals, list(bits_list), masks, g,
                                    max_groups, block_rows=B))
                _PROBE_CACHE[key] = True
            except Exception as e:  # noqa: BLE001 — fallback must be visible
                import logging

                logging.getLogger(__name__).warning(
                    "pallas groupby kernel probe failed (falling back to "
                    "the XLA einsum path): %s: %s", type(e).__name__, e)
                _PROBE_CACHE[key] = False
    return _PROBE_CACHE[key]
