"""Fused Pallas equi-join probe kernels over narrow keys.

Reference parity: the ``LookupJoinOperator`` hot loop plus
``BenchmarkHashBuildAndJoinOperators`` [SURVEY §2.1, §6] — except the
"hash table" here is a **VMEM-resident lookup table** and the probe is
a single in-register ``tpu.dynamic_gather`` per row instead of an HBM
gather (the XLA dense probe's wall: ~11-12 ns *per element* regardless
of table size, notes/perf_q3_r5.py).

The core trick — REPLICATED tables. Mosaic lowers exactly two batched
gather forms to ``tpu.dynamic_gather``: per-lane sublane select
(``y[r,l] = t[idx[r,l], l]``) and per-sublane lane select. Neither can
address an arbitrary ``t[hi[r,l], lo[r,l]]`` cell (the round-5b note's
chained composition evaluates ``hi`` at the wrong position — it was an
unvalidated experiment; this module's tests caught it). So tables are
stored **replicated across the 128 lanes**: ``tab[s, l] = flat[s]``
for every ``l``, and ONE per-lane sublane select resolves any flat
slot from any lane. The cost is 128x VMEM for the table, which caps
the domain (``_TABLE_BUDGET``); the win is a VPU-rate probe.

Three probe modes, all over a dense key domain ``[key_min, key_max]``
proven by connector stats (advisory — a violating build key discards
the tables loudly, never mis-joins):

- **exists**: packed bitmask, 32 keys/word — domain <= 2^19 at the
  8 MB budget. Serves semi/anti joins and unique inner joins with no
  build payload (duplicate build keys are existence-safe).
- **payload**: a present table plus one int32 value table per build
  output column — the full build->probe->project fusion, one gather
  per output column, probe-aligned output. Unique builds only (the
  scatter keeps one row per key). Domain <= 16384/(1+ncols) rows.
- **sketch**: a two-hash Bloom bitmask over ``SKETCH_BITS`` bits — no
  domain bound at all, but FALSE POSITIVES are possible (rate roughly
  ``(1 - exp(-2n/m))^2`` for n build keys in m bits). Only reachable
  through the ``approx_join`` session property, and only for semi
  joins / existence probes where an extra row is the documented
  approximation (never anti: a false positive would silently DROP
  rows).

Exactness story (exists/payload): the in-range mask is computed by
direct comparison in the key's own dtype — never via the subtraction,
which may wrap — so an out-of-domain probe key can never alias into
the table; gather indices are clipped and the clipped lookup is masked
by that exact in-range bit.

The Mosaic/x64 scaffolding (int32-pinned literals and index maps,
keepdims reductions, per-major accumulation, compile probes with
visible fallback) follows ops/pallas_groupby.py, which documents each
workaround.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from presto_tpu.ops.hashing import mix32_slots
from presto_tpu.ops.pallas_groupby import emit_slots

_I0 = np.int32(0)
_LANES = 128
#: replicated-table VMEM budget (the table is duplicated across all
#: 128 lanes; 16 MB scoped VMEM minus probe blocks and double buffers)
_TABLE_BUDGET = 8 << 20
#: sketch-mode Bloom bits (power of two; 2^19 bits -> 16384 words ->
#: exactly the table budget when replicated)
SKETCH_BITS = 1 << 19

_INT32_MIN = -(1 << 31)
_INT32_MAX = (1 << 31) - 1


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad8(n: int) -> int:
    return -(-n // 8) * 8


# ---------------------------------------------------------------------------
# Static eligibility — the kernel's VALUE-DOMAIN PROOFS (the pallas_q1
# gid-domain guard discipline: every in-kernel int32 quantity is
# bounded here, statically, and every ADVISORY bound has a loud typed
# fallback at runtime — ``join.pallas_fallback`` + the XLA probes —
# never a silent wrap):
#
# - packed-key bit budget: exists/sketch tables pack 32 keys per int32
#   word. Bit 31 is reached through an int64 shift in ``_pack_words``
#   (an int32 shift of 1<<31 is UB-adjacent overflow in XLA's eyes;
#   int64 lands the sign-bit pattern exactly, and the final int32 cast
#   wraps to the intended bit pattern — asserted by
#   test_bloom_no_false_negatives over full-range int64 keys).
# - slot arithmetic: ``slot = key - key_min`` is computed ONLY under
#   the ``inr`` mask, which compares in the key's own dtype first —
#   for in-range keys 0 <= slot < domain <= 2^19 (exists, at the 8 MB
#   budget: 16384 words * 32) or <= 16384 (payload), both far inside
#   int32; out-of-range keys may wrap the subtraction but their rows
#   are already masked and their gather indices clipped. A LIVE build
#   key outside the advisory [key_min, key_max] sets ``oob`` at build
#   time: the tables are DISCARDED (typed, counted fallback), so a
#   probe can never consult a table whose domain proof was violated.
# - probe chunk bounds: ``probe_block`` admits only capacities with
#   cap % (sp * 128) == 0 and sp <= 512, so the [cap] -> [nblk*sp,128]
#   reshape is an exact bijection (no probe row dropped or invented)
#   and a block holds at most 2^16 rows — row-relative quantities stay
#   inside int32 with 2^15x margin. Non-blocking capacities (the
#   grouped tier's tiny buckets) fall back per batch, counted.
# ---------------------------------------------------------------------------


def exists_words(domain: int) -> int | None:
    """Bitmask words for an exists-mode table, or None when the
    replicated table would blow the VMEM budget."""
    if domain <= 0:
        return None
    w = _pad8(-(-domain // 32))
    return w if w * _LANES * 4 <= _TABLE_BUDGET else None


def payload_rows(domain: int, ncols: int) -> int | None:
    """Padded table rows for payload mode (present + ncols values), or
    None when over budget."""
    if domain <= 0:
        return None
    d = _pad8(domain)
    return d if (1 + ncols) * d * _LANES * 4 <= _TABLE_BUDGET else None


def probe_block(cap: int) -> int | None:
    """Probe sublanes per grid block: the largest power-of-two block
    (<= 2^16 rows) evenly dividing the batch capacity; None when the
    capacity cannot block (non-multiple of 1024 — e.g. the grouped
    tier's tiny 16..512-row buckets)."""
    for sp in (512, 256, 128, 64, 32, 16, 8):
        if cap % (sp * _LANES) == 0:
            return sp
    return None


def interval_ok(key_min: int, key_max: int) -> bool:
    """The kernels compare keys as int32: the domain ends must fit."""
    return _INT32_MIN <= key_min and key_max <= _INT32_MAX and key_min <= key_max


def key_dtype_ok(dtype) -> bool:
    """Probe/build key storage the kernels accept: integer, <= 32 bits
    (the narrow-storage scan representation; int64 canonical keys fall
    back to the XLA probes)."""
    return jnp.issubdtype(dtype, jnp.integer) and jnp.iinfo(dtype).bits <= 32


@dataclass(frozen=True)
class PallasJoinSpec:
    """Planner-chosen fused-probe configuration, carried by the join
    build operator. ``payload`` names build-side source columns in
    projection order (payload mode); ``nbits`` > 0 selects sketch
    mode (approx_join) and makes key_min/key_max irrelevant."""

    mode: str  # "exists" | "payload" | "sketch"
    key_min: int = 0
    key_max: int = 0
    payload: tuple[str, ...] = ()
    nbits: int = 0

    def key(self):
        """Content tuple for executable-cache keys."""
        return (self.mode, self.key_min, self.key_max, self.payload,
                self.nbits)


# ---------------------------------------------------------------------------
# Table builders (traced; run inside the join-build jit)
# ---------------------------------------------------------------------------


def _pack_words(present8, nwords: int):
    """[nwords*32] 0/1 int8 -> [nwords] int32 bit-packed. The shift
    rides int64 so bit 31 lands exactly; the final cast wraps to the
    int32 bit pattern."""
    p = present8.reshape(nwords, 32).astype(jnp.int64)
    return (p << jnp.arange(32, dtype=jnp.int64)).sum(
        axis=1, dtype=jnp.int64).astype(jnp.int32)


def _replicate(flat):
    return jnp.broadcast_to(flat[:, None], (flat.shape[0], _LANES))


def build_exists_table(keys, live, key_min: int, key_max: int,
                       pad_words: int | None = None):
    """Replicated [W, 128] int32 bitmask over the key domain.

    Returns (table, oob): ``oob`` is True when some LIVE key fell
    outside the advisory stats domain — the caller must then discard
    the table (the generic probes take over; loud, never wrong).
    Duplicate keys are fine (existence semantics)."""
    domain = key_max - key_min + 1
    w = exists_words(domain)
    if pad_words is not None:
        w = pad_words
    k = keys.astype(jnp.int64)
    slot = k - np.int64(key_min)
    inr = (slot >= 0) & (slot < domain)
    ok = live & inr
    nbits = w * 32
    present8 = (
        jnp.zeros(nbits, jnp.int8)
        .at[jnp.where(ok, slot, nbits)]
        .set(1, mode="drop")
    )
    return _replicate(_pack_words(present8, w)), jnp.any(live & ~inr)


def build_payload_tables(keys, live, key_min: int, key_max: int, values):
    """Replicated present + value tables for the fused projection.

    ``values``: list of int-like [cap] arrays (the build payload
    columns, <= 32-bit storage). Unique build keys required — the
    scatter keeps an arbitrary row per duplicate key, which the
    planner must rule out (the unique flag it already proves for the
    FK->PK fast path). Returns (tables, oob) with tables[0] the
    present table."""
    domain = key_max - key_min + 1
    d = _pad8(domain)
    k = keys.astype(jnp.int64)
    slot = k - np.int64(key_min)
    inr = (slot >= 0) & (slot < domain)
    ok = live & inr
    idx = jnp.where(ok, slot, d)
    present = jnp.zeros(d, jnp.int32).at[idx].set(1, mode="drop")
    tables = [_replicate(present)]
    for v in values:
        t = jnp.zeros(d, jnp.int32).at[idx].set(
            v.astype(jnp.int32), mode="drop")
        tables.append(_replicate(t))
    return tuple(tables), jnp.any(live & ~inr)


def build_sketch_table(keys, live, nbits: int = SKETCH_BITS):
    """Replicated two-hash Bloom bitmask; no domain bound, no oob
    (every key hashes somewhere — approximate by construction).
    ``hashing.bloom_build`` is the ONE word builder — the in-kernel
    probe (``_sketch_kernel``) recomputes the same ``mix32_slots``,
    so build and probe must share bit layout or probes would miss."""
    from presto_tpu.ops.hashing import bloom_build

    return _replicate(bloom_build(keys, live, nbits))


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _rep_gather(tab, idx):
    """y[r, l] = tab[idx[r, l], l] — the per-lane sublane select form
    Mosaic lowers to tpu.dynamic_gather. ``tab`` is lane-replicated, so
    this resolves an arbitrary flat slot from any lane. lax.gather
    directly: take_along_axis promotes indices to int64 under x64,
    which Mosaic cannot lower."""
    dn = lax.GatherDimensionNumbers(
        offset_dims=(), collapsed_slice_dims=(0,), start_index_map=(0,),
        operand_batching_dims=(1,), start_indices_batching_dims=(1,))
    return lax.gather(tab, idx[..., None], dn, (1, 1),
                      mode=lax.GatherScatterMode.PROMISE_IN_BOUNDS)


def _bit_test(words, w_idx, bit_idx):
    """words replicated [W,128]; test bit bit_idx of word w_idx."""
    wv = _rep_gather(words, w_idx)
    return ((wv >> bit_idx) & np.int32(1)) != 0


def _exists_kernel(kmin, kmax, w, *refs):
    tab_ref, key_ref, live_ref, o_ref = refs
    keys = key_ref[...].astype(jnp.int32)
    live = live_ref[...] != 0
    # exact in-range by comparison (the subtraction may wrap for keys
    # far outside an int32 domain — those rows are masked here)
    inr = (keys >= kmin) & (keys <= kmax) & live
    slot = keys - kmin
    word = jnp.clip(slot >> np.int32(5), _I0, np.int32(w - 1))
    hit = _bit_test(tab_ref[...], word, slot & np.int32(31)) & inr
    o_ref[...] = hit.astype(jnp.int8)


def _sketch_kernel(nbits, *refs):
    tab_ref, key_ref, live_ref, o_ref = refs
    keys = key_ref[...].astype(jnp.int32)
    live = live_ref[...] != 0
    tab = tab_ref[...]
    s1, s2 = mix32_slots(keys, nbits)
    hit = (_bit_test(tab, s1 >> np.int32(5), s1 & np.int32(31))
           & _bit_test(tab, s2 >> np.int32(5), s2 & np.int32(31)) & live)
    o_ref[...] = hit.astype(jnp.int8)


def _payload_kernel(kmin, kmax, d, nval, *refs):
    tabs = refs[: 1 + nval]
    key_ref, live_ref = refs[1 + nval], refs[2 + nval]
    outs = refs[3 + nval:]
    keys = key_ref[...].astype(jnp.int32)
    live = live_ref[...] != 0
    inr = (keys >= kmin) & (keys <= kmax) & live
    slot = jnp.clip(keys - kmin, _I0, np.int32(d - 1))
    hit = (_rep_gather(tabs[0][...], slot) != 0) & inr
    outs[0][...] = hit.astype(jnp.int8)
    for i in range(nval):
        outs[1 + i][...] = jnp.where(hit, _rep_gather(tabs[1 + i][...], slot),
                                     _I0)


# ---------------------------------------------------------------------------
# Probe entry points (traced; call inside jitted probe steps)
# ---------------------------------------------------------------------------


def _blocked(arr, nblk, sp):
    return arr.reshape(nblk * sp, _LANES)


def exists_probe(table, key_min: int, key_max: int, keys, live,
                 interpret: bool | None = None):
    """matched bool [cap]: key present in the build bitmask."""
    cap = keys.shape[0]
    sp = probe_block(cap)
    nblk = cap // (sp * _LANES)
    w = table.shape[0]
    out = pl.pallas_call(
        partial(_exists_kernel, np.int32(key_min), np.int32(key_max), w),
        grid=(nblk,),
        in_specs=[pl.BlockSpec((w, _LANES), lambda i: (_I0, _I0)),
                  pl.BlockSpec((sp, _LANES), lambda i: (i, _I0)),
                  pl.BlockSpec((sp, _LANES), lambda i: (i, _I0))],
        out_specs=pl.BlockSpec((sp, _LANES), lambda i: (i, _I0)),
        out_shape=jax.ShapeDtypeStruct((nblk * sp, _LANES), jnp.int8),
        interpret=_interpret() if interpret is None else interpret,
    )(table, _blocked(keys, nblk, sp), _blocked(live.astype(jnp.int8),
                                                nblk, sp))
    return out.reshape(cap) != 0


def sketch_probe(table, nbits: int, keys, live,
                 interpret: bool | None = None):
    """APPROXIMATE matched bool [cap] (Bloom: false positives
    possible, never false negatives)."""
    cap = keys.shape[0]
    sp = probe_block(cap)
    nblk = cap // (sp * _LANES)
    w = table.shape[0]
    out = pl.pallas_call(
        partial(_sketch_kernel, nbits),
        grid=(nblk,),
        in_specs=[pl.BlockSpec((w, _LANES), lambda i: (_I0, _I0)),
                  pl.BlockSpec((sp, _LANES), lambda i: (i, _I0)),
                  pl.BlockSpec((sp, _LANES), lambda i: (i, _I0))],
        out_specs=pl.BlockSpec((sp, _LANES), lambda i: (i, _I0)),
        out_shape=jax.ShapeDtypeStruct((nblk * sp, _LANES), jnp.int8),
        interpret=_interpret() if interpret is None else interpret,
    )(table, _blocked(keys, nblk, sp), _blocked(live.astype(jnp.int8),
                                                nblk, sp))
    return out.reshape(cap) != 0


def payload_probe(tables, key_min: int, key_max: int, keys, live,
                  interpret: bool | None = None):
    """(matched bool [cap], [int32 [cap] payload values...]) — the
    fused probe+project: each output column is the build value at the
    probe key's slot (0 where unmatched; callers mask validity)."""
    cap = keys.shape[0]
    sp = probe_block(cap)
    nblk = cap // (sp * _LANES)
    d = tables[0].shape[0]
    nval = len(tables) - 1
    outs = pl.pallas_call(
        partial(_payload_kernel, np.int32(key_min), np.int32(key_max), d,
                nval),
        grid=(nblk,),
        in_specs=[pl.BlockSpec((d, _LANES), lambda i: (_I0, _I0))
                  for _ in tables]
        + [pl.BlockSpec((sp, _LANES), lambda i: (i, _I0))
           for _ in range(2)],
        out_specs=[pl.BlockSpec((sp, _LANES), lambda i: (i, _I0))
                   for _ in range(1 + nval)],
        out_shape=[jax.ShapeDtypeStruct((nblk * sp, _LANES), jnp.int8)]
        + [jax.ShapeDtypeStruct((nblk * sp, _LANES), jnp.int32)
           for _ in range(nval)],
        interpret=_interpret() if interpret is None else interpret,
    )(*tables, _blocked(keys, nblk, sp), _blocked(live.astype(jnp.int8),
                                                  nblk, sp))
    matched = outs[0].reshape(cap) != 0
    return matched, [o.reshape(cap) for o in outs[1:]]


# ---------------------------------------------------------------------------
# Q3 bench kernel: partitioned bitmask probe + fused filter + agg.
# The engine modes above cap the domain at the VMEM budget; the bench's
# SF1 o_orderkey domain (6M) exceeds it, so this kernel PARTITIONS the
# bitmask across the outer grid dimension: partition p's 8 MB table
# slice loads once while every probe block streams past it (probe rows
# re-read nparts times — still HBM-sequential, no per-element gather).
# Each key lands in exactly one partition, so count/sum partials are
# exact; revenue = ep*(100-disc) < 2^31 (ep < 2^24, disc in [0,100],
# the Q1 kernel's proven bounds) splits into four unsigned 8-bit lanes
# accumulated int32-exactly per <= 2^23-row output major (255 * 2^23 <
# 2^31), recombined in int64 outside — ops/pallas_q1's arithmetic.
# ---------------------------------------------------------------------------

_MAJOR_ROWS = 1 << 23
_SLOTS = 1024
#: bench probe sublanes (2^16 rows/block: 12B/row double-buffered
#: inputs ~1.6 MB beside the 8 MB table slice)
_Q3_SP = 512


def q3_partitions(domain: int, wmax: int | None = None) -> tuple[int, int]:
    """(words per partition, partition count) covering ``domain``.
    ``wmax`` overrides the budget-derived partition width — the bench's
    compile-retry ladder shrinks it when Mosaic rejects the big table
    shape."""
    if wmax is None:
        wmax = _TABLE_BUDGET // (_LANES * 4)
    words = -(-domain // 32)
    nparts = -(-words // wmax)
    return wmax, nparts


def _rsum2d(x):
    """(sp, 128) int32 block -> (1, 1, 1) via per-axis keepdims sums
    (never a rank-0 reduce primitive — the Mosaic rule rsum32 follows
    for 3-D blocks)."""
    s = jnp.sum(x, axis=1, dtype=jnp.int32, keepdims=True)
    return jnp.sum(s, axis=0, dtype=jnp.int32, keepdims=True).reshape(1, 1, 1)


def _q3_kernel(kmin, w, nblk, spm, cutoff, *refs):
    tab_ref, key_ref, ship_ref, ep_ref, disc_ref, live_ref, o_ref = refs
    p = pl.program_id(0)
    b = pl.program_id(1)
    keys = key_ref[...].astype(jnp.int32)
    live = (live_ref[...] != 0) & (ship_ref[...].astype(jnp.int32) > cutoff)
    slot = keys - kmin
    # the bench key domain is stats-proven (the build asserts oob), so
    # slot is exact; partition membership selects each key once
    word = (slot >> np.int32(5)) - p * np.int32(w)
    inp = live & (word >= 0) & (word < np.int32(w))
    hit = _bit_test(tab_ref[...], jnp.clip(word, _I0, np.int32(w - 1)),
                    slot & np.int32(31)) & inp
    ep = jnp.where(hit, ep_ref[...].astype(jnp.int32), _I0)
    rev = ep * (np.int32(100) - disc_ref[...].astype(jnp.int32))
    scalars = [_rsum2d(hit.astype(jnp.int32))]
    for k in range(4):
        scalars.append(_rsum2d((rev >> np.int32(8 * k)) & np.int32(255)))
    emit_slots(o_ref, p * np.int32(nblk) + b, spm, scalars)


def q3_probe_step(table, key_min: int, domain: int, cutoff: int, lb,
                  interpret: bool | None = None, wmax: int | None = None):
    """Fused Q3 probe: shipdate filter + membership + revenue agg in
    one pass. ``table`` is the (padded, partition-concatenated)
    replicated bitmask from ``build_exists_table(pad_words=w*nparts)``.
    Returns (matched_count, revenue) int64 — revenue at scale 4."""
    cap = lb.capacity
    sp = min(_Q3_SP, probe_block(cap) or 0)
    assert sp, f"bench capacity {cap} cannot block"
    # revenue int32-exactness proof (the pallas_q1 lane discipline):
    # rev = ep * (100 - disc) with ep < 2^24 and disc in [0, 100]
    # (the Q1 kernel's proven TPC-H bounds) gives 0 <= rev <= 100*2^24
    # < 2^31 — the int32 product cannot wrap; each 8-bit lane partial
    # is <= 255 per row and a major accumulates <= _MAJOR_ROWS = 2^23
    # rows, so 255 * 2^23 < 2^31 keeps every per-major int32 sum exact
    # (recombined in int64 below). Violated bounds cannot happen from
    # the bench's stats-narrowed put_table arrays; engine routes never
    # reach this kernel (it is bench-only), so the guard is the pair
    # of static asserts + the oracle validation in bench_q3_join.
    assert _MAJOR_ROWS * 255 < (1 << 31) and 100 * (1 << 24) < (1 << 31)
    nblk = cap // (sp * _LANES)
    w, nparts = q3_partitions(domain, wmax)
    if nparts == 1:
        w = table.shape[0]
    B = sp * _LANES
    spm = max(1, _MAJOR_ROWS // B)
    nmajor = -(-(nparts * nblk) // spm)
    args = [lb[c].data for c in ("l_orderkey", "l_shipdate",
                                 "l_extendedprice", "l_discount")]
    args.append(lb.live.astype(jnp.int8))
    out = pl.pallas_call(
        partial(_q3_kernel, np.int32(key_min), w, nblk, np.int32(spm),
                np.int32(cutoff)),
        grid=(nparts, nblk),
        in_specs=[pl.BlockSpec((w, _LANES), lambda p, b: (p, _I0))]
        + [pl.BlockSpec((sp, _LANES), lambda p, b: (b, _I0)) for _ in args],
        out_specs=pl.BlockSpec(
            (1, 1, _SLOTS),
            lambda p, b: ((p * np.int32(nblk) + b) // np.int32(spm),
                          _I0, _I0)),
        out_shape=jax.ShapeDtypeStruct((nmajor, 1, _SLOTS), jnp.int32),
        interpret=_interpret() if interpret is None else interpret,
    )(table, *[_blocked(a, nblk, sp) for a in args])
    tot = out.astype(jnp.int64).sum(axis=(0, 1))
    rev = sum(tot[1 + k] << (8 * k) for k in range(4))
    return tot[0], rev


# ---------------------------------------------------------------------------
# Compile probes: the remote Mosaic helper can reject valid programs;
# callers fall back visibly (the pallas_groupby pattern). Keyed by the
# kernel configuration — the compiled artifact is shape-generic beyond
# the block/table shapes.
# ---------------------------------------------------------------------------

_PROBE_CACHE: dict = {}


def probe_ok(mode: str, table_rows: int, nval: int = 0,
             nbits: int = SKETCH_BITS) -> bool:
    """One tiny compile of the mode's kernel on the live backend."""
    if _interpret():
        return True
    key = (mode, table_rows, nval, nbits if mode == "sketch" else 0)
    if key not in _PROBE_CACHE:
        try:
            cap = 8 * _LANES
            keys = jnp.zeros(cap, jnp.int32)
            live = jnp.ones(cap, jnp.bool_)
            if mode == "exists":
                tab = jnp.zeros((table_rows, _LANES), jnp.int32)
                jax.block_until_ready(
                    exists_probe(tab, 0, table_rows * 32 - 1, keys, live))
            elif mode == "sketch":
                tab = jnp.zeros((nbits // 32, _LANES), jnp.int32)
                jax.block_until_ready(sketch_probe(tab, nbits, keys, live))
            else:
                tabs = tuple(jnp.zeros((table_rows, _LANES), jnp.int32)
                             for _ in range(1 + nval))
                jax.block_until_ready(
                    payload_probe(tabs, 0, table_rows - 1, keys, live))
            _PROBE_CACHE[key] = True
        except Exception as e:  # noqa: BLE001 — fallback must be visible
            import logging

            logging.getLogger(__name__).warning(
                "pallas join kernel probe failed (%s; falling back to the "
                "XLA join paths): %s: %s", mode, type(e).__name__, e)
            _PROBE_CACHE[key] = False
    return _PROBE_CACHE[key]
