"""Fully-fused TPC-H Q1 leaf fragment as ONE Pallas pass.

Reference parity: ``HandTpchQuery1`` in ``presto-benchmark`` [SURVEY
§6] — the hand-built operator pipeline for the Q1 hot loop. The generic
route (XLA predicate/expression prologue + ``ops.pallas_groupby``) pays
~4 extra HBM round trips materializing gids and zeroed int32 values;
this kernel computes predicate, group id, the two derived decimals, the
8-bit lane split, and the per-(group, lane) partial sums in VMEM and
registers, touching each input byte exactly once.

Measured (v5e, 60M-row resident batch, 2^17-row blocks): 30.9 ms =
1.94 Grows/s — the column read floor itself measures ~31 ms, i.e. the
kernel is HBM-bound with zero slack; the XLA einsum route took 131 ms.

Exactness: dp = ep*(100-disc) fits int32 when ep fits its declared 24
bits and disc is in [0, 100] (both guarded in-kernel). charge =
(dp*(100+tax) + 50)//100 would overflow int32, so it runs as
q*t + round(r*t/100) on the int32 divmod split dp = 100q + r, with the
divmod done in f32 reciprocal + two correction rounds (exact for dp up
to the reachable (2^24-1)*100 ≈ 1.678e9) and round(x/100) as
(x*5243)>>19 (exact for x <= 43698; the reachable r*t + 50 tops out at
12623) — both verified over their full domains
(notes/perf_q1_r5*.py); q*t itself fits int32 because the guard also
pins tax <= 27 (2^24 * 127 + 12700 < 2^31). Per-group lane partials
stay int32-exact because each output major covers <= 2^23 rows
(255 * 2^23 < 2^31); majors recombine in int64 outside.

The Mosaic/x64 scaffolding (keepdims reductions, int32-pinned scalars
and index maps, the per-major accumulate pattern, the int64 epilogue,
block sizing under the 16M scoped-VMEM limit) is shared with the
generic kernel — see ops/pallas_groupby.py, which documents each
workaround.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from presto_tpu.ops.pallas_groupby import emit_slots, rsum32, slots_pallas_call

G = 6  # |returnflag| x |linestatus| groups
_NLANES = (2, 3, 4, 4, 1)  # qty, ep, dp, ch, disc in unsigned 8-bit lanes
_NL = sum(_NLANES)
_CUTOFF = np.int32(
    np.datetime64("1998-09-02").astype("datetime64[D]").astype(np.int64)
)  # l_shipdate <= date '1998-12-01' - interval '90' day
_I0 = np.int32(0)

# per-block scoped-VMEM estimate (bytes/row): double-buffered narrow
# inputs (~13 B) + 14 int32 lane arrays (incl. sum_disc's) + int32
# temporaries. 2^17 rows -> ~12.8M, inside the 16M limit the 13-lane
# variant measured against; 2^18 measured to OOM.
_ROW_BYTES = 98
_VMEM_BUDGET = 14 << 20


def _block_rows(cap: int) -> int | None:
    for b in (1 << 17, 1 << 16):
        if cap % b == 0 and b * _ROW_BYTES <= _VMEM_BUDGET:
            return b
    return None


def supported(batch) -> bool:
    """Static eligibility: narrow integer columns, aligned capacity.

    Since stats-driven narrow storage became the engine's native scan
    representation (ISSUE-5), the SQL tier's canonical lineitem batch
    IS narrow (shipdate int16, flags int8, extendedprice int32, ...) —
    this check accepts it, so the fully-fused kernel fires for real
    queries as well as the hand-built bench/graft paths. Columns must
    be NULL-free over live rows, which scan batches prove by SHARING
    the live mask as their validity (``Batch.from_numpy``).
    """
    cols = ("l_shipdate", "l_returnflag", "l_linestatus", "l_quantity",
            "l_extendedprice", "l_discount", "l_tax")
    for c in cols:
        if c not in batch.columns:
            return False
        col = batch[c]
        dt = col.data.dtype
        if not (jnp.issubdtype(dt, jnp.integer)
                and jnp.iinfo(dt).bits <= 32):
            return False
        # the kernel reads raw data gated only by batch.live: a column
        # with its own validity mask (NULLs) would aggregate sentinel
        # values the generic route excludes
        if col.valid is not None and col.valid is not batch.live:
            return False
    return _block_rows(batch.capacity) is not None


def _divmod100(dp):
    """Exact (dp // 100, dp % 100) over the kernel's full reachable
    domain 0 <= dp <= (2^24 - 1) * 100 ≈ 1.678e9 (ep guarded to 24
    bits, disc to [0, 100]), int32/f32 only: the f32 reciprocal floor
    lands within +-2 of the true quotient everywhere below 2^31, and
    the two correction rounds absorb that margin."""
    q = jnp.floor(dp.astype(jnp.float32) * np.float32(0.01)).astype(jnp.int32)
    r = dp - 100 * q
    for _ in range(2):
        over = (r >= 100).astype(jnp.int32)
        q = q + over
        r = r - 100 * over
        under = (r < 0).astype(jnp.int32)
        q = q - under
        r = r + 100 * under
    return q, r


def _kernel(spm, ship_ref, rf_ref, ls_ref, qty_ref, ep_ref, disc_ref,
            tax_ref, live_ref, o_ref):
    i = pl.program_id(0)
    zero = _I0

    live = (live_ref[...] != 0) & (ship_ref[...].astype(jnp.int32) <= _CUTOFF)
    rf = rf_ref[...].astype(jnp.int32)
    ls = ls_ref[...].astype(jnp.int32)
    gid = jnp.where(live, rf * 2 + ls, np.int32(G))
    qty = jnp.where(live, qty_ref[...].astype(jnp.int32), zero)
    ep = jnp.where(live, ep_ref[...].astype(jnp.int32), zero)
    disc = disc_ref[...].astype(jnp.int32)
    tax = tax_ref[...].astype(jnp.int32)
    dp = ep * (100 - disc)
    t = 100 + tax
    q, r = _divmod100(dp)
    # charge = (dp*t + 50)//100 = q*t + (r*t + 50)//100; the latter via
    # the verified magic multiply: r <= 99 and t = 100 + tax <= 127
    # (tax guarded to [0, 27]) give r*t + 50 <= 12623, well inside the
    # (x*5243)>>19 == x//100 exactness domain (first violation at
    # x = 43699, exhaustively checked — a verified 3.46x margin over
    # the reachable maximum)
    ch = q * t + (((r * t + 50) * 5243) >> 19)
    # sum_disc feeds avg(l_discount) on the SQL route: disc is guarded
    # to [0, 100] (7 bits -> one lane; 100 * 2^23 < 2^31 stays exact
    # per output major), zeroed for dead rows like the other sums
    disc_live = jnp.where(live, disc, zero)

    lanes = []
    for v, nl in zip((qty, ep, dp, ch, disc_live), _NLANES):
        for k in range(nl):
            lanes.append((v >> (8 * k)) & 255)

    scalars = []
    for g in range(G):
        m = gid == np.int32(g)
        for lane in lanes:
            scalars.append(rsum32(jnp.where(m, lane, zero)))
        scalars.append(rsum32(m.astype(jnp.int32)))
    # overflow guard, CONSERVATIVE: flags every declared-bound
    # violation the generic route flags (qty 13 bits, ep 24 bits —
    # Q1_BITS), plus disc outside [0, 100] and tax outside [0, 27].
    # Those ranges are what PROVE dp and ch fit int32 here (dp <=
    # ep*100 < 2^31; ch <= q*t + 12700 <= 2^24 * 127 + 12700 < 2^31):
    # outside them the int32 arithmetic could wrap silently, so the
    # kernel flags rather than risk it — possibly flagging rows whose
    # int64 result would still have fit 31 bits (loud, never silent;
    # TPC-H data has disc <= 10, tax <= 8, so never in practice).
    # The group-id domain is guarded the same way: gid = rf*2 + ls is
    # neither clipped nor range-checked, so an out-of-domain
    # returnflag/linestatus code would silently vanish from every
    # group AND from count_order (the generic route clips into the
    # domain instead); flag it loudly like the other violations.
    bad = ((disc < 0) | (disc > 100) | (tax < 0) | (tax > 27)
           | (rf < 0) | (rf > 2) | (ls < 0) | (ls > 1)).astype(jnp.int32)
    ov = rsum32(jnp.where(live, (qty >> 13) | (ep >> 24) | bad, zero))
    scalars.append(ov)
    emit_slots(o_ref, i, spm, scalars)


def q1_step(batch, interpret: bool | None = None):
    """One Q1 partial-aggregation pass; same contract as
    ``workloads.q1_fused_step`` (dict of [G] sums/counts + flags)."""
    cap = batch.capacity
    B = _block_rows(cap)
    args = [batch[c].data for c in (
        "l_shipdate", "l_returnflag", "l_linestatus", "l_quantity",
        "l_extendedprice", "l_discount", "l_tax")]
    args.append(batch.live.astype(jnp.int8))
    o = slots_pallas_call(
        _kernel, args, cap, B,
        interpret=(jax.default_backend() != "tpu"
                   if interpret is None else interpret))
    per_g = o[: G * (_NL + 1)].reshape(G, _NL + 1)
    names = ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
             "sum_disc")
    res = {}
    idx = 0
    for name, nl in zip(names, _NLANES):
        s = jnp.zeros(G, jnp.int64)
        for k in range(nl):
            s = s + (per_g[:, idx + k] << (8 * k))
        res[name] = s
        idx += nl
    res["count_order"] = per_g[:, _NL]
    res["present"] = res["count_order"] > 0
    res["value_overflow"] = o[G * (_NL + 1)] != 0
    return res


# -- compile probe (same contract as ops.pallas_groupby's): the remote
# Mosaic helper can reject valid programs; callers fall back visibly --

_PROBE: dict = {}


def probe_supported(cap: int) -> bool:
    if jax.default_backend() != "tpu":
        return True
    B = _block_rows(cap)
    if B is None:
        return False
    if B not in _PROBE:
        try:
            from presto_tpu.batch import Batch, Column
            from presto_tpu.types import BIGINT

            c = 2 * B
            mk = {
                "l_shipdate": jnp.int16, "l_returnflag": jnp.int8,
                "l_linestatus": jnp.int8, "l_quantity": jnp.int16,
                "l_extendedprice": jnp.int32, "l_discount": jnp.int8,
                "l_tax": jnp.int8,
            }
            cols = {k: Column(jnp.ones(c, dt), None, BIGINT)
                    for k, dt in mk.items()}
            b = Batch(cols, jnp.ones(c, jnp.bool_))
            jax.block_until_ready(q1_step(b))
            _PROBE[B] = True
        except Exception as e:  # noqa: BLE001 — fallback must be visible
            import logging

            logging.getLogger(__name__).warning(
                "pallas Q1 kernel probe failed (falling back to the "
                "generic route): %s: %s", type(e).__name__, e)
            _PROBE[B] = False
    return _PROBE[B]
