"""Pallas TPU kernels for byte-string predicates (LIKE / prefix / eq).

Reference parity: ``LikeFunctions`` (compiled JONI regex per query) in
``presto-main`` ``operator.scalar`` [SURVEY §2.1]; the Pallas variants
are the SURVEY config-5 requirement ("LIKE/substr predicates as Pallas
scalar-UDF kernels").

The jnp reference kernels in ``ops.strings`` build one [rows, nshift]
sliding-window hit matrix **per pattern segment** in HBM. These Pallas
variants fuse the entire multi-segment match into a single kernel over
row tiles: the byte block is loaded into VMEM once and every segment's
sliding-window compare + earliest-occurrence scan runs on the VPU
without materializing intermediates. The pattern is static per query
(trace-time), so the segment/shift loops fully unroll.

Mosaic constraints honored throughout: every intermediate is 2-D
(column vectors [tile, 1]), all integer math is int32 (x64 mode would
otherwise promote to unsupported 64-bit vectors), and the output block
is int32 (nonzero == match), converted to bool outside the kernel.

Byte layout contract (same as ops.strings): rows are [n, W] uint8,
zero-padded on the right; byte 0 never appears in content.

On non-TPU backends the kernels run in interpreter mode (tests); the
engine routes BYTES LIKE through here when ``ops.strings.use_pallas()``
is on (default: auto — on for TPU backends).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from presto_tpu.ops.strings import encode_needle

_ROW_TILE = 256
_I32 = jnp.int32


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_rows(data, tile: int):
    n = data.shape[0]
    pad = (-n) % tile
    if pad:
        data = jnp.concatenate(
            [data, jnp.zeros((pad, data.shape[1]), data.dtype)], axis=0
        )
    return data, n


def _match_at(block, needle: np.ndarray, s: int, init=None):
    """[tile, 1] bool: needle matches the row at static shift s (ANDed
    onto ``init`` when given, keeping the whole chain left-associated —
    the remote Mosaic compile helper has crashed on right-nested AND
    trees of otherwise-identical programs). ``block`` is int32: bytes
    are widened OUTSIDE the kernel (no u8 converts in Mosaic)."""
    hit = init
    for j in range(len(needle)):
        c = block[:, s + j : s + j + 1] == np.int32(needle[j])
        hit = c if hit is None else (hit & c)
    return hit


def _bool_i32(mask):
    """bool -> int32 via select (astype would need a Mosaic convert)."""
    return jnp.where(mask, np.int32(1), np.int32(0))


def _row_lengths(block, width: int):
    """[tile, 1] int32 logical row lengths (bytes before zero pad).
    The sum dtype is pinned: x64 mode would otherwise accumulate into
    int64, which Mosaic rejects."""
    return jnp.sum(_bool_i32(block != 0), axis=1, keepdims=True, dtype=_I32)


def _segment_state(block, needle: np.ndarray, min_pos, width: int):
    """Earliest occurrence of ``needle`` at position >= min_pos per row
    of a [tile, W] VMEM block; (found[tile,1] i32, ok[tile,1] bool) —
    the kernel-side analog of ops.strings.find_from."""
    L = len(needle)
    if L > width:
        return jnp.zeros_like(min_pos), jnp.zeros_like(min_pos) > 0
    nshift = width - L + 1
    best = jnp.full_like(min_pos, nshift)  # sentinel: not found
    for s in range(nshift - 1, -1, -1):
        usable = _match_at(block, needle, s) & (min_pos <= np.int32(s))
        best = jnp.where(usable, np.int32(s), best)
    ok = best < nshift
    # np.int32(0), not a bare 0: weak python ints trace as i64 scalars,
    # which loops Mosaic's convert lowering
    return jnp.where(ok, best, np.int32(0)), ok


def _suffix_state(block, needle: np.ndarray, min_pos, width: int):
    """[tile, 1] bool: needle sits exactly at the logical row end at a
    position >= min_pos (end-anchored segment semantics)."""
    L = len(needle)
    if L > width:
        return jnp.zeros_like(min_pos) > 0
    lens = _row_lengths(block, width)
    nshift = width - L + 1
    ok = jnp.zeros_like(min_pos) > 0
    for s in range(nshift):
        at_end = lens == np.int32(s + L)
        after = min_pos <= np.int32(s)
        ok = ok | (_match_at(block, needle, s) & at_end & after)
    return ok


def _like_kernel(pattern: str, width: int, data_ref, out_ref):
    """One row tile of SQL LIKE with '%' wildcards (static pattern).
    Same algorithm as ops.strings.like_mask: greedy earliest-occurrence
    for interior segments, suffix match for the end-anchored segment."""
    block = data_ref[:]
    true_col = block[:, :1] == block[:, :1]
    false_col = ~true_col
    segs = pattern.split("%")
    anchored_start = segs[0] != ""
    anchored_end = segs[-1] != ""
    segs_nonempty = [s for s in segs if s != ""]
    if not segs_nonempty:
        if pattern == "":  # LIKE '' matches only empty rows
            out_ref[:] = _bool_i32(_row_lengths(block, width) == 0)
        else:  # all wildcards
            out_ref[:] = _bool_i32(true_col)
        return
    if len(segs) == 1:  # no '%': exact equality against the padded row
        needle = encode_needle(pattern)
        if len(needle) > width:
            out_ref[:] = _bool_i32(false_col)
            return
        padded = np.zeros(width, np.uint8)
        padded[: len(needle)] = needle
        out_ref[:] = _bool_i32(_match_at(block, padded, 0))
        return
    ok = true_col
    pos = jnp.zeros_like(_row_lengths(block, width))
    inner = segs_nonempty[:-1] if anchored_end else segs_nonempty
    for i, seg in enumerate(inner):
        needle = encode_needle(seg)
        if i == 0 and anchored_start:
            if len(needle) > width:
                ok = false_col
                break
            ok = _match_at(block, needle, 0, init=ok)
            pos = jnp.full_like(pos, len(needle))
            continue
        found, hit = _segment_state(block, needle, pos, width)
        ok = ok & hit
        pos = found + np.int32(len(seg))
    if anchored_end:
        last = encode_needle(segs_nonempty[-1])
        ok = ok & _suffix_state(block, last, pos, width)
    out_ref[:] = _bool_i32(ok)


def _run_rowwise(kernel, data) -> jnp.ndarray:
    """Launch a [tile, W] -> [tile, 1] int32 kernel over row tiles and
    return the bool [n] mask."""
    n0, width = data.shape
    padded, _ = _pad_rows(jnp.asarray(data), _ROW_TILE)
    padded = padded.astype(_I32)  # widen outside the kernel (see _match_at)
    grid = padded.shape[0] // _ROW_TILE
    # index maps return np.int32(0), NOT a bare 0: the weak python int
    # lowers to an i64 constant whose func.return fails MLIR
    # verification in the TPU compile helper
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((padded.shape[0], 1), _I32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_ROW_TILE, width), lambda i: (i, np.int32(0)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_ROW_TILE, 1), lambda i: (i, np.int32(0)),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(padded)
    return out[:n0, 0] > 0


def like_mask_pallas(data, pattern: str) -> jnp.ndarray:
    """SQL LIKE over [n, W] zero-padded byte rows — fused Pallas kernel.

    Supports '%' wildcards (as the jnp reference; '_' unsupported).
    """
    if "_" in pattern:
        raise NotImplementedError("LIKE '_' wildcard on byte columns")
    width = data.shape[1]
    return _run_rowwise(partial(_like_kernel, pattern, width), data)


#: (kind, pattern, width) -> did an eager TPU compile of this kernel
#: succeed? The tunnel's remote Mosaic compile helper crashes on some
#: valid programs (op-order sensitive); queries must not die on that,
#: so the expression evaluator probes here and falls back to the jnp
#: kernels when the probe fails. Interpret-mode backends always pass.
_PROBE_CACHE: dict = {}


def _probe(kind: str, pattern: str, width: int, fn) -> bool:
    key = (kind, pattern, width)
    if key not in _PROBE_CACHE:
        if _interpret():
            _PROBE_CACHE[key] = True
        else:
            try:
                dummy = np.zeros((_ROW_TILE, width), np.uint8)
                jax.block_until_ready(fn(dummy, pattern))
                _PROBE_CACHE[key] = True
            except Exception as e:  # noqa: BLE001 — see module comment:
                # the remote Mosaic compile helper crashes on some valid
                # programs; queries fall back to the jnp kernel, but the
                # fallback must be VISIBLE, not silent
                import logging

                logging.getLogger(__name__).warning(
                    "pallas %s kernel probe failed for pattern=%r width=%d "
                    "(falling back to the jnp kernel): %s: %s",
                    kind, pattern, width, type(e).__name__, e,
                )
                _PROBE_CACHE[key] = False
    return _PROBE_CACHE[key]


def like_supported(pattern: str, width: int) -> bool:
    """True when the fused LIKE kernel compiles for this pattern/width
    on the active backend (always true in interpret mode)."""
    if "_" in pattern:
        return False
    return _probe("like", pattern, width, like_mask_pallas)


def starts_with_supported(prefix: str, width: int) -> bool:
    return _probe("prefix", prefix, width, starts_with_pallas)


def _prefix_kernel(prefix: bytes, data_ref, out_ref):
    block = data_ref[:]
    out_ref[:] = _bool_i32(_match_at(block, np.frombuffer(prefix, np.uint8), 0))


def starts_with_pallas(data, prefix: str) -> jnp.ndarray:
    pb = prefix.encode("latin1")
    if not pb:
        # every string starts with the empty prefix; _match_at over an
        # empty needle would return None and crash the kernel wrapper
        return jnp.ones(data.shape[0], jnp.bool_)
    if len(pb) > data.shape[1]:
        return jnp.zeros(data.shape[0], jnp.bool_)
    return _run_rowwise(partial(_prefix_kernel, pb), data)
