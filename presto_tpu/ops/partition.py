"""Partitioning kernels: row -> destination packing for the exchange.

Reference parity: ``PartitionedOutputOperator`` (``PagePartitioner``,
per-partition PageBuilders) and the serialized-page OutputBuffer
[SURVEY §2.1, §2.5; reference tree unavailable].

TPU-first (SURVEY §2.5): instead of serializing pages into per-consumer
HTTP buffers, rows are scattered into a dense ``[P, Q]`` send tensor
(P destinations x Q quota rows) that feeds ``jax.lax.all_to_all``
directly. Quota overflow (skew) raises the overflow flag so the host
retries at a bigger quota or falls back to multi-round shuffles
(SURVEY §7.4 #4).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def partition_layout(pids, live, num_partitions: int, quota: int):
    """Compute each row's slot in the [P, quota] send buffer.

    Returns (slot, counts, overflow):
    - slot[cap]: flattened destination slot p*quota + rank, or P*quota
      (dropped) for dead/overflowing rows;
    - counts[P]: rows destined to each partition (pre-overflow);
    - overflow: any partition exceeded its quota.
    """
    cap = pids.shape[0]
    p = jnp.where(live, pids, num_partitions)
    # rank of each row within its partition (stable by row order):
    # sort rows by partition, rank = position - partition start
    order = jnp.argsort(p, stable=True)
    ps = p[order]
    counts = jnp.zeros(num_partitions + 1, dtype=jnp.int32).at[p].add(1)[
        :num_partitions
    ]
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(cap)
    start_of_row = jnp.where(ps < num_partitions, starts[jnp.minimum(ps, num_partitions - 1)], 0)
    rank_sorted = pos - start_of_row
    rank = jnp.zeros(cap, dtype=jnp.int32).at[order].set(rank_sorted.astype(jnp.int32))
    ok = live & (rank < quota)
    slot = jnp.where(ok, p * quota + rank, num_partitions * quota)
    overflow = jnp.any(counts > quota)
    return slot, counts, overflow


def destination_counts(pids, mask, num_partitions: int):
    """Per-destination row histogram of the masked rows (int64 [P]).

    The exchange-skew telemetry's device-side primitive: accumulated
    across shuffle rounds inside the compiled step (never a per-round
    host readback), psum'd over the worker axis at the end, and read
    back once per query — the ``_flush_filter_stats`` discipline. The
    extra slot absorbs masked-off rows (their pid may be garbage)."""
    dest = jnp.where(mask, pids, num_partitions)
    return jnp.zeros(num_partitions + 1, jnp.int64).at[dest].add(1)[
        :num_partitions
    ]


def scatter_to_buffer(values, slot, num_partitions: int, quota: int, fill=0):
    """Scatter a column into the dense [P, quota] send tensor."""
    flat = jnp.full((num_partitions * quota + 1,) + values.shape[1:], fill, values.dtype)
    flat = flat.at[slot].set(values)
    return flat[:-1].reshape((num_partitions, quota) + values.shape[1:])
