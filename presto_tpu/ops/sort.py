"""Ordering kernels: multi-key sort, Top-N.

Reference parity: ``OrderByOperator`` (PagesIndex sort), ``TopNOperator``
(bounded heap) [SURVEY §2.1; reference tree unavailable]. TPU-first:
stable chained ``argsort`` (the device bitonic/radix sort XLA emits) —
a heap is serial, a sort is parallel; Top-N is sort + static prefix.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np


def _desc_transform(k):
    """Order-reversing transform so a single ascending sort handles
    mixed ASC/DESC keys."""
    if jnp.issubdtype(k.dtype, jnp.floating):
        return -k
    return ~k.astype(jnp.int64)  # bitwise-not reverses int order, no overflow


def sort_indices(
    key_cols: Sequence[jnp.ndarray],
    descending: Sequence[bool],
    live,
    nulls_first: Sequence[bool] | None = None,
    valids: Sequence[jnp.ndarray] | None = None,
):
    """Row order: stable multi-key argsort; dead rows sort last.

    Returns order[cap] (original row indices, dead rows at the tail).
    """
    cap = live.shape[0]
    order = jnp.arange(cap)
    n = len(list(key_cols))
    for i in range(n - 1, -1, -1):
        kk = _desc_transform(key_cols[i]) if descending[i] else key_cols[i]
        order = order[jnp.argsort(kk[order], stable=True)]
        if valids is not None and valids[i] is not None:
            # null placement is more significant than the key value:
            # a second stable sort on the null flag (False sorts first)
            is_null = ~valids[i]
            nf = bool(nulls_first[i]) if nulls_first else False
            flag = ~is_null if nf else is_null
            order = order[jnp.argsort(flag[order], stable=True)]
    order = order[jnp.argsort(~live[order], stable=True)]
    return order


def top_n_indices(key_cols, descending, live, n: int):
    """Indices of the top-n rows by the sort order (sentinel cap
    beyond the live count)."""
    cap = live.shape[0]
    order = sort_indices(key_cols, descending, live)
    count = jnp.sum(live.astype(jnp.int32))
    take = order[:n]
    return jnp.where(jnp.arange(n) < count, take, cap)
