"""Ordering kernels: multi-key sort, Top-N.

Reference parity: ``OrderByOperator`` (PagesIndex sort), ``TopNOperator``
(bounded heap) [SURVEY §2.1; reference tree unavailable]. TPU-first:
stable chained ``argsort`` (the device bitonic/radix sort XLA emits) —
a heap is serial, a sort is parallel; Top-N is sort + static prefix.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np


def _desc_transform(k):
    """Order-reversing transform so a single ascending sort handles
    mixed ASC/DESC keys."""
    if jnp.issubdtype(k.dtype, jnp.floating):
        return -k
    return ~k.astype(jnp.int64)  # bitwise-not reverses int order, no overflow


def bytes_sort_chunks(data) -> list[jnp.ndarray]:
    """[n, W] bytes -> big-endian int64 chunks (7 bytes each), most
    significant first; comparing the chunk tuple == lexicographic
    byte comparison under PAD SPACE collation (zero padding compares
    as spaces, matching expr comparisons / bytes_pack / bytes_hash so
    a space-padded computed string groups and sorts with zero-padded
    storage of the same value)."""
    data = jnp.where(data == 0, jnp.uint8(32), data)
    w = data.shape[1]
    out = []
    for c0 in range(0, w, 7):
        chunk = data[:, c0 : c0 + 7]
        v = jnp.zeros(data.shape[0], jnp.int64)
        for i in range(chunk.shape[1]):
            v = (v << np.int64(8)) | chunk[:, i].astype(jnp.int64)
        out.append(v)
    return out


def _expand_keys(key_cols, descending, nulls_first, valids):
    """Expand 2-D BYTES keys into int64 chunk keys (lexicographic)."""
    ks, ds, nf, vs = [], [], [], []
    for i, k in enumerate(key_cols):
        d = descending[i]
        f = nulls_first[i] if nulls_first else False
        v = valids[i] if valids else None
        if k.ndim == 2:
            chunks = bytes_sort_chunks(k)
            for j, c in enumerate(chunks):
                ks.append(c)
                ds.append(d)
                # null flag only once (on the most significant chunk)
                nf.append(f)
                vs.append(v if j == 0 else None)
        else:
            ks.append(k)
            ds.append(d)
            nf.append(f)
            vs.append(v)
    return ks, ds, nf, vs


def sort_indices(
    key_cols: Sequence[jnp.ndarray],
    descending: Sequence[bool],
    live,
    nulls_first: Sequence[bool] | None = None,
    valids: Sequence[jnp.ndarray] | None = None,
):
    """Row order: stable multi-key argsort; dead rows sort last.

    Returns order[cap] (original row indices, dead rows at the tail).
    """
    key_cols, descending, nulls_first, valids = _expand_keys(
        list(key_cols), list(descending), nulls_first, valids
    )
    cap = live.shape[0]
    order = jnp.arange(cap)
    n = len(list(key_cols))
    for i in range(n - 1, -1, -1):
        kk = _desc_transform(key_cols[i]) if descending[i] else key_cols[i]
        order = order[jnp.argsort(kk[order], stable=True)]
        if valids is not None and valids[i] is not None:
            # null placement is more significant than the key value:
            # a second stable sort on the null flag (False sorts first)
            is_null = ~valids[i]
            nf = bool(nulls_first[i]) if nulls_first else False
            flag = ~is_null if nf else is_null
            order = order[jnp.argsort(flag[order], stable=True)]
    order = order[jnp.argsort(~live[order], stable=True)]
    return order


def top_n_indices(key_cols, descending, live, n: int):
    """Indices of the top-n rows by the sort order (sentinel cap
    beyond the live count)."""
    cap = live.shape[0]
    order = sort_indices(key_cols, descending, live)
    count = jnp.sum(live.astype(jnp.int32))
    take = order[:n]
    return jnp.where(jnp.arange(n) < count, take, cap)
