"""String kernels over fixed-width byte tensors.

Reference parity: the string function family in ``presto-main``
``operator.scalar`` (LikeFunctions with compiled JONI regex, substr)
[SURVEY §2.1; reference tree unavailable]. TPU-first: a LIKE pattern is
decomposed into ordered literal segments; each segment match is a
vectorized sliding-window byte comparison over the [rows, width]
tensor — all VPU-friendly compares/reductions, no regex automaton.
These are the jnp reference kernels; the Pallas variants fuse the
window loop (SURVEY config 5).

Byte layout contract: rows are zero-padded on the right (the padding
byte 0 never appears in content).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def use_pallas() -> bool:
    """Route BYTES string predicates through the fused Pallas kernels
    (ops.pallas_strings). Default: on for TPU backends; override with
    PRESTO_TPU_PALLAS=1/0."""
    import os

    import jax

    v = os.environ.get("PRESTO_TPU_PALLAS")
    if v is not None:
        return v.strip().lower() not in ("0", "false", "off", "no", "")
    return jax.default_backend() == "tpu"


def encode_needle(s: str) -> np.ndarray:
    return np.frombuffer(s.encode("latin1"), dtype=np.uint8)


def pad_literal(s: str, width: int) -> np.ndarray:
    out = np.zeros(width, dtype=np.uint8)
    b = s.encode("latin1")[:width]
    out[: len(b)] = np.frombuffer(b, dtype=np.uint8)
    return out


def row_lengths(data) -> jnp.ndarray:
    """Logical length of each row = bytes before the zero padding."""
    return jnp.sum((data != 0).astype(jnp.int32), axis=1)


def hits_matrix(data, needle: np.ndarray) -> jnp.ndarray:
    """[n, nshift] bool: needle matches at shift s of each row."""
    width = data.shape[1]
    L = len(needle)
    nshift = width - L + 1
    return jnp.stack(
        [jnp.all(data[:, s : s + L] == jnp.asarray(needle), axis=1) for s in range(nshift)],
        axis=1,
    )


def find_from(data, needle: np.ndarray, min_pos):
    """Earliest occurrence index of ``needle`` at position >= min_pos
    per row; returns (found_pos, ok)."""
    n, width = data.shape
    L = len(needle)
    if L > width:
        z = jnp.zeros(n, jnp.int32)
        return z, jnp.zeros(n, jnp.bool_)
    nshift = width - L + 1
    valid = hits_matrix(data, needle) & (
        jnp.arange(nshift)[None, :] >= min_pos[:, None]
    )
    ok = jnp.any(valid, axis=1)
    found = jnp.argmax(valid, axis=1).astype(jnp.int32)
    return found, ok


def ends_at_length(data, needle: np.ndarray, min_pos) -> jnp.ndarray:
    """True when ``needle`` occurs at exactly the end of the logical row
    (position == row_length - len) at a position >= min_pos."""
    n, width = data.shape
    L = len(needle)
    if L > width:
        return jnp.zeros(n, jnp.bool_)
    nshift = width - L + 1
    lens = row_lengths(data)
    s_idx = jnp.arange(nshift)
    valid = (
        hits_matrix(data, needle)
        & (s_idx[None, :] >= min_pos[:, None])
        & (s_idx[None, :] + L == lens[:, None])
    )
    return jnp.any(valid, axis=1)


def like_mask(data, pattern: str) -> jnp.ndarray:
    """SQL LIKE on byte rows. Supports '%' wildcards (not '_').

    Greedy earliest-occurrence matching for interior segments (the
    classic %-pattern algorithm); the final segment of an
    end-anchored pattern is matched as a SUFFIX at the logical row
    length (earliest-occurrence is wrong there: '%1' must match
    '...011' even though a '1' occurs earlier)."""
    if "_" in pattern:
        raise NotImplementedError("LIKE '_' wildcard on byte columns")
    n, width = data.shape
    segs = pattern.split("%")
    anchored_start = segs[0] != ""
    anchored_end = segs[-1] != ""
    segs_nonempty = [s for s in segs if s != ""]
    if not segs_nonempty:
        if pattern == "":  # LIKE '' matches only empty strings
            return row_lengths(data) == 0
        return jnp.ones(n, jnp.bool_)  # all wildcards
    if len(segs) == 1:  # no '%': exact equality (padding included)
        if len(pattern) > width:
            return jnp.zeros(n, jnp.bool_)
        return bytes_eq_literal(data, pattern)
    ok = jnp.ones(n, jnp.bool_)
    pos = jnp.zeros(n, jnp.int32)
    inner = segs_nonempty[:-1] if anchored_end else segs_nonempty
    for i, seg in enumerate(inner):
        needle = encode_needle(seg)
        if i == 0 and anchored_start:
            L = len(needle)
            if L > width:
                return jnp.zeros(n, jnp.bool_)
            ok = ok & jnp.all(data[:, :L] == jnp.asarray(needle), axis=1)
            pos = jnp.full(n, L, jnp.int32)
            continue
        found, hit = find_from(data, needle, pos)
        ok = ok & hit
        pos = found + np.int32(len(seg))
    if anchored_end:
        # (anchored_start implies the prefix segment was consumed from
        # `inner` above — a no-'%' pattern never reaches here)
        last = encode_needle(segs_nonempty[-1])
        ok = ok & ends_at_length(data, last, pos)
    return ok


def starts_with_mask(data, prefix: str) -> jnp.ndarray:
    needle = encode_needle(prefix)
    L = len(needle)
    if L > data.shape[1]:
        return jnp.zeros(data.shape[0], jnp.bool_)
    return jnp.all(data[:, :L] == jnp.asarray(needle), axis=1)


def substr(data, start: int, length: int):
    """1-based SQL substr with static bounds -> BYTES(length)."""
    return data[:, start - 1 : start - 1 + length]


def rtrim_bytes(data):
    """Strip trailing spaces: canonical zero-padding after the last
    non-space content byte (positions past it become pad zeros)."""
    content = (data != 0) & (data != 32)
    w = data.shape[1]
    # last content index + 1 per row (0 when all spaces/pad)
    rev_any = jnp.cumsum(content[:, ::-1].astype(jnp.int32), axis=1)[:, ::-1]
    keep = rev_any > 0  # position <= last content byte
    return jnp.where(keep, data, 0).astype(jnp.uint8)


def ltrim_bytes(data):
    """Strip leading spaces: content shifts left, tail becomes pad."""
    w = data.shape[1]
    lead = jnp.cumprod((data == 32).astype(jnp.int32), axis=1).sum(
        axis=1, keepdims=True
    )  # count of leading spaces per row
    idx = jnp.arange(w)[None, :] + lead
    shifted = jnp.take_along_axis(data, jnp.minimum(idx, w - 1), axis=1)
    return jnp.where(idx < w, shifted, 0).astype(jnp.uint8)


def trim_bytes(data):
    return ltrim_bytes(rtrim_bytes(data))


def reverse_bytes(data):
    """Reverse each row's logical content (padding stays behind)."""
    w = data.shape[1]
    lens = row_lengths(data)
    idx = lens[:, None] - 1 - jnp.arange(w)[None, :]
    out = jnp.take_along_axis(data, jnp.clip(idx, 0, w - 1), axis=1)
    return jnp.where(idx >= 0, out, 0).astype(jnp.uint8)


def position_in(data, needle: str) -> jnp.ndarray:
    """SQL POSITION(needle IN col): 1-based first occurrence, 0 when
    absent; empty needle is position 1."""
    n = data.shape[0]
    if needle == "":
        return jnp.ones(n, jnp.int32)
    enc = encode_needle(needle)
    found, ok = find_from(data, enc, jnp.zeros(n, jnp.int32))
    return jnp.where(ok, found + 1, 0).astype(jnp.int32)


def bytes_eq_literal(data, s: str) -> jnp.ndarray:
    lit = pad_literal(s, data.shape[1])
    return jnp.all(data == jnp.asarray(lit), axis=1)


def bytes_compare(a, b):
    """Lexicographic 3-way compare of two [n, W] byte tensors:
    returns int32 in {-1, 0, 1} per row."""
    diff = a != b
    any_diff = jnp.any(diff, axis=1)
    first = jnp.argmax(diff, axis=1)
    idx = jnp.arange(a.shape[0])
    av = a[idx, first].astype(jnp.int32)
    bv = b[idx, first].astype(jnp.int32)
    sign = jnp.sign(av - bv)
    return jnp.where(any_diff, sign, 0).astype(jnp.int32)
