"""Window-function kernels over sorted row blocks.

Reference parity: ``com.facebook.presto.operator.WindowOperator`` +
``operator.window.{FrameInfo,WindowPartition}``, ``RowNumberOperator``,
``TopNRowNumberOperator`` [SURVEY §2.1; reference tree unavailable,
paths reconstructed].

TPU-first: the reference walks each partition row-by-row with
accumulator objects; here a window computation is a handful of
data-parallel primitives over the *whole sorted batch at once*:

- partition / peer boundaries  -> adjacent-diff flags;
- partition starts, peer-group ends -> ``lax.cummax`` / reversed
  ``lax.cummin`` of flagged positions;
- running aggregates           -> segmented inclusive scans
  (``lax.associative_scan`` with a (value, segment-start) combine);
- RANGE-frame peer semantics   -> gather the running value at each
  row's last peer index.

Everything is O(n log n) scan/sort work with zero data-dependent
control flow — exactly what XLA tiles well.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.runtime.errors import InternalError


def change_flags(cols, valids=None) -> jnp.ndarray:
    """True where row i differs from row i-1 on any column (row 0 is
    always True). ``valids`` compares null flags as part of the value."""
    if not cols:
        raise InternalError("change_flags needs at least one column")
    n = cols[0].shape[0]
    first = jnp.zeros(n, jnp.bool_).at[0].set(True)
    diff = jnp.zeros(n - 1, jnp.bool_)
    for i, c in enumerate(cols):
        diff = diff | (c[1:] != c[:-1])
        if valids is not None and valids[i] is not None:
            v = valids[i]
            diff = diff | (v[1:] != v[:-1])
    return first.at[1:].set(diff)


def segment_starts(flags: jnp.ndarray) -> jnp.ndarray:
    """Per row: index of the most recent True flag at or before it."""
    pos = jnp.arange(flags.shape[0])
    return jax.lax.cummax(jnp.where(flags, pos, -1))


def segment_ends(next_flags: jnp.ndarray) -> jnp.ndarray:
    """Per row i: smallest j >= i such that j is the LAST row of i's
    segment — i.e. j == n-1 or next_flags[j+1] is True."""
    n = next_flags.shape[0]
    pos = jnp.arange(n)
    is_end = jnp.concatenate([next_flags[1:], jnp.ones(1, jnp.bool_)])
    cand = jnp.where(is_end, pos, n)
    return jnp.flip(jax.lax.cummin(jnp.flip(cand)))


def seg_scan(vals: jnp.ndarray, reset: jnp.ndarray, kind: str) -> jnp.ndarray:
    """Inclusive segmented scan: restarts wherever ``reset`` is True.
    kind: 'sum' | 'min' | 'max'."""
    if kind == "sum":
        op = jnp.add
    elif kind == "min":
        op = jnp.minimum
    elif kind == "max":
        op = jnp.maximum
    else:
        raise InternalError(f"unknown scan kind {kind!r}")

    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf

    v, _ = jax.lax.associative_scan(combine, (vals, reset))
    return v


def scan_identity(kind: str, dtype):
    if kind == "min":
        return (
            jnp.asarray(np.inf, dtype)
            if jnp.issubdtype(dtype, jnp.floating)
            else jnp.asarray(jnp.iinfo(dtype).max, dtype)
        )
    if kind == "max":
        return (
            jnp.asarray(-np.inf, dtype)
            if jnp.issubdtype(dtype, jnp.floating)
            else jnp.asarray(jnp.iinfo(dtype).min, dtype)
        )
    return jnp.asarray(0, dtype)


def rank_values(part_change, peer_change):
    """(row_number, rank, dense_rank), all int64, over sorted rows."""
    n = part_change.shape[0]
    pos = jnp.arange(n)
    pstart = segment_starts(part_change)
    fpeer = segment_starts(peer_change)
    row_number = pos - pstart + 1
    rank = fpeer - pstart + 1
    cpeer = jnp.cumsum(peer_change.astype(jnp.int64))
    dense = cpeer - cpeer[pstart] + 1
    return (
        row_number.astype(jnp.int64),
        rank.astype(jnp.int64),
        dense.astype(jnp.int64),
    )


def windowed_agg(vals, contrib, part_change, peer_change, kind: str, frame: str):
    """One windowed aggregate over sorted rows.

    frame: 'rows'  -> running value at this row (ROWS UNBOUNDED
                      PRECEDING .. CURRENT ROW);
           'range' -> running value at the last peer (SQL default
                      RANGE frame: peers share the frame end);
           'full'  -> value at the partition end (whole partition).
    Returns (value, count) where count is the number of contributing
    rows in the frame (for NULL semantics: count == 0 -> NULL).
    """
    masked = jnp.where(contrib, vals, scan_identity(kind, vals.dtype))
    running = seg_scan(masked, part_change, kind)
    counts = seg_scan(contrib.astype(jnp.int64), part_change, "sum")
    if frame == "rows":
        return running, counts
    boundary = part_change if frame == "full" else peer_change
    last = segment_ends(boundary)
    return running[last], counts[last]
