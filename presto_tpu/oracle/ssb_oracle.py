"""Independent pandas oracle for the SSB query flights (H2QueryRunner
role [SURVEY §4]); consumes the connector's decoded DataFrames."""

from __future__ import annotations

import numpy as np
import pandas as pd


def _lo_date(t):
    return t["lineorder"].merge(t["date"], left_on="lo_orderdate",
                                right_on="d_datekey")


def q1_1(t):
    j = _lo_date(t)
    j = j[(j.d_year == 1993) & j.lo_discount.between(1, 3) & (j.lo_quantity < 25)]
    return pd.DataFrame({"revenue": [(j.lo_extendedprice * j.lo_discount).sum()]})


def q1_2(t):
    j = _lo_date(t)
    j = j[(j.d_yearmonthnum == 199401) & j.lo_discount.between(4, 6)
          & j.lo_quantity.between(26, 35)]
    return pd.DataFrame({"revenue": [(j.lo_extendedprice * j.lo_discount).sum()]})


def q1_3(t):
    j = _lo_date(t)
    j = j[(j.d_weeknuminyear == 6) & (j.d_year == 1994)
          & j.lo_discount.between(5, 7) & j.lo_quantity.between(26, 35)]
    return pd.DataFrame({"revenue": [(j.lo_extendedprice * j.lo_discount).sum()]})


def _q2(t, part_pred, region):
    j = _lo_date(t)
    p = t["part"]
    j = j.merge(p[part_pred(p)], left_on="lo_partkey", right_on="p_partkey")
    s = t["supplier"]
    j = j.merge(s[s.s_region == region], left_on="lo_suppkey", right_on="s_suppkey")
    g = j.groupby(["d_year", "p_brand1"], as_index=False).agg(
        revenue=("lo_revenue", "sum")
    )
    g = g.sort_values(["d_year", "p_brand1"], kind="stable").reset_index(drop=True)
    return g[["revenue", "d_year", "p_brand1"]]


def q2_1(t):
    return _q2(t, lambda p: p.p_category == "MFGR#12", "AMERICA")


def q2_2(t):
    return _q2(
        t, lambda p: p.p_brand1.between("MFGR#2221", "MFGR#2228"), "ASIA"
    )


def q2_3(t):
    return _q2(t, lambda p: p.p_brand1 == "MFGR#2239", "EUROPE")


def _q3(t, cpred, spred, dpred, ckey, skey):
    j = _lo_date(t)
    c = t["customer"]
    s = t["supplier"]
    j = j.merge(c[cpred(c)], left_on="lo_custkey", right_on="c_custkey")
    j = j.merge(s[spred(s)], left_on="lo_suppkey", right_on="s_suppkey")
    j = j[dpred(j)]
    g = j.groupby([ckey, skey, "d_year"], as_index=False).agg(
        revenue=("lo_revenue", "sum")
    )
    g = g.sort_values(["d_year", "revenue"], ascending=[True, False],
                      kind="stable").reset_index(drop=True)
    return g[[ckey, skey, "d_year", "revenue"]]


def q3_1(t):
    return _q3(
        t, lambda c: c.c_region == "ASIA", lambda s: s.s_region == "ASIA",
        lambda j: j.d_year.between(1992, 1997), "c_nation", "s_nation",
    )


def q3_2(t):
    return _q3(
        t, lambda c: c.c_nation == "UNITED STATES",
        lambda s: s.s_nation == "UNITED STATES",
        lambda j: j.d_year.between(1992, 1997), "c_city", "s_city",
    )


def q3_3(t):
    cities = ["UNITED KI1", "UNITED KI5"]
    return _q3(
        t, lambda c: c.c_city.isin(cities), lambda s: s.s_city.isin(cities),
        lambda j: j.d_year.between(1992, 1997), "c_city", "s_city",
    )


def q3_4(t):
    cities = ["UNITED KI1", "UNITED KI5"]
    return _q3(
        t, lambda c: c.c_city.isin(cities), lambda s: s.s_city.isin(cities),
        lambda j: j.d_yearmonth == "Dec1997", "c_city", "s_city",
    )


def _q4(t, cpred, spred, ppred, dpred, keys):
    j = _lo_date(t)
    j = j.merge(t["customer"][cpred(t["customer"])],
                left_on="lo_custkey", right_on="c_custkey")
    j = j.merge(t["supplier"][spred(t["supplier"])],
                left_on="lo_suppkey", right_on="s_suppkey")
    j = j.merge(t["part"][ppred(t["part"])],
                left_on="lo_partkey", right_on="p_partkey")
    j = j[dpred(j)].copy()
    j["profit"] = j.lo_revenue - j.lo_supplycost
    g = j.groupby(keys, as_index=False).agg(profit=("profit", "sum"))
    g = g.sort_values(keys, kind="stable").reset_index(drop=True)
    return g[keys + ["profit"]]


def q4_1(t):
    return _q4(
        t, lambda c: c.c_region == "AMERICA", lambda s: s.s_region == "AMERICA",
        lambda p: p.p_mfgr.isin(["MFGR#1", "MFGR#2"]), lambda j: np.ones(len(j), bool),
        ["d_year", "c_nation"],
    )


def q4_2(t):
    return _q4(
        t, lambda c: c.c_region == "AMERICA", lambda s: s.s_region == "AMERICA",
        lambda p: p.p_mfgr.isin(["MFGR#1", "MFGR#2"]),
        lambda j: j.d_year.isin([1997, 1998]),
        ["d_year", "s_nation", "p_category"],
    )


def q4_3(t):
    return _q4(
        t, lambda c: np.ones(len(c), bool),
        lambda s: s.s_nation == "UNITED STATES",
        lambda p: p.p_category == "MFGR#14",
        lambda j: j.d_year.isin([1997, 1998]),
        ["d_year", "s_city", "p_brand1"],
    )


def q_like_part(t):
    p = t["part"]
    j = t["lineorder"].merge(
        p[p.p_name.str.contains("sky")], left_on="lo_partkey", right_on="p_partkey"
    )
    return pd.DataFrame(
        {"cnt": [len(j)], "revenue": [j.lo_revenue.sum()]}
    )


def q_like_phone(t):
    c = t["customer"]
    c = c[c.c_name.str.match(r"Customer.*1$") & (c.c_phone.str[:2] != "33")]
    j = t["lineorder"].merge(c, left_on="lo_custkey", right_on="c_custkey")
    g = j.groupby("c_region", as_index=False).agg(cnt=("lo_orderkey", "size"))
    g["cnt"] = g["cnt"].astype(np.int64)
    return g.sort_values("c_region", kind="stable").reset_index(drop=True)


ORACLES = {
    name: globals()[name]
    for name in ["q1_1", "q1_2", "q1_3", "q2_1", "q2_2", "q2_3",
                 "q3_1", "q3_2", "q3_3", "q3_4", "q4_1", "q4_2", "q4_3",
                 "q_like_part", "q_like_phone"]
}
