"""Independent pandas oracle for the modeled TPC-DS query subset.

Reference parity: the H2QueryRunner role for TPC-DS suites [SURVEY §4].
Hand-written pandas translations of the query semantics (from the
public TPC-DS spec templates, with the same documented adaptations as
``connectors.tpcds.queries``); shares no code with the engine's
planner/kernels. Inputs are the connector's decoded DataFrames — NULL
FK values arrive as NaN, and pandas inner merges drop them exactly as
SQL inner joins do (the dimension sides never carry NaN keys).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

D = np.datetime64


def _ss_dd_it(t):
    j = t["store_sales"].merge(
        t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk"
    )
    return j.merge(t["item"], left_on="ss_item_sk", right_on="i_item_sk")


def q3(t):
    j = _ss_dd_it(t)
    j = j[(j.i_manufact_id <= 50) & (j.d_moy == 11)]
    g = j.groupby(["d_year", "i_brand", "i_brand_id"], as_index=False).agg(
        sum_agg=("ss_ext_discount_amt", "sum")
    )
    g = g.sort_values(
        ["d_year", "sum_agg", "i_brand_id"],
        ascending=[True, False, True], kind="stable",
    ).head(100)
    return g[["d_year", "i_brand_id", "i_brand", "sum_agg"]].reset_index(drop=True)


def q7(t):
    cd = t["customer_demographics"]
    cd = cd[
        (cd.cd_gender == "M") & (cd.cd_marital_status == "S")
        & (cd.cd_education_status == "College")
    ]
    p = t["promotion"]
    p = p[(p.p_channel_email == "N") | (p.p_channel_event == "N")]
    j = _ss_dd_it(t)
    j = j[j.d_year == 2000]
    j = j.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(p, left_on="ss_promo_sk", right_on="p_promo_sk")
    g = j.groupby("i_item_id", as_index=False).agg(
        agg1=("ss_quantity", "mean"),
        agg2=("ss_list_price", "mean"),
        agg3=("ss_coupon_amt", "mean"),
        agg4=("ss_sales_price", "mean"),
    )
    return g.sort_values("i_item_id", kind="stable").head(100).reset_index(drop=True)


def _revenue_ratio(t, fact, prefix, cats, lo, hi):
    f = t[fact].merge(
        t["date_dim"], left_on=f"{prefix}_sold_date_sk", right_on="d_date_sk"
    )
    f = f.merge(t["item"], left_on=f"{prefix}_item_sk", right_on="i_item_sk")
    f = f[f.i_category.isin(cats) & (f.d_date >= D(lo)) & (f.d_date <= D(hi))]
    g = f.groupby(
        ["i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"],
        as_index=False,
    ).agg(itemrevenue=(f"{prefix}_ext_sales_price", "sum"))
    g["revenueratio"] = (
        g.itemrevenue * 100 / g.groupby("i_class")["itemrevenue"].transform("sum")
    )
    g = g.sort_values(
        ["i_category", "i_class", "i_item_id", "i_item_desc", "revenueratio"],
        kind="stable",
    )
    return g.reset_index(drop=True)


def q12(t):
    return _revenue_ratio(
        t, "web_sales", "ws", ["Sports", "Books", "Home"],
        "1999-02-22", "1999-04-22",
    ).head(100)


def q19(t):
    j = _ss_dd_it(t)
    j = j[(j.i_manager_id <= 30) & (j.d_moy == 11) & (j.d_year == 1998)]
    j = j.merge(t["customer"], left_on="ss_customer_sk", right_on="c_customer_sk")
    j = j.merge(
        t["customer_address"], left_on="c_current_addr_sk", right_on="ca_address_sk"
    )
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j[j.ca_zip.str[:5] != j.s_zip.str[:5]]
    g = j.groupby(
        ["i_brand", "i_brand_id", "i_manufact_id", "i_manufact"], as_index=False
    ).agg(ext_price=("ss_ext_sales_price", "sum"))
    g = g.sort_values(
        ["ext_price", "i_brand", "i_brand_id", "i_manufact_id", "i_manufact"],
        ascending=[False, True, True, True, True], kind="stable",
    ).head(100)
    return g[
        ["i_brand_id", "i_brand", "i_manufact_id", "i_manufact", "ext_price"]
    ].reset_index(drop=True)


def q20(t):
    return _revenue_ratio(
        t, "catalog_sales", "cs", ["Jewelry", "Music", "Women"],
        "2001-01-12", "2001-03-12",
    ).head(100)


def q26(t):
    cd = t["customer_demographics"]
    cd = cd[
        (cd.cd_gender == "F") & (cd.cd_marital_status == "W")
        & (cd.cd_education_status == "Primary")
    ]
    p = t["promotion"]
    p = p[(p.p_channel_email == "N") | (p.p_channel_event == "N")]
    j = t["catalog_sales"].merge(
        t["date_dim"], left_on="cs_sold_date_sk", right_on="d_date_sk"
    )
    j = j.merge(t["item"], left_on="cs_item_sk", right_on="i_item_sk")
    j = j[j.d_year == 2000]
    j = j.merge(cd, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(p, left_on="cs_promo_sk", right_on="p_promo_sk")
    g = j.groupby("i_item_id", as_index=False).agg(
        agg1=("cs_quantity", "mean"),
        agg2=("cs_list_price", "mean"),
        agg3=("cs_coupon_amt", "mean"),
        agg4=("cs_sales_price", "mean"),
    )
    return g.sort_values("i_item_id", kind="stable").head(100).reset_index(drop=True)


def q42(t):
    j = _ss_dd_it(t)
    j = j[(j.i_manager_id <= 20) & (j.d_moy == 11) & (j.d_year == 1998)]
    g = j.groupby(["d_year", "i_category_id", "i_category"], as_index=False).agg(
        total_sales=("ss_ext_sales_price", "sum")
    )
    g = g.sort_values(
        ["total_sales", "d_year", "i_category_id", "i_category"],
        ascending=[False, True, True, True], kind="stable",
    ).head(100)
    return g[["d_year", "i_category_id", "i_category", "total_sales"]].reset_index(
        drop=True
    )


def q52(t):
    j = _ss_dd_it(t)
    j = j[(j.i_manager_id <= 20) & (j.d_moy == 12) & (j.d_year == 1999)]
    g = j.groupby(["d_year", "i_brand", "i_brand_id"], as_index=False).agg(
        ext_price=("ss_ext_sales_price", "sum")
    )
    g = g.sort_values(
        ["d_year", "ext_price", "i_brand_id"],
        ascending=[True, False, True], kind="stable",
    ).head(100)
    return g[["d_year", "i_brand_id", "i_brand", "ext_price"]].reset_index(drop=True)


def q53(t):
    j = _ss_dd_it(t)
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j[
        j.d_month_seq.isin(range(1188, 1200))
        & j.i_category.isin(
            ["Books", "Children", "Electronics", "Home", "Jewelry", "Men"]
        )
    ]
    g = j.groupby(["i_manufact_id", "d_qoy"], as_index=False).agg(
        sum_sales=("ss_sales_price", "sum")
    )
    g["avg_quarterly_sales"] = g.groupby("i_manufact_id")["sum_sales"].transform("mean")
    screen = np.where(
        g.avg_quarterly_sales > 0,
        np.abs(g.sum_sales - g.avg_quarterly_sales) / g.avg_quarterly_sales,
        0.0,
    )
    g = g[screen > 0.05]
    g = g.sort_values(
        ["avg_quarterly_sales", "sum_sales", "i_manufact_id"], kind="stable"
    ).head(100)
    return g[["i_manufact_id", "sum_sales", "avg_quarterly_sales"]].reset_index(
        drop=True
    )


def q55(t):
    j = _ss_dd_it(t)
    j = j[(j.i_manager_id <= 28) & (j.d_moy == 11) & (j.d_year == 1999)]
    g = j.groupby(["i_brand", "i_brand_id"], as_index=False).agg(
        ext_price=("ss_ext_sales_price", "sum")
    )
    g = g.sort_values(
        ["ext_price", "i_brand_id"], ascending=[False, True], kind="stable"
    ).head(100)
    return g[["i_brand_id", "i_brand", "ext_price"]].reset_index(drop=True)


def q89(t):
    j = _ss_dd_it(t)
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j[
        (j.d_year == 1999)
        & j.i_category.isin(["Books", "Electronics", "Sports", "Men", "Music", "Women"])
    ]
    g = j.groupby(
        ["i_category", "i_class", "i_brand", "s_store_name", "s_company_name",
         "d_moy"],
        as_index=False,
    ).agg(sum_sales=("ss_sales_price", "sum"))
    g["avg_monthly_sales"] = g.groupby(
        ["i_category", "i_brand", "s_store_name", "s_company_name"]
    )["sum_sales"].transform("mean")
    screen = np.where(
        g.avg_monthly_sales != 0,
        np.abs(g.sum_sales - g.avg_monthly_sales) / g.avg_monthly_sales,
        0.0,
    )
    g = g[screen > 0.1].copy()
    g["diff"] = g.sum_sales - g.avg_monthly_sales
    g = g.sort_values(
        ["diff", "s_store_name", "i_category", "i_class", "i_brand", "d_moy"],
        kind="stable",
    ).head(100)
    return g[
        ["i_category", "i_class", "i_brand", "s_store_name", "s_company_name",
         "d_moy", "sum_sales", "avg_monthly_sales"]
    ].reset_index(drop=True)


def q98(t):
    g = _revenue_ratio(
        t, "store_sales", "ss", ["Children", "Shoes", "Electronics"],
        "2000-01-29", "2000-03-29",
    )
    return g  # no LIMIT in q98


# -- round-3 breadth (batch 1): returns/inventory/time/ship periphery


def _srt(df, cols, ascending=None):
    return df.sort_values(
        cols, ascending=ascending if ascending is not None else True,
        kind="stable",
    ).reset_index(drop=True)


def q13(t):
    j = t["store_sales"].merge(
        t["store"], left_on="ss_store_sk", right_on="s_store_sk"
    ).merge(t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2001]
    j = j.merge(t["customer_demographics"], left_on="ss_cdemo_sk",
                right_on="cd_demo_sk")
    j = j.merge(t["household_demographics"], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
    j = j.merge(t["customer_address"], left_on="ss_addr_sk",
                right_on="ca_address_sk")
    demo = (
        ((j.cd_marital_status == "M") & (j.cd_education_status == "Advanced Degree")
         & j.ss_sales_price.between(50.0, 150.0))
        | ((j.cd_marital_status == "S") & (j.cd_education_status == "College")
           & j.ss_sales_price.between(20.0, 100.0))
        | ((j.cd_marital_status == "W") & (j.cd_education_status == "2 yr Degree")
           & j.ss_sales_price.between(50.0, 200.0))
    )
    geo = (
        (j.ca_state.isin(["TX", "OH", "KY"]) & j.ss_net_profit.between(-5000, 20000))
        | (j.ca_state.isin(["WA", "NE", "GA"]) & j.ss_net_profit.between(-5000, 30000))
        | (j.ca_state.isin(["MT", "MS", "IN"]) & j.ss_net_profit.between(-5000, 25000))
    )
    j = j[demo & geo]
    return pd.DataFrame({
        "a1": [j.ss_quantity.mean()],
        "a2": [j.ss_ext_sales_price.mean()],
        "a3": [j.ss_ext_wholesale_cost.mean()],
        "a4": [j.ss_ext_wholesale_cost.sum()],
    })


def q21(t):
    lo = D("2000-03-11") - np.timedelta64(30, "D")
    hi = D("2000-03-11") + np.timedelta64(30, "D")
    j = t["inventory"].merge(
        t["warehouse"], left_on="inv_warehouse_sk", right_on="w_warehouse_sk"
    ).merge(t["item"], left_on="inv_item_sk", right_on="i_item_sk").merge(
        t["date_dim"], left_on="inv_date_sk", right_on="d_date_sk"
    )
    j = j[(j.d_date >= lo) & (j.d_date <= hi)]
    pivot = D("2000-03-11")
    j = j.assign(
        inv_before=np.where(j.d_date < pivot, j.inv_quantity_on_hand, 0),
        inv_after=np.where(j.d_date >= pivot, j.inv_quantity_on_hand, 0),
    )
    # NULL quantities contribute 0 to both buckets (CASE yields the
    # quantity only when non-null; engine sums skip NULL)
    j["inv_before"] = j["inv_before"].fillna(0)
    j["inv_after"] = j["inv_after"].fillna(0)
    g = j.groupby(["w_warehouse_name", "i_item_id"], as_index=False).agg(
        inv_before=("inv_before", "sum"), inv_after=("inv_after", "sum")
    )
    g = g[g.inv_before > 0]
    g["inv_before"] = g["inv_before"].astype(np.int64)
    g["inv_after"] = g["inv_after"].astype(np.int64)
    return _srt(g, ["w_warehouse_name", "i_item_id"]).head(100)


def _sales_return_catalog(t, d1_years, d2_years, d3_years):
    ss = t["store_sales"].merge(
        t["date_dim"][["d_date_sk", "d_year", "d_qoy"]],
        left_on="ss_sold_date_sk", right_on="d_date_sk",
    )
    ss = ss[ss.d_year.isin(d1_years)]
    j = ss.merge(
        t["store_returns"],
        left_on=["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
        right_on=["sr_customer_sk", "sr_item_sk", "sr_ticket_number"],
    )
    d2 = t["date_dim"][["d_date_sk", "d_year"]].rename(
        columns={"d_date_sk": "d2_sk", "d_year": "d2_year"}
    )
    j = j.merge(d2, left_on="sr_returned_date_sk", right_on="d2_sk")
    j = j[j.d2_year.isin(d2_years)]
    j = j.merge(
        t["catalog_sales"],
        left_on=["sr_customer_sk", "sr_item_sk"],
        right_on=["cs_bill_customer_sk", "cs_item_sk"],
    )
    d3 = t["date_dim"][["d_date_sk", "d_year"]].rename(
        columns={"d_date_sk": "d3_sk", "d_year": "d3_year"}
    )
    j = j.merge(d3, left_on="cs_sold_date_sk", right_on="d3_sk")
    j = j[j.d3_year.isin(d3_years)]
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"], left_on="ss_item_sk", right_on="i_item_sk")
    return j


def q25(t):
    j = _sales_return_catalog(t, [2000], [2000], [2000])
    g = j.groupby(
        ["i_item_id", "i_item_desc", "s_store_id", "s_store_name"],
        as_index=False,
    ).agg(
        store_sales_profit=("ss_net_profit", "sum"),
        store_returns_loss=("sr_net_loss", "sum"),
        catalog_sales_profit=("cs_net_profit", "sum"),
    )
    return _srt(
        g, ["i_item_id", "i_item_desc", "s_store_id", "s_store_name"]
    ).head(100)


def q29(t):
    j = _sales_return_catalog(t, [1999], [1999, 2000], [1999, 2000, 2001])
    g = j.groupby(
        ["i_item_id", "i_item_desc", "s_store_id", "s_store_name"],
        as_index=False,
    ).agg(
        store_sales_quantity=("ss_quantity", "sum"),
        store_returns_quantity=("sr_return_quantity", "sum"),
        catalog_sales_quantity=("cs_quantity", "sum"),
    )
    return _srt(
        g, ["i_item_id", "i_item_desc", "s_store_id", "s_store_name"]
    ).head(100)


def q37(t):
    it = t["item"]
    it = it[it.i_current_price.between(10.0, 60.0) & (it.i_manufact_id <= 300)]
    j = it.merge(t["inventory"], left_on="i_item_sk", right_on="inv_item_sk")
    j = j.merge(t["date_dim"], left_on="inv_date_sk", right_on="d_date_sk")
    j = j[(j.d_date >= D("2000-01-01")) & (j.d_date <= D("2000-03-01"))]
    j = j[j.inv_quantity_on_hand.between(100, 700)]
    j = j.merge(
        t["catalog_sales"][["cs_item_sk"]], left_on="i_item_sk",
        right_on="cs_item_sk",
    )
    g = j.groupby(
        ["i_item_id", "i_item_desc", "i_current_price"], as_index=False
    ).size()[["i_item_id", "i_item_desc", "i_current_price"]]
    return _srt(g, ["i_item_id"]).head(100)


def q43(t):
    st = t["store"]
    st = st[st.s_gmt_offset <= -5]
    j = t["store_sales"].merge(
        t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk"
    )
    j = j[j.d_year == 2000]
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    days = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
            "Saturday"]
    names = ["sun_sales", "mon_sales", "tue_sales", "wed_sales", "thu_sales",
             "fri_sales", "sat_sales"]
    for d, nm in zip(days, names):
        j[nm] = j.ss_sales_price.where(j.d_day_name == d)
    g = j.groupby(["s_store_name", "s_store_id"], as_index=False)[names].sum(
        min_count=1
    )
    return _srt(g, ["s_store_name", "s_store_id"]).head(100)


def _ship_lag(t, fact, prefix, dims):
    f = t[fact]
    lag = f[f"{prefix}_ship_date_sk"] - f[f"{prefix}_sold_date_sk"]
    f = f.assign(
        d30=(lag <= 30).astype(int),
        d60=((lag > 30) & (lag <= 60)).astype(int),
        d90=((lag > 60) & (lag <= 90)).astype(int),
        d120=(lag > 90).astype(int),
    )
    dd = t["date_dim"]
    dd = dd[dd.d_month_seq.between(1200, 1211)]
    j = f.merge(dd, left_on=f"{prefix}_ship_date_sk", right_on="d_date_sk")
    for table, lk, rk in dims:
        j = j.merge(t[table], left_on=lk, right_on=rk)
    return j


def q62(t):
    j = _ship_lag(t, "web_sales", "ws", [
        ("warehouse", "ws_warehouse_sk", "w_warehouse_sk"),
        ("ship_mode", "ws_ship_mode_sk", "sm_ship_mode_sk"),
        ("web_site", "ws_web_site_sk", "web_site_sk"),
    ])
    g = j.groupby(["w_warehouse_name", "sm_type", "web_name"],
                  as_index=False)[["d30", "d60", "d90", "d120"]].sum()
    return _srt(g, ["w_warehouse_name", "sm_type", "web_name"]).head(100)


def q99(t):
    j = _ship_lag(t, "catalog_sales", "cs", [
        ("warehouse", "cs_warehouse_sk", "w_warehouse_sk"),
        ("ship_mode", "cs_ship_mode_sk", "sm_ship_mode_sk"),
        ("call_center", "cs_call_center_sk", "cc_call_center_sk"),
    ])
    g = j.groupby(["w_warehouse_name", "sm_type", "cc_name"],
                  as_index=False)[["d30", "d60", "d90", "d120"]].sum()
    return _srt(g, ["w_warehouse_name", "sm_type", "cc_name"]).head(100)


def q79(t):
    j = t["store_sales"].merge(
        t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk"
    )
    j = j[(j.d_dow == 1) & (j.d_year == 2000)]
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["household_demographics"], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
    j = j[(j.hd_dep_count == 6) | (j.hd_vehicle_count > 2)]
    g = j.groupby(
        ["ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "s_city"],
        as_index=False, dropna=False,
    ).agg(amt=("ss_coupon_amt", "sum"), profit=("ss_net_profit", "sum"))
    g = g.merge(t["customer"], left_on="ss_customer_sk",
                right_on="c_customer_sk")
    out = g[["c_last_name", "c_first_name", "s_city", "ss_ticket_number",
             "amt", "profit"]]
    return _srt(
        out, ["c_last_name", "c_first_name", "s_city", "profit",
              "ss_ticket_number"],
    ).head(100)


def q91(t):
    j = t["catalog_returns"].merge(
        t["call_center"], left_on="cr_call_center_sk",
        right_on="cc_call_center_sk",
    ).merge(t["date_dim"], left_on="cr_returned_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2000]
    j = j.merge(t["customer"], left_on="cr_returning_customer_sk",
                right_on="c_customer_sk")
    j = j.merge(t["customer_demographics"], left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    j = j.merge(t["household_demographics"], left_on="c_current_hdemo_sk",
                right_on="hd_demo_sk")
    j = j[
        ((j.cd_marital_status == "M") & (j.cd_education_status == "Unknown"))
        | ((j.cd_marital_status == "W")
           & (j.cd_education_status == "Advanced Degree"))
    ]
    j = j[j.hd_buy_potential.str.startswith("0-500")]
    g = j.groupby(["cc_call_center_id", "cc_name", "cc_manager"],
                  as_index=False).agg(returns_loss=("cr_net_loss", "sum"))
    return _srt(g, ["returns_loss", "cc_call_center_id"],
                ascending=[False, True]).head(100)


def q93(t):
    re = t["reason"]
    re = re[re.r_reason_desc == "Stopped working"]
    j = t["store_sales"].merge(
        t["store_returns"],
        left_on=["ss_item_sk", "ss_ticket_number"],
        right_on=["sr_item_sk", "sr_ticket_number"],
    )
    j = j.merge(re, left_on="sr_reason_sk", right_on="r_reason_sk")
    act = np.where(
        j.sr_return_quantity.notna(),
        (j.ss_quantity - j.sr_return_quantity) * j.ss_sales_price,
        j.ss_quantity * j.ss_sales_price,
    )
    j = j.assign(act_sales=act)
    g = j.groupby("ss_customer_sk", as_index=False).agg(
        sumsales=("act_sales", "sum")
    )
    return _srt(g, ["sumsales", "ss_customer_sk"]).head(100)


def q96(t):
    j = t["store_sales"].merge(
        t["time_dim"], left_on="ss_sold_time_sk", right_on="t_time_sk"
    ).merge(t["household_demographics"], left_on="ss_hdemo_sk",
            right_on="hd_demo_sk").merge(
        t["store"], left_on="ss_store_sk", right_on="s_store_sk"
    )
    j = j[(j.t_hour == 20) & (j.t_minute >= 30) & (j.hd_dep_count == 7)
          & (j.s_store_name == "ese")]
    return pd.DataFrame({"cnt": [len(j)]})





# -- round-3 breadth (batch 2)


def q15(t):
    j = t["catalog_sales"].merge(
        t["customer"], left_on="cs_bill_customer_sk", right_on="c_customer_sk"
    ).merge(t["customer_address"], left_on="c_current_addr_sk",
            right_on="ca_address_sk").merge(
        t["date_dim"], left_on="cs_sold_date_sk", right_on="d_date_sk"
    )
    j = j[(j.d_qoy == 2) & (j.d_year == 2000)]
    j = j[j.ca_state.isin(["CA", "WA", "GA"]) | (j.cs_sales_price > 70)]
    j = j.assign(zip=j.ca_zip.str[:5])
    g = j.groupby("zip", as_index=False).agg(tot=("cs_sales_price", "sum"))
    return _srt(g, ["zip"]).head(100)


def q45(t):
    j = t["web_sales"].merge(
        t["customer"], left_on="ws_bill_customer_sk", right_on="c_customer_sk"
    ).merge(t["customer_address"], left_on="c_current_addr_sk",
            right_on="ca_address_sk").merge(
        t["date_dim"], left_on="ws_sold_date_sk", right_on="d_date_sk"
    )
    j = j[(j.d_qoy == 2) & (j.d_year == 2000)]
    j = j[j.ca_state.isin(["CA", "WA", "GA"]) | (j.ws_sales_price > 50)]
    j = j.assign(zip=j.ca_zip.str[:5])
    g = j.groupby("zip", as_index=False).agg(tot=("ws_sales_price", "sum"))
    return _srt(g, ["zip"]).head(100)


def q17(t):
    j = _sales_return_catalog(t, [2000], [2000], [2000])
    j = j[j.d_qoy == 1]  # d1 quarter restriction rides the ss-side dates

    def stats(g, col, names):
        cnt = g[col].count()
        ave = g[col].mean()
        sd = g[col].std()
        return {names[0]: cnt, names[1]: ave, names[2]: sd,
                names[3]: sd / ave}

    rows = []
    for key, g in j.groupby(["i_item_id", "i_item_desc", "s_state"]):
        row = dict(zip(["i_item_id", "i_item_desc", "s_state"], key))
        row.update(stats(g, "ss_quantity", [
            "store_sales_quantitycount", "store_sales_quantityave",
            "store_sales_quantitystdev", "store_sales_quantitycov"]))
        row.update(stats(g, "sr_return_quantity", [
            "store_returns_quantitycount", "store_returns_quantityave",
            "store_returns_quantitystdev", "store_returns_quantitycov"]))
        row.update(stats(g, "cs_quantity", [
            "catalog_sales_quantitycount", "catalog_sales_quantityave",
            "catalog_sales_quantitystdev", "catalog_sales_quantitycov"]))
        rows.append(row)
    out = pd.DataFrame(rows)
    return _srt(out, ["i_item_id", "i_item_desc", "s_state"]).head(100)


def _excess_discount(t, fact, prefix, manu_cap):
    f = t[fact].merge(t["date_dim"], left_on=f"{prefix}_sold_date_sk",
                      right_on="d_date_sk")
    f = f[(f.d_date >= D("2000-01-01")) & (f.d_date <= D("2000-12-31"))]
    avg_disc = f.groupby(f"{prefix}_item_sk")[
        f"{prefix}_ext_discount_amt"
    ].mean().rename("avg_disc").reset_index()
    it = t["item"]
    it = it[it.i_manufact_id <= manu_cap]
    j = f.merge(it, left_on=f"{prefix}_item_sk", right_on="i_item_sk")
    j = j.merge(avg_disc, on=f"{prefix}_item_sk")
    j = j[j[f"{prefix}_ext_discount_amt"] > 1.3 * j.avg_disc]
    return pd.DataFrame(
        {"excess_discount_amount": [j[f"{prefix}_ext_discount_amt"].sum()]}
    )


def q32(t):
    return _excess_discount(t, "catalog_sales", "cs", 100)


def q92(t):
    return _excess_discount(t, "web_sales", "ws", 150)


def _bulk_tickets(t, dom_pred, potentials, ratio):
    j = t["store_sales"].merge(
        t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk"
    )
    j = j[j.d_year.isin([1999, 2000, 2001]) & dom_pred(j)]
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["household_demographics"], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
    j = j[j.hd_buy_potential.isin(potentials) & (j.hd_vehicle_count > 0)]
    j = j[(j.hd_dep_count / j.hd_vehicle_count) > ratio]
    g = j.groupby(["ss_ticket_number", "ss_customer_sk"],
                  as_index=False).size().rename(columns={"size": "cnt"})
    g = g[g.cnt.between(1, 5)]
    return g.merge(t["customer"], left_on="ss_customer_sk",
                   right_on="c_customer_sk")


def q34(t):
    g = _bulk_tickets(
        t, lambda j: j.d_dom.between(1, 3) | j.d_dom.between(25, 28),
        [">10000", "0-500"], 1.2,
    )
    out = g[["c_last_name", "c_first_name", "c_salutation",
             "c_preferred_cust_flag", "ss_ticket_number", "cnt"]]
    return _srt(
        out,
        ["c_last_name", "c_first_name", "c_salutation",
         "c_preferred_cust_flag", "ss_ticket_number"],
        ascending=[True, True, True, False, True],
    ).head(100)


def q73(t):
    g = _bulk_tickets(
        t, lambda j: j.d_dom.between(1, 2), [">10000", "Unknown"], 1,
    )
    out = g[["c_last_name", "c_first_name", "c_salutation",
             "c_preferred_cust_flag", "ss_ticket_number", "cnt"]]
    return _srt(
        out, ["cnt", "c_last_name", "c_first_name", "ss_ticket_number"],
        ascending=[False, True, True, True],
    ).head(100)


def _city_mismatch(t, dow_filter, hd_filter, aggs):
    j = t["store_sales"].merge(
        t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk"
    )
    j = j[dow_filter(j) & j.d_year.isin([1999, 2000, 2001])]
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["household_demographics"], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
    j = j[hd_filter(j)]
    j = j.merge(t["customer_address"], left_on="ss_addr_sk",
                right_on="ca_address_sk")
    g = j.groupby(
        ["ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "ca_city"],
        as_index=False,
    ).agg(**aggs)
    g = g.rename(columns={"ca_city": "bought_city"})
    g = g.merge(t["customer"], left_on="ss_customer_sk",
                right_on="c_customer_sk")
    g = g.merge(
        t["customer_address"].add_prefix("cur_"),
        left_on="c_current_addr_sk", right_on="cur_ca_address_sk",
    )
    return g[g.cur_ca_city != g.bought_city]


def q46(t):
    g = _city_mismatch(
        t, lambda j: j.d_dow.isin([0, 6]),
        lambda j: (j.hd_dep_count == 5) | (j.hd_vehicle_count == 3),
        dict(amt=("ss_coupon_amt", "sum"), profit=("ss_net_profit", "sum")),
    )
    out = g[["c_last_name", "c_first_name", "cur_ca_city", "bought_city",
             "ss_ticket_number", "amt", "profit"]]
    return _srt(out, ["c_last_name", "c_first_name", "cur_ca_city",
                      "bought_city", "ss_ticket_number"]).head(100)


def q68(t):
    g = _city_mismatch(
        t, lambda j: j.d_dom.between(1, 2),
        lambda j: (j.hd_dep_count == 5) | (j.hd_vehicle_count == 3),
        dict(extended_price=("ss_ext_sales_price", "sum"),
             list_price=("ss_ext_list_price", "sum"),
             extended_tax=("ss_ext_tax", "sum")),
    )
    out = g[["c_last_name", "c_first_name", "cur_ca_city", "bought_city",
             "ss_ticket_number", "extended_price", "extended_tax",
             "list_price"]]
    return _srt(out, ["c_last_name", "cur_ca_city", "bought_city",
                      "ss_ticket_number"]).head(100)


def q48(t):
    j = t["store_sales"].merge(
        t["store"], left_on="ss_store_sk", right_on="s_store_sk"
    ).merge(t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2001]
    j = j.merge(t["customer_demographics"], left_on="ss_cdemo_sk",
                right_on="cd_demo_sk")
    j = j.merge(t["customer_address"], left_on="ss_addr_sk",
                right_on="ca_address_sk")
    demo = (
        ((j.cd_marital_status == "M") & (j.cd_education_status == "4 yr Degree")
         & j.ss_sales_price.between(50.0, 150.0))
        | ((j.cd_marital_status == "D") & (j.cd_education_status == "2 yr Degree")
           & j.ss_sales_price.between(10.0, 100.0))
        | ((j.cd_marital_status == "S") & (j.cd_education_status == "College")
           & j.ss_sales_price.between(50.0, 200.0))
    )
    geo = (
        (j.ca_state.isin(["CO", "OH", "TX"]) & j.ss_net_profit.between(0, 22000))
        | (j.ca_state.isin(["OR", "MN", "KY"]) & j.ss_net_profit.between(0, 30000))
        | (j.ca_state.isin(["VA", "CA", "MS"]) & j.ss_net_profit.between(0, 25000))
    )
    j = j[demo & geo & (j.ca_country == "United States")]
    return pd.DataFrame({"total_quantity": [j.ss_quantity.sum()]})


def q65(t):
    f = t["store_sales"].merge(
        t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk"
    )
    f = f[f.d_month_seq.between(1200, 1211)]
    sc = f.groupby(["ss_store_sk", "ss_item_sk"], as_index=False).agg(
        revenue=("ss_sales_price", "sum")
    )
    sb = sc.groupby("ss_store_sk", as_index=False).agg(ave=("revenue", "mean"))
    j = sc.merge(sb, on="ss_store_sk")
    j = j[j.revenue <= 1.0 * j.ave]
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"], left_on="ss_item_sk", right_on="i_item_sk")
    out = j[["s_store_name", "i_item_desc", "revenue", "i_current_price",
             "i_wholesale_cost", "i_brand"]]
    return _srt(out, ["s_store_name", "i_item_desc", "revenue"]).head(100)


def q85(t):
    j = t["web_sales"].merge(
        t["web_returns"],
        left_on=["ws_item_sk", "ws_order_number"],
        right_on=["wr_item_sk", "wr_order_number"],
    ).merge(t["web_page"], left_on="ws_web_page_sk", right_on="wp_web_page_sk")
    j = j.merge(t["date_dim"], left_on="ws_sold_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2000]
    cd1 = t["customer_demographics"].add_prefix("cd1_")
    cd2 = t["customer_demographics"].add_prefix("cd2_")
    j = j.merge(cd1, left_on="wr_refunded_cdemo_sk", right_on="cd1_cd_demo_sk")
    j = j.merge(cd2, left_on="wr_returning_cdemo_sk", right_on="cd2_cd_demo_sk")
    j = j.merge(t["customer_address"], left_on="wr_refunded_addr_sk",
                right_on="ca_address_sk")
    j = j.merge(t["reason"], left_on="wr_reason_sk", right_on="r_reason_sk")
    demo = (
        ((j.cd1_cd_marital_status == "M") & j.ws_sales_price.between(50.0, 150.0))
        | ((j.cd1_cd_marital_status == "S") & j.ws_sales_price.between(10.0, 100.0))
        | ((j.cd1_cd_marital_status == "W") & j.ws_sales_price.between(50.0, 200.0))
    )
    geo = (
        (j.ca_state.isin(["IN", "OH", "NJ"])
         & j.ws_net_profit.between(-10000, 10000))
        | (j.ca_state.isin(["WI", "CT", "KY"])
           & j.ws_net_profit.between(-10000, 20000))
        | (j.ca_state.isin(["LA", "IA", "AR"])
           & j.ws_net_profit.between(-10000, 30000))
    )
    j = j[demo & geo]
    g = j.groupby("r_reason_desc", as_index=False).agg(
        q=("ws_quantity", "mean"), rc=("wr_refunded_cash", "mean"),
        f=("wr_fee", "mean"),
    )
    return _srt(g, ["r_reason_desc"]).head(100)


def _traffic_count(t, hour, half):
    j = t["store_sales"].merge(
        t["time_dim"], left_on="ss_sold_time_sk", right_on="t_time_sk"
    ).merge(t["household_demographics"], left_on="ss_hdemo_sk",
            right_on="hd_demo_sk").merge(
        t["store"], left_on="ss_store_sk", right_on="s_store_sk"
    )
    j = j[(j.t_hour == hour)
          & ((j.t_minute >= 30) if half else (j.t_minute < 30))]
    j = j[
        ((j.hd_dep_count == 4) & (j.hd_vehicle_count <= 6))
        | ((j.hd_dep_count == 2) & (j.hd_vehicle_count <= 4))
        | ((j.hd_dep_count == 0) & (j.hd_vehicle_count <= 2))
    ]
    return len(j[j.s_store_name == "ese"])


def q88(t):
    return pd.DataFrame({
        "h8_30_to_9": [_traffic_count(t, 8, True)],
        "h9_to_9_30": [_traffic_count(t, 9, False)],
        "h9_30_to_10": [_traffic_count(t, 9, True)],
        "h10_to_10_30": [_traffic_count(t, 10, False)],
    })


def q90(t):
    def cnt(lo, hi):
        j = t["web_sales"].merge(
            t["time_dim"], left_on="ws_sold_time_sk", right_on="t_time_sk"
        ).merge(t["web_page"], left_on="ws_web_page_sk",
                right_on="wp_web_page_sk")
        j = j[j.t_hour.between(lo, hi) & j.wp_char_count.between(2000, 6000)]
        return len(j)

    return pd.DataFrame({"am_pm_ratio": [cnt(8, 9) / cnt(19, 20)]})


# -- round-3 breadth (batch 3)


def q1(t):
    ctr = t["store_returns"].merge(
        t["date_dim"], left_on="sr_returned_date_sk", right_on="d_date_sk"
    )
    ctr = ctr[ctr.d_year == 2000]
    ctr = ctr.groupby(["sr_customer_sk", "sr_store_sk"], as_index=False).agg(
        ctr_total_return=("sr_return_amt", "sum")
    )
    ave = ctr.groupby("sr_store_sk")["ctr_total_return"].mean().rename(
        "store_avg"
    ).reset_index()
    j = ctr.merge(ave, on="sr_store_sk")
    j = j[j.ctr_total_return > 1.2 * j.store_avg]
    j = j.merge(t["store"], left_on="sr_store_sk", right_on="s_store_sk")
    j = j.merge(t["customer"], left_on="sr_customer_sk",
                right_on="c_customer_sk")
    out = j[["c_customer_id"]]
    return _srt(out, ["c_customer_id"]).head(100)


def _multi_order_unreturned(t, fact, prefix, returns, rprefix, extra):
    f = t[fact]
    dd = t["date_dim"]
    dd = dd[(dd.d_date >= D("2000-03-01")) & (dd.d_date <= D("2000-06-30"))]
    j = f.merge(dd, left_on=f"{prefix}_ship_date_sk", right_on="d_date_sk")
    j = j.merge(t["customer_address"], left_on=f"{prefix}_ship_addr_sk",
                right_on="ca_address_sk")
    j = extra(j)
    # EXISTS (official): the same order shipped from ANOTHER warehouse —
    # the order has >=2 distinct non-null warehouses and this row's
    # warehouse is non-null
    n_wh = f.groupby(f"{prefix}_order_number")[
        f"{prefix}_warehouse_sk"
    ].nunique().rename("n_wh").reset_index()
    j = j.merge(n_wh, on=f"{prefix}_order_number")
    j = j[(j.n_wh > 1) & j[f"{prefix}_warehouse_sk"].notna()]
    # NOT EXISTS: order never returned
    returned = set(t[returns][f"{rprefix}_order_number"].dropna())
    j = j[~j[f"{prefix}_order_number"].isin(returned)]
    return pd.DataFrame(
        {"order_count": [j[f"{prefix}_order_number"].nunique()]}
    )


def q16(t):
    def extra(j):
        return j.merge(t["call_center"], left_on="cs_call_center_sk",
                       right_on="cc_call_center_sk")

    return _multi_order_unreturned(
        t, "catalog_sales", "cs", "catalog_returns", "cr", extra
    )


def q94(t):
    def extra(j):
        w = t["web_site"]
        w = w[w.web_company_name.str.strip() == "able"]
        return j.merge(w, left_on="ws_web_site_sk", right_on="web_site_sk")

    return _multi_order_unreturned(
        t, "web_sales", "ws", "web_returns", "wr", extra
    )


def _channel_union(t, item_filter, year, group_col):
    it = t["item"]
    wanted = set(it[item_filter(it)][group_col])
    parts = []
    for fact, prefix in (("store_sales", "ss"), ("catalog_sales", "cs"),
                         ("web_sales", "ws")):
        f = t[fact].merge(t["date_dim"], left_on=f"{prefix}_sold_date_sk",
                          right_on="d_date_sk")
        f = f[f.d_year == year]
        f = f.merge(it, left_on=f"{prefix}_item_sk", right_on="i_item_sk")
        f = f[f[group_col].isin(wanted)]
        g = f.groupby(group_col, as_index=False).agg(
            total_sales=(f"{prefix}_ext_sales_price", "sum")
        )
        parts.append(g)
    u = pd.concat(parts, ignore_index=True)
    g = u.groupby(group_col, as_index=False).agg(
        total_sales=("total_sales", "sum")
    )
    return _srt(g, ["total_sales", group_col]).head(100)[
        [group_col, "total_sales"]
    ]


def q33(t):
    return _channel_union(
        t, lambda it: it.i_category.isin(["Books"]), 2000, "i_manufact_id"
    )


def q56(t):
    return _channel_union(
        t, lambda it: it.i_color.isin(["blue", "orchid", "pink"]), 2000,
        "i_item_id",
    )


def q60(t):
    return _channel_union(
        t, lambda it: it.i_category.isin(["Music"]), 1999, "i_item_id"
    )


def q71(t):
    parts = []
    for fact, prefix in (("web_sales", "ws"), ("catalog_sales", "cs"),
                         ("store_sales", "ss")):
        f = t[fact].merge(t["date_dim"], left_on=f"{prefix}_sold_date_sk",
                          right_on="d_date_sk")
        f = f[(f.d_moy == 11) & (f.d_year == 2000)]
        parts.append(pd.DataFrame({
            "ext_price": f[f"{prefix}_ext_sales_price"],
            "sold_item_sk": f[f"{prefix}_item_sk"],
            "time_sk": f[f"{prefix}_sold_time_sk"],
        }))
    u = pd.concat(parts, ignore_index=True)
    it = t["item"]
    it = it[it.i_manager_id <= 20]
    j = u.merge(it, left_on="sold_item_sk", right_on="i_item_sk")
    td = t["time_dim"]
    td = td[td.t_meal_time.isin(["breakfast", "dinner"])]
    j = j.merge(td, left_on="time_sk", right_on="t_time_sk")
    g = j.groupby(["i_brand_id", "i_brand", "t_hour", "t_minute"],
                  as_index=False).agg(ext_price=("ext_price", "sum"))
    g = g.rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
    out = _srt(g, ["ext_price", "brand_id", "t_hour", "t_minute"],
               ascending=[False, True, True, True]).head(100)
    return out[["brand_id", "brand", "t_hour", "t_minute", "ext_price"]]


def q76(t):
    parts = []
    for ch, colname, nullcol, fact, prefix in (
            ("store", "ss_store_sk", "ss_store_sk", "store_sales", "ss"),
            ("web", "ws_ship_customer_sk", "ws_ship_customer_sk",
             "web_sales", "ws"),
            ("catalog", "cs_ship_addr_sk", "cs_ship_addr_sk",
             "catalog_sales", "cs")):
        f = t[fact]
        f = f[f[nullcol].isna()]
        f = f.merge(t["date_dim"], left_on=f"{prefix}_sold_date_sk",
                    right_on="d_date_sk")
        f = f.merge(t["item"], left_on=f"{prefix}_item_sk",
                    right_on="i_item_sk")
        parts.append(pd.DataFrame({
            "channel": ch, "col_name": colname, "d_year": f.d_year,
            "d_qoy": f.d_qoy, "i_category": f.i_category,
            "ext_sales_price": f[f"{prefix}_ext_sales_price"],
        }))
    u = pd.concat(parts, ignore_index=True)
    g = u.groupby(["channel", "col_name", "d_year", "d_qoy", "i_category"],
                  as_index=False).agg(
        sales_cnt=("ext_sales_price", "size"),
        sales_amt=("ext_sales_price", "sum"),
    )
    return _srt(g, ["channel", "col_name", "d_year", "d_qoy",
                    "i_category"]).head(100)


def q22(t):
    j = t["inventory"].merge(
        t["date_dim"], left_on="inv_date_sk", right_on="d_date_sk"
    ).merge(t["item"], left_on="inv_item_sk", right_on="i_item_sk")
    j = j[j.d_month_seq.between(1200, 1211)]
    # NULL-able int decodes as an object column; numeric mean needs float
    j = j.assign(inv_quantity_on_hand=pd.to_numeric(j.inv_quantity_on_hand))
    rollup_cols = ["i_product_name", "i_brand", "i_class", "i_category"]
    levels = [rollup_cols[:k] for k in range(len(rollup_cols), -1, -1)]
    parts = []
    for lv in levels:
        if lv:
            g = j.groupby(lv, as_index=False).agg(
                qoh=("inv_quantity_on_hand", "mean")
            )
        else:
            g = pd.DataFrame({"qoh": [j.inv_quantity_on_hand.mean()]})
        for c in rollup_cols:
            if c not in g:
                g[c] = None
        parts.append(g[rollup_cols + ["qoh"]])
    u = pd.concat(parts, ignore_index=True)
    u = u.sort_values(
        ["qoh"] + rollup_cols,
        na_position="last", kind="stable",
    ).reset_index(drop=True)
    return u.head(100)


def _margin_hierarchy(t, fact, prefix, num_col, den_col, asc, date_filter,
                      extra_dims):
    """Rollup(i_category, i_class) metric + rank within parent. The
    metric is sum(num)/sum(den) (den_col None -> just sum(num))."""
    f = t[fact].merge(t["date_dim"], left_on=f"{prefix}_sold_date_sk",
                      right_on="d_date_sk")
    f = date_filter(f)
    for table, lk, rk in extra_dims:
        f = f.merge(t[table], left_on=lk, right_on=rk)
    f = f.merge(t["item"], left_on=f"{prefix}_item_sk", right_on="i_item_sk")

    def metric_frame(g):
        if den_col is None:
            g["m"] = g["num"]
            return g.drop(columns=["num"])
        g["m"] = g["num"] / g["den"]
        return g.drop(columns=["num", "den"])

    levels = [(["i_category", "i_class"], 0), (["i_category"], 1), ([], 2)]
    parts = []
    for lv, loc in levels:
        agg = {"num": (num_col, "sum")}
        if den_col is not None:
            agg["den"] = (den_col, "sum")
        if lv:
            g = f.groupby(lv, as_index=False).agg(**agg)
        else:
            g = pd.DataFrame({k: [f[v[0]].sum()] for k, v in agg.items()})
        g = metric_frame(g)
        for c in ["i_category", "i_class"]:
            if c not in g:
                g[c] = None
        g["lochierarchy"] = loc
        parts.append(g[["m", "i_category", "i_class", "lochierarchy"]])
    u = pd.concat(parts, ignore_index=True)
    u["parent"] = np.where(u.lochierarchy == 0, u.i_category, None)
    u["rank_within_parent"] = (
        u.groupby(["lochierarchy", "parent"], dropna=False)["m"]
        .rank(method="min", ascending=asc).astype(np.int64)
    )
    # ORDER BY lochierarchy desc, parent nulls first, rank, i_class
    # nulls last — composed as stable sorts, least significant first
    u = u.sort_values("i_class", na_position="last", kind="stable")
    u = u.sort_values("rank_within_parent", kind="stable")
    u = u.sort_values("parent", na_position="first", kind="stable")
    u = u.sort_values("lochierarchy", ascending=False, kind="stable")
    return u.drop(columns=["parent"]).reset_index(drop=True)


def q36(t):
    u = _margin_hierarchy(
        t, "store_sales", "ss", "ss_net_profit", "ss_ext_sales_price", True,
        lambda f: f[f.d_year == 2000],
        [("store", "ss_store_sk", "s_store_sk")],
    )
    u = u.rename(columns={"m": "gross_margin"})
    return u[["gross_margin", "i_category", "i_class",
              "lochierarchy", "rank_within_parent"]].head(100)


def q86(t):
    u = _margin_hierarchy(
        t, "web_sales", "ws", "ws_net_paid", None, False,
        lambda f: f[f.d_month_seq.between(1200, 1211)], [],
    )
    u = u.rename(columns={"m": "total_sum"})
    return u[["total_sum", "i_category", "i_class",
              "lochierarchy", "rank_within_parent"]].head(100)




def _channel_customer_days(t, fact, prefix, cust_col):
    f = t[fact].merge(t["date_dim"], left_on=f"{prefix}_sold_date_sk",
                      right_on="d_date_sk")
    f = f[f.d_month_seq.between(1200, 1211)]
    f = f.merge(t["customer"], left_on=cust_col, right_on="c_customer_sk")
    return set(map(tuple, f[["c_last_name", "c_first_name", "d_date"]]
                   .drop_duplicates().itertuples(index=False)))


def q38(t):
    ss = _channel_customer_days(t, "store_sales", "ss", "ss_customer_sk")
    cs = _channel_customer_days(t, "catalog_sales", "cs", "cs_bill_customer_sk")
    ws = _channel_customer_days(t, "web_sales", "ws", "ws_bill_customer_sk")
    return pd.DataFrame({"cnt": [len(ss & cs & ws)]})


def q87(t):
    ss = _channel_customer_days(t, "store_sales", "ss", "ss_customer_sk")
    cs = _channel_customer_days(t, "catalog_sales", "cs", "cs_bill_customer_sk")
    ws = _channel_customer_days(t, "web_sales", "ws", "ws_bill_customer_sk")
    return pd.DataFrame({"cnt": [len(ss - cs - ws)]})


# -- round-3 breadth (batch 4)


def q28(t):
    ss = t["store_sales"]
    bands = [
        ((0, 5), (8, 108), (0, 1000), (7, 57)),
        ((6, 10), (9, 109), (0, 2000), (31, 81)),
        ((11, 15), (14, 114), (0, 3000), (17, 67)),
        ((16, 20), (6, 106), (0, 4000), (30, 80)),
        ((21, 25), (10, 110), (0, 5000), (37, 87)),
        ((26, 30), (17, 117), (0, 6000), (33, 83)),
    ]
    out = {}
    for i, (q, lp, cp, wc) in enumerate(bands, 1):
        f = ss[ss.ss_quantity.between(*q)
               & (ss.ss_list_price.between(*lp)
                  | ss.ss_coupon_amt.between(*cp)
                  | ss.ss_wholesale_cost.between(*wc))]
        out[f"b{i}_cntd"] = [f.ss_list_price.dropna().nunique()]
    return pd.DataFrame(out)


def _returners_above_state_avg(t, returns, cust_col, addr_col, amt_col):
    date_col = [c for c in t[returns].columns
                if c.endswith("returned_date_sk")][0]
    ctr = t[returns].merge(
        t["date_dim"], left_on=date_col, right_on="d_date_sk"
    )
    ctr = ctr[ctr.d_year == 2000]
    ctr = ctr.merge(t["customer_address"], left_on=addr_col,
                    right_on="ca_address_sk")
    # dropna=False: SQL keeps the NULL-customer group (the generator
    # makes wr_returning_customer_sk ~2% NULL), and the per-state
    # average in the subquery includes it
    g = ctr.groupby([cust_col, "ca_state"], as_index=False,
                    dropna=False).agg(
        ctr_total_return=(amt_col, "sum")
    )
    ave = g.groupby("ca_state")["ctr_total_return"].mean().rename(
        "state_avg"
    ).reset_index()
    j = g.merge(ave, on="ca_state")
    j = j[j.ctr_total_return > 1.2 * j.state_avg]
    j = j.merge(t["customer"], left_on=cust_col, right_on="c_customer_sk")
    out = j[["c_customer_id", "c_salutation", "c_first_name", "c_last_name",
             "ctr_total_return"]]
    return _srt(out, ["c_customer_id", "ctr_total_return"]).head(100)


def q30(t):
    return _returners_above_state_avg(
        t, "web_returns", "wr_returning_customer_sk", "wr_refunded_addr_sk",
        "wr_return_amt",
    )


def q81(t):
    return _returners_above_state_avg(
        t, "catalog_returns", "cr_returning_customer_sk",
        "cr_returning_addr_sk", "cr_return_amount",
    )


def q50(t):
    j = t["store_sales"].merge(
        t["store_returns"],
        left_on=["ss_ticket_number", "ss_item_sk", "ss_customer_sk"],
        right_on=["sr_ticket_number", "sr_item_sk", "sr_customer_sk"],
    )
    dd = t["date_dim"]
    dd = dd[(dd.d_year == 2000) & (dd.d_moy == 8)]
    j = j.merge(dd, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    lag = j.sr_returned_date_sk - j.ss_sold_date_sk
    j = j.assign(
        d30=(lag <= 30).astype(int),
        d60=((lag > 30) & (lag <= 60)).astype(int),
        d90=((lag > 60) & (lag <= 90)).astype(int),
        d120=(lag > 90).astype(int),
    )
    g = j.groupby(["s_store_sk", "s_store_name", "s_store_id", "s_state"],
                  as_index=False)[["d30", "d60", "d90", "d120"]].sum()
    g = g.drop(columns=["s_store_sk"])
    return _srt(g, ["s_store_name", "s_store_id", "s_state"]).head(100)


def q61(t):
    def revenue(with_promo):
        f = t["store_sales"].merge(
            t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk"
        )
        f = f[f.d_year == 2000]
        f = f.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
        f = f.merge(t["customer"], left_on="ss_customer_sk",
                    right_on="c_customer_sk")
        f = f.merge(t["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        f = f[f.ca_gmt_offset <= -5]
        it = t["item"]
        f = f.merge(it[it.i_category == "Jewelry"], left_on="ss_item_sk",
                    right_on="i_item_sk")
        if with_promo:
            p = t["promotion"]
            p = p[(p.p_channel_dmail == "Y") | (p.p_channel_email == "Y")
                  | (p.p_channel_tv == "Y")]
            f = f.merge(p, left_on="ss_promo_sk", right_on="p_promo_sk")
        return f.ss_ext_sales_price.sum()

    promo = revenue(True)
    total = revenue(False)
    share = (float(promo) / float(total) * 100) if total else np.nan
    return pd.DataFrame({"promotions": [promo], "total": [total],
                         "share": [share]})


def q69(t):
    c = t["customer"].merge(
        t["customer_address"], left_on="c_current_addr_sk",
        right_on="ca_address_sk",
    )
    c = c[c.ca_state.isin(["KY", "GA", "NM", "CA", "TX", "OH"])]
    c = c.merge(t["customer_demographics"], left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")

    def buyers(fact, prefix, cust_col):
        f = t[fact].merge(t["date_dim"], left_on=f"{prefix}_sold_date_sk",
                          right_on="d_date_sk")
        return set(f[f.d_year == 2001][cust_col].dropna())

    ss = buyers("store_sales", "ss", "ss_customer_sk")
    ws = buyers("web_sales", "ws", "ws_bill_customer_sk")
    cs = buyers("catalog_sales", "cs", "cs_bill_customer_sk")
    c = c[c.c_customer_sk.isin(ss - ws - cs)]
    g = c.groupby(["cd_gender", "cd_marital_status", "cd_education_status",
                   "cd_purchase_estimate"], as_index=False).size()
    g["cnt1"] = g["size"]
    g["cnt2"] = g["size"]
    g = g[["cd_gender", "cd_marital_status", "cd_education_status", "cnt1",
           "cd_purchase_estimate", "cnt2"]]
    return _srt(g, ["cd_gender", "cd_marital_status", "cd_education_status",
                    "cd_purchase_estimate"]).head(100)


# -- round-3 breadth (batch 5)


def q6(t):
    dd = t["date_dim"]
    mseq = dd[(dd.d_year == 2001) & (dd.d_moy == 1)].d_month_seq.unique()
    assert len(mseq) == 1
    it = t["item"]
    cat_avg = it.groupby("i_category")["i_current_price"].mean().rename(
        "cat_avg"
    ).reset_index()
    it = it.merge(cat_avg, on="i_category")
    it = it[it.i_current_price > 1.2 * it.cat_avg]
    j = t["store_sales"].merge(dd[dd.d_month_seq == mseq[0]],
                               left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(t["customer"], left_on="ss_customer_sk",
                right_on="c_customer_sk")
    j = j.merge(t["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    g = j.groupby("ca_state", dropna=False, as_index=False).size().rename(
        columns={"size": "cnt", "ca_state": "state"}
    )
    g = g[g.cnt >= 1]
    return _srt(g[["state", "cnt"]], ["cnt", "state"]).head(100)


def q9(t):
    ss = t["store_sales"]
    out = {}
    for i, (lo, hi) in enumerate(
        [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)], 1
    ):
        f = ss[ss.ss_quantity.between(lo, hi)]
        v = (f.ss_ext_discount_amt.mean() if len(f) > 1000
             else f.ss_net_paid.mean())
        out[f"bucket{i}"] = [v]
    return pd.DataFrame(out)


def q59(t):
    j = t["store_sales"].merge(t["date_dim"], left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    days = [("Sunday", "sun"), ("Monday", "mon"), ("Friday", "fri"),
            ("Saturday", "sat")]
    for d, tag in days:
        j[f"{tag}_sales"] = j.ss_sales_price.where(j.d_day_name == d)
    wss = j.groupby(["d_week_seq", "ss_store_sk"], as_index=False)[
        [f"{tag}_sales" for _, tag in days]
    ].sum(min_count=1)
    # the SQL joins every date_dim DAY row of the week (multiplicity
    # up to 7, split across month boundaries) - mirror it exactly
    dd = t["date_dim"][["d_week_seq", "d_month_seq"]]
    wss = wss.merge(dd, on="d_week_seq")
    wss = wss.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    y = wss[wss.d_month_seq.between(1200, 1211)]
    x = wss[wss.d_month_seq.between(1212, 1223)]
    m = y.merge(x, left_on=["ss_store_sk"], right_on=["ss_store_sk"],
                suffixes=("1", "2"))
    m = m[m.d_week_seq1 == m.d_week_seq2 - 52]
    out = pd.DataFrame({
        "s_store_name1": m.s_store_name1,
        "d_week_seq1": m.d_week_seq1,
        "sun_r": m.sun_sales1 / m.sun_sales2,
        "mon_r": m.mon_sales1 / m.mon_sales2,
        "fri_r": m.fri_sales1 / m.fri_sales2,
        "sat_r": m.sat_sales1 / m.sat_sales2,
    })
    return _srt(out, ["s_store_name1", "d_week_seq1"]).head(100)


def q63(t):
    j = t["store_sales"].merge(t["date_dim"], left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j[j.d_month_seq.between(1200, 1211)]
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    it = t["item"]
    sel = (
        (it.i_category.isin(["Books", "Children", "Electronics"])
         & it.i_class.isin(["books-accent", "children-accent",
                            "electronics-accent"]))
        | (it.i_category.isin(["Women", "Music", "Men"])
           & it.i_class.isin(["women-pants", "music-pants", "men-pants"]))
    )
    j = j.merge(it[sel], left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_manager_id", "d_moy"], as_index=False).agg(
        sum_sales=("ss_sales_price", "sum")
    )
    g["avg_monthly_sales"] = g.groupby("i_manager_id")[
        "sum_sales"
    ].transform("mean")
    g = g[np.where(
        g.avg_monthly_sales > 0,
        np.abs(g.sum_sales - g.avg_monthly_sales) / g.avg_monthly_sales,
        0.0,
    ) > 0.1]
    out = g[["i_manager_id", "sum_sales", "avg_monthly_sales"]]
    return _srt(out, ["i_manager_id", "avg_monthly_sales", "sum_sales"]).head(100)


def q82(t):
    it = t["item"]
    it = it[it.i_current_price.between(20.0, 70.0) & (it.i_manufact_id <= 400)]
    j = it.merge(t["inventory"], left_on="i_item_sk", right_on="inv_item_sk")
    j = j.merge(t["date_dim"], left_on="inv_date_sk", right_on="d_date_sk")
    j = j[(j.d_date >= D("2000-05-25")) & (j.d_date <= D("2000-07-24"))]
    j = j[pd.to_numeric(j.inv_quantity_on_hand).between(100, 500)]
    j = j.merge(t["store_sales"][["ss_item_sk"]], left_on="i_item_sk",
                right_on="ss_item_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "i_current_price"],
                  as_index=False).size()[
        ["i_item_id", "i_item_desc", "i_current_price"]
    ]
    return _srt(g, ["i_item_id"]).head(100)


# -- round-3 breadth (batch 6)


def q2(t):
    parts = []
    for fact, prefix in (("web_sales", "ws"), ("catalog_sales", "cs")):
        f = t[fact]
        parts.append(pd.DataFrame({
            "sold_date_sk": f[f"{prefix}_sold_date_sk"],
            "sales_price": f[f"{prefix}_ext_sales_price"],
        }))
    wscs = pd.concat(parts, ignore_index=True)
    j = wscs.merge(t["date_dim"], left_on="sold_date_sk",
                   right_on="d_date_sk")
    for d, tag in (("Sunday", "sun"), ("Monday", "mon"), ("Friday", "fri"),
                   ("Saturday", "sat")):
        j[f"{tag}_sales"] = j.sales_price.where(j.d_day_name == d)
    wswscs = j.groupby("d_week_seq", as_index=False)[
        ["sun_sales", "mon_sales", "fri_sales", "sat_sales"]
    ].sum(min_count=1)
    dd = t["date_dim"][["d_week_seq", "d_year"]]
    wk = wswscs.merge(dd, on="d_week_seq")  # per-day multiplicity
    y = wk[wk.d_year == 2000]
    z = wk[wk.d_year == 2001]
    m = y.merge(z, how="cross", suffixes=("1", "2"))
    m = m[m.d_week_seq1 == m.d_week_seq2 - 53]
    out = pd.DataFrame({
        "d_week_seq1": m.d_week_seq1,
        "r_sun": (m.sun_sales1 / m.sun_sales2).round(2),
        "r_mon": (m.mon_sales1 / m.mon_sales2).round(2),
        "r_fri": (m.fri_sales1 / m.fri_sales2).round(2),
        "r_sat": (m.sat_sales1 / m.sat_sales2).round(2),
    })
    return _srt(out, ["d_week_seq1"]).head(100)


def q31(t):
    def channel(fact, prefix, addr_col, out_col):
        f = t[fact].merge(t["date_dim"], left_on=f"{prefix}_sold_date_sk",
                          right_on="d_date_sk")
        f = f.merge(t["customer_address"], left_on=addr_col,
                    right_on="ca_address_sk")
        return f.groupby(["ca_county", "d_qoy", "d_year"],
                         as_index=False).agg(
            **{out_col: (f"{prefix}_ext_sales_price", "sum")}
        )

    ss = channel("store_sales", "ss", "ss_addr_sk", "store_sales")
    ws = channel("web_sales", "ws", "ws_ship_addr_sk", "web_sales")

    def pick(g, q, col):
        f = g[(g.d_qoy == q) & (g.d_year == 2000)]
        return f[["ca_county", col]].rename(columns={col: f"{col}{q}"})

    m = pick(ss, 1, "store_sales").merge(pick(ss, 2, "store_sales"),
                                         on="ca_county")
    m = m.merge(pick(ws, 1, "web_sales"), on="ca_county")
    m = m.merge(pick(ws, 2, "web_sales"), on="ca_county")
    web_r = np.where(m.web_sales1 > 0, m.web_sales2 / m.web_sales1, np.nan)
    store_r = np.where(m.store_sales1 > 0,
                       m.store_sales2 / m.store_sales1, np.nan)
    keep = web_r > store_r  # NULL comparisons are false
    out = pd.DataFrame({
        "ca_county": m.ca_county[keep], "d_year": 2000,
        "web_q1_q2_increase": web_r[keep],
        "store_q1_q2_increase": store_r[keep],
    })
    return _srt(out, ["ca_county"]).head(100)


def q39(t):
    j = t["inventory"].merge(t["item"], left_on="inv_item_sk",
                             right_on="i_item_sk")
    j = j.merge(t["warehouse"], left_on="inv_warehouse_sk",
                right_on="w_warehouse_sk")
    j = j.merge(t["date_dim"], left_on="inv_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2000]
    j = j.assign(q=pd.to_numeric(j.inv_quantity_on_hand))
    g = j.groupby(["w_warehouse_sk", "i_item_sk", "d_moy"],
                  as_index=False).agg(stdev=("q", "std"), mean=("q", "mean"))
    g["cov"] = np.where(g["mean"] == 0, np.nan, g.stdev / g["mean"])
    g = g[np.where(g["mean"] == 0, 0.0, g.stdev / g["mean"]) > 0.5]
    a = g[g.d_moy == 1]
    b = g[g.d_moy == 2]
    m = a.merge(b, on=["w_warehouse_sk", "i_item_sk"], suffixes=("1", "2"))
    out = pd.DataFrame({
        "wsk1": m.w_warehouse_sk, "isk1": m.i_item_sk, "moy1": m.d_moy1,
        "mean1": m.mean1, "cov1": m.cov1, "moy2": m.d_moy2,
        "mean2": m.mean2, "cov2": m.cov2,
    })
    return _srt(out, ["wsk1", "isk1", "moy1", "mean1", "cov1"]).head(100)


def q44(t):
    ss = t["store_sales"]
    ss = ss[ss.ss_store_sk == 4]
    base = ss.ss_net_profit.mean()
    g = ss.groupby("ss_item_sk", as_index=False).agg(
        rank_col=("ss_net_profit", "mean")
    )
    g = g[g.rank_col > 0.9 * base]
    g["rnk_asc"] = g.rank_col.rank(method="min", ascending=True).astype(int)
    g["rnk_desc"] = g.rank_col.rank(method="min", ascending=False).astype(int)
    a = g[g.rnk_asc < 11][["ss_item_sk", "rnk_asc"]].rename(
        columns={"rnk_asc": "rnk"}
    )
    d = g[g.rnk_desc < 11][["ss_item_sk", "rnk_desc"]].rename(
        columns={"rnk_desc": "rnk"}
    )
    m = a.merge(d, on="rnk", suffixes=("_a", "_d"))
    it = t["item"][["i_item_sk", "i_product_name"]]
    m = m.merge(it, left_on="ss_item_sk_a", right_on="i_item_sk")
    m = m.rename(columns={"i_product_name": "best_performing"})
    m = m.merge(it, left_on="ss_item_sk_d", right_on="i_item_sk",
                suffixes=("", "_d"))
    m = m.rename(columns={"i_product_name": "worst_performing"})
    out = m[["rnk", "best_performing", "worst_performing"]]
    return _srt(out, ["rnk"]).head(100)


def _q47_like(t, fact, prefix, dim, fkey, pkey, dname, price_col):
    f = t[fact].merge(t["date_dim"], left_on=f"{prefix}_sold_date_sk",
                      right_on="d_date_sk")
    f = f[(f.d_year == 2000) | ((f.d_year == 1999) & (f.d_moy == 12))
          | ((f.d_year == 2001) & (f.d_moy == 1))]
    f = f.merge(t[dim], left_on=fkey, right_on=pkey)
    f = f.merge(t["item"], left_on=f"{prefix}_item_sk", right_on="i_item_sk")
    keys = ["i_category", "i_brand", dname]
    g = f.groupby(keys + ["d_year", "d_moy"], as_index=False).agg(
        sum_sales=(price_col, "sum")
    )
    g["avg_monthly_sales"] = g.groupby(keys + ["d_year"])[
        "sum_sales"
    ].transform("mean")
    g = g.sort_values(keys + ["d_year", "d_moy"], kind="stable")
    g["psum"] = g.groupby(keys)["sum_sales"].shift(1)
    g["nsum"] = g.groupby(keys)["sum_sales"].shift(-1)
    g = g[(g.d_year == 2000) & (g.avg_monthly_sales > 0)]
    g = g[np.abs(g.sum_sales - g.avg_monthly_sales) / g.avg_monthly_sales > 0.1]
    g["delta"] = g.sum_sales - g.avg_monthly_sales
    out = _srt(g, ["delta", "i_category", "i_brand", dname, "d_moy"]).head(100)
    return out[["i_category", "i_brand", dname, "d_year", "d_moy",
                "sum_sales", "avg_monthly_sales", "psum", "nsum"]]


def q47(t):
    return _q47_like(t, "store_sales", "ss", "store", "ss_store_sk",
                     "s_store_sk", "s_store_name", "ss_sales_price")


def q57(t):
    return _q47_like(t, "catalog_sales", "cs", "call_center",
                     "cs_call_center_sk", "cc_call_center_sk", "cc_name",
                     "cs_sales_price")


def q40(t):
    j = t["catalog_sales"].merge(
        t["catalog_returns"][["cr_order_number", "cr_item_sk",
                              "cr_refunded_cash"]],
        left_on=["cs_order_number", "cs_item_sk"],
        right_on=["cr_order_number", "cr_item_sk"], how="left",
    )
    it = t["item"]
    it = it[it.i_current_price.between(10.0, 60.0)]
    j = j.merge(it, left_on="cs_item_sk", right_on="i_item_sk")
    j = j.merge(t["warehouse"], left_on="cs_warehouse_sk",
                right_on="w_warehouse_sk")
    j = j.merge(t["date_dim"], left_on="cs_sold_date_sk",
                right_on="d_date_sk")
    lo = D("2000-03-11") - np.timedelta64(30, "D")
    hi = D("2000-03-11") + np.timedelta64(30, "D")
    j = j[(j.d_date >= lo) & (j.d_date <= hi)]
    net = j.cs_sales_price - j.cr_refunded_cash.fillna(0)
    pivot = D("2000-03-11")
    j = j.assign(
        sales_before=np.where(j.d_date < pivot, net, 0.0),
        sales_after=np.where(j.d_date >= pivot, net, 0.0),
    )
    g = j.groupby(["w_state", "i_item_id"], as_index=False)[
        ["sales_before", "sales_after"]
    ].sum()
    return _srt(g, ["w_state", "i_item_id"]).head(100)


def q18(t):
    cd = t["customer_demographics"]
    cd = cd[(cd.cd_gender == "F") & (cd.cd_education_status == "Unknown")]
    c = t["customer"]
    c = c[c.c_birth_month.isin([1, 2, 6, 8, 9, 12])]
    j = t["catalog_sales"].merge(t["date_dim"], left_on="cs_sold_date_sk",
                                 right_on="d_date_sk")
    j = j[j.d_year == 2001]
    j = j.merge(t["item"], left_on="cs_item_sk", right_on="i_item_sk")
    j = j.merge(cd, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(c, left_on="cs_bill_customer_sk", right_on="c_customer_sk")
    j = j.merge(t["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    aggs = {
        "agg1": "cs_quantity", "agg2": "cs_list_price",
        "agg3": "cs_coupon_amt", "agg4": "cs_sales_price",
        "agg5": "cs_net_profit", "agg6": "c_birth_year",
        "agg7": "cd_dep_count",
    }
    levels = [["i_item_id", "ca_country", "ca_state", "ca_county"],
              ["i_item_id", "ca_country", "ca_state"],
              ["i_item_id", "ca_country"], ["i_item_id"], []]
    parts = []
    for lv in levels:
        if lv:
            g = j.groupby(lv, as_index=False).agg(
                **{k: (v, "mean") for k, v in aggs.items()}
            )
        else:
            g = pd.DataFrame({k: [j[v].mean()] for k, v in aggs.items()})
        for col in ["i_item_id", "ca_country", "ca_state", "ca_county"]:
            if col not in g:
                g[col] = None
        parts.append(g[["i_item_id", "ca_country", "ca_state", "ca_county"]
                       + list(aggs)])
    u = pd.concat(parts, ignore_index=True)
    u = u.sort_values("i_item_id", na_position="last", kind="stable")
    u = u.sort_values("ca_county", na_position="last", kind="stable")
    u = u.sort_values("ca_state", na_position="last", kind="stable")
    u = u.sort_values("ca_country", na_position="last", kind="stable")
    return u.reset_index(drop=True).head(100)


def q5(t):
    lo = D("2000-08-03")
    hi = lo + np.timedelta64(14, "D")
    dd = t["date_dim"]
    dd = dd[(dd.d_date >= lo) & (dd.d_date <= hi)][["d_date_sk"]]

    def channel(sales, s_unit, s_date, s_price, s_profit,
                rets, r_unit, r_date, r_amt, r_loss, dim_keys):
        s = pd.DataFrame({
            "unit_sk": sales[s_unit], "date_sk": sales[s_date],
            "sales_price": sales[s_price], "profit": sales[s_profit],
            "return_amt": 0.0, "net_loss": 0.0,
        })
        r = pd.DataFrame({
            "unit_sk": rets[r_unit], "date_sk": rets[r_date],
            "sales_price": 0.0, "profit": 0.0,
            "return_amt": rets[r_amt], "net_loss": rets[r_loss],
        })
        u = pd.concat([s, r], ignore_index=True)
        u = u.merge(dd, left_on="date_sk", right_on="d_date_sk")
        u = u[u.unit_sk.isin(dim_keys)]
        g = u.groupby("unit_sk", as_index=False).agg(
            sales=("sales_price", "sum"), returns_=("return_amt", "sum"),
            profit=("profit", "sum"), profit_loss=("net_loss", "sum"),
        )
        g["profit"] = g.profit - g.profit_loss
        return g.rename(columns={"unit_sk": "id"})[
            ["id", "sales", "returns_", "profit"]
        ]

    wr = t["web_returns"].merge(
        t["web_sales"][["ws_item_sk", "ws_order_number", "ws_web_site_sk"]],
        left_on=["wr_item_sk", "wr_order_number"],
        right_on=["ws_item_sk", "ws_order_number"],
    )
    parts = [
        channel(t["store_sales"], "ss_store_sk", "ss_sold_date_sk",
                "ss_ext_sales_price", "ss_net_profit",
                t["store_returns"], "sr_store_sk", "sr_returned_date_sk",
                "sr_return_amt", "sr_net_loss",
                set(t["store"].s_store_sk)).assign(channel=1),
        channel(t["catalog_sales"], "cs_call_center_sk", "cs_sold_date_sk",
                "cs_ext_sales_price", "cs_net_profit",
                t["catalog_returns"], "cr_call_center_sk",
                "cr_returned_date_sk", "cr_return_amount", "cr_net_loss",
                set(t["call_center"].cc_call_center_sk)).assign(channel=2),
        channel(t["web_sales"], "ws_web_site_sk", "ws_sold_date_sk",
                "ws_ext_sales_price", "ws_net_profit",
                wr, "ws_web_site_sk", "wr_returned_date_sk",
                "wr_return_amt", "wr_net_loss",
                set(t["web_site"].web_site_sk)).assign(channel=3),
    ]
    x = pd.concat(parts, ignore_index=True)
    detail = x.groupby(["channel", "id"], as_index=False)[
        ["sales", "returns_", "profit"]
    ].sum()
    per_ch = x.groupby("channel", as_index=False)[
        ["sales", "returns_", "profit"]
    ].sum()
    per_ch["id"] = None
    total = pd.DataFrame({
        "channel": [None], "id": [None], "sales": [x.sales.sum()],
        "returns_": [x.returns_.sum()], "profit": [x.profit.sum()],
    })
    u = pd.concat(
        [detail, per_ch[["channel", "id", "sales", "returns_", "profit"]],
         total], ignore_index=True,
    )
    u = u.sort_values("id", na_position="last", kind="stable")
    u = u.sort_values("channel", na_position="last", kind="stable")
    return u[["channel", "id", "sales", "returns_", "profit"]].reset_index(
        drop=True
    ).head(100)

def q97(t):
    d = t["date_dim"]
    dd = d[(d.d_month_seq >= 1200) & (d.d_month_seq <= 1211)][["d_date_sk"]]
    ss = t["store_sales"].merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    ssci = ss[["ss_customer_sk", "ss_item_sk"]].drop_duplicates()
    cs = t["catalog_sales"].merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
    csci = cs[["cs_bill_customer_sk", "cs_item_sk"]].drop_duplicates()
    # NULL keys never match in SQL (pandas outer merge WOULD match
    # NaN==NaN): count inner matches among fully-non-null pairs, then
    # derive the full-outer buckets arithmetically (both sides are
    # duplicate-free on the pair).
    both = ssci.dropna().merge(
        csci.dropna(),
        left_on=["ss_customer_sk", "ss_item_sk"],
        right_on=["cs_bill_customer_sk", "cs_item_sk"],
    )
    n = len(both)
    return pd.DataFrame({
        "store_only": [len(ssci) - n],
        "catalog_only": [len(csci) - n],
        "store_and_catalog": [n],
    })


def q51(t):
    d = t["date_dim"]
    dd = d[(d.d_month_seq >= 1200) & (d.d_month_seq <= 1211)][
        ["d_date_sk", "d_date"]
    ]

    def v1(tbl, item, date_col, price):
        j = t[tbl].merge(dd, left_on=date_col, right_on="d_date_sk")
        j = j[j[item].notna()]
        g = j.groupby([item, "d_date"], as_index=False).agg(s=(price, "sum"))
        g = g.sort_values([item, "d_date"], kind="stable")
        g["cume_sales"] = g.groupby(item)["s"].cumsum()
        return g.rename(columns={item: "item_sk"})[
            ["item_sk", "d_date", "cume_sales"]
        ]

    web = v1("web_sales", "ws_item_sk", "ws_sold_date_sk", "ws_sales_price")
    store = v1("store_sales", "ss_item_sk", "ss_sold_date_sk", "ss_sales_price")
    # keys are non-null (filtered above), so pandas outer == SQL full outer
    m = web.merge(store, on=["item_sk", "d_date"], how="outer",
                  suffixes=("_w", "_s"))
    m = m.rename(columns={"cume_sales_w": "web_sales",
                          "cume_sales_s": "store_sales"})
    m = m.sort_values(["item_sk", "d_date"], kind="stable")
    # SQL running MAX ignores NULLs: pandas cummax leaves NaN at NaN
    # input positions, so forward-fill within the partition (an all-NaN
    # prefix stays NaN, matching MAX over an empty value set)
    for out, src in (("web_cumulative", "web_sales"),
                     ("store_cumulative", "store_sales")):
        m[out] = m.groupby("item_sk")[src].cummax()
        m[out] = m.groupby("item_sk")[out].ffill()
    r = m[m.web_cumulative > m.store_cumulative]
    r = r.sort_values(["item_sk", "d_date"], kind="stable").head(100)
    return r[["item_sk", "d_date", "web_sales", "store_sales",
              "web_cumulative", "store_cumulative"]].reset_index(drop=True)


def q27(t):
    j = _ss_dd_it(t).merge(
        t["customer_demographics"], left_on="ss_cdemo_sk",
        right_on="cd_demo_sk",
    ).merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j[(j.cd_gender == "M") & (j.cd_marital_status == "S")
          & (j.cd_education_status == "College") & (j.d_year == 2000)
          & (j.s_state.isin(["HI", "KY", "LA"]))]
    vals = ["ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price"]

    def level(keys):
        if keys:
            g = j.groupby(keys, as_index=False, dropna=False)[vals].mean()
        else:
            g = j[vals].mean().to_frame().T
        return g

    detail = level(["i_item_id", "s_state"]); detail["g_state"] = 0
    sub = level(["i_item_id"]); sub["g_state"] = 1; sub["s_state"] = None
    grand = level([]); grand["g_state"] = 1
    grand["i_item_id"] = None; grand["s_state"] = None
    u = pd.concat([detail, sub, grand], ignore_index=True)
    u = u.sort_values(["i_item_id", "s_state"], na_position="last",
                      kind="stable").head(100)
    u = u.rename(columns=dict(zip(vals, ["agg1", "agg2", "agg3", "agg4"])))
    return u[["i_item_id", "s_state", "g_state",
              "agg1", "agg2", "agg3", "agg4"]].reset_index(drop=True)


def q70(t):
    d = t["date_dim"]
    dd = d[(d.d_month_seq >= 1200) & (d.d_month_seq <= 1211)][["d_date_sk"]]
    j = t["store_sales"].merge(dd, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    # the official subquery ranks PARTITION BY s_state over a GROUP BY
    # s_state — one row per partition, so ranking is always 1 and the
    # `ranking <= 5` filter keeps every state (the well-known q70
    # quirk); mirror that exactly
    by_state = j.groupby("s_state")["ss_net_profit"].sum()
    j = j[j.s_state.isin(by_state.index)]
    detail = j.groupby(["s_state", "s_county"], as_index=False,
                       dropna=False).agg(total_sum=("ss_net_profit", "sum"))
    detail["lochierarchy"] = 0
    sub = j.groupby(["s_state"], as_index=False, dropna=False).agg(
        total_sum=("ss_net_profit", "sum"))
    sub["s_county"] = None; sub["lochierarchy"] = 1
    grand = pd.DataFrame({"total_sum": [j.ss_net_profit.sum()],
                          "s_state": [None], "s_county": [None],
                          "lochierarchy": [2]})
    u = pd.concat([detail, sub, grand], ignore_index=True)
    part_state = u.s_state.where(u.lochierarchy == 0, None)
    u["rank_within_parent"] = u.groupby(
        [u.lochierarchy, part_state], dropna=False
    )["total_sum"].rank(ascending=False, method="min").astype(int)
    u = u.sort_values(["s_state", "s_county"], na_position="last",
                      kind="stable")
    u = u.sort_values("rank_within_parent", kind="stable")
    u["ck"] = part_state
    u = u.sort_values("ck", na_position="last", kind="stable")
    u = u.sort_values("lochierarchy", ascending=False, kind="stable")
    return u[["total_sum", "s_state", "s_county", "lochierarchy",
              "rank_within_parent"]].head(100).reset_index(drop=True)


def q67(t):
    d = t["date_dim"]
    dd = d[(d.d_month_seq >= 1200) & (d.d_month_seq <= 1211)][
        ["d_date_sk", "d_year", "d_qoy", "d_moy"]]
    j = t["store_sales"].merge(dd, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"], left_on="ss_item_sk", right_on="i_item_sk")
    # exact integer cents: the engine ranks on exact scaled-int decimal
    # sums, so a float oracle can flip near-tie rank boundaries
    j["sales"] = (
        (j.ss_sales_price * 100).round().fillna(0).astype(np.int64)
        * j.ss_quantity.fillna(0).astype(np.int64)
    )
    cols = ["i_category", "i_class", "i_brand", "i_product_name",
            "d_year", "d_qoy", "d_moy", "s_store_id"]
    frames = []
    for k in range(len(cols), -1, -1):
        keys = cols[:k]
        if keys:
            g = j.groupby(keys, as_index=False, dropna=False).agg(
                sumsales=("sales", "sum"))
        else:
            g = pd.DataFrame({"sumsales": [j.sales.sum()]})
        for c in cols[k:]:
            g[c] = None
        frames.append(g)
    u = pd.concat(frames, ignore_index=True)
    u["rk"] = u.groupby("i_category", dropna=False)["sumsales"].rank(
        ascending=False, method="min").astype(int)
    u = u[u.rk <= 100]
    u = u.sort_values(["rk"], kind="stable")
    u = u.sort_values(["sumsales"], kind="stable")
    for c in reversed(cols):
        u = u.sort_values(c, na_position="last", kind="stable")
    u["sumsales"] = u.sumsales / 100.0
    return u[cols + ["sumsales", "rk"]].head(100).reset_index(drop=True)


def _active_customers(t, extra_pred):
    """Customers with store activity AND (web OR catalog) activity in
    the window (q10/q35 EXISTS semantics)."""
    d = t["date_dim"]
    dd = d[extra_pred(d)][["d_date_sk"]]
    c = t["customer"]
    ss = t["store_sales"].merge(dd, left_on="ss_sold_date_sk",
                                right_on="d_date_sk")
    ws = t["web_sales"].merge(dd, left_on="ws_sold_date_sk",
                              right_on="d_date_sk")
    cs = t["catalog_sales"].merge(dd, left_on="cs_sold_date_sk",
                                  right_on="d_date_sk")
    has_ss = c.c_customer_sk.isin(ss.ss_customer_sk.dropna())
    has_wc = (c.c_customer_sk.isin(ws.ws_bill_customer_sk.dropna())
              | c.c_customer_sk.isin(cs.cs_ship_customer_sk.dropna()))
    return c[has_ss & has_wc]


def q10(t):
    c = _active_customers(
        t, lambda d: (d.d_year == 2000) & (d.d_moy >= 1) & (d.d_moy <= 4))
    j = c.merge(t["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    j = j[j.ca_county.isin(["Williamson County", "Huron County",
                            "Daviess County", "Maricopa County",
                            "Ziebach County"])]
    j = j.merge(t["customer_demographics"], left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    keys = ["cd_gender", "cd_marital_status", "cd_education_status",
            "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count",
            "cd_dep_employed_count", "cd_dep_college_count"]
    g = j.groupby(keys, as_index=False, dropna=False).agg(
        cnt1=("cd_demo_sk", "size"))
    for n in ("cnt2", "cnt3", "cnt4", "cnt5", "cnt6"):
        g[n] = g.cnt1
    g = g.sort_values(keys, kind="stable").head(100)
    return g[["cd_gender", "cd_marital_status", "cd_education_status",
              "cnt1", "cd_purchase_estimate", "cnt2", "cd_credit_rating",
              "cnt3", "cd_dep_count", "cnt4", "cd_dep_employed_count",
              "cnt5", "cd_dep_college_count", "cnt6"]].reset_index(drop=True)


def q35(t):
    c = _active_customers(t, lambda d: (d.d_year == 2000) & (d.d_qoy < 4))
    j = c.merge(t["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    j = j.merge(t["customer_demographics"], left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    keys = ["ca_state", "cd_gender", "cd_marital_status", "cd_dep_count",
            "cd_dep_employed_count", "cd_dep_college_count"]
    g = j.groupby(keys, as_index=False, dropna=False).agg(
        cnt1=("cd_demo_sk", "size"),
        a1=("cd_dep_count", "mean"), m1=("cd_dep_count", "max"),
        s1=("cd_dep_count", "sum"),
        a2=("cd_dep_employed_count", "mean"),
        m2=("cd_dep_employed_count", "max"),
        s2=("cd_dep_employed_count", "sum"),
        a3=("cd_dep_college_count", "mean"),
        m3=("cd_dep_college_count", "max"),
        s3=("cd_dep_college_count", "sum"),
    )
    g["cnt2"] = g.cnt1
    g["cnt3"] = g.cnt1
    g = g.sort_values(keys, na_position="last", kind="stable").head(100)
    return g[["ca_state", "cd_gender", "cd_marital_status", "cd_dep_count",
              "cnt1", "a1", "m1", "s1", "cd_dep_employed_count", "cnt2",
              "a2", "m2", "s2", "cd_dep_college_count", "cnt3", "a3",
              "m3", "s3"]].reset_index(drop=True)


def q41(t):
    it = t["item"]
    c1 = (it.i_category == "Home") & it.i_size.isin(["medium", "economy"])
    c2 = ((it.i_category == "Electronics")
          & it.i_size.isin(["petite", "medium"]))
    c3 = (it.i_category == "Men") & it.i_size.isin(["medium", "economy"])
    c4 = ((it.i_category == "Jewelry")
          & it.i_size.isin(["petite", "extra large"]))
    good_manufacts = set(it[c1 | c2 | c3 | c4].i_manufact.dropna())
    sel = it[(it.i_manufact_id >= 600) & (it.i_manufact_id <= 800)
             & it.i_manufact.isin(good_manufacts)]
    names = sorted(sel.i_product_name.dropna().unique())[:100]
    return pd.DataFrame({"i_product_name": names})


def q84(t):
    cu = t["customer"]
    j = cu.merge(t["customer_address"], left_on="c_current_addr_sk",
                 right_on="ca_address_sk")
    j = j[j.ca_city.str.strip() == "after"]
    j = j.merge(t["customer_demographics"], left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    j = j.merge(t["household_demographics"], left_on="c_current_hdemo_sk",
                right_on="hd_demo_sk")
    ib = t["income_band"]
    ib = ib[(ib.ib_lower_bound >= 30001) & (ib.ib_upper_bound <= 80000)]
    j = j.merge(ib, left_on="hd_income_band_sk", right_on="ib_income_band_sk")
    j = j.merge(t["store_returns"], left_on="c_customer_sk",
                right_on="sr_customer_sk")
    j = j.sort_values("c_customer_id", kind="stable").head(100)
    # the engine's || emits the full fixed CHAR width of the left part
    # (c_last_name is bytes(30)); trailing padding of the final part is
    # stripped on decode
    name = (j.c_last_name.fillna("").str.ljust(30) + ", "
            + j.c_first_name.fillna("").str.ljust(20))
    return pd.DataFrame({"customer_id": j.c_customer_id.to_numpy(),
                         "customername": name.to_numpy()})


def q8(t):
    ca = t["customer_address"]
    ziplist = ["50183", "00355", "50970", "22225", "00565", "50602",
               "22614", "68502", "45287", "98313"]
    a = set(ca.ca_zip.dropna().str[:5]) & set(ziplist)
    pref = t["customer"][t["customer"].c_preferred_cust_flag == "Y"].merge(
        ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    vc = pref.ca_zip.dropna().str[:5].value_counts()
    b = set(vc[vc > 1].index)
    v1 = pd.DataFrame({"ca_zip2": [z[:2] for z in sorted(a & b)]})
    d = t["date_dim"]
    dd = d[(d.d_qoy == 2) & (d.d_year == 2000)][["d_date_sk"]]
    st = t["store"].copy()
    st["s_zip2"] = st.s_zip.str[:2]
    j = t["store_sales"].merge(dd, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(v1, left_on="s_zip2", right_on="ca_zip2")
    g = j.groupby("s_store_name", as_index=False).agg(
        profit=("ss_net_profit", "sum"))
    return g.sort_values("s_store_name", kind="stable").head(
        100).reset_index(drop=True)


def _q83_channel(t, tbl, item_col, date_col, qty_col):
    d = t["date_dim"]
    weeks = set(d[d.d_date.isin([D("2000-04-22"), D("2000-07-01"),
                                 D("2000-10-21")])].d_week_seq)
    dates = d[d.d_week_seq.isin(weeks)][["d_date_sk"]]
    j = t[tbl].merge(dates, left_on=date_col, right_on="d_date_sk")
    j = j.merge(t["item"], left_on=item_col, right_on="i_item_sk")
    return j.groupby("i_item_id", as_index=False).agg(q=(qty_col, "sum"))


def q83(t):
    sr = _q83_channel(t, "store_returns", "sr_item_sk",
                      "sr_returned_date_sk", "sr_return_quantity")
    cr = _q83_channel(t, "catalog_returns", "cr_item_sk",
                      "cr_returned_date_sk", "cr_return_quantity")
    wr = _q83_channel(t, "web_returns", "wr_item_sk",
                      "wr_returned_date_sk", "wr_return_quantity")
    j = sr.merge(cr, on="i_item_id", suffixes=("_sr", "_cr")).merge(
        wr, on="i_item_id")
    j = j.rename(columns={"q_sr": "sr_item_qty", "q_cr": "cr_item_qty",
                          "q": "wr_item_qty"})
    tot = j.sr_item_qty + j.cr_item_qty + j.wr_item_qty
    j["sr_dev"] = j.sr_item_qty / tot / 3.0 * 100
    j["cr_dev"] = j.cr_item_qty / tot / 3.0 * 100
    j["wr_dev"] = j.wr_item_qty / tot / 3.0 * 100
    j["average"] = tot / 3.0
    j = j.sort_values(["i_item_id", "sr_item_qty"], kind="stable").head(100)
    return j.rename(columns={"i_item_id": "item_id"})[
        ["item_id", "sr_item_qty", "sr_dev", "cr_item_qty", "cr_dev",
         "wr_item_qty", "wr_dev", "average"]].reset_index(drop=True)


def _q58_channel(t, tbl, item_col, date_col, rev_col):
    d = t["date_dim"]
    wk = d[d.d_date == D("2000-10-07")].d_week_seq.iloc[0]
    dates = d[d.d_week_seq == wk][["d_date_sk"]]
    j = t[tbl].merge(dates, left_on=date_col, right_on="d_date_sk")
    j = j.merge(t["item"], left_on=item_col, right_on="i_item_sk")
    return j.groupby("i_item_id", as_index=False).agg(r=(rev_col, "sum"))


def q58(t):
    ss = _q58_channel(t, "store_sales", "ss_item_sk", "ss_sold_date_sk",
                      "ss_ext_sales_price")
    cs = _q58_channel(t, "catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                      "cs_ext_sales_price")
    ws = _q58_channel(t, "web_sales", "ws_item_sk", "ws_sold_date_sk",
                      "ws_ext_sales_price")
    j = ss.merge(cs, on="i_item_id", suffixes=("_ss", "_cs")).merge(
        ws, on="i_item_id")
    j = j.rename(columns={"r_ss": "ss_item_rev", "r_cs": "cs_item_rev",
                          "r": "ws_item_rev"})
    m = ((j.ss_item_rev.between(0.1 * j.cs_item_rev, 10.0 * j.cs_item_rev))
         & (j.ss_item_rev.between(0.1 * j.ws_item_rev, 10.0 * j.ws_item_rev))
         & (j.cs_item_rev.between(0.1 * j.ss_item_rev, 10.0 * j.ss_item_rev))
         & (j.cs_item_rev.between(0.1 * j.ws_item_rev, 10.0 * j.ws_item_rev))
         & (j.ws_item_rev.between(0.1 * j.ss_item_rev, 10.0 * j.ss_item_rev))
         & (j.ws_item_rev.between(0.1 * j.cs_item_rev, 10.0 * j.cs_item_rev)))
    j = j[m]
    avg = (j.ss_item_rev + j.cs_item_rev + j.ws_item_rev) / 3
    j["ss_dev"] = j.ss_item_rev / avg * 100
    j["cs_dev"] = j.cs_item_rev / avg * 100
    j["ws_dev"] = j.ws_item_rev / avg * 100
    j["average"] = avg
    j = j.sort_values(["i_item_id", "ss_item_rev"], kind="stable").head(100)
    return j.rename(columns={"i_item_id": "item_id"})[
        ["item_id", "ss_item_rev", "ss_dev", "cs_item_rev", "cs_dev",
         "ws_item_rev", "ws_dev", "average"]].reset_index(drop=True)


_Q66_MONTHS = ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
               "sep", "oct", "nov", "dec"]


def _q66_channel(t, tbl, wh_col, date_col, time_col, mode_col,
                 price_col, net_col, qty_col):
    d = t["date_dim"]
    td = t["time_dim"]
    sm = t["ship_mode"]
    j = t[tbl].merge(t["warehouse"], left_on=wh_col,
                     right_on="w_warehouse_sk")
    j = j.merge(d[d.d_year == 2001][["d_date_sk", "d_year", "d_moy"]],
                left_on=date_col, right_on="d_date_sk")
    j = j.merge(td[(td.t_time >= 30838) & (td.t_time <= 59638)][["t_time_sk"]],
                left_on=time_col, right_on="t_time_sk")
    j = j.merge(sm[sm.sm_carrier.isin(["DHL", "BARIAN"])][["sm_ship_mode_sk"]],
                left_on=mode_col, right_on="sm_ship_mode_sk")
    keys = ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
            "w_state", "w_country", "d_year"]
    for i, mn in enumerate(_Q66_MONTHS):
        moy = j.d_moy == i + 1
        j[f"{mn}_sales"] = (j[price_col] * j[qty_col]).where(moy, 0.0)
        j[f"{mn}_net"] = (j[net_col] * j[qty_col]).where(moy, 0.0)
    cols = [f"{mn}_sales" for mn in _Q66_MONTHS] + [
        f"{mn}_net" for mn in _Q66_MONTHS]
    g = j.groupby(keys, as_index=False, dropna=False)[cols].sum()
    g["ship_carriers"] = "DHL,BARIAN"
    return g


def q66(t):
    web = _q66_channel(t, "web_sales", "ws_warehouse_sk", "ws_sold_date_sk",
                       "ws_sold_time_sk", "ws_ship_mode_sk",
                       "ws_ext_sales_price", "ws_net_paid", "ws_quantity")
    cat = _q66_channel(t, "catalog_sales", "cs_warehouse_sk",
                       "cs_sold_date_sk", "cs_sold_time_sk",
                       "cs_ship_mode_sk", "cs_sales_price", "cs_net_paid",
                       "cs_quantity")
    u = pd.concat([web, cat], ignore_index=True)
    keys = ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
            "w_state", "w_country", "ship_carriers", "d_year"]
    u["jan_spsf"] = u.jan_sales / u.w_warehouse_sq_ft
    u["dec_spsf"] = u.dec_sales / u.w_warehouse_sq_ft
    cols = ([f"{mn}_sales" for mn in _Q66_MONTHS] + ["jan_spsf", "dec_spsf"]
            + [f"{mn}_net" for mn in _Q66_MONTHS])
    g = u.groupby(keys, as_index=False, dropna=False)[cols].sum()
    g = g.sort_values("w_warehouse_name", kind="stable").head(100)
    out_cols = (keys[:7] + ["d_year"]
                + [f"{mn}_sales" for mn in _Q66_MONTHS]
                + ["jan_spsf", "dec_spsf"]
                + [f"{mn}_net" for mn in _Q66_MONTHS])
    g = g[keys + cols]
    return g.reset_index(drop=True)


def _yt(t, tbl, cust_col, date_col, val_fn, extra_keys=()):
    """Per-customer-per-year channel totals in exact integer cents."""
    j = t["customer"].merge(t[tbl], left_on="c_customer_sk",
                            right_on=cust_col)
    j = j.merge(t["date_dim"][["d_date_sk", "d_year"]], left_on=date_col,
                right_on="d_date_sk")
    j = j[j.d_year.isin([1999, 2000])]
    j = j.assign(v=val_fn(j))
    keys = (["c_customer_id", "c_first_name", "c_last_name"]
            + list(extra_keys) + ["d_year"])
    return j.groupby(keys, as_index=False, dropna=False).agg(
        total=("v", "sum"))


def _cents(s):
    return (s * 100).round().fillna(0)


def _ratio32(sec, first):
    """Replicate the engine's DOUBLE division: decimal -> float32."""
    f32 = lambda s: (s.to_numpy() / 100.0).astype(np.float32)  # noqa: E731
    return f32(sec) / f32(first)


def q74(t):
    s = _yt(t, "store_sales", "ss_customer_sk", "ss_sold_date_sk",
            lambda j: _cents(j.ss_net_paid))
    w = _yt(t, "web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
            lambda j: _cents(j.ws_net_paid))
    s1 = s[s.d_year == 1999]
    s2 = s[s.d_year == 2000]
    w1 = w[w.d_year == 1999]
    w2 = w[w.d_year == 2000]
    m = (s2.merge(s1[["c_customer_id", "total"]], on="c_customer_id",
                  suffixes=("", "_s1"))
         .merge(w1[["c_customer_id", "total"]].rename(
             columns={"total": "total_w1"}), on="c_customer_id")
         .merge(w2[["c_customer_id", "total"]].rename(
             columns={"total": "total_w2"}), on="c_customer_id"))
    m = m[(m.total_s1 > 0) & (m.total_w1 > 0)]
    m = m[_ratio32(m.total_w2, m.total_w1) > _ratio32(m.total, m.total_s1)]
    m = m.sort_values(["c_customer_id", "c_first_name", "c_last_name"],
                      kind="stable").head(100)
    return m.rename(columns={
        "c_customer_id": "customer_id",
        "c_first_name": "customer_first_name",
        "c_last_name": "customer_last_name",
    })[["customer_id", "customer_first_name",
        "customer_last_name"]].reset_index(drop=True)


def q11(t):
    s = _yt(t, "store_sales", "ss_customer_sk", "ss_sold_date_sk",
            lambda j: _cents(j.ss_ext_list_price - j.ss_ext_discount_amt),
            extra_keys=("c_email_address",))
    w = _yt(t, "web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
            lambda j: _cents(j.ws_ext_list_price - j.ws_ext_discount_amt),
            extra_keys=("c_email_address",))
    s1 = s[s.d_year == 1999]
    s2 = s[s.d_year == 2000]
    w1 = w[w.d_year == 1999]
    w2 = w[w.d_year == 2000]
    m = (s2.merge(s1[["c_customer_id", "total"]], on="c_customer_id",
                  suffixes=("", "_s1"))
         .merge(w1[["c_customer_id", "total"]].rename(
             columns={"total": "total_w1"}), on="c_customer_id")
         .merge(w2[["c_customer_id", "total"]].rename(
             columns={"total": "total_w2"}), on="c_customer_id"))
    m = m[(m.total_s1 > 0) & (m.total_w1 > 0)]
    m = m[_ratio32(m.total_w2, m.total_w1) > _ratio32(m.total, m.total_s1)]
    m = m.sort_values(["c_customer_id", "c_first_name", "c_last_name",
                       "c_email_address"], kind="stable").head(100)
    return m.rename(columns={
        "c_customer_id": "customer_id",
        "c_first_name": "customer_first_name",
        "c_last_name": "customer_last_name",
        "c_email_address": "customer_email_address",
    })[["customer_id", "customer_first_name", "customer_last_name",
        "customer_email_address"]].reset_index(drop=True)


def q4(t):
    def half(j, p):
        return _cents(((j[f"{p}_ext_list_price"]
                        - j[f"{p}_ext_wholesale_cost"]
                        - j[f"{p}_ext_discount_amt"])
                       + j[f"{p}_ext_sales_price"]) / 2)

    s = _yt(t, "store_sales", "ss_customer_sk", "ss_sold_date_sk",
            lambda j: half(j, "ss"))
    c = _yt(t, "catalog_sales", "cs_bill_customer_sk", "cs_sold_date_sk",
            lambda j: half(j, "cs"))
    w = _yt(t, "web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
            lambda j: half(j, "ws"))
    m = s[s.d_year == 2000].merge(
        s[s.d_year == 1999][["c_customer_id", "total"]],
        on="c_customer_id", suffixes=("", "_s1"))
    for nm, fr in (("c1", c[c.d_year == 1999]), ("c2", c[c.d_year == 2000]),
                   ("w1", w[w.d_year == 1999]), ("w2", w[w.d_year == 2000])):
        m = m.merge(fr[["c_customer_id", "total"]].rename(
            columns={"total": f"total_{nm}"}), on="c_customer_id")
    m = m[(m.total_s1 > 0) & (m.total_c1 > 0) & (m.total_w1 > 0)]
    rc = _ratio32(m.total_c2, m.total_c1)
    m = m[(rc > _ratio32(m.total, m.total_s1))
          & (rc > _ratio32(m.total_w2, m.total_w1))]
    m = m.sort_values(["c_customer_id", "c_first_name", "c_last_name"],
                      kind="stable").head(100)
    return m.rename(columns={
        "c_customer_id": "customer_id",
        "c_first_name": "customer_first_name",
        "c_last_name": "customer_last_name",
    })[["customer_id", "customer_first_name",
        "customer_last_name"]].reset_index(drop=True)


def _date_window(t, lo="2000-08-03", days=30):
    d = t["date_dim"]
    return d[(d.d_date >= D(lo))
             & (d.d_date <= D(lo) + np.timedelta64(days, "D"))][["d_date_sk"]]


def q77(t):
    dd = _date_window(t)
    ss = (t["store_sales"].merge(dd, left_on="ss_sold_date_sk",
                                 right_on="d_date_sk")
          .merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
          .groupby("s_store_sk", as_index=False)
          .agg(sales=("ss_ext_sales_price", "sum"),
               profit=("ss_net_profit", "sum")))
    sr = (t["store_returns"].merge(dd, left_on="sr_returned_date_sk",
                                   right_on="d_date_sk")
          .groupby("sr_store_sk", as_index=False)
          .agg(returns_=("sr_return_amt", "sum"),
               profit_loss=("sr_net_loss", "sum")))
    store = ss.merge(sr.dropna(subset=["sr_store_sk"]),
                     left_on="s_store_sk", right_on="sr_store_sk",
                     how="left")
    store = pd.DataFrame({
        "channel": "store channel", "id": store.s_store_sk,
        "sales": store.sales, "returns_": store.returns_.fillna(0),
        "profit": store.profit - store.profit_loss.fillna(0)})
    cs = (t["catalog_sales"].merge(dd, left_on="cs_sold_date_sk",
                                   right_on="d_date_sk")
          .groupby("cs_call_center_sk", as_index=False, dropna=False)
          .agg(sales=("cs_ext_sales_price", "sum"),
               profit=("cs_net_profit", "sum")))
    crj = t["catalog_returns"].merge(dd, left_on="cr_returned_date_sk",
                                     right_on="d_date_sk")
    cat = pd.DataFrame({
        "channel": "catalog channel", "id": cs.cs_call_center_sk,
        "sales": cs.sales,
        "returns_": float(crj.cr_return_amount.sum()),
        "profit": cs.profit - float(crj.cr_net_loss.sum())})
    wsj = t["web_sales"].merge(dd, left_on="ws_sold_date_sk",
                               right_on="d_date_sk")
    ws = (wsj[wsj.ws_web_page_sk.notna()]
          .groupby("ws_web_page_sk", as_index=False)
          .agg(sales=("ws_ext_sales_price", "sum"),
               profit=("ws_net_profit", "sum")))
    wrj = (t["web_returns"].merge(
        t["web_sales"][["ws_order_number", "ws_item_sk", "ws_web_page_sk"]],
        left_on=["wr_order_number", "wr_item_sk"],
        right_on=["ws_order_number", "ws_item_sk"])
        .merge(dd, left_on="wr_returned_date_sk", right_on="d_date_sk"))
    wr = (wrj[wrj.ws_web_page_sk.notna()]
          .groupby("ws_web_page_sk", as_index=False)
          .agg(returns_=("wr_return_amt", "sum"),
               profit_loss=("wr_net_loss", "sum")))
    web = ws.merge(wr, on="ws_web_page_sk", how="left")
    web = pd.DataFrame({
        "channel": "web channel", "id": web.ws_web_page_sk,
        "sales": web.sales, "returns_": web.returns_.fillna(0),
        "profit": web.profit - web.profit_loss.fillna(0)})
    x = pd.concat([store, cat, web], ignore_index=True)
    detail = x.groupby(["channel", "id"], as_index=False, dropna=False)[
        ["sales", "returns_", "profit"]].sum()
    sub = x.groupby(["channel"], as_index=False)[
        ["sales", "returns_", "profit"]].sum()
    sub["id"] = None
    grand = x[["sales", "returns_", "profit"]].sum().to_frame().T
    grand["channel"] = None
    grand["id"] = None
    u = pd.concat([detail, sub, grand], ignore_index=True)
    u = u.sort_values("sales", kind="stable")
    u = u.sort_values("id", na_position="last", kind="stable")
    u = u.sort_values("channel", na_position="last", kind="stable")
    return u[["channel", "id", "sales", "returns_",
              "profit"]].head(100).reset_index(drop=True)


def _q80_channel(t, tbl, rtbl, sale_keys, ret_keys, ret_amt, ret_loss,
                 date_col, loc_join, loc_id, promo_col, chan, sales_col,
                 profit_col):
    dd = _date_window(t)
    j = t[tbl].merge(t[rtbl][ret_keys + [ret_amt, ret_loss]],
                     left_on=sale_keys, right_on=ret_keys, how="left")
    j = j.merge(dd, left_on=date_col, right_on="d_date_sk")
    j = j.merge(t[loc_join[0]], left_on=loc_join[1], right_on=loc_join[2])
    it = t["item"][t["item"].i_current_price > 50]
    j = j.merge(it[["i_item_sk"]], left_on=sale_keys[0],
                right_on="i_item_sk")
    pr = t["promotion"][t["promotion"].p_channel_tv == "N"]
    j = j.merge(pr[["p_promo_sk"]], left_on=promo_col,
                right_on="p_promo_sk")
    j = j.assign(ret_=j[ret_amt].fillna(0),
                 prof_=j[profit_col] - j[ret_loss].fillna(0))
    g = j.groupby(loc_id, as_index=False).agg(
        sales=(sales_col, "sum"), returns_=("ret_", "sum"),
        profit=("prof_", "sum"))
    return pd.DataFrame({"channel": chan, "id": g[loc_id],
                         "sales": g.sales, "returns_": g.returns_,
                         "profit": g.profit})


def q80(t):
    store = _q80_channel(
        t, "store_sales", "store_returns",
        ["ss_item_sk", "ss_ticket_number"],
        ["sr_item_sk", "sr_ticket_number"], "sr_return_amt", "sr_net_loss",
        "ss_sold_date_sk", ("store", "ss_store_sk", "s_store_sk"),
        "s_store_id", "ss_promo_sk", "store channel",
        "ss_ext_sales_price", "ss_net_profit")
    cat = _q80_channel(
        t, "catalog_sales", "catalog_returns",
        ["cs_item_sk", "cs_order_number"],
        ["cr_item_sk", "cr_order_number"], "cr_return_amount",
        "cr_net_loss", "cs_sold_date_sk",
        ("call_center", "cs_call_center_sk", "cc_call_center_sk"),
        "cc_call_center_id", "cs_promo_sk", "catalog channel",
        "cs_ext_sales_price", "cs_net_profit")
    web = _q80_channel(
        t, "web_sales", "web_returns",
        ["ws_item_sk", "ws_order_number"],
        ["wr_item_sk", "wr_order_number"], "wr_return_amt", "wr_net_loss",
        "ws_sold_date_sk", ("web_site", "ws_web_site_sk", "web_site_sk"),
        "web_site_id", "ws_promo_sk", "web channel",
        "ws_ext_sales_price", "ws_net_profit")
    x = pd.concat([store, cat, web], ignore_index=True)
    detail = x.groupby(["channel", "id"], as_index=False, dropna=False)[
        ["sales", "returns_", "profit"]].sum()
    sub = x.groupby(["channel"], as_index=False)[
        ["sales", "returns_", "profit"]].sum()
    sub["id"] = None
    grand = x[["sales", "returns_", "profit"]].sum().to_frame().T
    grand["channel"] = None
    grand["id"] = None
    u = pd.concat([detail, sub, grand], ignore_index=True)
    u = u.sort_values("sales", kind="stable")
    u = u.sort_values("id", na_position="last", kind="stable")
    u = u.sort_values("channel", na_position="last", kind="stable")
    return u[["channel", "id", "sales", "returns_",
              "profit"]].head(100).reset_index(drop=True)


def _q75_channel(t, tbl, item_col, date_col, ret_tbl, sale_ret_keys,
                 qty_col, amt_col, rqty_col, ramt_col):
    j = t[tbl].merge(t["item"], left_on=item_col, right_on="i_item_sk")
    j = j[j.i_category == "Books"]
    j = j.merge(t["date_dim"][["d_date_sk", "d_year"]], left_on=date_col,
                right_on="d_date_sk")
    j = j.merge(t[ret_tbl][list(sale_ret_keys[1]) + [rqty_col, ramt_col]],
                left_on=list(sale_ret_keys[0]),
                right_on=list(sale_ret_keys[1]), how="left")
    out = pd.DataFrame({
        "d_year": j.d_year, "i_brand_id": j.i_brand_id,
        "i_class_id": j.i_class_id, "i_category_id": j.i_category_id,
        "i_manufact_id": j.i_manufact_id,
        "sales_cnt": j[qty_col] - j[rqty_col].fillna(0),
        "sales_amt": j[amt_col] - j[ramt_col].fillna(0),
    })
    return out


def q75(t):
    cat = _q75_channel(t, "catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                       "catalog_returns",
                       (("cs_order_number", "cs_item_sk"),
                        ("cr_order_number", "cr_item_sk")),
                       "cs_quantity", "cs_ext_sales_price",
                       "cr_return_quantity", "cr_return_amount")
    st = _q75_channel(t, "store_sales", "ss_item_sk", "ss_sold_date_sk",
                      "store_returns",
                      (("ss_ticket_number", "ss_item_sk"),
                       ("sr_ticket_number", "sr_item_sk")),
                      "ss_quantity", "ss_ext_sales_price",
                      "sr_return_quantity", "sr_return_amt")
    web = _q75_channel(t, "web_sales", "ws_item_sk", "ws_sold_date_sk",
                       "web_returns",
                       (("ws_order_number", "ws_item_sk"),
                        ("wr_order_number", "wr_item_sk")),
                       "ws_quantity", "ws_ext_sales_price",
                       "wr_return_quantity", "wr_return_amt")
    sd = pd.concat([cat, st, web], ignore_index=True)
    sd["sales_amt"] = sd.sales_amt.round(2)
    sd = sd.drop_duplicates()  # UNION dedups
    g = sd.groupby(["d_year", "i_brand_id", "i_class_id", "i_category_id",
                    "i_manufact_id"], as_index=False, dropna=False).agg(
        sales_cnt=("sales_cnt", "sum"), sales_amt=("sales_amt", "sum"))
    cur = g[g.d_year == 2000]
    prev = g[g.d_year == 1999]
    m = cur.merge(prev, on=["i_brand_id", "i_class_id", "i_category_id",
                            "i_manufact_id"], suffixes=("", "_p"))
    r = (m.sales_cnt.to_numpy().astype(np.float32)
         / m.sales_cnt_p.to_numpy().astype(np.float32))
    m = m[r < 0.9]
    out = pd.DataFrame({
        "prev_year": m.d_year_p, "year_": m.d_year,
        "i_brand_id": m.i_brand_id, "i_class_id": m.i_class_id,
        "i_category_id": m.i_category_id, "i_manufact_id": m.i_manufact_id,
        "prev_yr_cnt": m.sales_cnt_p, "curr_yr_cnt": m.sales_cnt,
        "sales_cnt_diff": m.sales_cnt - m.sales_cnt_p,
        "sales_amt_diff": m.sales_amt - m.sales_amt_p,
    })
    out = out.sort_values(
        ["sales_cnt_diff", "sales_amt_diff", "i_brand_id", "i_class_id",
         "i_manufact_id"], kind="stable").head(100)
    return out.reset_index(drop=True)


def _q78_channel(t, tbl, ret_tbl, keys, date_col, year_out, item_out,
                 cust_src, cust_out, qty, wc, sp, prefix):
    j = t[tbl].merge(t[ret_tbl][list(keys[1])], left_on=list(keys[0]),
                     right_on=list(keys[1]), how="left")
    j = j[j[keys[1][0]].isna()]
    j = j.merge(t["date_dim"][["d_date_sk", "d_year"]], left_on=date_col,
                right_on="d_date_sk")
    g = j.groupby(["d_year", keys[0][1], cust_src], as_index=False,
                  dropna=False).agg(**{
                      f"{prefix}_qty": (qty, "sum"),
                      f"{prefix}_wc": (wc, "sum"),
                      f"{prefix}_sp": (sp, "sum")})
    return g.rename(columns={"d_year": year_out, keys[0][1]: item_out,
                             cust_src: cust_out})


def q78(t):
    ws = _q78_channel(t, "web_sales", "web_returns",
                      (("ws_order_number", "ws_item_sk"),
                       ("wr_order_number", "wr_item_sk")),
                      "ws_sold_date_sk", "ws_sold_year", "ws_item_sk",
                      "ws_bill_customer_sk", "ws_customer_sk",
                      "ws_quantity", "ws_wholesale_cost", "ws_sales_price",
                      "ws")
    cs = _q78_channel(t, "catalog_sales", "catalog_returns",
                      (("cs_order_number", "cs_item_sk"),
                       ("cr_order_number", "cr_item_sk")),
                      "cs_sold_date_sk", "cs_sold_year", "cs_item_sk",
                      "cs_bill_customer_sk", "cs_customer_sk",
                      "cs_quantity", "cs_wholesale_cost", "cs_sales_price",
                      "cs")
    ss = _q78_channel(t, "store_sales", "store_returns",
                      (("ss_ticket_number", "ss_item_sk"),
                       ("sr_ticket_number", "sr_item_sk")),
                      "ss_sold_date_sk", "ss_sold_year", "ss_item_sk",
                      "ss_customer_sk", "ss_customer_sk2",
                      "ss_quantity", "ss_wholesale_cost", "ss_sales_price",
                      "ss")
    ss = ss.rename(columns={"ss_customer_sk2": "ss_customer_sk"})
    m = ss.merge(
        ws.dropna(subset=["ws_item_sk", "ws_customer_sk"]),
        left_on=["ss_sold_year", "ss_item_sk", "ss_customer_sk"],
        right_on=["ws_sold_year", "ws_item_sk", "ws_customer_sk"],
        how="left")
    m = m.merge(
        cs.dropna(subset=["cs_item_sk", "cs_customer_sk"]),
        left_on=["ss_sold_year", "ss_item_sk", "ss_customer_sk"],
        right_on=["cs_sold_year", "cs_item_sk", "cs_customer_sk"],
        how="left")
    m = m[(m.ws_qty.fillna(0) > 0) | (m.cs_qty.fillna(0) > 0)]
    m = m[m.ss_sold_year == 2000]
    other_qty = m.ws_qty.fillna(0) + m.cs_qty.fillna(0)
    out = pd.DataFrame({
        "ss_customer_sk": m.ss_customer_sk,
        "ratio": (m.ss_qty / other_qty).round(2),
        "store_qty": m.ss_qty,
        "store_wholesale_cost": m.ss_wc,
        "store_sales_price": m.ss_sp,
        "other_chan_qty": other_qty,
        "other_chan_wholesale_cost": m.ws_wc.fillna(0) + m.cs_wc.fillna(0),
        "other_chan_sales_price": m.ws_sp.fillna(0) + m.cs_sp.fillna(0),
    })
    out = out.sort_values(
        ["other_chan_qty", "other_chan_wholesale_cost",
         "other_chan_sales_price", "ratio"], kind="stable")
    out = out.sort_values(["store_qty", "store_wholesale_cost",
                           "store_sales_price"], ascending=False,
                          kind="stable")
    out = out.sort_values("ss_customer_sk", kind="stable")
    return out.head(100).reset_index(drop=True)


def _q49_channel(t, tbl, rtbl, skeys, rkeys, qty, rqty, paid, ramt,
                 profit, chan):
    d = t["date_dim"]
    dd = d[(d.d_year == 2001) & (d.d_moy == 12)][["d_date_sk"]]
    j = t[tbl].merge(t[rtbl][rkeys + [rqty, ramt]], left_on=skeys,
                     right_on=rkeys, how="left")
    j = j.merge(dd, left_on=f"{skeys[1].split('_')[0]}_sold_date_sk",
                right_on="d_date_sk")
    j = j[(j[ramt] > 100) & (j[profit] > 1) & (j[paid] > 0)
          & (j[qty] > 0)]
    g = j.groupby(skeys[1], as_index=False).agg(
        rq=(rqty, lambda s: s.fillna(0).sum()), q=(qty, "sum"),
        ra=(ramt, lambda s: s.fillna(0).sum()), p=(paid, "sum"))
    f32 = lambda s: s.to_numpy().astype(np.float32)  # noqa: E731
    g["return_ratio"] = f32(g.rq) / f32(g.q)
    g["currency_ratio"] = f32(g.ra) / f32(g.p)
    g["return_rank"] = g.return_ratio.rank(method="min").astype(int)
    g["currency_rank"] = g.currency_ratio.rank(method="min").astype(int)
    g = g[(g.return_rank <= 10) | (g.currency_rank <= 10)]
    return pd.DataFrame({"channel": chan, "item": g[skeys[1]],
                         "return_ratio": g.return_ratio,
                         "currency_rank": g.currency_rank,
                         "return_rank": g.return_rank})


def q49(t):
    web = _q49_channel(t, "web_sales", "web_returns",
                       ["ws_order_number", "ws_item_sk"],
                       ["wr_order_number", "wr_item_sk"],
                       "ws_quantity", "wr_return_quantity", "ws_net_paid",
                       "wr_return_amt", "ws_net_profit", "web")
    cat = _q49_channel(t, "catalog_sales", "catalog_returns",
                       ["cs_order_number", "cs_item_sk"],
                       ["cr_order_number", "cr_item_sk"],
                       "cs_quantity", "cr_return_quantity", "cs_net_paid",
                       "cr_return_amount", "cs_net_profit", "catalog")
    st = _q49_channel(t, "store_sales", "store_returns",
                      ["ss_ticket_number", "ss_item_sk"],
                      ["sr_ticket_number", "sr_item_sk"],
                      "ss_quantity", "sr_return_quantity", "ss_net_paid",
                      "sr_return_amt", "ss_net_profit", "store")
    u = pd.concat([web, cat, st], ignore_index=True)
    u["return_ratio"] = u.return_ratio.round(6)
    u = u.drop_duplicates()
    u = u.sort_values(["channel", "return_rank", "currency_rank", "item"],
                      kind="stable").head(100)
    return u[["channel", "item", "return_ratio", "return_rank",
              "currency_rank"]].reset_index(drop=True)


def q95(t):
    ws = t["web_sales"]
    n_wh = ws.groupby("ws_order_number")["ws_warehouse_sk"].nunique()
    multi_wh = set(n_wh[n_wh > 1].index)
    d = t["date_dim"]
    dd = d[(d.d_date >= D("2000-02-01"))
           & (d.d_date <= D("2000-02-01") + np.timedelta64(60, "D"))][
        ["d_date_sk"]]
    j = ws.merge(dd, left_on="ws_ship_date_sk", right_on="d_date_sk")
    ca = t["customer_address"]
    j = j.merge(ca[ca.ca_state.str.strip() == "AR"][["ca_address_sk"]],
                left_on="ws_ship_addr_sk", right_on="ca_address_sk")
    wsit = t["web_site"]
    j = j.merge(wsit[wsit.web_company_name.str.strip() == "able"][
        ["web_site_sk"]], left_on="ws_web_site_sk", right_on="web_site_sk")
    j = j[j.ws_order_number.isin(multi_wh)]
    returned = set(t["web_returns"].wr_order_number.dropna()) & multi_wh
    j = j[j.ws_order_number.isin(returned)]
    return pd.DataFrame({
        "order_count": [j.ws_order_number.nunique()],
        "total_shipping_cost": [j.ws_ext_sales_price.sum()],
        "total_net_profit": [j.ws_net_profit.sum()],
    })


def q72(t):
    d = t["date_dim"][["d_date_sk", "d_week_seq", "d_year", "d_date"]]
    j = t["catalog_sales"].merge(
        d.rename(columns={c: c + "1" for c in d.columns}),
        left_on="cs_sold_date_sk", right_on="d_date_sk1")
    j = j[j.d_year1 == 2000]
    cd = t["customer_demographics"]
    j = j.merge(cd[cd.cd_marital_status == "D"][["cd_demo_sk"]],
                left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
    cu = t["customer"][["c_customer_sk", "c_current_hdemo_sk"]]
    j = j.merge(cu, left_on="cs_bill_customer_sk", right_on="c_customer_sk")
    hd = t["household_demographics"]
    j = j.merge(hd[hd.hd_buy_potential == ">10000"][["hd_demo_sk"]],
                left_on="c_current_hdemo_sk", right_on="hd_demo_sk")
    j = j.merge(d.rename(columns={c: c + "3" for c in d.columns}),
                left_on="cs_ship_date_sk", right_on="d_date_sk3")
    j = j[j.d_date3 > j.d_date1 + np.timedelta64(5, "D")]
    inv = t["inventory"].merge(
        d.rename(columns={c: c + "2" for c in d.columns}),
        left_on="inv_date_sk", right_on="d_date_sk2")
    j = j.merge(inv, left_on="cs_item_sk", right_on="inv_item_sk")
    j = j[(j.d_week_seq1 == j.d_week_seq2)
          & (j.inv_quantity_on_hand < j.cs_quantity)]
    j = j.merge(t["warehouse"][["w_warehouse_sk", "w_warehouse_name"]],
                left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_desc"]],
                left_on="cs_item_sk", right_on="i_item_sk")
    promo = set(t["promotion"].p_promo_sk)
    j["has_promo"] = j.cs_promo_sk.isin(promo)
    g = j.groupby(["i_item_desc", "w_warehouse_name", "d_week_seq1"],
                  as_index=False, dropna=False).agg(
        no_promo=("has_promo", lambda s: int((~s).sum())),
        promo=("has_promo", lambda s: int(s.sum())),
        total_cnt=("has_promo", "size"))
    g = g.sort_values(["i_item_desc", "w_warehouse_name", "d_week_seq1"],
                      kind="stable")
    g = g.sort_values("total_cnt", ascending=False, kind="stable")
    return g.rename(columns={"d_week_seq1": "d_week_seq"})[
        ["i_item_desc", "w_warehouse_name", "d_week_seq", "no_promo",
         "promo", "total_cnt"]].head(100).reset_index(drop=True)


def q54(t):
    u = pd.concat([
        t["catalog_sales"][["cs_sold_date_sk", "cs_bill_customer_sk",
                            "cs_item_sk"]].rename(columns={
            "cs_sold_date_sk": "sold_date_sk",
            "cs_bill_customer_sk": "customer_sk",
            "cs_item_sk": "item_sk"}),
        t["web_sales"][["ws_sold_date_sk", "ws_bill_customer_sk",
                        "ws_item_sk"]].rename(columns={
            "ws_sold_date_sk": "sold_date_sk",
            "ws_bill_customer_sk": "customer_sk",
            "ws_item_sk": "item_sk"}),
    ], ignore_index=True)
    it = t["item"]
    sel = it[(it.i_category == "Women") & (it.i_class == "women-infants")]
    d = t["date_dim"]
    dd = d[d.d_year == 1999]
    j = u.merge(sel[["i_item_sk"]], left_on="item_sk", right_on="i_item_sk")
    j = j.merge(dd[["d_date_sk"]], left_on="sold_date_sk",
                right_on="d_date_sk")
    cu = t["customer"]
    mc = cu[cu.c_customer_sk.isin(j.customer_sk.dropna())][
        ["c_customer_sk", "c_current_addr_sk"]].drop_duplicates()
    base_seq = int(d[(d.d_moy == 12) & (d.d_year == 1999)].d_month_seq.iloc[0])
    win = d[(d.d_month_seq >= base_seq + 1) & (d.d_month_seq <= base_seq + 3)]
    ss = t["store_sales"].merge(win[["d_date_sk"]],
                                left_on="ss_sold_date_sk",
                                right_on="d_date_sk")
    j2 = mc.merge(t["customer_address"], left_on="c_current_addr_sk",
                  right_on="ca_address_sk")
    j2 = j2.merge(t["store"], left_on="ca_county", right_on="s_county")
    j2 = j2.merge(ss, left_on="c_customer_sk", right_on="ss_customer_sk")
    rev = j2.groupby("c_customer_sk", as_index=False).agg(
        revenue=("ss_ext_sales_price", "sum"))
    # engine cast truncates the float32 division toward zero
    seg = np.trunc(rev.revenue.to_numpy().astype(np.float32)
                   / np.float32(50)).astype(np.int64)
    g = pd.Series(seg).value_counts().sort_index()
    return pd.DataFrame({"segment": g.index.to_numpy(),
                         "num_customers": g.to_numpy(),
                         "segment_base": g.index.to_numpy() * 50}
                        ).head(100).reset_index(drop=True)


def q24(t):
    j = t["store_sales"].merge(
        t["store_returns"][["sr_ticket_number", "sr_item_sk"]],
        left_on=["ss_ticket_number", "ss_item_sk"],
        right_on=["sr_ticket_number", "sr_item_sk"])
    j = j.merge(t["customer"], left_on="ss_customer_sk",
                right_on="c_customer_sk")
    j = j.merge(t["item"], left_on="ss_item_sk", right_on="i_item_sk")
    st = t["store"]
    j = j.merge(st[st.s_market_id == 8], left_on="ss_store_sk",
                right_on="s_store_sk")
    j = j.merge(t["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    j = j[j.s_zip.str[:1] == j.ca_zip.str[:1]]
    keys = ["c_last_name", "c_first_name", "s_store_name", "ca_state",
            "s_state", "i_color", "i_current_price", "i_manufact_id",
            "i_units", "i_size"]
    ssales = j.groupby(keys, as_index=False, dropna=False).agg(
        netpaid=("ss_net_paid", "sum"))
    thr = 0.05 * ssales.netpaid.mean()
    red = ssales[ssales.i_color == "burlywood"]
    g = red.groupby(["c_last_name", "c_first_name", "s_store_name"],
                    as_index=False, dropna=False).agg(
        paid=("netpaid", "sum"))
    g = g[g.paid > thr]
    g = g.sort_values(["c_last_name", "c_first_name", "s_store_name"],
                      na_position="last", kind="stable").head(100)
    return g.reset_index(drop=True)


def q23(t):
    d = t["date_dim"]
    dd = d[d.d_year.isin([1999, 2000, 2001, 2002])]
    ssj = t["store_sales"].merge(dd[["d_date_sk", "d_date"]],
                                 left_on="ss_sold_date_sk",
                                 right_on="d_date_sk")
    ssj = ssj.merge(t["item"][["i_item_sk", "i_item_desc"]],
                    left_on="ss_item_sk", right_on="i_item_sk")
    ssj = ssj.assign(itemdesc=ssj.i_item_desc.str[:30])
    f = ssj.groupby(["itemdesc", "i_item_sk", "d_date"]).size()
    frequent = set(f[f > 1].reset_index().i_item_sk)
    cs2 = t["store_sales"].merge(t["customer"][["c_customer_sk"]],
                                 left_on="ss_customer_sk",
                                 right_on="c_customer_sk")
    spend = (cs2.merge(dd[["d_date_sk"]], left_on="ss_sold_date_sk",
                       right_on="d_date_sk")
             .assign(v=lambda x: (x.ss_quantity * x.ss_sales_price))
             .groupby("c_customer_sk").v.sum())
    cmax = spend.max()
    all_spend = (cs2.assign(v=lambda x: x.ss_quantity * x.ss_sales_price)
                 .groupby("c_customer_sk").v.sum())
    best = set(all_spend[all_spend > 0.5 * cmax].index)
    d2 = d[(d.d_year == 2000) & (d.d_moy == 2)][["d_date_sk"]]
    cs = t["catalog_sales"].merge(d2, left_on="cs_sold_date_sk",
                                  right_on="d_date_sk")
    cs = cs[cs.cs_item_sk.isin(frequent)
            & cs.cs_bill_customer_sk.isin(best)]
    ws = t["web_sales"].merge(d2, left_on="ws_sold_date_sk",
                              right_on="d_date_sk")
    ws = ws[ws.ws_item_sk.isin(frequent)
            & ws.ws_bill_customer_sk.isin(best)]
    total = float((cs.cs_quantity * cs.cs_list_price).sum()
                  + (ws.ws_quantity * ws.ws_list_price).sum())
    return pd.DataFrame({"total_sales": [total]})


def q14(t):
    d = t["date_dim"]
    dd3 = d[(d.d_year >= 1999) & (d.d_year <= 2001)][["d_date_sk"]]
    it = t["item"]

    def ids(tbl, icol, dcol):
        j = t[tbl].merge(dd3, left_on=dcol, right_on="d_date_sk")
        j = j.merge(it, left_on=icol, right_on="i_item_sk")
        j = j.dropna(subset=["i_brand_id", "i_class_id", "i_category_id"])
        return set(map(tuple, j[["i_brand_id", "i_class_id",
                                 "i_category_id"]].to_numpy().tolist()))

    common = (ids("store_sales", "ss_item_sk", "ss_sold_date_sk")
              & ids("catalog_sales", "cs_item_sk", "cs_sold_date_sk")
              & ids("web_sales", "ws_item_sk", "ws_sold_date_sk"))
    key = it[["i_brand_id", "i_class_id", "i_category_id"]].apply(
        tuple, axis=1)
    cross_items = set(it[key.isin(common)].i_item_sk)

    def month_qlp(tbl, icol, dcol, qty, lp):
        j = t[tbl].merge(dd3, left_on=dcol, right_on="d_date_sk")
        return (j[qty] * j[lp])

    avg_sales = np.float32(pd.concat([
        month_qlp("store_sales", "ss_item_sk", "ss_sold_date_sk",
                  "ss_quantity", "ss_list_price"),
        month_qlp("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                  "cs_quantity", "cs_list_price"),
        month_qlp("web_sales", "ws_item_sk", "ws_sold_date_sk",
                  "ws_quantity", "ws_list_price"),
    ], ignore_index=True).mean())

    dm = d[(d.d_year == 2001) & (d.d_moy == 11)][["d_date_sk"]]

    def channel(tbl, icol, dcol, qty, lp, chan):
        j = t[tbl].merge(dm, left_on=dcol, right_on="d_date_sk")
        j = j[j[icol].isin(cross_items)]
        j = j.merge(it[["i_item_sk", "i_brand_id", "i_class_id",
                        "i_category_id"]], left_on=icol,
                    right_on="i_item_sk")
        j = j.assign(v=j[qty] * j[lp])
        g = j.groupby(["i_brand_id", "i_class_id", "i_category_id"],
                      as_index=False, dropna=False).agg(
            sales=("v", "sum"), number_sales=("v", "size"))
        g = g[g.sales.to_numpy().astype(np.float32) > avg_sales]
        g["channel"] = chan
        return g

    y = pd.concat([
        channel("store_sales", "ss_item_sk", "ss_sold_date_sk",
                "ss_quantity", "ss_list_price", "store"),
        channel("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                "cs_quantity", "cs_list_price", "catalog"),
        channel("web_sales", "ws_item_sk", "ws_sold_date_sk",
                "ws_quantity", "ws_list_price", "web"),
    ], ignore_index=True)
    cols = ["channel", "i_brand_id", "i_class_id", "i_category_id"]
    frames = []
    for k in range(len(cols), -1, -1):
        keys = cols[:k]
        if keys:
            g = y.groupby(keys, as_index=False, dropna=False).agg(
                sales=("sales", "sum"), number_sales=("number_sales", "sum"))
        else:
            g = pd.DataFrame({"sales": [y.sales.sum()],
                              "number_sales": [y.number_sales.sum()]})
        for c in cols[k:]:
            g[c] = None
        frames.append(g)
    u = pd.concat(frames, ignore_index=True)
    for c in reversed(cols):
        u = u.sort_values(c, na_position="last", kind="stable")
    u = u[cols + ["sales", "number_sales"]].head(100).reset_index(drop=True)
    for c in ("i_brand_id", "i_class_id", "i_category_id"):
        if u[c].notna().all():
            u[c] = u[c].astype(np.int64)  # match the engine's int column
    return u


def q64(t):
    cs = t["catalog_sales"].merge(
        t["catalog_returns"], left_on=["cs_item_sk", "cs_order_number"],
        right_on=["cr_item_sk", "cr_order_number"])
    cs = cs.assign(refund=cs.cr_refunded_cash + cs.cr_store_credit)
    g = cs.groupby("cs_item_sk", as_index=False).agg(
        sale=("cs_ext_list_price", "sum"), refund=("refund", "sum"))
    cs_ui = set(g[g.sale > 2 * g.refund].cs_item_sk)

    j = t["store_sales"].merge(
        t["store_returns"][["sr_item_sk", "sr_ticket_number"]],
        left_on=["ss_item_sk", "ss_ticket_number"],
        right_on=["sr_item_sk", "sr_ticket_number"])
    j = j[j.ss_item_sk.isin(cs_ui)]
    it = t["item"]
    j = j.merge(it[(it.i_current_price >= 10)
                   & (it.i_current_price <= 70)][
        ["i_item_sk", "i_product_name"]], left_on="ss_item_sk",
        right_on="i_item_sk")
    j = j.merge(t["date_dim"][["d_date_sk", "d_year"]],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk", "s_store_name", "s_zip"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["customer"], left_on="ss_customer_sk",
                right_on="c_customer_sk")
    cd = t["customer_demographics"][["cd_demo_sk", "cd_marital_status"]]
    j = j.merge(cd.rename(columns={"cd_demo_sk": "cd1_sk",
                                   "cd_marital_status": "ms1"}),
                left_on="ss_cdemo_sk", right_on="cd1_sk")
    j = j.merge(cd.rename(columns={"cd_demo_sk": "cd2_sk",
                                   "cd_marital_status": "ms2"}),
                left_on="c_current_cdemo_sk", right_on="cd2_sk")
    j = j[j.ms1 != j.ms2]
    hd = t["household_demographics"][["hd_demo_sk", "hd_income_band_sk"]]
    j = j.merge(hd.rename(columns={"hd_demo_sk": "hd1_sk",
                                   "hd_income_band_sk": "ib1_sk"}),
                left_on="ss_hdemo_sk", right_on="hd1_sk")
    j = j.merge(hd.rename(columns={"hd_demo_sk": "hd2_sk",
                                   "hd_income_band_sk": "ib2_sk"}),
                left_on="c_current_hdemo_sk", right_on="hd2_sk")
    ib = t["income_band"][["ib_income_band_sk"]]
    j = j.merge(ib.rename(columns={"ib_income_band_sk": "ib1"}),
                left_on="ib1_sk", right_on="ib1")
    j = j.merge(ib.rename(columns={"ib_income_band_sk": "ib2"}),
                left_on="ib2_sk", right_on="ib2")
    j = j.merge(t["promotion"][["p_promo_sk"]], left_on="ss_promo_sk",
                right_on="p_promo_sk")
    ca = t["customer_address"][["ca_address_sk", "ca_address_id",
                                "ca_city", "ca_zip"]]
    j = j.merge(ca.rename(columns={
        "ca_address_sk": "ad1_sk", "ca_address_id": "b_street_number",
        "ca_city": "b_city", "ca_zip": "b_zip"}),
        left_on="ss_addr_sk", right_on="ad1_sk")
    j = j.merge(ca.rename(columns={
        "ca_address_sk": "ad2_sk", "ca_address_id": "c_street_number",
        "ca_city": "c_city", "ca_zip": "c_zip"}),
        left_on="c_current_addr_sk", right_on="ad2_sk")
    keys = ["i_product_name", "i_item_sk", "s_store_name", "s_zip",
            "b_street_number", "b_city", "b_zip", "c_street_number",
            "c_city", "c_zip", "d_year"]
    g = j.groupby(keys, as_index=False, dropna=False).agg(
        cnt=("ss_item_sk", "size"), s1=("ss_wholesale_cost", "sum"),
        s2=("ss_list_price", "sum"), s3=("ss_coupon_amt", "sum"))
    cs1 = g[g.d_year == 1999]
    cs2 = g[g.d_year == 2000]
    m = cs1.merge(cs2, on=["i_item_sk", "s_store_name", "s_zip"],
                  suffixes=("", "_2"))
    m = m[m.cnt_2 <= m.cnt]
    m = m.sort_values(["i_product_name", "s_store_name", "cnt_2",
                       "b_zip", "c_zip", "s1_2"], kind="stable").head(100)
    return pd.DataFrame({
        "product_name": m.i_product_name, "store_name": m.s_store_name,
        "store_zip": m.s_zip, "b_street_number": m.b_street_number,
        "b_city": m.b_city, "b_zip": m.b_zip,
        "c_street_number": m.c_street_number, "c_city": m.c_city,
        "c_zip": m.c_zip, "syear": m.d_year, "cnt": m.cnt, "s1": m.s1,
        "s2": m.s2, "s3": m.s3, "s1_2": m.s1_2, "s2_2": m.s2_2,
        "s3_2": m.s3_2, "syear2": m.d_year_2, "cnt2": m.cnt_2,
    }).reset_index(drop=True)


ORACLES = {
    name: globals()[name]
    for name in ["q1", "q2", "q3", "q4", "q5", "q6", "q7", "q8", "q9", "q10", "q11", "q12", "q13", "q14", "q15", "q16", "q17", "q18", "q19",
                 "q20", "q21", "q22", "q23", "q24", "q25", "q26", "q27", "q28", "q29", "q30", "q31", "q32", "q33",
                 "q34", "q35", "q36", "q37", "q38", "q39", "q40", "q41", "q42", "q43", "q44", "q45", "q46", "q47", "q48", "q49", "q50", "q51",
                 "q52", "q53", "q54", "q55", "q56", "q57", "q58", "q59", "q60", "q61", "q62", "q63", "q64", "q65", "q66", "q67", "q68", "q69", "q70",
                 "q71", "q72", "q73", "q74", "q75", "q76", "q77", "q78", "q79", "q80", "q81", "q82", "q83", "q84", "q85", "q86", "q87", "q88", "q89",
                 "q90", "q91", "q92", "q93", "q94", "q95", "q96", "q97", "q98", "q99"]
}
