"""Independent pandas oracle for the modeled TPC-DS query subset.

Reference parity: the H2QueryRunner role for TPC-DS suites [SURVEY §4].
Hand-written pandas translations of the query semantics (from the
public TPC-DS spec templates, with the same documented adaptations as
``connectors.tpcds.queries``); shares no code with the engine's
planner/kernels. Inputs are the connector's decoded DataFrames — NULL
FK values arrive as NaN, and pandas inner merges drop them exactly as
SQL inner joins do (the dimension sides never carry NaN keys).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

D = np.datetime64


def _ss_dd_it(t):
    j = t["store_sales"].merge(
        t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk"
    )
    return j.merge(t["item"], left_on="ss_item_sk", right_on="i_item_sk")


def q3(t):
    j = _ss_dd_it(t)
    j = j[(j.i_manufact_id <= 50) & (j.d_moy == 11)]
    g = j.groupby(["d_year", "i_brand", "i_brand_id"], as_index=False).agg(
        sum_agg=("ss_ext_discount_amt", "sum")
    )
    g = g.sort_values(
        ["d_year", "sum_agg", "i_brand_id"],
        ascending=[True, False, True], kind="stable",
    ).head(100)
    return g[["d_year", "i_brand_id", "i_brand", "sum_agg"]].reset_index(drop=True)


def q7(t):
    cd = t["customer_demographics"]
    cd = cd[
        (cd.cd_gender == "M") & (cd.cd_marital_status == "S")
        & (cd.cd_education_status == "College")
    ]
    p = t["promotion"]
    p = p[(p.p_channel_email == "N") | (p.p_channel_event == "N")]
    j = _ss_dd_it(t)
    j = j[j.d_year == 2000]
    j = j.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(p, left_on="ss_promo_sk", right_on="p_promo_sk")
    g = j.groupby("i_item_id", as_index=False).agg(
        agg1=("ss_quantity", "mean"),
        agg2=("ss_list_price", "mean"),
        agg3=("ss_coupon_amt", "mean"),
        agg4=("ss_sales_price", "mean"),
    )
    return g.sort_values("i_item_id", kind="stable").head(100).reset_index(drop=True)


def _revenue_ratio(t, fact, prefix, cats, lo, hi):
    f = t[fact].merge(
        t["date_dim"], left_on=f"{prefix}_sold_date_sk", right_on="d_date_sk"
    )
    f = f.merge(t["item"], left_on=f"{prefix}_item_sk", right_on="i_item_sk")
    f = f[f.i_category.isin(cats) & (f.d_date >= D(lo)) & (f.d_date <= D(hi))]
    g = f.groupby(
        ["i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"],
        as_index=False,
    ).agg(itemrevenue=(f"{prefix}_ext_sales_price", "sum"))
    g["revenueratio"] = (
        g.itemrevenue * 100 / g.groupby("i_class")["itemrevenue"].transform("sum")
    )
    g = g.sort_values(
        ["i_category", "i_class", "i_item_id", "i_item_desc", "revenueratio"],
        kind="stable",
    )
    return g.reset_index(drop=True)


def q12(t):
    return _revenue_ratio(
        t, "web_sales", "ws", ["Sports", "Books", "Home"],
        "1999-02-22", "1999-04-22",
    ).head(100)


def q19(t):
    j = _ss_dd_it(t)
    j = j[(j.i_manager_id <= 30) & (j.d_moy == 11) & (j.d_year == 1998)]
    j = j.merge(t["customer"], left_on="ss_customer_sk", right_on="c_customer_sk")
    j = j.merge(
        t["customer_address"], left_on="c_current_addr_sk", right_on="ca_address_sk"
    )
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j[j.ca_zip.str[:5] != j.s_zip.str[:5]]
    g = j.groupby(
        ["i_brand", "i_brand_id", "i_manufact_id", "i_manufact"], as_index=False
    ).agg(ext_price=("ss_ext_sales_price", "sum"))
    g = g.sort_values(
        ["ext_price", "i_brand", "i_brand_id", "i_manufact_id", "i_manufact"],
        ascending=[False, True, True, True, True], kind="stable",
    ).head(100)
    return g[
        ["i_brand_id", "i_brand", "i_manufact_id", "i_manufact", "ext_price"]
    ].reset_index(drop=True)


def q20(t):
    return _revenue_ratio(
        t, "catalog_sales", "cs", ["Jewelry", "Music", "Women"],
        "2001-01-12", "2001-03-12",
    ).head(100)


def q26(t):
    cd = t["customer_demographics"]
    cd = cd[
        (cd.cd_gender == "F") & (cd.cd_marital_status == "W")
        & (cd.cd_education_status == "Primary")
    ]
    p = t["promotion"]
    p = p[(p.p_channel_email == "N") | (p.p_channel_event == "N")]
    j = t["catalog_sales"].merge(
        t["date_dim"], left_on="cs_sold_date_sk", right_on="d_date_sk"
    )
    j = j.merge(t["item"], left_on="cs_item_sk", right_on="i_item_sk")
    j = j[j.d_year == 2000]
    j = j.merge(cd, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(p, left_on="cs_promo_sk", right_on="p_promo_sk")
    g = j.groupby("i_item_id", as_index=False).agg(
        agg1=("cs_quantity", "mean"),
        agg2=("cs_list_price", "mean"),
        agg3=("cs_coupon_amt", "mean"),
        agg4=("cs_sales_price", "mean"),
    )
    return g.sort_values("i_item_id", kind="stable").head(100).reset_index(drop=True)


def q42(t):
    j = _ss_dd_it(t)
    j = j[(j.i_manager_id <= 20) & (j.d_moy == 11) & (j.d_year == 1998)]
    g = j.groupby(["d_year", "i_category_id", "i_category"], as_index=False).agg(
        total_sales=("ss_ext_sales_price", "sum")
    )
    g = g.sort_values(
        ["total_sales", "d_year", "i_category_id", "i_category"],
        ascending=[False, True, True, True], kind="stable",
    ).head(100)
    return g[["d_year", "i_category_id", "i_category", "total_sales"]].reset_index(
        drop=True
    )


def q52(t):
    j = _ss_dd_it(t)
    j = j[(j.i_manager_id <= 20) & (j.d_moy == 12) & (j.d_year == 1999)]
    g = j.groupby(["d_year", "i_brand", "i_brand_id"], as_index=False).agg(
        ext_price=("ss_ext_sales_price", "sum")
    )
    g = g.sort_values(
        ["d_year", "ext_price", "i_brand_id"],
        ascending=[True, False, True], kind="stable",
    ).head(100)
    return g[["d_year", "i_brand_id", "i_brand", "ext_price"]].reset_index(drop=True)


def q53(t):
    j = _ss_dd_it(t)
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j[
        j.d_month_seq.isin(range(1188, 1200))
        & j.i_category.isin(
            ["Books", "Children", "Electronics", "Home", "Jewelry", "Men"]
        )
    ]
    g = j.groupby(["i_manufact_id", "d_qoy"], as_index=False).agg(
        sum_sales=("ss_sales_price", "sum")
    )
    g["avg_quarterly_sales"] = g.groupby("i_manufact_id")["sum_sales"].transform("mean")
    screen = np.where(
        g.avg_quarterly_sales > 0,
        np.abs(g.sum_sales - g.avg_quarterly_sales) / g.avg_quarterly_sales,
        0.0,
    )
    g = g[screen > 0.05]
    g = g.sort_values(
        ["avg_quarterly_sales", "sum_sales", "i_manufact_id"], kind="stable"
    ).head(100)
    return g[["i_manufact_id", "sum_sales", "avg_quarterly_sales"]].reset_index(
        drop=True
    )


def q55(t):
    j = _ss_dd_it(t)
    j = j[(j.i_manager_id <= 28) & (j.d_moy == 11) & (j.d_year == 1999)]
    g = j.groupby(["i_brand", "i_brand_id"], as_index=False).agg(
        ext_price=("ss_ext_sales_price", "sum")
    )
    g = g.sort_values(
        ["ext_price", "i_brand_id"], ascending=[False, True], kind="stable"
    ).head(100)
    return g[["i_brand_id", "i_brand", "ext_price"]].reset_index(drop=True)


def q89(t):
    j = _ss_dd_it(t)
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    j = j[
        (j.d_year == 1999)
        & j.i_category.isin(["Books", "Electronics", "Sports", "Men", "Music", "Women"])
    ]
    g = j.groupby(
        ["i_category", "i_class", "i_brand", "s_store_name", "s_company_name",
         "d_moy"],
        as_index=False,
    ).agg(sum_sales=("ss_sales_price", "sum"))
    g["avg_monthly_sales"] = g.groupby(
        ["i_category", "i_brand", "s_store_name", "s_company_name"]
    )["sum_sales"].transform("mean")
    screen = np.where(
        g.avg_monthly_sales != 0,
        np.abs(g.sum_sales - g.avg_monthly_sales) / g.avg_monthly_sales,
        0.0,
    )
    g = g[screen > 0.1].copy()
    g["diff"] = g.sum_sales - g.avg_monthly_sales
    g = g.sort_values(
        ["diff", "s_store_name", "i_category", "i_class", "i_brand", "d_moy"],
        kind="stable",
    ).head(100)
    return g[
        ["i_category", "i_class", "i_brand", "s_store_name", "s_company_name",
         "d_moy", "sum_sales", "avg_monthly_sales"]
    ].reset_index(drop=True)


def q98(t):
    g = _revenue_ratio(
        t, "store_sales", "ss", ["Children", "Shoes", "Electronics"],
        "2000-01-29", "2000-03-29",
    )
    return g  # no LIMIT in q98


ORACLES = {
    name: globals()[name]
    for name in ["q3", "q7", "q12", "q19", "q20", "q26", "q42", "q52", "q53",
                 "q55", "q89", "q98"]
}
