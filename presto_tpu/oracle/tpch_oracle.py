"""Independent pandas oracle for the 22 TPC-H queries.

Reference parity: the ``H2QueryRunner`` role — every SQL test runs the
same query on an independent engine and diffs results [SURVEY §4].
These are hand-written pandas translations of the query *semantics*
(from the public TPC-H spec), sharing no code with the engine's
planner/kernels; inputs are the connector's decoded DataFrames.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

D = np.datetime64


def _rev(df):
    return df.l_extendedprice * (1 - df.l_discount)


def q1(t):
    li = t["lineitem"]
    m = li.l_shipdate <= D("1998-09-02")
    d = li[m].copy()
    d["disc_price"] = _rev(d)
    d["charge"] = d.disc_price * (1 + d.l_tax)
    g = d.groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"),
    )
    return g.sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)


def q2(t):
    p, s, ps, n, r = t["part"], t["supplier"], t["partsupp"], t["nation"], t["region"]
    eu = n.merge(r[r.r_name == "EUROPE"], left_on="n_regionkey", right_on="r_regionkey")
    sup = s.merge(eu, left_on="s_nationkey", right_on="n_nationkey")
    j = ps.merge(sup, left_on="ps_suppkey", right_on="s_suppkey")
    pp = p[(p.p_size == 15) & p.p_type.str.endswith("BRASS")]
    j = j.merge(pp, left_on="ps_partkey", right_on="p_partkey")
    mn = j.groupby("p_partkey")["ps_supplycost"].transform("min")
    j = j[j.ps_supplycost == mn]
    j = j.sort_values(
        ["s_acctbal", "n_name", "s_name", "p_partkey"],
        ascending=[False, True, True, True], kind="stable",
    ).head(100)
    return j[["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
              "s_address", "s_phone", "s_comment"]].reset_index(drop=True)


def q3(t):
    c, o, li = t["customer"], t["orders"], t["lineitem"]
    c = c[c.c_mktsegment == "BUILDING"]
    o = o[o.o_orderdate < D("1995-03-15")]
    li = li[li.l_shipdate > D("1995-03-15")].copy()
    j = li.merge(o.merge(c, left_on="o_custkey", right_on="c_custkey"),
                 left_on="l_orderkey", right_on="o_orderkey")
    j["revenue"] = _rev(j)
    g = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"], as_index=False)[
        "revenue"
    ].sum()
    g = g.sort_values(["revenue", "o_orderdate"], ascending=[False, True],
                      kind="stable").head(10)
    return g[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]].reset_index(
        drop=True
    )


def q4(t):
    o, li = t["orders"], t["lineitem"]
    o = o[(o.o_orderdate >= D("1993-07-01")) & (o.o_orderdate < D("1993-10-01"))]
    late = li[li.l_commitdate < li.l_receiptdate].l_orderkey.unique()
    o = o[o.o_orderkey.isin(late)]
    g = o.groupby("o_orderpriority", as_index=False).size()
    g.columns = ["o_orderpriority", "order_count"]
    return g.sort_values("o_orderpriority").reset_index(drop=True)


def q5(t):
    c, o, li, s, n, r = (t["customer"], t["orders"], t["lineitem"],
                         t["supplier"], t["nation"], t["region"])
    asia = n.merge(r[r.r_name == "ASIA"], left_on="n_regionkey",
                   right_on="r_regionkey")
    o = o[(o.o_orderdate >= D("1994-01-01")) & (o.o_orderdate < D("1995-01-01"))]
    j = li.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(c, left_on="o_custkey", right_on="c_custkey")
    j = j.merge(s, left_on="l_suppkey", right_on="s_suppkey")
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(asia, left_on="s_nationkey", right_on="n_nationkey")
    j["revenue"] = _rev(j)
    g = j.groupby("n_name", as_index=False)["revenue"].sum()
    return g.sort_values("revenue", ascending=False).reset_index(drop=True)


def q6(t):
    li = t["lineitem"]
    m = (
        (li.l_shipdate >= D("1994-01-01")) & (li.l_shipdate < D("1995-01-01"))
        & (li.l_discount >= 0.05 - 1e-9) & (li.l_discount <= 0.07 + 1e-9)
        & (li.l_quantity < 24)
    )
    return pd.DataFrame({"revenue": [(li[m].l_extendedprice * li[m].l_discount).sum()]})


def _q7_shipping(t):
    s, li, o, c, n = (t["supplier"], t["lineitem"], t["orders"], t["customer"],
                      t["nation"])
    j = li.merge(s, left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(c, left_on="o_custkey", right_on="c_custkey")
    n1 = n[["n_nationkey", "n_name"]].rename(
        columns={"n_nationkey": "sk", "n_name": "supp_nation"})
    n2 = n[["n_nationkey", "n_name"]].rename(
        columns={"n_nationkey": "ck", "n_name": "cust_nation"})
    j = j.merge(n1, left_on="s_nationkey", right_on="sk")
    j = j.merge(n2, left_on="c_nationkey", right_on="ck")
    return j


def q7(t):
    j = _q7_shipping(t)
    m = (
        ((j.supp_nation == "FRANCE") & (j.cust_nation == "GERMANY"))
        | ((j.supp_nation == "GERMANY") & (j.cust_nation == "FRANCE"))
    ) & (j.l_shipdate >= D("1995-01-01")) & (j.l_shipdate <= D("1996-12-31"))
    d = j[m].copy()
    d["l_year"] = d.l_shipdate.dt.year
    d["volume"] = _rev(d)
    g = d.groupby(["supp_nation", "cust_nation", "l_year"], as_index=False)[
        "volume"
    ].sum()
    g = g.rename(columns={"volume": "revenue"})
    return g.sort_values(["supp_nation", "cust_nation", "l_year"]).reset_index(
        drop=True
    )


def q8(t):
    p, s, li, o, c, n, r = (t["part"], t["supplier"], t["lineitem"], t["orders"],
                            t["customer"], t["nation"], t["region"])
    j = li.merge(p[p.p_type == "ECONOMY ANODIZED STEEL"], left_on="l_partkey",
                 right_on="p_partkey")
    j = j.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    j = j[(j.o_orderdate >= D("1995-01-01")) & (j.o_orderdate <= D("1996-12-31"))]
    j = j.merge(c, left_on="o_custkey", right_on="c_custkey")
    am = n.merge(r[r.r_name == "AMERICA"], left_on="n_regionkey",
                 right_on="r_regionkey")
    j = j.merge(am[["n_nationkey"]], left_on="c_nationkey", right_on="n_nationkey")
    n2 = n[["n_nationkey", "n_name"]].rename(
        columns={"n_nationkey": "sk", "n_name": "nation"})
    j = j.merge(s, left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(n2, left_on="s_nationkey", right_on="sk")
    j["o_year"] = j.o_orderdate.dt.year
    j["volume"] = _rev(j)
    g = j.groupby("o_year").apply(
        lambda d: (d.volume * (d.nation == "BRAZIL")).sum() / d.volume.sum()
        if len(d) else 0.0,
        include_groups=False,
    ).reset_index(name="mkt_share")
    return g.sort_values("o_year").reset_index(drop=True)


def q9(t):
    p, s, li, ps, o, n = (t["part"], t["supplier"], t["lineitem"], t["partsupp"],
                          t["orders"], t["nation"])
    pp = p[p.p_name.str.contains("green")]
    j = li.merge(pp[["p_partkey"]], left_on="l_partkey", right_on="p_partkey")
    j = j.merge(ps, left_on=["l_partkey", "l_suppkey"],
                right_on=["ps_partkey", "ps_suppkey"])
    j = j.merge(o[["o_orderkey", "o_orderdate"]], left_on="l_orderkey",
                right_on="o_orderkey")
    j = j.merge(s[["s_suppkey", "s_nationkey"]], left_on="l_suppkey",
                right_on="s_suppkey")
    j = j.merge(n[["n_nationkey", "n_name"]], left_on="s_nationkey",
                right_on="n_nationkey")
    j["o_year"] = j.o_orderdate.dt.year
    j["amount"] = _rev(j) - j.ps_supplycost * j.l_quantity
    g = j.groupby(["n_name", "o_year"], as_index=False)["amount"].sum()
    g = g.rename(columns={"n_name": "nation", "amount": "sum_profit"})
    return g.sort_values(["nation", "o_year"], ascending=[True, False]).reset_index(
        drop=True
    )


def q10(t):
    c, o, li, n = t["customer"], t["orders"], t["lineitem"], t["nation"]
    o = o[(o.o_orderdate >= D("1993-10-01")) & (o.o_orderdate < D("1994-01-01"))]
    li = li[li.l_returnflag == "R"]
    j = li.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(c, left_on="o_custkey", right_on="c_custkey")
    j = j.merge(n, left_on="c_nationkey", right_on="n_nationkey")
    j["revenue"] = _rev(j)
    g = j.groupby(
        ["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name", "c_address",
         "c_comment"], as_index=False,
    )["revenue"].sum()
    g = g.sort_values("revenue", ascending=False, kind="stable").head(20)
    return g[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
              "c_address", "c_phone", "c_comment"]].reset_index(drop=True)


def q11(t):
    ps, s, n = t["partsupp"], t["supplier"], t["nation"]
    de = s.merge(n[n.n_name == "GERMANY"], left_on="s_nationkey",
                 right_on="n_nationkey")
    j = ps.merge(de[["s_suppkey"]], left_on="ps_suppkey", right_on="s_suppkey")
    j["value"] = j.ps_supplycost * j.ps_availqty
    total = j.value.sum() * 0.0001
    g = j.groupby("ps_partkey", as_index=False)["value"].sum()
    g = g[g.value > total]
    return g.sort_values("value", ascending=False).reset_index(drop=True)


def q12(t):
    o, li = t["orders"], t["lineitem"]
    m = (
        li.l_shipmode.isin(["MAIL", "SHIP"])
        & (li.l_commitdate < li.l_receiptdate)
        & (li.l_shipdate < li.l_commitdate)
        & (li.l_receiptdate >= D("1994-01-01"))
        & (li.l_receiptdate < D("1995-01-01"))
    )
    j = li[m].merge(o, left_on="l_orderkey", right_on="o_orderkey")
    hi = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    g = (
        j.assign(hi=hi.astype(int), lo=(~hi).astype(int))
        .groupby("l_shipmode", as_index=False)
        .agg(high_line_count=("hi", "sum"), low_line_count=("lo", "sum"))
    )
    return g.sort_values("l_shipmode").reset_index(drop=True)


def q13(t):
    c, o = t["customer"], t["orders"]
    oo = o[~o.o_comment.str.contains(r"special.*requests", regex=True)]
    cnt = (
        c[["c_custkey"]]
        .merge(oo[["o_custkey", "o_orderkey"]], left_on="c_custkey",
               right_on="o_custkey", how="left")
        .groupby("c_custkey")["o_orderkey"]
        .count()
        .reset_index(name="c_count")
    )
    g = cnt.groupby("c_count", as_index=False).size()
    g.columns = ["c_count", "custdist"]
    return g.sort_values(["custdist", "c_count"], ascending=[False, False]).reset_index(
        drop=True
    )


def q14(t):
    li, p = t["lineitem"], t["part"]
    li = li[(li.l_shipdate >= D("1995-09-01")) & (li.l_shipdate < D("1995-10-01"))]
    j = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    rev = _rev(j)
    promo = rev * j.p_type.str.startswith("PROMO")
    return pd.DataFrame({"promo_revenue": [100.0 * promo.sum() / rev.sum()]})


def q15(t):
    li, s = t["lineitem"], t["supplier"]
    li = li[(li.l_shipdate >= D("1996-01-01")) & (li.l_shipdate < D("1996-04-01"))]
    rev = (
        li.assign(r=_rev(li))
        .groupby("l_suppkey", as_index=False)["r"]
        .sum()
        .rename(columns={"l_suppkey": "supplier_no", "r": "total_revenue"})
    )
    mx = rev.total_revenue.max()
    j = s.merge(rev[rev.total_revenue >= mx - 1e-6], left_on="s_suppkey",
                right_on="supplier_no")
    return j[["s_suppkey", "s_name", "s_address", "s_phone", "total_revenue"]]\
        .sort_values("s_suppkey").reset_index(drop=True)


def q16(t):
    ps, p, s = t["partsupp"], t["part"], t["supplier"]
    bad = s[s.s_comment.str.contains(r"Customer.*Complaints", regex=True)].s_suppkey
    pp = p[
        (p.p_brand != "Brand#45")
        & ~p.p_type.str.startswith("MEDIUM POLISHED")
        & p.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])
    ]
    j = ps.merge(pp, left_on="ps_partkey", right_on="p_partkey")
    j = j[~j.ps_suppkey.isin(bad)]
    g = j.groupby(["p_brand", "p_type", "p_size"], as_index=False)[
        "ps_suppkey"
    ].nunique()
    g = g.rename(columns={"ps_suppkey": "supplier_cnt"})
    return g.sort_values(
        ["supplier_cnt", "p_brand", "p_type", "p_size"],
        ascending=[False, True, True, True],
    ).reset_index(drop=True)


def q17(t):
    li, p = t["lineitem"], t["part"]
    pp = p[(p.p_brand == "Brand#23") & (p.p_container == "MED BOX")]
    j = li.merge(pp[["p_partkey"]], left_on="l_partkey", right_on="p_partkey")
    avg02 = li.groupby("l_partkey")["l_quantity"].mean() * 0.2
    j = j[j.l_quantity < j.l_partkey.map(avg02)]
    return pd.DataFrame({"avg_yearly": [j.l_extendedprice.sum() / 7.0]})


def q18(t):
    c, o, li = t["customer"], t["orders"], t["lineitem"]
    big = li.groupby("l_orderkey")["l_quantity"].sum()
    big = big[big > 300].index
    j = li[li.l_orderkey.isin(big)].merge(
        o, left_on="l_orderkey", right_on="o_orderkey"
    )
    j = j.merge(c, left_on="o_custkey", right_on="c_custkey")
    g = j.groupby(
        ["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice"],
        as_index=False,
    )["l_quantity"].sum()
    g = g.sort_values(["o_totalprice", "o_orderdate"], ascending=[False, True],
                      kind="stable").head(100)
    return g.reset_index(drop=True)


def q19(t):
    li, p = t["lineitem"], t["part"]
    j = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    common = j.l_shipmode.isin(["AIR", "AIR REG"]) & (
        j.l_shipinstruct == "DELIVER IN PERSON"
    )
    b1 = (
        (j.p_brand == "Brand#12")
        & j.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
        & (j.l_quantity >= 1) & (j.l_quantity <= 11)
        & (j.p_size >= 1) & (j.p_size <= 5)
    )
    b2 = (
        (j.p_brand == "Brand#23")
        & j.p_container.isin(["MED BAG", "MED BOX", "MED PKG", "MED PACK"])
        & (j.l_quantity >= 10) & (j.l_quantity <= 20)
        & (j.p_size >= 1) & (j.p_size <= 10)
    )
    b3 = (
        (j.p_brand == "Brand#34")
        & j.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
        & (j.l_quantity >= 20) & (j.l_quantity <= 30)
        & (j.p_size >= 1) & (j.p_size <= 15)
    )
    m = common & (b1 | b2 | b3)
    return pd.DataFrame({"revenue": [_rev(j[m]).sum()]})


def q20(t):
    s, n, ps, p, li = (t["supplier"], t["nation"], t["partsupp"], t["part"],
                       t["lineitem"])
    forest = p[p.p_name.str.startswith("forest")].p_partkey
    li94 = li[(li.l_shipdate >= D("1994-01-01")) & (li.l_shipdate < D("1995-01-01"))]
    qty = li94.groupby(["l_partkey", "l_suppkey"])["l_quantity"].sum() * 0.5
    pss = ps[ps.ps_partkey.isin(forest)].copy()
    key = list(zip(pss.ps_partkey, pss.ps_suppkey))
    pss["thresh"] = [qty.get(k, np.nan) for k in key]
    good = pss[pss.ps_availqty > pss.thresh].ps_suppkey.unique()
    ca = s.merge(n[n.n_name == "CANADA"], left_on="s_nationkey",
                 right_on="n_nationkey")
    out = ca[ca.s_suppkey.isin(good)]
    return out[["s_name", "s_address"]].sort_values("s_name").reset_index(drop=True)


def q21(t):
    s, li, o, n = t["supplier"], t["lineitem"], t["orders"], t["nation"]
    l1 = li[li.l_receiptdate > li.l_commitdate]
    ok_orders = o[o.o_orderstatus == "F"][["o_orderkey"]]
    j = l1.merge(ok_orders, left_on="l_orderkey", right_on="o_orderkey")
    per_order = li.groupby("l_orderkey")["l_suppkey"].agg(["min", "max"])
    late_per_order = l1.groupby("l_orderkey")["l_suppkey"].agg(["min", "max"])
    j = j.merge(per_order, left_on="l_orderkey", right_index=True)
    j = j.merge(late_per_order, left_on="l_orderkey", right_index=True,
                suffixes=("", "_late"))
    exists_other = (j["min"] != j.l_suppkey) | (j["max"] != j.l_suppkey)
    not_exists_other_late = (j["min_late"] == j.l_suppkey) & (
        j["max_late"] == j.l_suppkey
    )
    j = j[exists_other & not_exists_other_late]
    sa = s.merge(n[n.n_name == "SAUDI ARABIA"], left_on="s_nationkey",
                 right_on="n_nationkey")
    j = j.merge(sa, left_on="l_suppkey", right_on="s_suppkey")
    g = j.groupby("s_name", as_index=False).size()
    g.columns = ["s_name", "numwait"]
    return g.sort_values(["numwait", "s_name"], ascending=[False, True],
                         kind="stable").head(100).reset_index(drop=True)


def q22(t):
    c, o = t["customer"], t["orders"]
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cc = c[c.c_phone.str[:2].isin(codes)].copy()
    avg = cc[cc.c_acctbal > 0].c_acctbal.mean()
    cc = cc[cc.c_acctbal > avg]
    cc = cc[~cc.c_custkey.isin(o.o_custkey)]
    cc["cntrycode"] = cc.c_phone.str[:2]
    g = cc.groupby("cntrycode", as_index=False).agg(
        numcust=("c_acctbal", "size"), totacctbal=("c_acctbal", "sum")
    )
    return g.sort_values("cntrycode").reset_index(drop=True)


ORACLES = {f"q{i}": globals()[f"q{i}"] for i in range(1, 23)}
