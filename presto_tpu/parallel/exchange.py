"""Distributed exchange: the inter-device data plane.

Reference parity: the whole L8 shuffle stack — ``PartitionedOutputOperator``
(PagePartitioner), ``OutputBuffer`` (partitioned/broadcast), ``PagesSerde``,
``ExchangeClient``/``ExchangeOperator`` pulling
``GET /v1/task/{id}/results/{buffer}/{token}`` [SURVEY §2.1, §2.5, §3.3;
reference tree unavailable, paths reconstructed].

TPU-first (SURVEY §2.5, §7.1): the pull-based HTTP page shuffle becomes
**compiled push-style collectives over ICI**:

- hash-partitioned exchange  -> ``jax.lax.all_to_all`` of a dense
  ``[P, quota]`` send tensor per column (P = mesh size);
- broadcast exchange         -> ``jax.lax.all_gather``;
- single/gather exchange     -> ``all_gather`` + host slice.

Serialization disappears (arrays stay columnar on device); token-based
flow control becomes static capacity planning: every device reserves a
``quota`` of rows per destination, and quota overflow (skew, SURVEY
§7.4 #4) raises a flag that the host handles by re-running the step at
a doubled quota — the moral equivalent of output-buffer backpressure.

The functions here are *per-device* bodies, meant to be called inside
``shard_map`` over the ``workers`` mesh axis; the executor fuses them
into larger traced fragment steps (partial-agg -> shuffle -> final-agg
compiles to ONE XLA program with the collective in the middle).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column
from presto_tpu.ops.partition import (
    destination_counts,
    partition_layout,
    scatter_to_buffer,
)
from presto_tpu.parallel.mesh import WORKERS, worker_axes


def _a2a(x, axes=WORKERS):
    """all_to_all along the worker axes (a 2-D dcn/ici mesh passes the
    axis tuple — XLA splits the collective over DCN + ICI legs); bools
    ride as uint8."""
    if x.dtype == jnp.bool_:
        return _a2a(x.astype(jnp.uint8), axes).astype(jnp.bool_)
    return jax.lax.all_to_all(x, axes, split_axis=0, concat_axis=0)


def _ag(x, axes=WORKERS):
    """Tiled all_gather along the worker axes (concat on rows)."""
    if x.dtype == jnp.bool_:
        return _ag(x.astype(jnp.uint8), axes).astype(jnp.bool_)
    return jax.lax.all_gather(x, axes, axis=0, tiled=True)


def exchange_local(batch: Batch, pids, num_partitions: int, quota: int,
                   axes=WORKERS):
    """Per-device hash-partitioned shuffle body.

    ``pids[cap]``: destination partition of each row (int32, computed by
    the caller — typically ``ops.hashing.partition_ids`` over the
    repartitioning keys so every device agrees on the row->owner map).

    Returns ``(received, overflow)``: a local Batch of capacity
    ``num_partitions * quota`` holding every row whose key this device
    owns, and this device's *send-side* overflow flag (psum it across
    the axis before acting on it).
    """
    slot, _counts, overflow = partition_layout(
        pids, batch.live, num_partitions, quota
    )

    def send_recv(values, fill=0):
        buf = scatter_to_buffer(values, slot, num_partitions, quota, fill)
        out = _a2a(buf, axes)
        return out.reshape((num_partitions * quota,) + values.shape[1:])

    cols = {}
    for name, c in batch.columns.items():
        cols[name] = Column(
            send_recv(c.data),
            send_recv(c.valid, False),
            c.dtype,
            c.dictionary,
        )
    live = send_recv(batch.live, False)
    return Batch(cols, live), overflow


def exchange_multiround(
    batch: Batch,
    pids,
    num_partitions: int,
    quota: int,
    recv_cap: int,
    max_rounds: int | None = None,
    axes=WORKERS,
    with_rounds: bool = False,
    with_stats: bool = False,
):
    """Skew-aware per-device shuffle body: multi-round, fixed wire quota.

    The single-round ``exchange_local`` couples the *wire* quota (rows
    per destination per ``all_to_all``) to the *receive* capacity
    (``P * quota``): one hot key forces the host to double the quota and
    recompile the whole fragment step (SURVEY §7.4 #4). Here the two are
    decoupled — the moral equivalent of the reference's token-paged
    ``ExchangeClient`` pulls (a bounded buffer drained over as many
    round trips as the data needs [SURVEY §2.5]):

    - every round moves at most ``quota`` rows per (sender, dest) pair
      through one ``all_to_all``; undelivered rows wait for the next
      round (``lax.while_loop`` — rounds are data-dependent but the
      program is compiled once);
    - receivers append compacted rows into a ``recv_cap`` buffer;
      overflow now means "this device *owns* more rows than recv_cap"
      (true placement skew), never "one destination was hot this round".

    Returns ``(received, overflow)`` like ``exchange_local``; overflow
    is this device's receive-side flag OR an undrained-after-
    ``max_rounds`` flag (psum across the axis before acting).
    ``with_rounds=True`` additionally returns the executed round count
    (int32; identical on every device — the while cond is driven by
    the global pending flag) so the host can account exact wire bytes
    (``a2a_wire_bytes`` x rounds) for the exchange metrics.
    ``with_stats=True`` appends the GLOBAL per-destination delivered
    row counts (int64 [P], psum'd over the axis — identical on every
    device): the exchange-skew telemetry's raw material, accumulated
    in the while-loop carry so no round ever pays a host readback.
    """
    P = num_partitions
    cap = batch.live.shape[0]
    if max_rounds is None:
        # a sender drains at most `cap` rows to one destination
        max_rounds = max(1, -(-cap // quota))
    names = list(batch.columns)

    def empty_buf(c: Column):
        tail = tuple(c.data.shape[1:])
        return (
            jnp.zeros((recv_cap,) + tail, c.data.dtype),
            jnp.zeros(recv_cap, jnp.bool_),
        )

    def any_pending(remaining):
        # psum lives in the body (a collective in the while cond is
        # not portable); the cond reads the carried flag
        return jax.lax.psum(jnp.any(remaining).astype(jnp.int32), axes) > 0

    init = (
        batch.live,  # remaining: rows not yet delivered
        any_pending(batch.live),  # pending anywhere on the axis
        jnp.zeros((), jnp.int64),  # receive write offset
        jnp.zeros((), jnp.bool_),  # receive-side overflow
        jnp.zeros((), jnp.int32),  # round counter
        jnp.zeros(P, jnp.int64),  # per-destination delivered rows
        {n: empty_buf(batch.columns[n]) for n in names},
    )

    def cond(state):
        _remaining, pending, _off, _ovf, rnd, _dest, _bufs = state
        return pending & (rnd < max_rounds)

    def body(state):
        remaining, _pending, off, ovf, rnd, dest, bufs = state
        slot, _counts, _ = partition_layout(pids, remaining, P, quota)
        sent = remaining & (slot < P * quota)

        def send_recv(values, fill=0):
            buf = scatter_to_buffer(values, slot, P, quota, fill)
            return _a2a(buf, axes).reshape((P * quota,) + values.shape[1:])

        got = send_recv(sent, False)
        pos = off + jnp.cumsum(got.astype(jnp.int64)) - 1
        pos = jnp.where(got, pos, recv_cap)  # dead slots drop
        total = jnp.sum(got.astype(jnp.int64))

        new_bufs = {}
        for n in names:
            c = batch.columns[n]
            data, valid = bufs[n]
            rdata = send_recv(c.data)
            rvalid = send_recv(c.valid, False)
            new_bufs[n] = (
                data.at[pos].set(rdata, mode="drop"),
                valid.at[pos].set(rvalid, mode="drop"),
            )
        new_off = off + total
        new_remaining = remaining & ~sent
        return (
            new_remaining,
            any_pending(new_remaining),
            new_off,
            ovf | (new_off > recv_cap),
            rnd + 1,
            # skew telemetry: delivered-rows-by-destination, carried on
            # device across rounds (the host reads the total once).
            # Gated: stats-less callers (window/sort shuffles) loop the
            # zeros through untouched — the [P] carry rides for free,
            # the per-round scatter-add is only paid when someone reads
            (dest + destination_counts(pids, sent, P) if with_stats
             else dest),
            new_bufs,
        )

    remaining, _pending, off, ovf, rnd, dest, bufs = jax.lax.while_loop(
        cond, body, init
    )
    undrained = jnp.any(remaining)
    cols = {
        n: Column(bufs[n][0], bufs[n][1], batch.columns[n].dtype,
                  batch.columns[n].dictionary)
        for n in names
    }
    live = jnp.arange(recv_cap) < off
    out = Batch(cols, live)
    res = (out, ovf | undrained)
    if with_rounds:
        res = res + (rnd,)
    if with_stats:
        # every device sees the same global per-destination totals
        # (sender-local histograms psum'd over the axis)
        res = res + (jax.lax.psum(dest, axes),)
    return res


def broadcast_local(batch: Batch, axes=WORKERS) -> Batch:
    """Per-device broadcast body: every device ends up with all rows
    (reference: BroadcastOutputBuffer / REPLICATED join distribution)."""
    cols = {
        n: Column(_ag(c.data, axes), _ag(c.valid, axes), c.dtype, c.dictionary)
        for n, c in batch.columns.items()
    }
    return Batch(cols, _ag(batch.live, axes))


def any_flag(flag, axes=WORKERS):
    """Combine per-device overflow flags (inside shard_map)."""
    return jax.lax.psum(flag.astype(jnp.int32), axes) > 0


# ---------------------------------------------------------------------------
# Exchange metrics (the observability layer's view of the data plane)
# ---------------------------------------------------------------------------
#
# Wire-byte accounting is *capacity-based and exact for the dense
# collectives*: an ``all_to_all`` moves the full ``[P, quota]`` send
# tensor per column per device regardless of row liveness, so bytes =
# rounds x P senders x (P x quota) rows x row_bytes. ``all_gather``
# replication moves each device's shard to the P-1 others. Dispatch
# time is the host-observed wall of the enclosing compiled step — the
# collective is fused inside it, so the step IS the exchange dispatch
# unit (SURVEY §7.1).


def a2a_wire_bytes(row_bytes: int, num_partitions: int, quota: int,
                   rounds: int = 1) -> int:
    """Total bytes one hash-partitioned exchange moved across the mesh
    (all devices, all rounds)."""
    return int(rounds) * num_partitions * num_partitions * quota * row_bytes


def gather_wire_bytes(row_bytes: int, capacity: int, mesh_size: int) -> int:
    """Bytes an all_gather/replication of a row-sharded batch of global
    ``capacity`` moves (each shard travels to the other P-1 devices)."""
    return capacity * max(mesh_size - 1, 0) * row_bytes


def record_exchange(site: str, nbytes: int, partitions: int,
                    dispatch_s: float, rounds: int = 1,
                    hot_partition: int | None = None) -> None:
    """Publish one exchange dispatch: process metrics (counters +
    ``exchange.dispatch_s`` histogram) and a completed trace span
    under the active recorder, carrying the byte/partition/round
    accounting in its args. ``hot_partition`` names the partition that
    tripped a capacity overflow (skew telemetry: the retry's doubled
    buffers are THIS destination's fault — the span records who)."""
    from presto_tpu.runtime import trace
    from presto_tpu.runtime.metrics import REGISTRY

    REGISTRY.counter("exchange.dispatches").add()
    REGISTRY.counter("exchange.bytes").add(float(nbytes))
    REGISTRY.counter("exchange.rounds").add(float(rounds))
    REGISTRY.histogram("exchange.dispatch_s").add(dispatch_s)
    args = {"bytes": int(nbytes), "partitions": int(partitions),
            "rounds": int(rounds)}
    if hot_partition is not None:
        REGISTRY.counter("exchange.quota_overflow").add()
        args["hot_partition"] = int(hot_partition)
    trace.add_complete(
        f"exchange:{site}", "exchange",
        time.perf_counter() - dispatch_s, dispatch_s, args,
    )


def skew_ratio(counts) -> float:
    """max/mean partition ratio of a per-destination row histogram
    (1.0 = perfectly balanced; P = everything on one destination;
    0.0 when nothing moved)."""
    total = float(np.sum(counts))
    if total <= 0 or len(counts) == 0:
        return 0.0
    return float(np.max(counts) / (total / len(counts)))


# ---------------------------------------------------------------------------
# Standalone jitted steps (tests + the shuffle microbenchmark)
# ---------------------------------------------------------------------------


def make_shuffle_step(mesh, num_partitions: int, quota: int):
    """jitted (sharded Batch, sharded pids) -> (sharded Batch, overflow).

    The building block the ICI-shuffle GB/s microbench times
    (BASELINE metric: ici_shuffle_gbps).
    """
    from presto_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    axes = worker_axes(mesh)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axes), P(axes)),
        out_specs=(P(axes), P()),
        check_vma=False,
    )
    def step(batch: Batch, pids):
        out, ovf = exchange_local(batch, pids, num_partitions, quota, axes)
        return out, any_flag(ovf, axes)

    return jax.jit(step)


def make_multiround_shuffle_step(
    mesh, num_partitions: int, quota: int, recv_cap: int
):
    """jitted (sharded Batch, sharded pids) -> (sharded Batch, overflow)
    using the skew-aware multi-round exchange: a zipfian key stream
    completes at a small fixed wire quota instead of forcing the host
    to double-and-recompile (SURVEY §7.4 #4)."""
    from presto_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    axes = worker_axes(mesh)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axes), P(axes)),
        out_specs=(P(axes), P()),
        check_vma=False,
    )
    def step(batch: Batch, pids):
        out, ovf = exchange_multiround(
            batch, pids, num_partitions, quota, recv_cap, axes=axes
        )
        return out, any_flag(ovf, axes)

    return jax.jit(step)


def make_broadcast_step(mesh):
    """jitted sharded Batch -> replicated Batch (all rows everywhere)."""
    from presto_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    axes = worker_axes(mesh)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axes),),
        out_specs=P(),
        check_vma=False,
    )
    def step(batch: Batch):
        return broadcast_local(batch, axes)

    return jax.jit(step)
