"""Device mesh setup — the worker set.

Reference parity: the coordinator's view of the cluster
(``DiscoveryNodeManager``'s NodeMap + ``NodeScheduler`` placing tasks
on workers [SURVEY §2.1]). TPU-first: the "cluster" is a
``jax.sharding.Mesh``; placement is a sharding annotation, and the
entire REST control plane collapses into the single-controller driver
(SURVEY §7.1).

One mesh axis ``"workers"`` plays the role of Presto's worker set: scan
splits are data-parallel across it, hash-partitioned exchanges are
``all_to_all`` along it, broadcasts are ``all_gather``.

Multi-host (SURVEY §2.5 DCN row): ``make_dcn_mesh`` builds a 2-D
``("dcn", "ici")`` mesh — the outer axis crosses hosts, the inner axis
stays on-slice. Fragment steps shard and exchange over the COMBINED
axes (every collective here accepts an axis tuple), so the same
compiled programs run on either mesh shape; XLA routes the inter-host
legs of the collectives over DCN and the intra-host legs over ICI.
Bootstrap a real multi-process run with ``parallel.multihost``.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from presto_tpu.runtime.errors import UserError

try:  # jax >= 0.6: top-level export, ``check_vma`` kwarg
    from jax import shard_map
except ImportError:  # jax 0.4/0.5: experimental module, ``check_rep`` kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        """Compat wrapper: the engine's shard_map call shape (the
        modern ``check_vma`` signature) on older jax releases."""
        return _shard_map_exp(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kw,
        )


WORKERS = "workers"
DCN = "dcn"
ICI = "ici"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise UserError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (WORKERS,))


def make_dcn_mesh(n_hosts: int, per_host: int | None = None, devices=None) -> Mesh:
    """2-D multi-host mesh: outer ``dcn`` axis across hosts, inner
    ``ici`` axis within a host. Devices are explicitly sorted
    host-major — ``jax.devices()`` order follows device ids/topology
    and is NOT guaranteed host-contiguous, and a row mixing hosts
    would silently route "ici" traffic over DCN."""
    devs = list(devices) if devices is not None else jax.devices()
    devs.sort(key=lambda d: (d.process_index, d.id))
    if per_host is None:
        if len(devs) % n_hosts:
            raise UserError(f"{len(devs)} devices not divisible by {n_hosts}")
        per_host = len(devs) // n_hosts
    need = n_hosts * per_host
    if len(devs) < need:
        raise UserError(f"need {need} devices, have {len(devs)}")
    return Mesh(np.array(devs[:need]).reshape(n_hosts, per_host), (DCN, ICI))


def worker_axes(mesh: Mesh) -> tuple[str, ...]:
    """The axis names playing the worker-set role for this mesh shape;
    collectives and shardings use the full tuple."""
    return tuple(mesh.axis_names)


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Shard batch rows across the worker axes (data parallel scan)."""
    return NamedSharding(mesh, PartitionSpec(worker_axes(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
