"""Device mesh setup — the worker set.

Reference parity: the coordinator's view of the cluster
(``DiscoveryNodeManager``'s NodeMap + ``NodeScheduler`` placing tasks
on workers [SURVEY §2.1]). TPU-first: the "cluster" is a
``jax.sharding.Mesh``; placement is a sharding annotation, and the
entire REST control plane collapses into the single-controller driver
(SURVEY §7.1).

One mesh axis ``"workers"`` plays the role of Presto's worker set: scan
splits are data-parallel across it, hash-partitioned exchanges are
``all_to_all`` along it, broadcasts are ``all_gather``. Multi-host later
adds an outer DCN axis without changing fragment code.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

WORKERS = "workers"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (WORKERS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Shard batch rows across the worker axis (data parallel scan)."""
    return NamedSharding(mesh, PartitionSpec(WORKERS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
