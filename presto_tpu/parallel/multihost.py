"""Multi-host (DCN) bootstrap — the cluster-membership tier.

Reference parity: Airlift discovery + ``DiscoveryNodeManager`` — how
the reference's workers find each other and form a cluster
[SURVEY §2.5 discovery row]. TPU-first (SURVEY §2.5 DCN row): cluster
formation is ``jax.distributed`` — every host runs the SAME
single-controller program, the coordination service rendezvouses them,
and after initialization ``jax.devices()`` returns the GLOBAL device
list. There is no worker announce/poll loop to build: gang-scheduled
SPMD replaces the discovery protocol, and a host that dies kills the
step (the failure posture in README — query-level retry).

Usage, on every host of the cluster (identical program)::

    from presto_tpu.parallel import multihost
    multihost.initialize("10.0.0.1:8476", num_processes=4,
                         process_id=<this host's rank>)
    mesh = multihost.global_dcn_mesh()        # ("dcn", "ici") 2-D mesh
    session = Session({"tpch": conn}, mesh=mesh)
    df = session.sql("select ...")            # same program everywhere

Every fragment step shards and exchanges over the mesh's combined
axes (see ``parallel.mesh`` / ``parallel.exchange``), so the same
compiled programs run single-host or multi-host; XLA routes the
inter-host legs of each collective over DCN and the intra-host legs
over ICI. On TPU pods, ``initialize()`` with no arguments picks the
cluster configuration up from the TPU environment.
"""

from __future__ import annotations

import jax

from presto_tpu.parallel.mesh import make_dcn_mesh, make_mesh


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
):
    """Join (or form) the cluster. Arguments mirror
    ``jax.distributed.initialize``; on TPU pods all of them are
    auto-detected from the environment and may be omitted."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def num_hosts() -> int:
    return jax.process_count()


def global_dcn_mesh(per_host: int | None = None):
    """The cluster-wide 2-D ("dcn", "ici") mesh: one dcn row per host.
    Falls back to a flat single-axis mesh when there is one process."""
    hosts = jax.process_count()
    if hosts <= 1:
        return make_mesh()
    return make_dcn_mesh(hosts, per_host)
