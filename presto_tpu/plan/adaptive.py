"""Adaptive execution: the feedback controller that turns telemetry
into plan decisions (ROADMAP item 2 — the loop-closing half of the
plan-stats history that PR 8/10 only *reported*).

Three coupled decision kinds, one controller:

- ``salt`` — skew-salted repartitioning. When a recurring plan
  fingerprint's history shows a hot exchange destination on a
  repartition join (``skew`` >= :data:`SKEW_THRESHOLD` with a known
  ``hot_partition``), the repartition exchange is rewritten to spread
  the hot destination's probe rows round-robin across S salted
  partitions and REPLICATE the matching build rows to all S — equal
  keys still meet (each probe row sees exactly one copy of every
  matching build row), so output is bit-identical while the measured
  per-destination imbalance collapses toward 1x. The NDV-contention
  findings of *"Global Hash Tables Strike Back!"* (PAPERS.md) motivate
  the split; the approximate-tier precedent of *"Approximate
  Distributed Joins in Apache Spark"* (PAPERS.md) is why RECURRING
  history, not a one-shot estimate, is the trigger.
- ``join_flip`` / ``bucket`` — history-corrected sizing at the local
  executor's static-estimate strategy points: a build (or aggregate)
  whose recorded actuals contradict the planner's estimate past
  ``MISEST_FACTOR`` has its byte estimate recomputed from measured
  rows, flipping grouped execution back to in-memory when the build
  actually fits (and vice versa), and resizing grouped bucket counts
  from actuals instead of guesses.
- ``route`` — a Pallas-routed join whose advisory stats LIED (the
  build fell back at runtime: ``join.pallas_fallback``) stops
  re-attempting the fused route for that fingerprint.

Every decision passes the **compile-budget gate** before it is
allowed: a re-specialization changes an executable-cache key, so its
first run pays a cold trace+compile. The ``system.exec_cache`` ledger
knows the measured cold-vs-warm wall per step kind; when the predicted
compile cost exceeds the predicted win at the fingerprint's observed
recurrence rate, the specialization is REFUSED
(``adaptive.compile_budget_refused``) and the stable plan keeps its
warm executable.

Guards (the decision table in README "Adaptive execution"):

- history only steers on ``runs >= 2`` (the ``Session._plan_hints``
  corridor already enforces this — one-off queries never flip);
- decisions stand down while a fault injector is active
  (``runtime.faults.active()``) or while the flight recorder is
  capturing successes (``flight_record_successes``): a fault campaign
  or a repro capture must observe the BASELINE plan, deterministically
  (``adaptive.stand_down`` counts the suppressed passes);
- decisions are STICKY per (fingerprint, node): a salted run records
  ~1x skew, which would un-salt the next run and oscillate between two
  executables (each flip a retrace). Once made, a decision holds for
  the session; DDL rotates the fingerprint and naturally resets it.

The controller is per-Session state. Applied/refused decisions land in
a bounded ring (``system.adaptive``), in ``adaptive.*`` counters, and
on the executor's ``adaptive_events`` list so flight records carry
them — the first PR where a query's plan depends on the plans that ran
before it must stay debuggable.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Optional

from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.stats import MISEST_FACTOR

#: minimum recorded exchange skew (max/mean) that triggers salting
SKEW_THRESHOLD = 2.0

#: decision-ring retention (``system.adaptive`` depth)
RING_LIMIT = 256

#: predicted future recurrence per observed run: a fingerprint seen R
#: times is priced as if it will arrive ~8R more times. The budget
#: gate compares ONE cold compile against the per-run win over that
#: horizon — so a hot serving template re-specializes after a couple
#: of observations, while a one-off test query (milliseconds of wall)
#: never buys a multi-second recompile
RECURRENCE_HORIZON = 8


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def salt_factor(skew: float, nworkers: int, salt_max: int) -> int:
    """S for a measured skew ratio: the hot destination held ~``skew``x
    its fair share, so spreading it over ``ceil(skew)`` partitions
    (rounded up to a power of two for stable cache keys) restores
    balance. Clamped to the mesh size and the session's
    ``adaptive_salt_max`` — replication cost grows linearly in S."""
    s = _next_pow2(max(2, -(-int(skew) // 1)))
    return max(2, min(s, nworkers, salt_max))


@dataclass
class AdaptiveDecision:
    """One steering decision for one plan node of one fingerprint."""

    kind: str  # "salt" | "join_flip" | "bucket" | "route"
    node_id: int
    #: salt partition count (kind == "salt")
    salt: int = 0
    #: hot destination the salt spreads (kind == "salt")
    hot_partition: int = -1
    #: history-corrected byte estimate (join_flip / bucket)
    est_bytes: int = -1
    #: human-readable trigger for logs/EXPLAIN
    trigger: str = ""

    def to_event(self, applied: bool = True) -> dict:
        return {
            "kind": self.kind,
            "node_id": self.node_id,
            "salt": self.salt,
            "hot_partition": self.hot_partition,
            "est_bytes": self.est_bytes,
            "trigger": self.trigger,
            "applied": bool(applied),
        }


def predicted_compile_cost(kind_prefix: str) -> float:
    """Cheapest measured cold-minus-warm wall over executable-cache
    entries of one step kind — the ledger's estimate of what ONE new
    specialization's first run will pay. The MINIMUM, not the worst:
    a re-specialization (e.g. the salted variant of a join already
    compiled unsalted) shares most of its HLO with existing entries
    of the kind, so the marginal compile tracks the best case the
    compiler has shown for that shape, not a one-off worst that
    would ratchet the bar up for the life of the process. 0.0 when
    the ledger has no entry of that kind yet (the optimistic first
    specialization: with nothing measured there is nothing to
    predict, and refusing forever would deadlock adaptivity)."""
    from presto_tpu.cache.exec_cache import EXEC_CACHE

    best = 0.0
    for row in EXEC_CACHE.stats_rows():
        if row.get("kind") != kind_prefix:
            continue
        cold = float(row.get("cold_call_s", 0.0) or 0.0)
        warm = float(row.get("warm_call_s", 0.0) or 0.0)
        if cold > warm > 0.0:
            delta = cold - warm
            best = delta if best == 0.0 else min(best, delta)
    return best


#: executable-cache step kind whose ledger prices each decision kind
#: (the nearest measured proxy for what the re-specialized step will
#: pay to trace+compile)
_COST_KIND = {
    "salt": "dist_repart_join",
    "join_flip": "join_build",
    "bucket": "global_agg",
    "route": "join_build",
}


class AdaptiveController:
    """Per-Session feedback controller: plan-stats history in,
    per-node :class:`AdaptiveDecision` maps out, with sticky replay,
    compile-budget admission, and a decision log."""

    def __init__(self):
        #: sticky decisions keyed (fingerprint, node_id) — survive the
        #: telemetry they erase (see module docstring, oscillation)
        self._sticky: dict[tuple, AdaptiveDecision] = {}
        #: (fingerprint, node_id) pairs the budget gate refused — a
        #: refusal is sticky too (re-pricing every run would flap)
        self._refused: set = set()
        #: bounded decision log (``system.adaptive`` rows)
        self.ring: collections.deque = collections.deque(maxlen=RING_LIMIT)

    # ---- decision pass ------------------------------------------------
    def decide(self, plan, hints: dict, catalog, fingerprint: str,
               nworkers: int = 1, salt_max: int = 8,
               for_render: bool = False, recording: bool = False) -> dict:
        """One decision pass: {id(live node) -> {kind ->
        AdaptiveDecision}} for the executor (the ``plan_hints`` wiring
        shape; a node can carry several independent kinds — a salted
        repartition join may also have its Pallas route disabled).
        ``hints`` is ``Session._plan_hints`` output — present only when
        the fingerprint has recurred (runs >= 2), so the corridor's
        gate is inherited. ``for_render`` computes WOULD-BE decisions
        for EXPLAIN without logging or consulting the runtime
        stand-down guards (EXPLAIN shows the steady-state plan).
        ``recording`` marks an active repro/success-capture recorder
        (``flight_record_successes``) — those runs observe the
        baseline plan only."""
        if not hints:
            return {}
        if not for_render:
            from presto_tpu.runtime import faults

            if faults.active() is not None or recording:
                REGISTRY.counter("adaptive.stand_down").add()
                return {}
        from presto_tpu.plan import nodes as N
        from presto_tpu.runtime.memory import node_row_bytes

        out: dict = {}

        def bytes_for(node, rows: int) -> int:
            try:
                return max(0, int(rows)) * max(1, node_row_bytes(
                    node, catalog))
            except Exception:  # noqa: BLE001 — stats gaps never block
                return -1

        def admit(node, dec: AdaptiveDecision, runs: int,
                  wall_s: float, win_frac: float) -> None:
            """Budget-gate one candidate, then stick + log it."""
            skey = (fingerprint, dec.node_id, dec.kind)
            prior = self._sticky.get(skey)
            if prior is not None:
                out.setdefault(id(node), {})[dec.kind] = prior
                return
            if skey in self._refused:
                return
            if not for_render:
                cost = predicted_compile_cost(_COST_KIND[dec.kind])
                win = (max(0.0, wall_s) * win_frac
                       * max(1, runs) * RECURRENCE_HORIZON)
                if cost > 0.0 and cost > win:
                    self._refused.add(skey)
                    REGISTRY.counter(
                        "adaptive.compile_budget_refused").add()
                    self._log(fingerprint, dec, applied=False,
                              query_id="", note=(
                                  f"cost {cost:.3f}s > win {win:.3f}s"))
                    return
                self._sticky[skey] = dec
            out.setdefault(id(node), {})[dec.kind] = dec

        def replayed(node, kind: str, node_id: int) -> bool:
            """Sticky-first: an ADMITTED decision replays even after
            its own effect erased the trigger from the history (a
            salted run records ~1x skew; a corrected estimate records
            no misestimate). Without this the decision would oscillate
            on/off every other run."""
            prior = self._sticky.get((fingerprint, node_id, kind))
            if prior is None:
                return False
            out.setdefault(id(node), {})[kind] = prior
            return True

        def walk(node):
            rec = hints.get(id(node))
            if isinstance(node, (N.Join, N.SemiJoin)):
                if rec is not None:
                    runs = int(rec.get("runs", 0))
                    wall = float(rec.get("wall_s", 0.0))
                    skew = float(rec.get("skew", 0.0))
                    hot = int(rec.get("hot_partition", -1))
                    nid = int(rec.get("node_id", -1))
                    if not replayed(node, "salt", nid) and (
                            isinstance(node, N.Join) and nworkers > 1
                            and node.kind != "full"
                            and skew >= SKEW_THRESHOLD and hot >= 0):
                        s = salt_factor(skew, nworkers, salt_max)
                        admit(node, AdaptiveDecision(
                            "salt", nid, salt=s,
                            hot_partition=hot,
                            trigger=f"skew {skew:.1f}x hot={hot}",
                        ), runs, wall, win_frac=1.0 - 1.0 / s)
                    if not replayed(node, "route", nid) and \
                            rec.get("route_fallback"):
                        admit(node, AdaptiveDecision(
                            "route", nid,
                            trigger="pallas route fell back (lying stats)",
                        ), runs, wall, win_frac=0.5)
                # build-size correction reads the BUILD CHILD's actuals
                brec = hints.get(id(node.right))
                if brec is not None:
                    bid = int(brec.get("node_id", -1))
                    if not replayed(node, "join_flip", bid) and (
                            float(brec.get("misest", 0.0)) >= MISEST_FACTOR
                            and int(brec.get("actual_rows", -1)) >= 0):
                        eb = bytes_for(node.right, brec["actual_rows"])
                        if eb >= 0:
                            admit(node, AdaptiveDecision(
                                "join_flip", bid, est_bytes=eb,
                                trigger=(
                                    f"build est {brec.get('est_rows')} vs "
                                    f"actual {brec.get('actual_rows')}"),
                            ), int(brec.get("runs", 0)),
                                float(brec.get("wall_s", 0.0)),
                                win_frac=0.5)
            elif isinstance(node, N.Aggregate):
                if rec is not None:
                    nid = int(rec.get("node_id", -1))
                    if not replayed(node, "bucket", nid) and (
                            float(rec.get("misest", 0.0)) >= MISEST_FACTOR
                            and int(rec.get("actual_rows", -1)) >= 0):
                        eb = bytes_for(node, rec["actual_rows"])
                        if eb >= 0:
                            admit(node, AdaptiveDecision(
                                "bucket", nid, est_bytes=eb,
                                trigger=(
                                    f"agg est {rec.get('est_rows')} vs "
                                    f"actual {rec.get('actual_rows')}"),
                            ), int(rec.get("runs", 0)),
                                float(rec.get("wall_s", 0.0)),
                                win_frac=0.5)
            for c in node.children:
                walk(c)

        try:
            walk(plan)
        except Exception:  # noqa: BLE001 — adaptivity never fails a query
            return {}
        return out

    # ---- decision log -------------------------------------------------
    def _log(self, fingerprint: str, dec: AdaptiveDecision,
             applied: bool, query_id: str, note: str = "") -> None:
        ev = dec.to_event(applied)
        ev.update({
            "fingerprint": fingerprint,
            "query_id": query_id,
            "trigger": (f"{dec.trigger}; {note}" if note else dec.trigger),
            "created_at": time.time(),
        })
        self.ring.append(ev)

    def note_applied(self, fingerprint: str, query_id: str,
                     events: list) -> None:
        """Stitch an executor's applied-decision events into the ring
        (the ``system.adaptive`` / flight-record path)."""
        for ev in events:
            ev = dict(ev)
            ev.setdefault("fingerprint", fingerprint)
            ev.setdefault("query_id", query_id)
            ev.setdefault("created_at", time.time())
            self.ring.append(ev)

    def rows(self) -> list:
        """Decision-log rows, oldest first (``system.adaptive``)."""
        return list(self.ring)

    def clear(self) -> None:
        self._sticky.clear()
        self._refused.clear()
        self.ring.clear()
