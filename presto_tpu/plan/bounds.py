"""Static value-interval inference over plans and expressions.

Feeds ``AggSpec.value_bits`` from connector column statistics
(reference parity: the stats-driven micro-decisions the reference's
``StatsCalculator`` feeds into operator implementations [SURVEY §2.1
optimizer row]): the fused one-hot-matmul segment sum needs a static
bound on |value| to pick its lane count, and tighter bounds mean fewer
lanes per pass. Bounds are *advisory* — a runtime guard inside
``fused_small_sums`` trips ``value_overflow`` when a declared bound is
violated, and the executor retries with the unbounded 63-bit path — so
a wrong stat can cost a recompile but never a wrong answer.

Intervals are closed [lo, hi] over the PHYSICAL representation
(scaled ints for decimals, day numbers for dates, dictionary codes for
varchars); ``None`` means unbounded/unknown. The arithmetic mirrors
``presto_tpu.expr``'s physical semantics (``_to_physical`` rescaling,
``mul``'s excess-scale rounding) conservatively: any rounding step
widens the interval by 1.
"""

from __future__ import annotations

import math
from typing import Optional

from presto_tpu.expr import Call, Expr, InputRef, Literal
from presto_tpu.plan import nodes as N
from presto_tpu.types import DataType, TypeKind

Interval = Optional[tuple[int, int]]


def _hull(a: Interval, b: Interval) -> Interval:
    if a is None or b is None:
        return None
    return (min(a[0], b[0]), max(a[1], b[1]))


def _rescale(iv: Interval, src: DataType, dst: DataType) -> Interval:
    """Mirror ``_to_physical`` for decimal/integer rescaling."""
    if iv is None:
        return None
    s_src = src.scale if src.kind is TypeKind.DECIMAL else 0
    s_dst = dst.scale if dst.kind is TypeKind.DECIMAL else 0
    if s_dst >= s_src:
        f = 10 ** (s_dst - s_src)
        return (iv[0] * f, iv[1] * f)
    f = 10 ** (s_src - s_dst)
    # round-half-away bound: |x/f| rounded <= |x|/f + 1
    lo = -(abs(iv[0]) // f + 1) if iv[0] < 0 else iv[0] // f
    hi = iv[1] // f + 1 if iv[1] > 0 else -(abs(iv[1]) // f)
    return (lo, hi)


_INTEGERISH = (TypeKind.INTEGER, TypeKind.BIGINT, TypeKind.DECIMAL, TypeKind.DATE)


def expr_interval(e: Expr, env: dict[str, Interval]) -> Interval:
    """Physical-value interval of ``e`` given column intervals ``env``."""
    if e.dtype.kind not in _INTEGERISH and e.dtype.kind is not TypeKind.BOOLEAN:
        return None  # floats/strings: no lane bound needed or derivable
    if isinstance(e, InputRef):
        return env.get(e.name)
    if isinstance(e, Literal):
        if e.value is None:
            return (0, 0)  # NULL slots hold the physical fill value 0
        try:
            v = int(e.dtype.to_physical(e.value))
        except (TypeError, ValueError):
            return None
        return (v, v)
    if not isinstance(e, Call):
        return None
    args = e.args

    def arg_iv(i: int, target: DataType | None = None) -> Interval:
        iv = expr_interval(args[i], env)
        if target is not None and iv is not None:
            return _rescale(iv, args[i].dtype, target)
        return iv

    fn = e.fn
    if fn in ("add", "sub"):
        a, b = arg_iv(0, e.dtype), arg_iv(1, e.dtype)
        if a is None or b is None:
            return None
        if fn == "add":
            return (a[0] + b[0], a[1] + b[1])
        return (a[0] - b[1], a[1] - b[0])
    if fn == "mul":
        a, b = arg_iv(0), arg_iv(1)
        if a is None or b is None:
            return None
        prods = [x * y for x in a for y in b]
        lo, hi = min(prods), max(prods)
        if e.dtype.kind is TypeKind.DECIMAL:
            sa = args[0].dtype.scale if args[0].dtype.kind is TypeKind.DECIMAL else 0
            sb = args[1].dtype.scale if args[1].dtype.kind is TypeKind.DECIMAL else 0
            excess = sa + sb - e.dtype.scale
            if excess > 0:
                f = 10**excess
                lo = -(abs(lo) // f + 1) if lo < 0 else lo // f
                hi = hi // f + 1 if hi > 0 else -(abs(hi) // f)
        return (lo, hi)
    if fn == "neg":
        a = arg_iv(0)
        return None if a is None else (-a[1], -a[0])
    if fn == "abs":
        a = arg_iv(0)
        if a is None:
            return None
        return (0 if a[0] <= 0 <= a[1] else min(abs(a[0]), abs(a[1])),
                max(abs(a[0]), abs(a[1])))
    if fn == "cast_bigint":
        return arg_iv(0, e.dtype)
    if fn in ("if", "case"):
        # if(cond, then, else); case(when1, then1, ..., [else])
        if fn == "if":
            branches = list(args[1:])
            out: Interval = None
        else:
            branches = [a for i, a in enumerate(args) if i % 2 == 1] + (
                [args[-1]] if len(args) % 2 == 1 else []
            )
            # an un-elsed CASE yields the physical fill 0 on no match
            out = (0, 0) if len(args) % 2 == 0 else None
        for i, b in enumerate(branches):
            iv = expr_interval(b, env)
            iv = None if iv is None else _rescale(iv, b.dtype, e.dtype)
            out = iv if i == 0 and out is None else _hull(out, iv)
            if out is None:
                return None
        return out
    if fn == "coalesce":
        out = None
        for i, a in enumerate(args):
            iv = expr_interval(a, env)
            iv = None if iv is None else _rescale(iv, a.dtype, e.dtype)
            out = iv if i == 0 else _hull(out, iv)
            if out is None:
                return None
        return out
    if fn == "year":
        return (0, 9999)
    if fn == "month":
        return (1, 12)
    if fn == "day":
        return (1, 31)
    if fn in ("eq", "ne", "lt", "le", "gt", "ge", "and", "or", "not",
              "between", "in", "is_null", "is_not_null", "like",
              "starts_with"):
        return (0, 1)
    if fn == "mod":
        b = arg_iv(1, e.dtype)
        if b is None:
            return None
        m = max(abs(b[0]), abs(b[1]))
        return (-m, m) if m else (0, 0)
    return None  # div and anything unknown: unbounded


def _stats_interval(stats, dtype: DataType) -> Interval:
    # the ONE logical->physical stats scaling rule, shared with scan
    # narrowing (spi.narrowed_schema): intervals and narrowed storage
    # must be derived identically or a narrowed column could hold
    # values its declared interval excludes
    from presto_tpu.spi import stats_physical_interval

    return stats_physical_interval(stats, dtype)


def node_intervals(node: N.PlanNode, catalog,
                   memo: Optional[dict] = None) -> dict[str, Interval]:
    """Per-output-column physical intervals for a plan subtree.

    Conservative: anything not provably bounded maps to None. Filters
    pass their child through un-refined (a tighter bound is never
    required for correctness — the runtime guard has the last word).

    ``memo``: optional per-walk cache (keyed on ``id(node)`` — safe
    only while the caller holds the plan alive, which every walk does).
    Callers that visit every node of a plan (the estimate snapshot)
    pass one dict so the walk is linear instead of quadratic; the
    memoization is pure — identical results with or without it.
    """
    if memo is not None:
        hit = memo.get(("iv", id(node)))
        if hit is not None:
            return hit
    out = _node_intervals(node, catalog, memo)
    if memo is not None:
        memo[("iv", id(node))] = out
    return out


def _node_intervals(node: N.PlanNode, catalog,
                    memo: Optional[dict]) -> dict[str, Interval]:
    if isinstance(node, N.TableScan):
        out: dict[str, Interval] = {}
        for (name, src), t in zip(node.columns, node.types):
            out[name] = _stats_interval(
                catalog.stats(node.connector, node.table, src), t
            )
        return out
    if isinstance(node, N.Project):
        env = node_intervals(node.child, catalog, memo)
        return {n: expr_interval(e, env) for n, e in node.exprs}
    if isinstance(node, N.Aggregate):
        env = node_intervals(node.child, catalog, memo)
        out = {n: expr_interval(e, env) for n, e in node.keys}
        for n, e in node.passengers:
            out[n] = expr_interval(e, env)
        for a in node.aggs:
            out[a.name] = None  # running sums: unbounded without row counts
        return out
    if isinstance(node, (N.Join,)):
        out = dict(node_intervals(node.left, catalog, memo))
        right = node_intervals(node.right, catalog, memo)
        if node.kind == "left":
            # unmatched probe rows carry the physical fill 0 on build cols
            right = {n: _hull(iv, (0, 0)) for n, iv in right.items()}
        out.update(right)
        return out
    children = node.children
    if len(children) == 1:
        env = node_intervals(children[0], catalog, memo)
        return {f.name: env.get(f.name) for f in node.fields}
    if children:
        # first child wins on name collisions: multi-child nodes other
        # than Join (handled above) emit their FIRST child's fields
        # (SemiJoin, BindScalars), so a same-named right column must not
        # shadow the left interval
        out = {}
        for c in children:
            for n, iv in node_intervals(c, catalog, memo).items():
                out.setdefault(n, iv)
        return {f.name: out.get(f.name) for f in node.fields}
    return {f.name: None for f in node.fields}


def resolve_source_column(node: N.PlanNode, name: str):
    """Trace an output column back to its (connector, table, source
    column) through rename/project/filter/join chains; None when the
    column is computed. Lets the planner answer metadata questions
    (dictionary domains, stats) without scanning any data."""
    if isinstance(node, N.TableScan):
        for n, src in node.columns:
            if n == name:
                return (node.connector, node.table, src)
        return None
    if isinstance(node, N.Project):
        for n, e in node.exprs:
            if n == name:
                if isinstance(e, InputRef):
                    return resolve_source_column(node.child, e.name)
                return None
        return None
    if isinstance(node, N.Aggregate):
        for n, e in list(node.keys) + list(node.passengers):
            if n == name:
                if isinstance(e, InputRef):
                    return resolve_source_column(node.child, e.name)
                return None
        return None
    if isinstance(node, N.Join):
        if name in {f.name for f in node.left.fields}:
            return resolve_source_column(node.left, name)
        return resolve_source_column(node.right, name)
    if isinstance(node, N.SemiJoin):
        return resolve_source_column(node.left, name)
    children = node.children
    if len(children) == 1:
        return resolve_source_column(children[0], name)
    return None


def key_dictionary(node: N.PlanNode, name: str, catalog):
    """The ordered dictionary behind an output column, via metadata."""
    src = resolve_source_column(node, name)
    if src is None:
        return None
    connector, table, col = src
    conn = catalog.connector(connector)
    if not hasattr(conn, "dictionaries"):
        return None
    return conn.dictionaries(table).get(col)


def estimate_rows(node: N.PlanNode, catalog,
                  memo: Optional[dict] = None) -> int:
    """Coarse output-row estimate from connector stats (the
    StatsCalculator role, radically simplified). Used to size sort-
    strategy group capacities and streaming morsel state up front;
    always backed by the capacity-overflow retry loop, so a bad
    estimate costs a replay, never a wrong answer.

    ``memo``: optional per-walk cache (see :func:`node_intervals`) —
    pure memoization, identical estimates with or without it."""
    if memo is not None:
        hit = memo.get(("rows", id(node)))
        if hit is not None:
            return hit
    out = _estimate_rows(node, catalog, memo)
    if memo is not None:
        memo[("rows", id(node))] = out
    return out


def _estimate_rows(node: N.PlanNode, catalog, memo: Optional[dict]) -> int:
    if isinstance(node, N.TableScan):
        conn = catalog.connector(node.connector)
        rows = int(conn.row_count(node.table)) if hasattr(conn, "row_count") else 1 << 16
        return max(1, rows // (3 if node.predicate is not None else 1))
    if isinstance(node, N.Filter):
        return max(1, estimate_rows(node.child, catalog, memo) // 3)
    if isinstance(node, N.Aggregate):
        return max(1, estimate_rows(node.child, catalog, memo) // 8)
    if isinstance(node, N.Join):
        left = estimate_rows(node.left, catalog, memo)
        if node.unique:
            return left
        return max(left, estimate_rows(node.right, catalog, memo))
    if isinstance(node, N.SemiJoin):
        return estimate_rows(node.left, catalog, memo)
    if isinstance(node, N.TopN):
        return node.count
    if isinstance(node, N.Limit):
        return node.count
    if isinstance(node, N.Union):
        return sum(estimate_rows(c, catalog, memo) for c in node.inputs)
    children = node.children
    if children:
        return max(estimate_rows(c, catalog, memo) for c in children)
    return 1 << 10


def estimate_groups(node: "N.Aggregate", catalog,
                    memo: Optional[dict] = None) -> Optional[int]:
    """NDV-based group-cardinality estimate for a keyed Aggregate, or
    None when any key's distinct-value count is unknowable from
    metadata. The product of per-key NDVs (dictionary domain size for
    VARCHAR keys, connector ``stats.ndv`` for source-traceable numeric
    keys), clamped by the child's estimated rows — the left-hand side
    of the partial-aggregation bypass rule (*Partial Partial
    Aggregates* / *Global Hash Tables Strike Back!*): when groups
    approach rows, pre-aggregating per morsel reduces nothing."""
    if not isinstance(node, N.Aggregate) or not node.keys:
        return None
    prod = 1
    for name, e in node.keys:
        if not isinstance(e, InputRef):
            return None
        d = key_dictionary(node.child, name, catalog)
        if d is not None:
            prod *= max(len(d), 1)
            continue
        src = resolve_source_column(node.child, name)
        if src is None:
            return None
        stats = catalog.stats(*src)
        ndv = getattr(stats, "ndv", None) if stats is not None else None
        if not ndv:
            return None
        prod *= max(int(ndv), 1)
        if prod > (1 << 40):  # clamp before the product explodes
            break
    return max(1, min(prod, estimate_rows(node.child, catalog, memo)))


def estimate_record(node: N.PlanNode, catalog,
                    memo: Optional[dict] = None) -> dict:
    """The planner's full row prediction for one node — the plan-time
    half of estimate-vs-actual telemetry (runtime/stats.py snapshots
    this per node before execution): the selectivity-guessing
    ``estimate_rows``, the SOUND ``fragmenter.upper_bound_rows`` (None
    when unprovable), and whether that bound is exact (no predicate
    below — the proven-broadcast condition). Estimate quality is
    legible only when both numbers travel together: actual > upper
    bound means a soundness bug, actual far from est_rows means the
    selectivity guesses misfired."""
    from presto_tpu.plan.fragmenter import is_unfiltered, upper_bound_rows

    ub = upper_bound_rows(node, catalog)
    return {
        "est_rows": estimate_rows(node, catalog, memo),
        "upper_bound_rows": ub,
        "exact": ub is not None and is_unfiltered(node),
    }


def agg_value_bits(agg: N.Aggregate, catalog) -> list[int]:
    """``value_bits`` for each of ``agg.aggs`` (63 when unbounded)."""
    env = node_intervals(agg.child, catalog)
    out = []
    for a in agg.aggs:
        bits = 63
        if (
            a.kind == "sum"
            and a.input is not None
            and a.input.dtype.kind in _INTEGERISH
        ):
            iv = expr_interval(a.input, env)
            if iv is not None:
                bits = max(1, max(abs(iv[0]), abs(iv[1])).bit_length())
        out.append(min(bits, 63))
    return out
