"""Catalog: table resolution + metadata for analysis and planning.

Reference parity: ``MetadataManager`` + ``ConnectorMetadata`` (schema
resolution, table handles, statistics for the CBO) [SURVEY §2.1;
reference tree unavailable, paths reconstructed].
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping

from presto_tpu.types import DataType

#: primary/unique keys per TPC-H table — drives the FK->PK unique-probe
#: fast path (reference: TpchMetadata's implicit key knowledge).
TPCH_UNIQUE_KEYS: dict[str, tuple[tuple[str, ...], ...]] = {
    "customer": (("c_custkey",), ("c_name",)),  # c_name = 'Customer#<key>'
    "orders": (("o_orderkey",),),
    "lineitem": (("l_orderkey", "l_linenumber"),),
    "part": (("p_partkey",),),
    "supplier": (("s_suppkey",), ("s_name",)),  # s_name = 'Supplier#<key>'
    "partsupp": (("ps_partkey", "ps_suppkey"),),
    "nation": (("n_nationkey",), ("n_name",)),
    "region": (("r_regionkey",), ("r_name",)),
}


@dataclass(frozen=True)
class TableMeta:
    connector_name: str
    table: str
    schema: Mapping[str, DataType]
    row_count: int
    unique_keys: tuple[tuple[str, ...], ...]
    #: declared functional dependencies: determined column -> its
    #: determinant columns (e.g. tpcds i_brand <- (i_brand_id,))
    func_deps: Mapping[str, tuple[str, ...]] = None


class Catalog:
    def __init__(self, connectors: Mapping[str, object], default: str = "tpch"):
        self.connectors = dict(connectors)
        self.default = default
        self._meta_cache: dict[str, TableMeta] = {}

    def connector(self, name: str):
        return self.connectors[name]

    def invalidate(self, table: str) -> None:
        """Drop cached metadata after DDL (CTAS/DROP) changes a table."""
        self._meta_cache.pop(table, None)

    def resolve(self, table: str) -> TableMeta:
        cached = self._meta_cache.get(table)
        if cached is not None:
            return cached
        meta = self._resolve_uncached(table)
        self._meta_cache[table] = meta
        return meta

    def _resolve_uncached(self, table: str) -> TableMeta:
        for cname, conn in self.connectors.items():
            if table in conn.tables():
                uk = getattr(conn, "unique_keys", lambda t: ())(table)
                if not uk and table in TPCH_UNIQUE_KEYS and cname == "tpch":
                    uk = TPCH_UNIQUE_KEYS[table]
                fd = getattr(conn, "func_deps", lambda t: {})(table)
                return TableMeta(
                    cname, table, dict(conn.schema(table)), conn.row_count(table),
                    tuple(uk), dict(fd),
                )
        raise KeyError(f"table not found in any catalog: {table}")

    def unique_keys(self, table: str) -> tuple[tuple[str, ...], ...]:
        """Unique keys of a table in any registered catalog (empty if
        unknown) — drives FK->PK probe fast paths and the
        functional-dependency passenger grouping."""
        try:
            return self.resolve(table).unique_keys
        except KeyError:
            return ()

    def func_deps(self, table: str) -> Mapping[str, tuple[str, ...]]:
        try:
            return self.resolve(table).func_deps or {}
        except KeyError:
            return {}

    def stats(self, connector_name: str, table: str, column: str):
        conn = self.connectors[connector_name]
        if hasattr(conn, "stats"):
            return conn.stats(table, column)
        return None
