"""Catalog: table resolution + metadata for analysis and planning.

Reference parity: ``MetadataManager`` + ``ConnectorMetadata`` (schema
resolution, table handles, statistics for the CBO) [SURVEY §2.1;
reference tree unavailable, paths reconstructed].
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Mapping

from presto_tpu.types import DataType

#: primary/unique keys per TPC-H table — drives the FK->PK unique-probe
#: fast path (reference: TpchMetadata's implicit key knowledge).
TPCH_UNIQUE_KEYS: dict[str, tuple[tuple[str, ...], ...]] = {
    "customer": (("c_custkey",), ("c_name",)),  # c_name = 'Customer#<key>'
    "orders": (("o_orderkey",),),
    "lineitem": (("l_orderkey", "l_linenumber"),),
    "part": (("p_partkey",),),
    "supplier": (("s_suppkey",), ("s_name",)),  # s_name = 'Supplier#<key>'
    "partsupp": (("ps_partkey", "ps_suppkey"),),
    "nation": (("n_nationkey",), ("n_name",)),
    "region": (("r_regionkey",), ("r_name",)),
}


@dataclass(frozen=True)
class TableMeta:
    connector_name: str
    table: str
    schema: Mapping[str, DataType]
    row_count: int
    unique_keys: tuple[tuple[str, ...], ...]
    #: declared functional dependencies: determined column -> its
    #: determinant columns (e.g. tpcds i_brand <- (i_brand_id,))
    func_deps: Mapping[str, tuple[str, ...]] = None


#: process-unique tokens distinguishing Catalog instances in shared
#: (process-wide) caches: two sessions' memory tables may share names
#: and versions while holding different data
_catalog_seq = itertools.count(1)


class Catalog:
    def __init__(self, connectors: Mapping[str, object], default: str = "tpch"):
        self.connectors = dict(connectors)
        self.default = default
        self._meta_cache: dict[str, TableMeta] = {}
        #: per-table DDL version counters — the caching subsystem's
        #: invalidation clock: CTAS/DROP/INSERT bump the table's
        #: version via invalidate(), and every plan fingerprint /
        #: result-cache entry folds the versions it read, so stale
        #: reuse is structurally impossible (cache/fingerprint.py)
        self._versions: dict[str, int] = {}
        #: callbacks fired on each invalidate (the session's result
        #: cache registers its eager-drop hook here)
        self._invalidation_listeners: list = []
        self._token = f"cat{next(_catalog_seq)}"

    def connector(self, name: str):
        return self.connectors[name]

    def cache_token(self) -> str:
        """Stable identity of THIS catalog instance for process-wide
        caches (never reused within a process, unlike ``id()``)."""
        return self._token

    def version(self, table: str) -> int:
        """Monotonic DDL version of a table (0 until first DDL)."""
        return self._versions.get(table, 0)

    def add_invalidation_listener(self, cb) -> None:
        self._invalidation_listeners.append(cb)

    def invalidate(self, table: str) -> None:
        """Drop cached metadata after DDL (CTAS/DROP/INSERT) changes a
        table, bump its version counter, and notify listeners. Every
        DDL path MUST route here — the regression test in
        tests/test_cache.py asserts a stale-metadata read after CTAS
        is impossible."""
        self._meta_cache.pop(table, None)
        self._versions[table] = self._versions.get(table, 0) + 1
        for cb in self._invalidation_listeners:
            cb(table)

    def resolve(self, table: str) -> TableMeta:
        cached = self._meta_cache.get(table)
        if cached is not None:
            return cached
        meta = self._resolve_uncached(table)
        self._meta_cache[table] = meta
        return meta

    def _resolve_uncached(self, table: str) -> TableMeta:
        for cname, conn in self.connectors.items():
            if table in conn.tables():
                uk = getattr(conn, "unique_keys", lambda t: ())(table)
                if not uk and table in TPCH_UNIQUE_KEYS and cname == "tpch":
                    uk = TPCH_UNIQUE_KEYS[table]
                fd = getattr(conn, "func_deps", lambda t: {})(table)
                return TableMeta(
                    cname, table, dict(conn.schema(table)), conn.row_count(table),
                    tuple(uk), dict(fd),
                )
        raise KeyError(f"table not found in any catalog: {table}")

    def unique_keys(self, table: str) -> tuple[tuple[str, ...], ...]:
        """Unique keys of a table in any registered catalog (empty if
        unknown) — drives FK->PK probe fast paths and the
        functional-dependency passenger grouping."""
        try:
            return self.resolve(table).unique_keys
        except KeyError:
            return ()

    def func_deps(self, table: str) -> Mapping[str, tuple[str, ...]]:
        try:
            return self.resolve(table).func_deps or {}
        except KeyError:
            return {}

    def stats(self, connector_name: str, table: str, column: str):
        conn = self.connectors[connector_name]
        if hasattr(conn, "stats"):
            return conn.stats(table, column)
        return None
