"""Plan fragmenter: explicit exchange boundaries + plan-time
distribution decisions.

Reference parity: ``PlanFragmenter`` (stages cut at ExchangeNode
boundaries), ``AddExchanges`` (partitioning decisions) and the CBO's
``DetermineJoinDistributionType`` (stats-driven broadcast vs
partitioned) [SURVEY §2.1 L3/L4 rows, §3.1; reference tree
unavailable, paths reconstructed].

TPU mapping (SURVEY §7.1): a fragment here is NOT a separately
scheduled stage — the distributed executor compiles each exchange
*into* its consumer's shard_map step (partial agg -> all_to_all ->
final agg is ONE XLA program). The fragment tree is still load-bearing
twice over:

- **Plan-time join distribution**: when connector stats give a SOUND
  upper bound on the build side (selectivity is never assumed — only
  row counts, unique-build joins, limits and unions propagate), the
  executor takes the broadcast path and skips its per-join
  ``live_count`` device sync plus the budget readback (round-3 ask #5
  class: blocking host round trips before a step can compile).
  Unprovable cases stay AUTOMATIC — the runtime cardinality check
  decides exactly as before.
- **EXPLAIN (TYPE DISTRIBUTED)**: the client-visible fragment/exchange
  rendering (reference: PlanPrinter's distributed mode).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from presto_tpu.plan import nodes as N


def upper_bound_rows(node: N.PlanNode, catalog) -> int | None:
    """SOUND output-row upper bound from connector stats, or None.

    Unlike ``bounds.estimate_rows`` (an estimate with selectivity
    guesses, fine for capacity sizing backed by retry), this never
    divides: a wrong broadcast decision would not be caught by any
    retry loop, so only provable bounds count.
    """
    ub = upper_bound_rows
    if isinstance(node, N.TableScan):
        conn = catalog.connector(node.connector)
        if hasattr(conn, "row_count"):
            return int(conn.row_count(node.table))
        return None
    if isinstance(node, (N.Filter, N.Project, N.Window, N.Sort)):
        return ub(node.child, catalog)
    if isinstance(node, N.BindScalars):
        return ub(node.child, catalog)
    if isinstance(node, N.ScalarValue):
        return 1
    if isinstance(node, N.Values):
        return 1
    if isinstance(node, N.Aggregate):
        c = ub(node.child, catalog)
        if not node.keys:
            # a keyless (global) aggregate emits one row even over an
            # empty input, so a child bound of 0 (or unknown) would
            # violate the SOUND-upper-bound contract
            return 1 if c is None else max(1, c)
        return c  # one row per group <= input rows
    if isinstance(node, N.Join):
        if node.unique and node.kind in ("inner", "left"):
            # each probe row matches at most one build row; LEFT adds
            # no extra rows beyond the probe side
            return ub(node.left, catalog)
        return None
    if isinstance(node, N.SemiJoin):
        return ub(node.left, catalog)
    if isinstance(node, (N.TopN, N.Limit)):
        c = ub(node.child, catalog)
        return node.count if c is None else min(c, node.count)
    if isinstance(node, N.Union):
        parts = [ub(c, catalog) for c in node.inputs]
        return None if any(p is None for p in parts) else sum(parts)
    if isinstance(node, N.Output):
        return ub(node.child, catalog)
    return None


def is_unfiltered(node: N.PlanNode) -> bool:
    """True when ``upper_bound_rows`` is EXACT for this subtree — no
    predicate anywhere, so the bound equals the actual row count. The
    executor's plan-proven broadcast fast path requires this: with a
    merely-loose bound, skipping the runtime ``live_count`` would size
    the replication compaction (and check the gather guard) against
    rows that are not really there."""
    if isinstance(node, N.TableScan):
        return node.predicate is None
    if isinstance(node, (N.Project, N.BindScalars)):
        return is_unfiltered(node.child)
    if isinstance(node, (N.Values, N.ScalarValue)):
        return True
    return False


def output_partitioned(node: N.PlanNode) -> bool:
    """Whether the node's OUTPUT is row-sharded at runtime. False for
    producers the executor leaves single/replicated: literal rows,
    global (keyless) aggregates (a plain-jit psum), and operators that
    sit above their own gather (sort/topN/limit/window)."""
    if isinstance(node, (N.Values, N.ScalarValue)):
        return False
    if isinstance(node, N.Aggregate):
        return bool(node.keys)
    if isinstance(node, (N.Sort, N.TopN, N.Limit, N.Window)):
        return False
    if isinstance(node, (N.Filter, N.Project, N.BindScalars, N.Output)):
        return output_partitioned(node.children[0])
    if isinstance(node, (N.Join, N.SemiJoin)):
        return output_partitioned(node.left)
    if isinstance(node, N.Union):
        return any(output_partitioned(c) for c in node.inputs)
    return True  # TableScan and anything unknown: assume sharded


@dataclass(frozen=True)
class Exchange:
    """A fragment boundary: how the producer's rows reach the consumer."""

    kind: str  # "broadcast" | "hash" | "gather"
    keys: tuple[str, ...] = ()


@dataclass
class Fragment:
    fid: int
    root: N.PlanNode
    partitioning: str  # "source" | "hash" | "single" | "replicated"
    #: (child fragment id, exchange feeding this fragment)
    consumes: list[tuple[int, Exchange]] = field(default_factory=list)


@dataclass
class FragmentPlan:
    fragments: list[Fragment]
    #: id(Join node) -> "broadcast" | "auto" (auto = runtime decides)
    join_strategy: dict
    #: id(Join node) -> True when the stats UB proves the build side
    #: fits the in-memory join budget (skips the runtime budget sync)
    join_fits_budget: dict
    #: id(Join node) -> sound build-row upper bound (replication
    #: capacity sizing without a device sync)
    join_rows_ub: dict
    #: catalog used for planning (renders scan columns' physical types)
    catalog: object = None

    def render(self, skew_history: "dict | None" = None) -> str:
        # roots of other fragments are rendering stop points: each
        # subtree prints in exactly one fragment, with an exchange stub
        # where it was cut out.
        # ``skew_history``: {id(plan node): observed exchange-partition
        # skew ratio} from plan-stats history (recurring fingerprints) —
        # rendered on the owning fragment's header so a hot partition
        # seen in PAST runs is visible at plan time.
        stops = {id(f.root): f.fid for f in self.fragments}
        ex_by_child = {}
        for f in self.fragments:
            for fid, ex in f.consumes:
                ex_by_child[fid] = ex

        def label(n: N.PlanNode) -> str:
            t = type(n).__name__
            if isinstance(n, N.TableScan):
                phys = ""
                if self.catalog is not None:
                    from presto_tpu.plan.nodes import scan_physical_types

                    narrowed = {
                        s: dt for s, dt in
                        scan_physical_types(n, self.catalog).items()
                        if dt.is_narrowed
                    }
                    if narrowed:
                        phys = " physical={" + ", ".join(
                            f"{s}:{dt.phys}" for s, dt in sorted(
                                narrowed.items())) + "}"
                return f"{t}[{n.connector}.{n.table}]{phys}"
            if isinstance(n, N.Aggregate):
                return f"{t}[keys={[k for k, _ in n.keys]}]"
            if isinstance(n, N.Join):
                strat = self.join_strategy.get(id(n))
                # an unproven broadcast (row UB fits the broadcast limit
                # but the byte budget is not plan-time proven) can still
                # take the grouped-spill path at runtime; render it as
                # tentative so EXPLAIN doesn't overstate the strategy
                if strat == "broadcast" and not self.join_fits_budget.get(
                        id(n)):
                    strat = "broadcast?"
                extra = f", dist={strat}" if strat else ""
                return f"{t}[{n.kind}{extra}]"
            return t

        def tree(n: N.PlanNode, own_fid: int, indent: int) -> list[str]:
            pad = "    " + "  " * indent
            fid = stops.get(id(n))
            if fid is not None and fid != own_fid:
                ex = ex_by_child.get(fid)
                how = (f"{ex.kind}" + (f"({', '.join(ex.keys)})"
                                       if ex and ex.keys else "")
                       if ex else "exchange")
                return [f"{pad}[{how} <- fragment {fid}]"]
            lines = [pad + label(n)]
            for c in n.children:
                lines.extend(tree(c, own_fid, indent + 1))
            return lines

        def fragment_skew(n: N.PlanNode, own_fid: int) -> float:
            """Worst history-observed skew over the nodes THIS fragment
            owns (stopping at other fragments' roots, like tree())."""
            fid = stops.get(id(n))
            if fid is not None and fid != own_fid:
                return 0.0
            worst = (skew_history or {}).get(id(n), 0.0)
            for c in n.children:
                worst = max(worst, fragment_skew(c, own_fid))
            return worst

        out = []
        for f in self.fragments:
            # the SOUND plan-time row bound per fragment root (the same
            # number the estimate-vs-actual snapshot records), so the
            # distributed rendering shows what the fragmenter's
            # distribution decisions were actually based on
            bound = ""
            if self.catalog is not None:
                ub = upper_bound_rows(f.root, self.catalog)
                if ub is not None:
                    bound = f" est<={ub:,} rows"
            skew = fragment_skew(f.root, f.fid)
            if skew > 0:
                bound += f" skew~{skew:.1f}x (observed)"
            out.append(f"Fragment {f.fid} [{f.partitioning}]{bound}")
            out.extend(tree(f.root, f.fid, 0))
        out.append(
            "(exchanges compile INTO their consumer's shard_map step — a "
            "fragment boundary is a collective, not an RPC hop)"
        )
        return "\n".join(out)


def fragment_plan(plan: N.PlanNode, catalog, broadcast_limit: int,
                  join_build_budget: int | None = None) -> FragmentPlan:
    """Cut the logical plan at exchange boundaries and decide join
    distribution from sound stats bounds."""
    from presto_tpu.runtime.memory import node_row_bytes

    fragments: list[Fragment] = []
    join_strategy: dict = {}
    join_fits: dict = {}
    join_rows_ub: dict = {}

    def new_fragment(root, partitioning) -> Fragment:
        f = Fragment(len(fragments), root, partitioning)
        fragments.append(f)
        return f

    def visit(node: N.PlanNode, frag: Fragment) -> None:
        if isinstance(node, N.Join):
            # probe side stays in this fragment; build side becomes its
            # own fragment delivered by broadcast or hash exchange
            ubr = upper_bound_rows(node.right, catalog)
            # physical (narrowed) widths, matching the runtime build
            # estimates — plan-time and run-time sizing must agree
            bytes_ub = (None if ubr is None
                        else ubr * node_row_bytes(node.right, catalog))
            if ubr is not None and ubr <= broadcast_limit:
                join_strategy[id(node)] = "broadcast"
                ex = Exchange("broadcast")
                part = "replicated"
            else:
                join_strategy[id(node)] = "auto"
                ex = Exchange("hash", tuple(map(str, node.right_keys)))
                part = "hash"
            # the executor's sync-skipping fast path additionally
            # requires the bound to be EXACT (no filtering below):
            # a loose bound would mis-size the replication compaction
            # and over-trip the gather guard
            join_fits[id(node)] = (
                join_build_budget is not None and bytes_ub is not None
                and bytes_ub <= join_build_budget
                and is_unfiltered(node.right)
            )
            if ubr is not None:
                join_rows_ub[id(node)] = ubr
            bf = new_fragment(node.right, part)
            frag.consumes.append((bf.fid, ex))
            visit(node.right, bf)
            visit(node.left, frag)
            return
        if isinstance(node, N.SemiJoin):
            ubr = upper_bound_rows(node.right, catalog)
            ex = (Exchange("broadcast")
                  if ubr is not None and ubr <= broadcast_limit
                  else Exchange("hash", tuple(map(str, node.right_keys))))
            bf = new_fragment(
                node.right,
                "replicated" if ex.kind == "broadcast" else "hash")
            frag.consumes.append((bf.fid, ex))
            visit(node.right, bf)
            visit(node.left, frag)
            return
        if isinstance(node, N.Aggregate) and node.keys:
            # PARTIAL below the hash exchange, FINAL above (the executor
            # fuses all three into one step; the boundary still exists)
            cf = new_fragment(node.child, "hash")
            frag.consumes.append(
                (cf.fid, Exchange("hash", tuple(n for n, _ in node.keys))))
            visit(node.child, cf)
            return
        single_ops = (N.Sort, N.TopN, N.Limit, N.Window)
        if isinstance(node, single_ops) or (
                isinstance(node, N.Aggregate)
                and frag.partitioning != "single"):
            # single-partition operators over a PARTITIONED child: the
            # gather happens below the INNERMOST such op (a chain like
            # Limit over Sort gathers once). A child whose output is
            # already single/replicated at runtime (Values, global
            # aggregate, another single op) gets NO spurious exchange.
            child = node.children[0]
            if isinstance(node, single_ops) and isinstance(
                    child, single_ops):
                visit(child, frag)
                return
            if not output_partitioned(child):
                visit(child, frag)
                return
            producer = child
            while isinstance(producer, (N.Project, N.Filter,
                                        N.BindScalars)):
                producer = producer.children[0]
            part = ("hash" if isinstance(producer, N.Aggregate)
                    and producer.keys else "source")
            cf = new_fragment(child, part)
            frag.consumes.append((cf.fid, Exchange("gather")))
            visit(child, cf)
            return
        for c in node.children:
            visit(c, frag)

    root = new_fragment(plan, "single")
    visit(plan, root)
    return FragmentPlan(fragments, join_strategy, join_fits, join_rows_ub,
                        catalog=catalog)
