"""Sideways information passing: runtime-join-filter placement.

Reference parity: ``DynamicFilterService`` + the ``dynamicFilter``
assignments ``LocalExecutionPlanner`` threads from join build sides
into probe-side scans [SURVEY §2.1 optimizer row; reference tree
unavailable, paths reconstructed] — the Presto/Velox "dynamic
filtering" design: when a join build side finishes, its key domain
(min/max + a Bloom-style membership sketch) is pushed into the
probe-side table scan so rows that cannot possibly join are dropped
at the scan, before any downstream operator materializes work for
them.

This module holds the PLAN-side half: deciding where a filter may be
placed (pure structural analysis, shared by the executor and EXPLAIN).
The runtime half (device bitmasks, live-mask application, counters)
lives in ``exec/local_planner.py`` + ``exec/joins.py``.

Soundness rules:

- Only INNER equi-joins and non-negated SEMI joins push filters: a
  probe row that cannot match contributes nothing to their output.
  LEFT/FULL outer joins and ANTI joins KEEP unmatched probe rows — a
  filter there would silently drop results.
- Filters attach only to a probe-side key reachable through a pure
  Filter/Project/InputRef chain from a TableScan: renames are followed,
  computed keys are not (the scan column's values would not be the join
  key's values).
- Filtering is semantics-preserving, so it composes with every other
  engine feature (caching fingerprints ignore the toggle; A/B runs
  must be bit-identical).
"""

from __future__ import annotations

from typing import Optional

from presto_tpu.expr import Expr, InputRef
from presto_tpu.plan import nodes as N
from presto_tpu.types import TypeKind

#: key kinds whose join-key normalization (exec/joinkeys.py) is the
#: IDENTITY: the build min/max published at fill is over the same
#: value domain as the probe scan column. VARCHAR is excluded even
#: though shared-dictionary joins pass codes through — whether the
#: normalizer hashes (cross-dictionary dict_bytes) is only decided
#: during execution, and hashed-domain bounds applied to raw codes
#: would prune silently wrong. BYTES always packs/hashes.
_FILTERABLE_KINDS = (TypeKind.INTEGER, TypeKind.BIGINT, TypeKind.DATE,
                     TypeKind.DECIMAL, TypeKind.TIMESTAMP)


def filterable_key_pair(lk: Expr, rk: Expr) -> bool:
    """May a runtime filter derived from build key ``rk`` prune a scan
    column behind probe key ``lk``? Both sides must be numeric kinds
    (identity normalization — see _FILTERABLE_KINDS)."""
    return (lk.dtype.kind in _FILTERABLE_KINDS
            and rk.dtype.kind in _FILTERABLE_KINDS)


def probe_scan_target(node: N.PlanNode, key: Expr
                      ) -> Optional[tuple[N.TableScan, str]]:
    """The (scan node, scan output column) a probe-side join key traces
    back to through Filter/Project chains, or None when the key is
    computed or crosses a multi-source node (the filter would then
    apply to rows that are not the join's probe rows)."""
    if not isinstance(key, InputRef):
        return None
    name = key.name
    while True:
        if isinstance(node, N.TableScan):
            for n, _src in node.columns:
                if n == name:
                    return (node, name)
            return None
        if isinstance(node, N.Filter):
            node = node.child
            continue
        if isinstance(node, N.Project):
            nxt = None
            for n, e in node.exprs:
                if n == name:
                    if isinstance(e, InputRef):
                        nxt = e.name
                    break
            if nxt is None:
                return None
            name = nxt
            node = node.child
            continue
        return None


def filter_edge_for(node: N.PlanNode
                    ) -> Optional[tuple[N.TableScan, str]]:
    """THE runtime-filter eligibility predicate: the (probe scan, scan
    column) a filter derived from this join's build side may prune, or
    None when the join is ineligible (wrong kind, multi-key,
    non-numeric keys, untraceable probe key — module docstring).
    EXPLAIN's ``filter_edges`` and the executor's
    ``_register_join_filter`` both call THIS function, so the rendered
    placement and the registered placement can never drift."""
    eligible = (
        (isinstance(node, N.Join) and node.kind == "inner")
        or (isinstance(node, N.SemiJoin) and not node.negated)
    )
    if not eligible:
        return None
    if len(node.left_keys) != 1 or len(node.right_keys) != 1:
        return None
    if not filterable_key_pair(node.left_keys[0], node.right_keys[0]):
        return None
    return probe_scan_target(node.left, node.left_keys[0])


def filter_edges(plan: N.PlanNode) -> list[tuple[object, N.TableScan, str]]:
    """Every (join node, probe scan, scan column) runtime-filter edge
    in the plan — the structural placement EXPLAIN renders and the
    executor registers (both via ``filter_edge_for``)."""
    out: list[tuple[object, N.TableScan, str]] = []

    def walk(n: N.PlanNode):
        if isinstance(n, (N.Join, N.SemiJoin)):
            tgt = filter_edge_for(n)
            if tgt is not None:
                out.append((n, tgt[0], tgt[1]))
        for c in n.children:
            walk(c)

    walk(plan)
    return out


def planned_join_strategy(node, catalog,
                          join_build_budget: int | None = None,
                          approx_join: bool = False,
                          memo: "dict | None" = None) -> str:
    """The probe strategy the executors will pick for this join, from
    stats alone: grouped (build over budget) > pallas (fused VMEM
    probe) > dense (direct-address table) > unique (sorted probe) >
    expand. Advisory like every stats decision — runtime ineligibility
    (storage dtypes, capacity blocks, domain violations) degrades one
    rung with a ``join.pallas_fallback`` counter, never silently.

    ``approx_join``: mirrors the session property — a non-negated SEMI
    join whose exact fused table cannot fit then plans as
    ``sketch(approx)``, rendering the APPROXIMATE mode distinctly in
    EXPLAIN (the other half of the never-silently-approximate
    contract; QueryInfo.approximate is the runtime half).

    ``memo``: optional per-walk estimate/interval cache
    (plan/bounds) — the estimate snapshot passes one dict over the
    whole plan so its per-join strategy calls stay linear."""
    from presto_tpu.ops import pallas_join
    from presto_tpu.plan.bounds import expr_interval, node_intervals
    from presto_tpu.runtime.memory import (
        device_budget_bytes,
        estimate_node_bytes,
    )

    if join_build_budget is None:
        join_build_budget = device_budget_bytes() // 4
    semi = isinstance(node, N.SemiJoin)
    est = estimate_node_bytes(node.right, catalog, memo)
    if est > join_build_budget and (semi or node.kind != "full"):
        # the planned out-of-core mode (exec/spill.plan_spill):
        # "hybrid" keeps the K hottest build partitions resident,
        # "grouped" streams every bucket — what the executors execute
        from presto_tpu.exec.spill import plan_spill

        return plan_spill(est, join_build_budget).mode
    iv = None
    if len(node.right_keys) == 1:
        iv = expr_interval(node.right_keys[0],
                           node_intervals(node.right, catalog, memo))
    unique = True if semi else node.unique
    if iv is not None and pallas_join.interval_ok(iv[0], iv[1]):
        domain = iv[1] - iv[0] + 1
        outs = () if semi else node.output_right
        if not outs and (semi or (unique and node.kind == "inner")) \
                and pallas_join.exists_words(domain):
            return "pallas"
        if outs and unique and node.kind in ("inner", "left") \
                and pallas_join.payload_rows(domain, len(outs)):
            return "pallas"
    if approx_join and semi and not node.negated:
        # no exact fused table fit above: the executor's _pallas_spec
        # will hand the build a Bloom sketch — approximate, and said so
        return "sketch(approx)"
    if iv is not None and unique and not semi:
        if 0 < iv[1] - iv[0] + 1 <= (1 << 31) - 1:
            return "dense"
    if semi or unique:
        return "dense" if iv is not None else "unique"
    return "expand"
