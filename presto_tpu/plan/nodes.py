"""Logical plan nodes.

Reference parity: ``com.facebook.presto.sql.planner.plan`` (``PlanNode``
hierarchy: TableScanNode, FilterNode, ProjectNode, AggregationNode,
JoinNode, SemiJoinNode, TopNNode, SortNode, LimitNode, ValuesNode ...)
[SURVEY §2.1; reference tree unavailable, paths reconstructed].

Fields are named, typed columns (the reference's Symbols); expressions
are the typed IR from ``presto_tpu.expr``. Scalar subqueries appear as
``ScalarValue`` nodes referenced by name from expressions (executed
before their consumers — the analog of uncorrelated-subquery plans
feeding filters).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from presto_tpu.exec.operators import AggSpec, SortKey
from presto_tpu.expr import Expr
from presto_tpu.types import DataType


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType


class PlanNode:
    @property
    def children(self) -> tuple["PlanNode", ...]:
        return ()

    @property
    def fields(self) -> tuple[Field, ...]:
        raise NotImplementedError

    def field_names(self) -> list[str]:
        return [f.name for f in self.fields]


@dataclass(frozen=True)
class TableScan(PlanNode):
    connector: str
    table: str
    columns: tuple[tuple[str, str], ...]  # (output field name, source column)
    types: tuple[DataType, ...]
    predicate: Optional[Expr] = None  # pushed-down filter

    @property
    def fields(self):
        return tuple(Field(n, t) for (n, _), t in zip(self.columns, self.types))


@dataclass(frozen=True)
class Filter(PlanNode):
    child: PlanNode
    predicate: Expr

    @property
    def children(self):
        return (self.child,)

    @property
    def fields(self):
        return self.child.fields


@dataclass(frozen=True)
class Project(PlanNode):
    child: PlanNode
    exprs: tuple[tuple[str, Expr], ...]  # (output name, expr)

    @property
    def children(self):
        return (self.child,)

    @property
    def fields(self):
        return tuple(Field(n, e.dtype) for n, e in self.exprs)


@dataclass(frozen=True)
class Aggregate(PlanNode):
    child: PlanNode
    keys: tuple[tuple[str, Expr], ...]  # (output name, key expr over child)
    aggs: tuple[AggSpec, ...]
    #: functionally-determined columns carried per group without being
    #: grouped on (their value is any row's value — legal because a
    #: unique key of their table is among ``keys``)
    passengers: tuple[tuple[str, Expr], ...] = ()
    #: alternative output-name sets each unique per output row (always
    #: includes the key names; hidden-PK grouping adds the named-key
    #: bijection set) — consumed by join unique-build detection
    unique_sets: tuple[tuple[str, ...], ...] = ()

    @property
    def children(self):
        return (self.child,)

    @property
    def fields(self):
        return (
            tuple(Field(n, e.dtype) for n, e in self.keys)
            + tuple(Field(n, e.dtype) for n, e in self.passengers)
            + tuple(Field(a.name, a.dtype) for a in self.aggs)
        )


@dataclass(frozen=True)
class Window(PlanNode):
    """Window functions over partitioned, ordered row frames
    (reference: WindowNode -> WindowOperator). ``funcs`` reuses
    AggSpec; kinds additionally include rank/dense_rank/row_number.
    frame: 'range' | 'rows' | 'full' (see sql.ast.WindowSpec)."""

    child: PlanNode
    partition_by: tuple[Expr, ...]
    order_by: tuple[SortKey, ...]
    funcs: tuple[AggSpec, ...]
    frame: str = "range"

    @property
    def children(self):
        return (self.child,)

    @property
    def fields(self):
        return self.child.fields + tuple(
            Field(f.name, f.dtype) for f in self.funcs
        )


@dataclass(frozen=True)
class Join(PlanNode):
    """Equi-join. probe = left child (streamed), build = right child.
    unique: build keys are unique (FK->PK fast path, no expansion)."""

    left: PlanNode
    right: PlanNode
    kind: str  # inner | left | full (right normalizes to left in the analyzer)
    left_keys: tuple[Expr, ...]
    right_keys: tuple[Expr, ...]
    unique: bool
    output_right: tuple[str, ...]  # build-side fields to carry

    @property
    def children(self):
        return (self.left, self.right)

    @property
    def fields(self):
        rmap = {f.name: f for f in self.right.fields}
        return self.left.fields + tuple(rmap[n] for n in self.output_right)


@dataclass(frozen=True)
class SemiJoin(PlanNode):
    """left WHERE left_key [NOT] IN (right keys) — filter-only join."""

    left: PlanNode
    right: PlanNode
    left_keys: tuple[Expr, ...]
    right_keys: tuple[Expr, ...]
    negated: bool = False

    @property
    def children(self):
        return (self.left, self.right)

    @property
    def fields(self):
        return self.left.fields


@dataclass(frozen=True)
class Values(PlanNode):
    """A single literal row with no columns — the FROM-less SELECT's
    source (reference: ValuesNode). Projections over it evaluate the
    select-list constants."""

    @property
    def fields(self):
        return ()


@dataclass(frozen=True)
class Union(PlanNode):
    """UNION ALL: bag concatenation of children producing identical
    field names/types (the analyzer inserts coercing Projects;
    reference: UnionNode + the exchange that merges its sources).
    UNION distinct is planned as a dedup Aggregate above this node."""

    inputs: tuple[PlanNode, ...]

    @property
    def children(self):
        return self.inputs

    @property
    def fields(self):
        return self.inputs[0].fields


@dataclass(frozen=True)
class Sort(PlanNode):
    child: PlanNode
    keys: tuple[SortKey, ...]

    @property
    def children(self):
        return (self.child,)

    @property
    def fields(self):
        return self.child.fields


@dataclass(frozen=True)
class TopN(PlanNode):
    child: PlanNode
    keys: tuple[SortKey, ...]
    count: int

    @property
    def children(self):
        return (self.child,)

    @property
    def fields(self):
        return self.child.fields


@dataclass(frozen=True)
class Limit(PlanNode):
    child: PlanNode
    count: int

    @property
    def children(self):
        return (self.child,)

    @property
    def fields(self):
        return self.child.fields


@dataclass(frozen=True)
class ScalarValue(PlanNode):
    """An uncorrelated scalar subquery: child must produce exactly one
    row/column; the value is bound as a runtime literal under ``name``
    (reference: EnforceSingleRowOperator + semi-join-less subquery
    plans)."""

    child: PlanNode
    name: str
    dtype: DataType

    @property
    def children(self):
        return (self.child,)

    @property
    def fields(self):
        return (Field(self.name, self.dtype),)


@dataclass(frozen=True)
class BindScalars(PlanNode):
    """Execute the scalar subplans first, bind their values into the
    child's ``Unbound`` expression slots."""

    child: PlanNode
    scalars: tuple[ScalarValue, ...]

    @property
    def children(self):
        return (self.child,) + self.scalars

    @property
    def fields(self):
        return self.child.fields


@dataclass(frozen=True)
class Output(PlanNode):
    """Final projection to client column names."""

    child: PlanNode
    names: tuple[str, ...]  # client-visible names
    sources: tuple[str, ...]  # child field names

    @property
    def children(self):
        return (self.child,)

    @property
    def fields(self):
        smap = {f.name: f for f in self.child.fields}
        return tuple(
            Field(n, smap[s].dtype) for n, s in zip(self.names, self.sources)
        )


def scan_physical_types(node: "TableScan", catalog) -> dict:
    """source column -> resolved physical DataType for a scan, via the
    owning connector's stats narrowing (empty when unavailable)."""
    try:
        conn = catalog.connectors.get(node.connector)
    except AttributeError:
        return {}
    if conn is None or not hasattr(conn, "physical_schema"):
        return {}
    try:
        return conn.physical_schema(node.table, [s for _n, s in node.columns])
    except KeyError:
        return {}


def plan_tree_str(node: PlanNode, indent: int = 0, catalog=None,
                  _filters=None, approx_join: bool = False,
                  plan_hints=None, agg_bypass: bool = True,
                  join_build_budget=None, adaptive=None) -> str:
    """EXPLAIN-style rendering (reference: PlanPrinter). With a
    ``catalog``, scan columns render their chosen PHYSICAL storage
    (``l_shipdate:date:int16``), joins render the stats-planned probe
    strategy (``strategy=pallas|dense|unique|expand|grouped``),
    aggregates render the adaptive aggregation strategy
    (``agg_strategy=fused|bypass|partial|single`` — exec/leaf_route.py,
    fed by ``plan_hints``: plan-stats history records for a recurring
    fingerprint, keyed by ``id(plan node)``), and probe-side scans
    render the runtime join filters that will be pushed into them
    (``runtime_filter=[l_orderkey]``) — the sideways information
    passing placement, visible before execution. With ``approx_join``
    (the session property), semi joins that would probe the Bloom
    sketch render ``strategy=sketch(approx)`` — the APPROXIMATE mode
    is never silent in EXPLAIN."""
    if _filters is None and catalog is not None:
        from presto_tpu.plan.joinfilters import filter_edges

        _filters = {}
        for _join, scan, col in filter_edges(node):
            _filters.setdefault(id(scan), []).append(col)
    pad = "  " * indent
    name = type(node).__name__
    detail = ""
    if isinstance(node, TableScan):
        phys = scan_physical_types(node, catalog) if catalog is not None else {}
        cols = [
            f"{c}:{phys[s].physical_str()}" if s in phys and phys[s].is_narrowed
            else c
            for c, s in node.columns
        ]
        rf = (_filters or {}).get(id(node))
        rfs = f" runtime_filter={rf}" if rf else ""
        detail = (f" {node.table}{' [pred]' if node.predicate is not None else ''}"
                  f" -> {cols}{rfs}")
    elif isinstance(node, Aggregate):
        detail = f" keys={[n for n, _ in node.keys]} aggs={[a.name for a in node.aggs]}"
        if catalog is not None:
            try:
                from presto_tpu.exec.leaf_route import agg_strategy_for

                s = agg_strategy_for(node, catalog, hints=plan_hints,
                                     bypass_enabled=agg_bypass)
            except Exception:  # noqa: BLE001 — EXPLAIN renders partial plans
                s = ""
            if s:
                detail += f" agg_strategy={s}"
    elif isinstance(node, (Join,)):
        detail = f" {node.kind}{' unique' if node.unique else ''}"
        detail += _strategy_str(node, catalog, approx_join, join_build_budget)
        # adaptive skew-salting decision (plan/adaptive.py, keyed by
        # id(live node) like plan_hints): the rewritten exchange is
        # never silent in EXPLAIN
        dec = (adaptive or {}).get(id(node), {}).get("salt")
        if dec is not None:
            detail += f" repartition=salted({dec.salt})"
    elif isinstance(node, Window):
        detail = f" funcs={[f.name for f in node.funcs]} frame={node.frame}"
    elif isinstance(node, SemiJoin):
        detail = f"{' anti' if node.negated else ''}"
        detail += _strategy_str(node, catalog, approx_join, join_build_budget)
    elif isinstance(node, (TopN,)):
        detail = f" n={node.count}"
    elif isinstance(node, Limit):
        detail = f" n={node.count}"
    elif isinstance(node, Output):
        detail = f" {list(node.names)}"
    elif isinstance(node, Project):
        detail = f" {[n for n, _ in node.exprs]}"
    out = f"{pad}{name}{detail}\n"
    for c in node.children:
        out += plan_tree_str(c, indent + 1, catalog=catalog,
                             _filters=_filters or {}, approx_join=approx_join,
                             plan_hints=plan_hints, agg_bypass=agg_bypass,
                             join_build_budget=join_build_budget,
                             adaptive=adaptive)
    return out


def _strategy_str(node, catalog, approx_join: bool = False,
                  join_build_budget=None) -> str:
    if catalog is None:
        return ""
    from presto_tpu.plan.joinfilters import planned_join_strategy

    try:
        s = planned_join_strategy(node, catalog,
                                  join_build_budget=join_build_budget,
                                  approx_join=approx_join)
    except Exception:  # noqa: BLE001 — EXPLAIN must render partial plans
        return ""
    out = f" strategy={s}"
    if s in ("hybrid", "grouped"):
        # the planned out-of-core shape, visible BEFORE execution:
        # spill=hybrid(2/8 resident) | spill=grouped(16 buckets)
        try:
            from presto_tpu.exec.spill import plan_spill
            from presto_tpu.runtime.memory import (
                device_budget_bytes,
                estimate_node_bytes,
            )

            budget = (device_budget_bytes() // 4
                      if join_build_budget is None else join_build_budget)
            decision = plan_spill(
                estimate_node_bytes(node.right, catalog), budget)
            out += f" spill={decision.explain()}"
        except Exception:  # noqa: BLE001
            pass
    return out
