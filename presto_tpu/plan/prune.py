"""Column pruning over the logical plan.

Reference parity: ``PruneUnreferencedOutputs`` /
``PruneTableScanColumns`` iterative optimizer rules [SURVEY §2.1;
reference tree unavailable]. Matters doubly here: the TPC-H connector
*generates* data, so pruning skips whole RNG streams, and unscanned
columns never occupy HBM.
"""

from __future__ import annotations

from dataclasses import replace

from presto_tpu.expr import Call, Expr, InputRef
from presto_tpu.plan import nodes as N


def expr_refs(e: Expr, out: set[str]):
    if isinstance(e, InputRef):
        out.add(e.name)
    elif isinstance(e, Call):
        for a in e.args:
            expr_refs(a, out)


def _refs(exprs) -> set[str]:
    out: set[str] = set()
    for e in exprs:
        if e is not None:
            expr_refs(e, out)
    return out


def prune(node: N.PlanNode, needed: set[str] | None = None) -> N.PlanNode:
    """Rewrite the tree so each node produces only what its parent
    consumes. ``needed=None`` means "all fields" (root)."""
    if isinstance(node, N.Output):
        child = prune(node.child, set(node.sources))
        return replace(node, child=child)
    if isinstance(node, N.BindScalars):
        child = prune(node.child, needed)
        scalars = tuple(
            replace(s, child=prune(s.child, None)) for s in node.scalars
        )
        return N.BindScalars(child, scalars)
    if isinstance(node, N.ScalarValue):
        return replace(node, child=prune(node.child, None))
    if isinstance(node, N.Project):
        exprs = node.exprs
        if needed is not None:
            exprs = tuple((n, e) for n, e in exprs if n in needed)
        child = prune(node.child, _refs(e for _, e in exprs))
        return N.Project(child, exprs)
    if isinstance(node, N.Filter):
        want = set(needed) if needed is not None else set(node.field_names())
        want |= _refs([node.predicate])
        return N.Filter(prune(node.child, want), node.predicate)
    if isinstance(node, N.Aggregate):
        keys = node.keys
        pax = node.passengers
        aggs = node.aggs
        if needed is not None:
            pax = tuple((n, e) for n, e in pax if n in needed)
            aggs = tuple(a for a in aggs if a.name in needed)
        want = _refs([e for _, e in keys] + [e for _, e in pax]
                     + [a.input for a in aggs])
        child = prune(node.child, want)
        return N.Aggregate(child, keys, aggs, pax, node.unique_sets)
    if isinstance(node, N.Join):
        want = set(needed) if needed is not None else set(node.field_names())
        left_fields = {f.name for f in node.left.fields}
        right_fields = {f.name for f in node.right.fields}
        out_right = tuple(n for n in node.output_right if n in want)
        lneed = (want & left_fields) | _refs(node.left_keys)
        rneed = set(out_right) | _refs(node.right_keys)
        return N.Join(
            prune(node.left, lneed), prune(node.right, rneed), node.kind,
            node.left_keys, node.right_keys, node.unique, out_right,
        )
    if isinstance(node, N.SemiJoin):
        want = set(needed) if needed is not None else set(node.field_names())
        lneed = want | _refs(node.left_keys)
        rneed = _refs(node.right_keys)
        return N.SemiJoin(
            prune(node.left, lneed), prune(node.right, rneed),
            node.left_keys, node.right_keys, node.negated,
        )
    if isinstance(node, N.Window):
        funcs = node.funcs
        if needed is not None:
            funcs = tuple(f for f in funcs if f.name in needed)
        want = set(needed) if needed is not None else set(node.field_names())
        want -= {f.name for f in node.funcs}
        want |= _refs(node.partition_by)
        want |= _refs([k.expr for k in node.order_by])
        want |= _refs([f.input for f in funcs])
        return replace(node, child=prune(node.child, want), funcs=funcs)
    if isinstance(node, (N.Sort, N.TopN)):
        want = set(needed) if needed is not None else set(node.field_names())
        want |= _refs([k.expr for k in node.keys])
        return replace(node, child=prune(node.child, want))
    if isinstance(node, N.Limit):
        return replace(node, child=prune(node.child, needed))
    if isinstance(node, N.Values):
        return node
    if isinstance(node, N.Union):
        # children share field names; each child is a Project the
        # recursion narrows to the same needed set
        return N.Union(tuple(prune(c, needed) for c in node.inputs))
    if isinstance(node, N.TableScan):
        cols = node.columns
        types = node.types
        if needed is not None:
            want = set(needed) | _refs([node.predicate])
            kept = [(c, t) for c, t in zip(cols, types) if c[0] in want]
            if not kept:  # count(*)-style: keep the narrowest column
                kept = [min(zip(cols, types), key=lambda ct: _width(ct[1]))]
            cols = tuple(c for c, _ in kept)
            types = tuple(t for _, t in kept)
        return replace(node, columns=cols, types=types)
    raise NotImplementedError(f"prune: {type(node).__name__}")


def _width(t) -> int:
    from presto_tpu.types import TypeKind

    if t.kind is TypeKind.BYTES:
        return t.width
    return t.np_dtype.itemsize
