"""Plan-template parameterization: literal slots + prepared statements.

Reference parity: prepared statements (``PREPARE`` / ``EXECUTE ...
USING``) whose plans are cached by *template* [SURVEY §2.1 protocol
row]. On this engine the payoff is larger than a planner-walk skip: a
plan-cache miss is an XLA re-trace + recompile, so two queries that
differ only in a literal (``o_orderkey < 100`` vs ``< 200``) used to
pay trace+compile twice. This pass lifts eligible constants out of the
traced program and into runtime scalar arguments (``expr.Param`` slots
threaded through every jitted step), so ONE compiled executable serves
every literal binding of the same template — the executable cache AND
jax's signature cache both hit across differing constants.

Eligibility (the correctness carve-outs, each counted under
``prepare.slot_ineligible.*``):

- ``leaf_route``: literals inside a fragment the leaf-route matcher
  (exec/leaf_route.py, incl. the Q1 specialization) would lower to the
  fused kernel family stay BAKED — filter bounds and value-grammar
  coefficients are part of the kernel's spec *proofs* (rescaled closed
  intervals, int32-exactness hulls), so a slotted literal would change
  kernel admission per binding. Baked literals keep their value in the
  fingerprint: distinct bindings of such fragments are distinct
  templates, loudly counted.
- ``limit``: LIMIT / TopN counts are plan *shapes* (static output
  capacities), never slots.
- ``string``: VARCHAR/BYTES literals encode against host dictionaries
  (predicate tables, code lookups) at trace time — host work a device
  scalar cannot replace.
- ``null``: typed NULL literals evaluate to an all-invalid column, a
  different pytree shape than a value slot.

Everything else — projection arithmetic, filter bounds outside leaf
fragments, join-key arithmetic, agg inputs, CASE/IN constants —
becomes a typed slot. Results stay bit-identical to ``plan_templates=0``
(the differential suite's contract): only trace/compile work is
shared; the result cache keys on the full binding (template fingerprint
+ slot values), never on the template alone.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Optional, Sequence

from presto_tpu.exec.operators import AggSpec, SortKey
from presto_tpu.expr import Call, Expr, Literal, Param
from presto_tpu.plan import nodes as N
from presto_tpu.types import DataType, TypeKind

#: literal kinds a device scalar can carry (physical representation via
#: DataType.to_physical: scaled ints, day numbers, epoch micros, ...)
_SLOT_KINDS = (TypeKind.INTEGER, TypeKind.BIGINT, TypeKind.DOUBLE,
               TypeKind.DECIMAL, TypeKind.DATE, TypeKind.TIMESTAMP,
               TypeKind.BOOLEAN)


@dataclass(frozen=True)
class ParamSlot:
    """One extracted literal: the slot id, its declared type, and the
    LOGICAL value this query binds (the ``Literal.value`` convention —
    what ``DataType.to_physical`` converts)."""

    slot: int
    dtype: DataType
    value: Any


@dataclass
class PreparedStatement:
    """A prepared plan template: the parameterized plan plus its slot
    layout. ``user_slots`` are the explicit ``?`` placeholders (slot id
    == placeholder ordinal, in lex order); ``auto_slots`` are the
    analyzer-parameterized literals with their statement-text values as
    defaults. ``execute(handle, params)`` binds user values by
    position and reuses the auto defaults."""

    name: str
    sql: str
    plan: N.PlanNode
    user_slots: tuple  # ((slot, DataType), ...) in slot order
    auto_slots: tuple  # (ParamSlot, ...)

    @property
    def n_user(self) -> int:
        return len(self.user_slots)

    def bind(self, args: Sequence[Any]) -> tuple:
        """Full slot-ordered (dtype, logical value) vector for one
        execution: user args by position, auto defaults after."""
        from presto_tpu.runtime.errors import UserError

        if len(args) != self.n_user:
            raise UserError(
                f"prepared statement {self.name!r} takes {self.n_user} "
                f"parameter(s), got {len(args)}"
            )
        out = {}
        for (slot, dt), v in zip(self.user_slots, args):
            out[slot] = (dt, _coerce_value(dt, v))
        for s in self.auto_slots:
            out[s.slot] = (s.dtype, s.value)
        return tuple(out[i] for i in range(len(out)))


def _coerce_value(dt: DataType, v: Any):
    """Validate/coerce one user-supplied parameter value to the slot's
    declared type (logical convention). Loud on mismatch — a silently
    truncated binding would be a wrong-results class."""
    from presto_tpu.runtime.errors import UserError

    try:
        if dt.kind in (TypeKind.INTEGER, TypeKind.BIGINT):
            out = int(v)
            if out != float(v):
                raise ValueError(v)
            return out
        if dt.kind is TypeKind.BOOLEAN:
            return bool(v)
        if dt.kind in (TypeKind.DOUBLE, TypeKind.DECIMAL):
            float(v)  # validates
            return v
        if dt.kind in (TypeKind.DATE, TypeKind.TIMESTAMP):
            dt.to_physical(v)  # validates (str or int forms)
            return v
    except (TypeError, ValueError):
        raise UserError(
            f"cannot bind {v!r} as a {dt} parameter"
        ) from None
    raise UserError(f"unsupported parameter type {dt}")


def device_params(bound: Sequence[tuple]) -> tuple:
    """(dtype, logical value) pairs -> the device-scalar tuple the
    executors thread through every jitted step (0-d arrays in the
    slot's canonical physical dtype — values never enter jit
    signatures, so bindings share one compiled program)."""
    import jax.numpy as jnp

    # Literal.value conventions are exactly what to_physical expects
    # (DATE values are already day numbers; DECIMAL values are floats
    # that scale to ints; the canonical jnp dtype keys the signature)
    return tuple(
        jnp.asarray(dt.to_physical(v), dt.canonical().jnp_dtype)
        for dt, v in bound
    )


def logical_values(bound: Sequence[tuple]) -> tuple:
    """The value half of a binding — what the result cache folds into
    the binding fingerprint (results stay per-binding)."""
    return tuple(v for _dt, v in bound)


def _count(reason: str, n: int = 1) -> None:
    if n <= 0:
        return
    from presto_tpu.runtime.metrics import REGISTRY

    REGISTRY.counter("prepare.slot_ineligible").add(n)
    REGISTRY.counter(f"prepare.slot_ineligible.{reason}").add(n)


class _Parameterizer:
    def __init__(self, catalog, start_slot: int):
        self.catalog = catalog
        self.next_slot = start_slot
        self.slots: list[ParamSlot] = []

    # ---- expressions -----------------------------------------------------
    def expr(self, e: Optional[Expr]) -> Optional[Expr]:
        if e is None or isinstance(e, Param):
            return e
        if isinstance(e, Literal):
            if e.dtype.kind not in _SLOT_KINDS:
                if e.dtype.kind in (TypeKind.VARCHAR, TypeKind.BYTES):
                    _count("string")
                return e
            if e.value is None:
                _count("null")
                return e
            slot = self.next_slot
            self.next_slot += 1
            self.slots.append(ParamSlot(slot, e.dtype, e.value))
            return Param(e.dtype, slot)
        if isinstance(e, Call):
            args = tuple(self.expr(a) for a in e.args)
            if all(a is b for a, b in zip(args, e.args)):
                return e
            return Call(e.dtype, e.fn, args)
        return e  # InputRef / Unbound: no literals below

    def _pairs(self, pairs):
        return tuple((n, self.expr(e)) for n, e in pairs)

    def _sort_keys(self, keys):
        return tuple(
            dataclasses.replace(k, expr=self.expr(k.expr)) for k in keys
        )

    def _agg_specs(self, aggs):
        return tuple(
            dataclasses.replace(a, input=self.expr(a.input))
            if a.input is not None else a
            for a in aggs
        )

    # ---- baked-fragment accounting --------------------------------------
    def _count_baked_literals(self, obj, reason: str) -> None:
        """Count the would-have-been-eligible literals of a subtree
        kept baked (observability: the tentpole's (c) carve-out)."""
        n = _count_eligible_literals(obj)
        _count(reason, n)

    def _leaf_routes(self, node: N.Aggregate) -> bool:
        """Would the leaf-route matcher lower this fragment to the
        fused kernel family? Its literals then feed spec PROOFS
        (rescaled bounds, value-grammar coefficients, membership
        domains) and must keep their values in plan + fingerprint.
        Conservative on any matcher error: keep baked."""
        try:
            from presto_tpu.exec.leaf_route import match_leaf_fragment

            route, _reason = match_leaf_fragment(node, self.catalog)
            return route is not None
        except Exception:  # noqa: BLE001 — advisory; never fail planning
            return True

    # ---- plan walk -------------------------------------------------------
    def node(self, node: N.PlanNode) -> N.PlanNode:
        if isinstance(node, N.Aggregate):
            if self._leaf_routes(node):
                # the WHOLE fragment stays literal-for-literal identical
                # (same object: the executors' matcher must see exactly
                # what this decision saw)
                self._count_baked_literals(node, "leaf_route")
                return node
            return N.Aggregate(
                self.node(node.child), self._pairs(node.keys),
                self._agg_specs(node.aggs), self._pairs(node.passengers),
                node.unique_sets,
            )
        if isinstance(node, N.TableScan):
            if node.predicate is None:
                return node
            return dataclasses.replace(
                node, predicate=self.expr(node.predicate))
        if isinstance(node, N.Filter):
            return N.Filter(self.node(node.child), self.expr(node.predicate))
        if isinstance(node, N.Project):
            return N.Project(self.node(node.child), self._pairs(node.exprs))
        if isinstance(node, N.Join):
            return dataclasses.replace(
                node,
                left=self.node(node.left), right=self.node(node.right),
                left_keys=tuple(self.expr(k) for k in node.left_keys),
                right_keys=tuple(self.expr(k) for k in node.right_keys),
            )
        if isinstance(node, N.SemiJoin):
            return dataclasses.replace(
                node,
                left=self.node(node.left), right=self.node(node.right),
                left_keys=tuple(self.expr(k) for k in node.left_keys),
                right_keys=tuple(self.expr(k) for k in node.right_keys),
            )
        if isinstance(node, N.Window):
            return dataclasses.replace(
                node,
                child=self.node(node.child),
                partition_by=tuple(self.expr(e) for e in node.partition_by),
                order_by=self._sort_keys(node.order_by),
                funcs=self._agg_specs(node.funcs),
            )
        if isinstance(node, (N.Sort,)):
            return N.Sort(self.node(node.child), self._sort_keys(node.keys))
        if isinstance(node, N.TopN):
            _count("limit")  # the count is a static output shape
            return N.TopN(self.node(node.child), self._sort_keys(node.keys),
                          node.count)
        if isinstance(node, N.Limit):
            _count("limit")
            return N.Limit(self.node(node.child), node.count)
        if isinstance(node, N.Union):
            return N.Union(tuple(self.node(c) for c in node.inputs))
        if isinstance(node, N.Output):
            return dataclasses.replace(node, child=self.node(node.child))
        if isinstance(node, N.BindScalars):
            return N.BindScalars(
                self.node(node.child),
                tuple(dataclasses.replace(s, child=self.node(s.child))
                      for s in node.scalars),
            )
        if isinstance(node, N.ScalarValue):
            return dataclasses.replace(node, child=self.node(node.child))
        if isinstance(node, N.Values):
            return node
        # unknown node type: keep baked — correctness over reuse
        return node


def _count_eligible_literals(obj) -> int:
    """Would-be-slot literals in a subtree (eligible kind, non-NULL)."""
    if isinstance(obj, Literal):
        return int(obj.dtype.kind in _SLOT_KINDS and obj.value is not None)
    if isinstance(obj, Call):
        return sum(_count_eligible_literals(a) for a in obj.args)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return sum(
            _count_eligible_literals(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        )
    if isinstance(obj, (tuple, list)):
        return sum(_count_eligible_literals(x) for x in obj)
    return 0


#: aggregate kinds the batched dispatcher's global-aggregation replay
#: covers (GlobalAggregationOperator's exact update/finish math)
_BATCHABLE_AGG_KINDS = frozenset({"sum", "count", "count_star", "min", "max"})


def unbatchable_reason(plan: N.PlanNode, catalog) -> Optional[str]:
    """Why a plan template cannot take the cross-query batched-dispatch
    route (``server/batcher.py``) — or ``None`` when it can.

    The batched dispatcher replays a template once with every queued
    binding's literal slots stacked on a leading axis (one vmapped
    device dispatch computes N results). That is only sound for plans
    whose execution is a PURE function of (scan data, params): exactly
    one table scan feeding a chain of streaming filter/project steps
    into at most one pipeline breaker whose finalize math is traceable
    (global aggregation, sort, top-N). Everything else — joins (their
    capacity-overflow retries and runtime-filter probes branch on
    per-binding values host-side), grouped aggregation (overflow /
    NULL-key flags are host-checked), windows, set ops, subqueries,
    LIMIT (value-dependent host cutoff), volatile system scans, and
    fragments the leaf-route matcher would lower to a fused kernel —
    falls back to PR 9's serialized template slot, counted per reason
    under ``batch.fallback.*``. The reasons are the observability
    contract: a serving workload that never batches should say WHY."""
    breakers = 0

    def walk(node: N.PlanNode) -> Optional[str]:
        nonlocal breakers
        if isinstance(node, N.Output):
            return walk(node.child)
        if isinstance(node, (N.TopN, N.Sort)):
            breakers += 1
            if breakers > 1:
                return "multi_breaker"
            return walk(node.child)
        if isinstance(node, N.Aggregate):
            # the serial executor's global-aggregation condition: no
            # keys, no passengers (a plain global agg's unique_sets is
            # the one empty grouping set, which that path ignores)
            if node.keys or node.passengers:
                return "grouped_agg"
            if any(a.kind not in _BATCHABLE_AGG_KINDS for a in node.aggs):
                return "agg_kind"
            try:
                from presto_tpu.exec.leaf_route import match_leaf_fragment

                route, _ = match_leaf_fragment(node, catalog)
                if route is not None:
                    # the serial path runs the fused kernel; batching
                    # must not silently re-route it through the
                    # generic replay
                    return "leaf_route"
            except Exception:  # noqa: BLE001 — conservative: no batch
                return "leaf_route"
            breakers += 1
            if breakers > 1:
                return "multi_breaker"
            return walk(node.child)
        if isinstance(node, (N.Filter, N.Project)):
            return walk(node.child)
        if isinstance(node, N.TableScan):
            conn = catalog.connectors.get(node.connector)
            if conn is None or getattr(conn, "volatile", False):
                return "volatile"
            return None
        if isinstance(node, (N.Join, N.SemiJoin)):
            return "join"
        if isinstance(node, N.Window):
            return "window"
        if isinstance(node, N.Union):
            return "union"
        if isinstance(node, (N.BindScalars, N.ScalarValue)):
            return "subquery"
        if isinstance(node, N.Limit):
            return "limit"
        if isinstance(node, N.Values):
            return "values"
        return "unsupported"

    try:
        return walk(plan)
    except Exception:  # noqa: BLE001 — advisory gate; never fail a query
        return "unsupported"


def parameterize_plan(plan: N.PlanNode, catalog, start_slot: int = 0):
    """Auto-parameterize a pruned plan: every eligible ``Literal``
    becomes a typed ``Param`` slot (numbered from ``start_slot``, after
    any explicit ``?`` placeholders, in deterministic pre-order — so
    identical templates from different statements assign identical
    slots and fingerprint identically).

    Returns ``(plan, auto_slots)``; ``plan`` is the input object when
    nothing was parameterized. Counts ``prepare.slots_bound`` and the
    per-reason ineligibility counters."""
    p = _Parameterizer(catalog, start_slot)
    out = p.node(plan)
    if p.slots:
        from presto_tpu.runtime.metrics import REGISTRY

        REGISTRY.counter("prepare.slots_bound").add(len(p.slots))
    return out, tuple(p.slots)
