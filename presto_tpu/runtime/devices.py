"""Device telemetry: the accelerator-side half of serving-tier health.

Reference parity: the coordinator's continuously observable workers —
``NodeScheduler`` consumes live per-node memory/CPU state before
placing work [SURVEY §2.1 node-state rows]. Single-controller JAX has
no remote workers to poll, but it does have local devices whose HBM
occupancy and dispatch wall are exactly the signals the hybrid-spill
tier and the admission ladder guess at today. This module makes them
queryable:

- ``sample_devices()`` — one row per ``jax.local_devices()`` entry
  with ``memory_stats()`` bytes-in-use / peak watermark / limit
  (CPU-safe: backends without allocator stats report zeros, rows still
  appear so ``system.device_stats`` is never empty), plus the
  per-device dispatch wall attributed from the fragment-dispatch choke
  point in ``runtime/lifecycle.py``.
- ``DISPATCH_WALL`` — process-wide ledger of time spent inside
  ``run_fragment`` dispatch. Every local device participates in every
  SPMD dispatch under the single-controller model, so the wall is
  attributed evenly across devices at read time (storing one float,
  not a per-dispatch device list).
- ``headroom_bytes()`` — min over devices of ``limit - in_use``; the
  number hybrid-spill residency decisions should be judged against
  (``None`` when no backend reports a limit, e.g. CPU meshes).
- ``gauges()`` — OpenMetrics gauge rows merged into
  ``Session.export_metrics``.
- ``peak_bytes()`` — max device watermark, stamped per query as
  ``QueryInfo.device_peak_bytes`` by the lifecycle.

Sampling cost is one ``memory_stats()`` call per device (a dict read
on TPU, ``None`` on CPU) — cheap enough to run per query; the
watchdog overhead bound in ``tests/test_health.py`` holds it to <5%.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import jax


class _DispatchLedger:
    """Accumulated wall seconds spent in fragment dispatch, plus the
    dispatch count — the per-device attribution divides the total by
    the device count at read time (every local device participates in
    every single-controller dispatch)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total_s = 0.0
        self._dispatches = 0

    def record(self, seconds: float) -> None:
        if seconds < 0:
            return
        with self._lock:
            self._total_s += seconds
            self._dispatches += 1

    def snapshot(self) -> "tuple[float, int]":
        with self._lock:
            return self._total_s, self._dispatches

    def reset(self) -> None:
        with self._lock:
            self._total_s = 0.0
            self._dispatches = 0


DISPATCH_WALL = _DispatchLedger()


def _memory_stats(device) -> dict:
    """``device.memory_stats()`` with every backend quirk absorbed:
    CPU returns ``None``, some backends raise ``NotImplementedError``
    (or anything else mid-teardown) — telemetry degrades to zeros, it
    never degrades a query."""
    try:
        return device.memory_stats() or {}
    except Exception:  # noqa: BLE001 — telemetry must not fail queries
        return {}


def sample_devices() -> "list[dict]":
    """One telemetry row per local device (the ``system.device_stats``
    backing store). Rows appear even when the backend reports no
    allocator stats so the table is populated on CPU meshes too."""
    devs = jax.local_devices()
    total_s, dispatches = DISPATCH_WALL.snapshot()
    per_device_s = total_s / len(devs) if devs else 0.0
    rows = []
    for d in devs:
        ms = _memory_stats(d)
        rows.append({
            "device_id": str(d.id),
            "platform": str(getattr(d, "platform", "unknown")),
            "bytes_in_use": int(ms.get("bytes_in_use", 0)),
            "peak_bytes": int(ms.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(ms.get("bytes_limit", 0)),
            "dispatch_wall_s": per_device_s,
            "dispatches": dispatches,
        })
    return rows


def peak_bytes() -> int:
    """Max device HBM watermark right now — stamped on each finished
    query as ``QueryInfo.device_peak_bytes`` (0 on backends without
    allocator stats)."""
    peak = 0
    for d in jax.local_devices():
        peak = max(peak, int(_memory_stats(d).get("peak_bytes_in_use", 0)))
    return peak


def headroom_bytes() -> Optional[int]:
    """Min over devices of ``bytes_limit - bytes_in_use`` — the real
    HBM headroom the hybrid-spill residency planner should be judged
    against. ``None`` when no device reports a limit (CPU meshes):
    absent telemetry must read as "unknown", not "infinite"."""
    headroom = None
    for d in jax.local_devices():
        ms = _memory_stats(d)
        limit = int(ms.get("bytes_limit", 0))
        if limit <= 0:
            continue
        free = limit - int(ms.get("bytes_in_use", 0))
        headroom = free if headroom is None else min(headroom, free)
    return headroom


def gauges() -> dict:
    """Per-device OpenMetrics gauges (merged into the session's
    ``export_metrics`` gauge set)."""
    out = {}
    for row in sample_devices():
        did = row["device_id"]
        out[f"device.bytes_in_use.{did}"] = row["bytes_in_use"]
        out[f"device.peak_bytes.{did}"] = row["peak_bytes"]
        out[f"device.bytes_limit.{did}"] = row["bytes_limit"]
        out[f"device.dispatch_wall_s.{did}"] = row["dispatch_wall_s"]
    return out


def timed_dispatch(fn):
    """Run ``fn()`` recording its wall into the dispatch ledger —
    the one-liner ``run_fragment`` wraps around every dispatch."""
    t0 = time.perf_counter()
    try:
        return fn()
    finally:
        DISPATCH_WALL.record(time.perf_counter() - t0)
