"""Typed error taxonomy — failure as a first-class state.

Reference parity: ``StandardErrorCode`` + ``PrestoException`` — every
failure carries a typed error code partitioned into USER_ERROR /
INSUFFICIENT_RESOURCES / EXTERNAL / INTERNAL_ERROR classes, and the
coordinator's retry policy keys off the class, not the message
[SURVEY §5.3; reference tree unavailable, paths reconstructed]. The
robust-hybrid-hash-join literature (PAPERS.md) makes the same point at
the operator level: robustness to misestimates has to be designed into
the execution path, which starts with failures the runtime can
*classify*.

Design rules:

- Every engine raise-site uses a taxonomy class (or an existing typed
  refusal like ``NotImplementedError``, which stays: a refusal is a
  permanent "cannot", not a failure state to recover from).
- ``UserError`` subclasses ``ValueError`` and the resource classes
  subclass ``RuntimeError`` so pre-taxonomy callers (and tests)
  catching the stdlib types keep working — migration is additive.
- ``retryable`` is a property of the CLASS (overridable per instance):
  only failures that are plausibly transient (injected faults, device
  loss) are retryable; deterministic failures (bad SQL, a capacity
  that WILL overflow again, an expired deadline) are not — retrying
  them burns the retry budget to reproduce the same failure.
"""

from __future__ import annotations


class PrestoError(Exception):
    """Base of the taxonomy: a typed error code plus a retry class."""

    #: stable machine-readable code (QueryInfo.error_code, events)
    error_code: str = "GENERIC_INTERNAL_ERROR"
    #: whether a retry of the same work could plausibly succeed
    retryable: bool = False

    def __init__(self, message: str, *, retryable: bool | None = None):
        super().__init__(message)
        if retryable is not None:
            self.retryable = retryable


class UserError(PrestoError, ValueError):
    """The query (or its session/config input) is at fault: syntax
    errors, unknown tables/columns/properties, DDL misuse, scalar
    subqueries with more than one row. Never retryable — the same
    statement fails the same way."""

    error_code = "USER_ERROR"
    retryable = False


class ResourceExhausted(PrestoError, RuntimeError):
    """The query needs more of a bounded resource than the engine will
    grant: admission-control rejections, gather-guard refusals,
    capacity-retry exhaustion. Not retryable — the resource demand is
    a property of the query, so a retry hits the same wall (the fix is
    a session property or a smaller query)."""

    error_code = "RESOURCE_EXHAUSTED"
    retryable = False


class DeviceOutOfMemory(ResourceExhausted):
    """A runtime (backend) out-of-memory: XLA raised RESOURCE_EXHAUSTED
    mid-dispatch, i.e. a plan-time estimate was WRONG and the static
    spill decision under-provisioned. Not retryable as-is — replaying
    the same compiled step allocates the same buffers — but
    *recoverable*: the lifecycle layer's adaptive degradation ladder
    (``oom_ladder_max``) re-plans the query with grouped execution /
    more buckets / smaller probe chunks and re-runs it, so a wrong
    estimate degrades throughput instead of correctness."""

    error_code = "DEVICE_OUT_OF_MEMORY"
    retryable = False


class SpillBudgetExceeded(ResourceExhausted):
    """The HOST-side spill store (``exec/grouped.HostSpill``) would
    grow past ``spill_host_budget_bytes``: the out-of-core tier's
    "disk" is host RAM, and silent growth there is the same bug as a
    device OOM one level up. Not retryable and NOT ladder-eligible —
    more buckets do not shrink the total spilled bytes; the fix is a
    bigger host budget or a smaller query."""

    error_code = "SPILL_BUDGET_EXCEEDED"
    retryable = False


class SpillPartitionOverflow(ResourceExhausted):
    """A cold spill partition still exceeds the per-unit byte budget
    after ``MAX_SPILL_RECURSION`` recursive re-partitionings
    (exec/spill.py): the rows share one hash residue at every doubled
    modulus — in practice one key's duplicate run — so further
    splitting cannot help. Loud and typed instead of a silent device
    blowup mid-stream."""

    error_code = "SPILL_PARTITION_OVERFLOW"
    retryable = False


class ExceededTimeLimit(PrestoError, RuntimeError):
    """The per-query wall-clock deadline (``query_max_run_time``)
    expired. Not retryable within the query — a retry starts from zero
    against the same limit."""

    error_code = "EXCEEDED_TIME_LIMIT"
    retryable = False


class ServerOverloaded(ResourceExhausted):
    """The serving tier shed this submission at admission: a queue
    ceiling or the EWMA-cost admission controller decided accepting it
    would push the backlog past what the engine can drain within SLO.
    Retryable — unlike the other resource walls, the demand is a
    property of the MOMENT, not the query: the same statement succeeds
    once the storm passes. Carries ``retry_after_s``, a monotone
    function of queue depth, surfaced as HTTP 429 + ``Retry-After``."""

    error_code = "SERVER_OVERLOADED"
    retryable = True

    def __init__(self, message: str, *, retry_after_s: float = 1.0,
                 retryable: bool | None = None):
        super().__init__(message, retryable=retryable)
        self.retry_after_s = float(retry_after_s)


class QueryCancelled(PrestoError, RuntimeError):
    """The query's ``CancelScope`` was flipped — an operator ``DELETE
    /v1/statement/<id>``, ``Session.cancel(query_id)``, or the overload
    controller — and a cooperative checkpoint observed it. Not
    retryable: cancellation is a decision, not a failure, and a retry
    would resurrect work someone explicitly killed. Reservations are
    released by the same ``finally`` paths as any other typed failure,
    so a cancel drains the pool within one checkpoint."""

    error_code = "QUERY_CANCELLED"
    retryable = False


class TransientFailure(PrestoError, RuntimeError):
    """A plausibly-transient fault: an injected fault, a lost device,
    a flaky interconnect step. Retryable — the fragment retry loop and
    the distributed->local degradation path both key off this class."""

    error_code = "TRANSIENT_FAILURE"
    retryable = True


class InternalError(PrestoError, RuntimeError):
    """An engine invariant broke (not the user's fault, not a resource
    wall). Not retryable by default: a broken invariant usually
    reproduces."""

    error_code = "GENERIC_INTERNAL_ERROR"
    retryable = False


def is_retryable(exc: BaseException) -> bool:
    """Retry class of ANY exception: taxonomy errors carry their own
    flag; foreign exceptions are conservatively non-retryable (query-
    level ``query_retries`` still re-runs them — that knob predates
    the taxonomy and deliberately retries everything)."""
    return bool(getattr(exc, "retryable", False))


def is_backend_oom(exc: BaseException) -> bool:
    """Does ``exc`` look like a backend out-of-memory? Matches the
    shapes the runtime actually throws — ``XlaRuntimeError`` carrying a
    RESOURCE_EXHAUSTED status, allocator "out of memory" messages, and
    stdlib ``MemoryError`` — plus the injector's backend-shaped
    ``BackendOom`` (runtime/faults.py), which exists so the recovery
    ladder is testable on CPU. Taxonomy errors are never re-classified:
    a ``ResourceExhausted`` admission rejection mentioning bytes must
    not morph into a recoverable device OOM."""
    if isinstance(exc, PrestoError):
        return False
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower()


def error_code(exc: BaseException) -> str:
    """Stable code for ANY exception (foreign ones are classified by
    their stdlib ancestry, the pre-taxonomy raise-sites' contract)."""
    code = getattr(exc, "error_code", None)
    if code is not None:
        return code
    if isinstance(exc, NotImplementedError):
        return "NOT_SUPPORTED"
    if isinstance(exc, ValueError):
        return "USER_ERROR"
    if isinstance(exc, (TimeoutError,)):
        return "EXCEEDED_TIME_LIMIT"
    if isinstance(exc, MemoryError):
        return "RESOURCE_EXHAUSTED"
    return "GENERIC_INTERNAL_ERROR"
