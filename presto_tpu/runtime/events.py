"""Query event pipeline.

Reference parity: ``QueryMonitor`` building ``QueryCreatedEvent`` /
``QueryCompletedEvent`` and fanning out to registered ``EventListener``
plugins — the SPI hook for audit logs, history stores, lineage
[SURVEY §5.5; reference tree unavailable]. Listeners receive the same
``QueryInfo`` the tracker stores; listener failures never fail the
query (logged and swallowed, as the reference does).
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Protocol

from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.stats import QueryInfo

log = logging.getLogger("presto_tpu.events")


class EventListener(Protocol):
    """Listeners implement any subset of these (missing methods are
    skipped); all receive the tracker's live QueryInfo."""

    def query_created(self, info: QueryInfo) -> None: ...

    def query_completed(self, info: QueryInfo) -> None: ...

    def query_failed(self, info: QueryInfo) -> None: ...

    def query_cached(self, info: QueryInfo) -> None: ...

    def fragment_retried(self, info: QueryInfo) -> None: ...

    def query_degraded(self, info: QueryInfo) -> None: ...


class EventDispatcher:
    def __init__(self, listeners=()):
        self.listeners = list(listeners)

    def add(self, listener: EventListener):
        self.listeners.append(listener)

    def _fire(self, method: str, info: QueryInfo):
        for l in self.listeners:
            fn = getattr(l, method, None)
            if fn is None:
                continue
            try:
                fn(info)
            except Exception:  # listener bugs never fail queries
                REGISTRY.counter("events.listener_errors").add()
                log.exception("event listener %r failed in %s", l, method)

    def query_created(self, info: QueryInfo):
        self._fire("query_created", info)

    def query_completed(self, info: QueryInfo):
        self._fire("query_completed", info)

    def query_failed(self, info: QueryInfo):
        """Fired on the FAILED transition, before query_completed
        (which fires for every terminal state, like the reference's
        QueryCompletedEvent carrying the failure info)."""
        self._fire("query_failed", info)

    def query_cached(self, info: QueryInfo):
        """Fired when a query is answered from the result cache
        (``info.cache_hit`` is already True); query_completed still
        follows, like every terminal state."""
        self._fire("query_cached", info)

    def fragment_retried(self, info: QueryInfo):
        """Fired on each fragment retry; ``info.fragment_retries`` has
        already been incremented when listeners see it."""
        self._fire("fragment_retried", info)

    def query_degraded(self, info: QueryInfo):
        """Fired each time the OOM recovery ladder steps a rung down
        (``info.oom_retries`` already reflects the new rung) — the
        runtime-OOM analog of fragment_retried."""
        self._fire("query_degraded", info)


class QueryHistoryBuffer:
    """Ring buffer of recently completed QueryInfos — the built-in
    EventListener feeding the ``system.query_history`` table
    (reference: an EventListener plugin persisting QueryCompletedEvents
    as queryable history). ``query_completed`` fires for every terminal
    state, so FAILED and cache-hit queries appear too."""

    def __init__(self, maxlen: int = 256):
        self._ring: deque[QueryInfo] = deque(maxlen=maxlen)

    def resize(self, maxlen: int) -> None:
        """Apply a changed ``query_history_limit`` (deque maxlen is
        immutable, so rebuild keeping the newest entries)."""
        if maxlen != self._ring.maxlen:
            self._ring = deque(self._ring, maxlen=maxlen)

    def query_completed(self, info: QueryInfo) -> None:
        self._ring.append(info)

    def infos(self) -> "list[QueryInfo]":
        return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)
