"""Deterministic fault injection — the testable half of robustness.

Reference parity: the reference proves its failure handling with an
in-process ``DistributedQueryRunner`` plus induced task failures; a
single-controller engine has no separate worker process to kill, so
faults inject at the host-side *hook points* instead: connector scans,
exchange steps, and aggregation steps call ``fault_point(site)`` right
before dispatching device work, and an installed :class:`FaultInjector`
decides — deterministically — whether that call raises.

Determinism rules (tests must replay exactly):

- ``times=N`` faults fire on the first N matching calls, then go
  silent — the shape retry tests need ("fail twice, then succeed").
- ``probability=p`` faults draw from the injector's OWN seeded
  ``random.Random`` stream, in call order; same seed + same call
  sequence = same fault sequence.
- Sites are dot-separated names (``"exchange.join"``); a spec for a
  prefix (``"exchange"``) matches every descendant site.

Hook points are no-ops (one module attribute read) when no injector is
installed, so production paths pay nothing.
"""

from __future__ import annotations

import random
from contextlib import contextmanager
from dataclasses import dataclass, field

from presto_tpu.runtime.errors import TransientFailure

#: canonical hook-point sites (descendants are fair game too)
SITES = (
    "scan",  # connector scan loops (both execution tiers)
    "exchange.aggregate",  # partial->all_to_all->final agg step
    "exchange.join",  # repartition-join all_to_all step
    "exchange.gather",  # replicate/broadcast all_gather
    "exchange.window",  # partitioned-window shuffle
    "exchange.sort",  # range-partition sort shuffle
    "aggregation",  # aggregation dispatch (local + distributed)
)


@dataclass
class FaultSpec:
    """One armed fault: where it fires, what it raises, how often."""

    site: str
    error: type = TransientFailure
    #: fire on the first N matching calls (None = every matching call)
    times: int | None = 1
    probability: float = 1.0
    message: str = ""
    fired: int = 0

    def matches(self, site: str) -> bool:
        return site == self.site or site.startswith(self.site + ".")


@dataclass
class FaultInjector:
    """Seedable registry of armed faults (install via :func:`injected`
    or :func:`install`)."""

    seed: int = 0
    specs: list[FaultSpec] = field(default_factory=list)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def inject(
        self,
        site: str,
        error: type = TransientFailure,
        times: int | None = 1,
        probability: float = 1.0,
        message: str = "",
    ) -> FaultSpec:
        """Arm a fault at ``site`` (or any descendant ``site.*``)."""
        spec = FaultSpec(site, error, times, probability, message)
        self.specs.append(spec)
        return spec

    def fired(self, site: str | None = None) -> int:
        """Total fires, optionally restricted to one armed site."""
        return sum(
            s.fired for s in self.specs if site is None or s.site == site
        )

    def check(self, site: str) -> None:
        """Raise the first armed fault matching ``site`` (hook-point
        body; engine code calls :func:`fault_point` instead)."""
        for spec in self.specs:
            if not spec.matches(site):
                continue
            if spec.times is not None and spec.fired >= spec.times:
                continue
            if spec.probability < 1.0 and (
                self._rng.random() >= spec.probability
            ):
                continue
            spec.fired += 1
            msg = spec.message or (
                f"injected fault at {site!r} (fire #{spec.fired})"
            )
            raise spec.error(msg)


#: the installed injector; None (the default) makes every hook a no-op
_ACTIVE: FaultInjector | None = None


def install(injector: FaultInjector | None) -> None:
    """Install (or, with None, clear) the process-wide injector."""
    global _ACTIVE
    _ACTIVE = injector


def active() -> FaultInjector | None:
    return _ACTIVE


@contextmanager
def injected(injector: FaultInjector):
    """Scoped install — the test-suite idiom."""
    prev = _ACTIVE
    install(injector)
    try:
        yield injector
    finally:
        install(prev)


def fault_point(site: str) -> None:
    """Engine hook point: raises iff an installed injector says so."""
    if _ACTIVE is not None:
        _ACTIVE.check(site)
