"""Deterministic fault injection — the testable half of robustness.

Reference parity: the reference proves its failure handling with an
in-process ``DistributedQueryRunner`` plus induced task failures; a
single-controller engine has no separate worker process to kill, so
faults inject at the host-side *hook points* instead: connector scans,
exchange steps, and aggregation steps call ``fault_point(site)`` right
before dispatching device work, and an installed :class:`FaultInjector`
decides — deterministically — whether that call raises.

Determinism rules (tests must replay exactly):

- ``times=N`` faults fire on the first N matching calls, then go
  silent — the shape retry tests need ("fail twice, then succeed").
- ``probability=p`` faults draw from the injector's OWN seeded
  ``random.Random`` stream, in call order; same seed + same call
  sequence = same fault sequence.
- Sites are dot-separated names (``"exchange.join"``); a spec for a
  prefix (``"exchange"``) matches every descendant site.

Hook points are no-ops (one module attribute read) when no injector is
installed, so production paths pay nothing.
"""

from __future__ import annotations

import random
from contextlib import contextmanager
from dataclasses import dataclass, field

from presto_tpu.runtime.errors import TransientFailure

#: canonical hook-point sites (descendants are fair game too)
SITES = (
    "scan",  # connector scan loops (both execution tiers)
    "exchange.aggregate",  # partial->all_to_all->final agg step
    "exchange.join",  # repartition-join all_to_all step
    "exchange.gather",  # replicate/broadcast all_gather
    "exchange.window",  # partitioned-window shuffle
    "exchange.sort",  # range-partition sort shuffle
    "aggregation",  # aggregation dispatch (local + distributed)
    "step.join_build",  # in-memory join build materialization/dispatch
    "step.grouped_join",  # grouped (bucketed) join bucket passes
    "step.agg",  # grouped-aggregation jitted-step dispatch
    "step.spill_transfer",  # host->device cold-partition transfer submits
    "step.spill_partition",  # recursive re-partition of an oversized bucket
    "step.cancel_checkpoint",  # cooperative cancel/deadline checkpoints
)


class BackendOom(RuntimeError):
    """Backend-SHAPED out-of-memory for the ``oom`` fault kind: NOT a
    taxonomy error — it mimics what ``jaxlib``'s ``XlaRuntimeError``
    raises at a jitted-step dispatch when HBM runs out, so the mapping
    layer (``runtime/errors.is_backend_oom`` at the fragment boundary)
    and the degradation ladder above it are exercised end-to-end on
    CPU, where a real allocator OOM is impractical to stage."""

    def __init__(self, message: str = ""):
        super().__init__(
            "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            "device buffer" + (f" ({message})" if message else " (injected)")
        )


@dataclass
class FaultSpec:
    """One armed fault: where it fires, what it raises, how often."""

    site: str
    error: type = TransientFailure
    #: fire on the first N matching calls (None = every matching call);
    #: with ``per_site`` the bound applies to each CONCRETE site a
    #: prefix spec matches, not to the spec as a whole
    times: int | None = 1
    probability: float = 1.0
    message: str = ""
    per_site: bool = False
    fired: int = 0
    fired_by_site: dict = field(default_factory=dict)

    def matches(self, site: str) -> bool:
        return site == self.site or site.startswith(self.site + ".")

    def exhausted(self, site: str) -> bool:
        if self.times is None:
            return False
        if self.per_site:
            return self.fired_by_site.get(site, 0) >= self.times
        return self.fired >= self.times

    def record_fire(self, site: str) -> None:
        self.fired += 1
        self.fired_by_site[site] = self.fired_by_site.get(site, 0) + 1


@dataclass
class FaultInjector:
    """Seedable registry of armed faults (install via :func:`injected`
    or :func:`install`)."""

    seed: int = 0
    specs: list[FaultSpec] = field(default_factory=list)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def inject(
        self,
        site: str,
        error: type = TransientFailure,
        times: int | None = 1,
        probability: float = 1.0,
        message: str = "",
        per_site: bool = False,
    ) -> FaultSpec:
        """Arm a fault at ``site`` (or any descendant ``site.*``)."""
        spec = FaultSpec(site, error, times, probability, message, per_site)
        self.specs.append(spec)
        return spec

    def inject_oom(
        self,
        site: str = "step",
        times: int | None = 1,
        probability: float = 1.0,
        per_site: bool = True,
    ) -> FaultSpec:
        """The ``oom`` fault kind: a backend-shaped RESOURCE_EXHAUSTED
        (:class:`BackendOom`) at jitted-step dispatch sites, with
        deterministic PER-SITE fire counts by default — "the in-memory
        build OOMs twice, the grouped pass succeeds" is expressible as
        one spec. The fragment boundary maps the raise into the typed
        ``DeviceOutOfMemory``, which drives the degradation ladder."""
        return self.inject(site, error=BackendOom, times=times,
                           probability=probability, per_site=per_site)

    def fired(self, site: str | None = None) -> int:
        """Total fires, optionally restricted to one armed site."""
        return sum(
            s.fired for s in self.specs if site is None or s.site == site
        )

    def fired_at(self, site: str) -> int:
        """Fires recorded at one CONCRETE site, across every spec
        (prefix specs included)."""
        return sum(s.fired_by_site.get(site, 0) for s in self.specs)

    def check(self, site: str) -> None:
        """Raise the first armed fault matching ``site`` (hook-point
        body; engine code calls :func:`fault_point` instead)."""
        for spec in self.specs:
            if not spec.matches(site):
                continue
            if spec.exhausted(site):
                continue
            if spec.probability < 1.0 and (
                self._rng.random() >= spec.probability
            ):
                continue
            spec.record_fire(site)
            msg = spec.message or (
                f"injected fault at {site!r} (fire #{spec.fired})"
            )
            raise spec.error(msg)


#: the installed injector; None (the default) makes every hook a no-op
_ACTIVE: FaultInjector | None = None


def install(injector: FaultInjector | None) -> None:
    """Install (or, with None, clear) the process-wide injector."""
    global _ACTIVE
    _ACTIVE = injector


def active() -> FaultInjector | None:
    return _ACTIVE


@contextmanager
def injected(injector: FaultInjector):
    """Scoped install — the test-suite idiom."""
    prev = _ACTIVE
    install(injector)
    try:
        yield injector
    finally:
        install(prev)


def fault_point(site: str) -> None:
    """Engine hook point: raises iff an installed injector says so."""
    if _ACTIVE is not None:
        _ACTIVE.check(site)
