"""Engine flight recorder: always-on failure post-mortems.

Reference parity: the coordinator's failed-query forensics — the full
``QueryInfo`` JSON of a failed query (error, stats, stages) retained
and served after the fact, plus the EventListener history stores built
on it [SURVEY §5.5; reference tree unavailable]. The adaptive layers
grown since PR 4 (OOM ladder, strategy picks, templates, coalescing)
raised the stakes: when a run degrades, skews, or dies, the evidence
used to evaporate — traces are per-query and ring-evicted, counters
are process-global, and the rung/retry history lived only in the
exception message.

A :class:`FlightRecord` is one query's complete post-mortem, captured
at ``run_plan``'s choke point (``runtime/lifecycle.py``) the moment a
query FAILS, DEGRADES (OOM rung > 0 or distributed->local), RETRIES a
fragment, or blows its deadline — and, on demand via the
``flight_record_successes`` session property, on success too. Captured
state:

- the plan snapshot rendered WITH the hints the run actually used
  (EXPLAIN-with-hints: strategy picks, history-driven bypass) — what
  the planner decided, not what a re-plan would decide now;
- the query's span trace (the live ``TraceRecorder``, flattened);
- the per-query metric delta (every counter this query moved —
  ``runtime/metrics.QueryMetricsDelta``, cross-query-bleed-free);
- the OOM rung history and fragment retry/deadline events;
- the exchange-skew summary + hot-partition ids of the last run;
- the memory pool's state at terminal time.

Capture is best-effort and side-effect-free: it deep-copies host
state, never touches the device, never takes a pool reservation, and a
capture failure counts ``flight.capture_errors`` instead of failing
the query. The per-session ring is bounded
(``flight_recorder_limit``); records are queryable as
``system.flight_recorder``, exportable as JSON via
``Session.export_flight_record`` and ``python -m presto_tpu
flightrec``.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from presto_tpu.runtime.metrics import REGISTRY

#: default ring bound (records hold span lists — heavier than
#: QueryInfo, lighter than a TraceRecorder; sized like the trace ring)
DEFAULT_LIMIT = 64


def _json_safe(v):
    """Span args / summaries may carry numpy or device scalars; the
    export contract is plain JSON, so coerce loudly-typed values and
    repr() anything exotic rather than fail the dump."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    try:
        import numpy as np

        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, np.bool_):
            return bool(v)
    except Exception:  # pragma: no cover - numpy always present here
        pass
    return repr(v)


@dataclass
class FlightRecord:
    """One query's post-mortem (see module docstring)."""

    query_id: str
    sql: str
    #: terminal state at capture ("FAILED" | "FINISHED")
    state: str
    #: why this record exists: subset of
    #: {"failed", "degraded", "retried", "deadline", "requested"}
    triggers: tuple
    captured_at: float
    error: Optional[str] = None
    error_code: Optional[str] = None
    retryable: Optional[bool] = None
    #: final OOM-ladder rung + the per-rung error history
    oom_rung: int = 0
    rung_history: list = field(default_factory=list)
    #: fragment retry events ({"site", "error"}) in occurrence order
    retry_events: list = field(default_factory=list)
    fragment_retries: int = 0
    degraded_to_local: bool = False
    deadline_s: Optional[float] = None
    execution_s: float = 0.0
    #: EXPLAIN-with-hints render of the executed plan
    plan_render: str = ""
    #: flattened span trace (start_s relative to the first span)
    spans: list = field(default_factory=list)
    dropped_spans: int = 0
    #: the query's attributed metric delta (QueryInfo.metrics)
    metrics: dict = field(default_factory=dict)
    #: exchange-skew summaries + hot partition ids of the LAST run
    exchange_skew: list = field(default_factory=list)
    hot_partitions: list = field(default_factory=list)
    #: executed out-of-core spill decisions of the LAST run (mode,
    #: partitions, resident/streamed counts, host bytes — ladder.py's
    #: ``_note_spill`` summaries)
    spill: list = field(default_factory=list)
    #: applied adaptive-execution decisions of the LAST run (salt /
    #: join_flip / bucket / route — ladder.py's ``_note_adaptive``
    #: events): a post-mortem of a history-steered plan must show what
    #: adaptivity changed
    adaptive: list = field(default_factory=list)
    #: memory pool state at terminal time (reservation released —
    #: recording a post-mortem never holds pool capacity)
    pool: dict = field(default_factory=dict)
    #: whether tracing was on for this query — distinguishes "traced
    #: nothing" (enabled, zero spans) from "tracing off" (empty spans
    #: carry no signal)
    trace_enabled: bool = False

    def to_dict(self) -> dict:
        return {
            "queryId": self.query_id,
            "sql": self.sql,
            "state": self.state,
            "triggers": list(self.triggers),
            "capturedAt": self.captured_at,
            "error": self.error,
            "errorCode": self.error_code,
            "retryable": self.retryable,
            "oomRung": self.oom_rung,
            "rungHistory": _json_safe(self.rung_history),
            "retryEvents": _json_safe(self.retry_events),
            "fragmentRetries": self.fragment_retries,
            "degradedToLocal": self.degraded_to_local,
            "deadlineS": self.deadline_s,
            "executionS": round(self.execution_s, 6),
            "planRender": self.plan_render,
            "spans": _json_safe(self.spans),
            "droppedSpans": self.dropped_spans,
            "metrics": _json_safe(
                {k: self.metrics[k] for k in sorted(self.metrics)}),
            "exchangeSkew": _json_safe(self.exchange_skew),
            "hotPartitions": _json_safe(self.hot_partitions),
            "spill": _json_safe(self.spill),
            "adaptive": _json_safe(self.adaptive),
            "pool": _json_safe(self.pool),
            "traceEnabled": self.trace_enabled,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


def _flatten_spans(tracer) -> "tuple[list, int]":
    """TraceRecorder -> JSON-ready span dicts. The record must own its
    copy (live Span.args stay mutable until export), so every args
    dict is coerced+copied here."""
    if tracer is None:
        return [], 0
    out = [
        {**d, "args": _json_safe(d["args"])}
        for d in tracer.to_span_dicts()
    ]
    return out, tracer.dropped


class FlightRecorder:
    """Bounded per-session ring of :class:`FlightRecord` post-mortems.

    Thread-safe: concurrent queries on one session capture from their
    own driver threads. Capture allocates host memory only — the ring
    bound (``flight_recorder_limit``) is the retention contract."""

    def __init__(self, limit: int = DEFAULT_LIMIT):
        self._ring: "deque[FlightRecord]" = deque(maxlen=limit)
        self._lock = threading.Lock()

    def resize(self, limit: int) -> None:
        """Apply a changed ``flight_recorder_limit`` immediately (the
        query_history_limit take-effect rule): oldest records drop NOW."""
        with self._lock:
            self._ring = deque(self._ring, maxlen=limit)

    # ---- capture ---------------------------------------------------------
    def capture(self, info, plan, session, executor=None,
                err=None, triggers=("requested",),
                tracer=None) -> FlightRecord:
        """Build and retain one post-mortem. Called from run_plan's
        finally (runtime/lifecycle.py) with the metric delta already
        attributed onto ``info``; ``err`` is the in-flight exception on
        the failure path (info.error is stamped later, upstream).
        ``tracer`` overrides the context-local recorder — the health
        watchdog captures a query from OUTSIDE its driver thread, where
        ``trace.current()`` would read the watchdog's (empty) context."""
        from presto_tpu.runtime import trace
        from presto_tpu.runtime.errors import error_code as _code
        from presto_tpu.runtime.errors import is_retryable

        render = ""
        try:
            from presto_tpu.plan.nodes import plan_tree_str

            render = plan_tree_str(
                plan, catalog=session.catalog,
                approx_join=bool(session.prop("approx_join")),
                plan_hints=getattr(executor, "plan_hints", None) or None,
                agg_bypass=bool(getattr(executor, "agg_bypass", True)),
                join_build_budget=getattr(executor, "join_build_budget",
                                          None),
            )
        except Exception:  # noqa: BLE001 — a render bug must not eat
            render = "<plan render failed>"  # the rest of the record
        if tracer is None:
            tracer = trace.current()
        spans, dropped = _flatten_spans(tracer)
        pool = {}
        try:
            p = session.pool()
            pool = dict(p.snapshot())
            pool["pool"] = p.name
        except Exception:  # noqa: BLE001
            pool = {}
        rec = FlightRecord(
            query_id=info.query_id,
            sql=info.sql,
            state="FAILED" if err is not None else "FINISHED",
            triggers=tuple(triggers),
            captured_at=time.time(),
            error=None if err is None else f"{type(err).__name__}: {err}",
            error_code=None if err is None else _code(err),
            # from the in-flight exception, NOT info.retryable: capture
            # runs during unwinding, before the session's except stamps
            # the info (error/error_code take the same route)
            retryable=None if err is None else bool(is_retryable(err)),
            oom_rung=int(info.oom_retries),
            rung_history=list(info.rung_history),
            retry_events=list(info.retry_events),
            fragment_retries=int(info.fragment_retries),
            degraded_to_local=bool(info.degraded),
            deadline_s=session.prop("query_max_run_time"),
            execution_s=info.execution_s,
            plan_render=render,
            spans=spans,
            dropped_spans=dropped,
            metrics=dict(info.metrics),
            exchange_skew=list(
                getattr(executor, "exchange_skew", ()) or ()),
            hot_partitions=list(
                getattr(executor, "hot_partitions", ()) or ()),
            spill=list(getattr(executor, "spill_events", ()) or ()),
            adaptive=list(getattr(executor, "adaptive_events", ()) or ()),
            pool=pool,
            trace_enabled=tracer is not None,
        )
        with self._lock:
            self._ring.append(rec)
        REGISTRY.counter("flight.captured").add()
        for t in rec.triggers:
            REGISTRY.counter(f"flight.trigger.{t}").add()
        return rec

    # ---- read ------------------------------------------------------------
    def records(self) -> "list[FlightRecord]":
        with self._lock:
            return list(self._ring)

    def for_query(self, query_id: str) -> Optional[FlightRecord]:
        with self._lock:
            for rec in reversed(self._ring):
                if rec.query_id == query_id:
                    return rec
        return None

    def latest(self) -> Optional[FlightRecord]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def to_json(self, query_id: Optional[str] = None) -> str:
        """JSON export: one record (by query id) or the whole ring,
        newest last — the ``Session.export_flight_record`` /
        ``python -m presto_tpu flightrec`` payload."""
        if query_id is not None:
            rec = self.for_query(query_id)
            if rec is None:
                from presto_tpu.runtime.errors import UserError

                raise UserError(
                    f"no flight record for query {query_id!r} "
                    "(nothing captured, or evicted from the ring)"
                )
            return rec.to_json()
        return json.dumps([r.to_dict() for r in self.records()])

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
