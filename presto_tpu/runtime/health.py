"""Serving-tier health: tenant SLOs and the anomaly watchdog.

Reference parity: the coordinator's cluster-health surface — resource
group SLAs plus the "why is p99 up" dashboards operators build over
``system.runtime`` [SURVEY §2.1 resource-group rows]. PRs 3/7/10 made
individual queries deeply observable; PRs 14/17 built a service
(tenants, batched dispatch, subscriptions) that is still blind
*between* queries: a latency regression that stays green never leaves
a post-mortem. Two pieces close that gap:

- ``SloTracker`` — per-tenant latency/freshness objectives with
  rolling-window burn rates. Objectives come from session properties
  (``slo_latency_objective_s`` / ``slo_freshness_objective_s``) with
  per-tenant overrides on ``TenantSpec``; outcomes are recorded by the
  session lifecycle (latency) and the subscription manager (refresh
  freshness). Queryable as ``system.slo``; counters ``slo.good`` /
  ``slo.breach`` (also per tenant/kind suffixed).
- ``HealthMonitor`` — a background watchdog sampling qps, p50/p99,
  admission-queue depth, pool occupancy, cache hit rate, subscription
  freshness lag, and SLO burn into a bounded ring (``system.health``),
  and comparing each sample against a trailing baseline. A breach
  (p99 regression factor, queue growth, SLO burn, stale-lag ceiling)
  fires a ``health_breach`` event AND a flight-recorder capture of the
  worst in-flight query — extending the PR 10 capture triggers so
  slow-but-green incidents leave a post-mortem too. A latch + cooldown
  makes one sustained incident one breach, not one per sample.

Every monitor registers in a module-level weak set so the test
harness can assert no watchdog thread outlives its test (the PT401/
PT402 global-state discipline, applied to threads).
"""

from __future__ import annotations

import re
import threading
import time
import weakref
from collections import deque
from typing import Callable, Optional

from presto_tpu.runtime.metrics import REGISTRY

_NAME_RE = re.compile(r"[^A-Za-z0-9_]")

#: reasons a sample can breach, in report-priority order
BREACH_REASONS = ("p99", "queue", "burn", "stale")


def _metric_name(name: str) -> str:
    return _NAME_RE.sub("_", name) or "_"


def _pctl(values: "list[float]", q: float) -> float:
    """Nearest-rank percentile of an unsorted list (0.0 when empty)."""
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


# ---------------------------------------------------------------------------
# tenant SLOs
# ---------------------------------------------------------------------------

class _SloState:
    __slots__ = ("latency_objective_s", "freshness_objective_s",
                 "latency_window", "freshness_window",
                 "latency_good", "latency_breach",
                 "freshness_good", "freshness_breach")

    def __init__(self, latency_objective_s, freshness_objective_s, window):
        self.latency_objective_s = latency_objective_s
        self.freshness_objective_s = freshness_objective_s
        self.latency_window = deque(maxlen=window)
        self.freshness_window = deque(maxlen=window)
        self.latency_good = 0
        self.latency_breach = 0
        self.freshness_good = 0
        self.freshness_breach = 0


class SloTracker:
    """Per-tenant service objectives with rolling burn rates.

    ``burn rate`` is the breach fraction over the rolling window
    (0.0 = every observation met its objective, 1.0 = none did) —
    the multiplier an error-budget alert would page on.
    """

    def __init__(self, latency_objective_s: float = 1.0,
                 freshness_objective_s: float = 10.0,
                 window: int = 256,
                 overrides: "Optional[dict]" = None):
        self._lock = threading.Lock()
        self.latency_objective_s = float(latency_objective_s)
        self.freshness_objective_s = float(freshness_objective_s)
        self.window = max(1, int(window))
        #: tenant -> (latency_objective_s | None, freshness_objective_s
        #: | None); None falls through to the tracker-wide default
        self._overrides = dict(overrides or {})
        self._tenants: "dict[str, _SloState]" = {}

    def _state_locked(self, tenant: str) -> _SloState:
        st = self._tenants.get(tenant)
        if st is None:
            lat, fresh = self._overrides.get(tenant, (None, None))
            st = self._tenants[tenant] = _SloState(
                self.latency_objective_s if lat is None else float(lat),
                self.freshness_objective_s if fresh is None else float(fresh),
                self.window)
        return st

    def observe_latency(self, tenant: str, seconds: float) -> None:
        tenant = tenant or "default"
        with self._lock:
            st = self._state_locked(tenant)
            good = seconds <= st.latency_objective_s
            st.latency_window.append(good)
            if good:
                st.latency_good += 1
            else:
                st.latency_breach += 1
        kind = "good" if good else "breach"
        REGISTRY.counter(f"slo.{kind}").add()
        REGISTRY.counter(f"slo.latency_{kind}.{_metric_name(tenant)}").add()

    def observe_freshness(self, tenant: str, lag_s: float) -> None:
        tenant = tenant or "default"
        with self._lock:
            st = self._state_locked(tenant)
            good = lag_s <= st.freshness_objective_s
            st.freshness_window.append(good)
            if good:
                st.freshness_good += 1
            else:
                st.freshness_breach += 1
        kind = "good" if good else "breach"
        REGISTRY.counter(f"slo.{kind}").add()
        REGISTRY.counter(f"slo.freshness_{kind}.{_metric_name(tenant)}").add()

    @staticmethod
    def _burn(window: deque) -> float:
        if not window:
            return 0.0
        return 1.0 - (sum(1 for g in window if g) / len(window))

    def burn_rate(self, tenant: Optional[str] = None) -> float:
        """Worst rolling breach fraction across latency+freshness for
        ``tenant`` (or across all tenants when ``None``)."""
        with self._lock:
            states = ([self._tenants[tenant]]
                      if tenant in self._tenants
                      else list(self._tenants.values())
                      if tenant is None else [])
            worst = 0.0
            for st in states:
                worst = max(worst, self._burn(st.latency_window),
                            self._burn(st.freshness_window))
            return worst

    def snapshot(self) -> "list[dict]":
        """One row per tenant (the ``system.slo`` backing store)."""
        with self._lock:
            rows = []
            for name in sorted(self._tenants):
                st = self._tenants[name]
                rows.append({
                    "tenant": name,
                    "latency_objective_s": st.latency_objective_s,
                    "freshness_objective_s": st.freshness_objective_s,
                    "latency_good": st.latency_good,
                    "latency_breach": st.latency_breach,
                    "freshness_good": st.freshness_good,
                    "freshness_breach": st.freshness_breach,
                    "latency_burn_rate": self._burn(st.latency_window),
                    "freshness_burn_rate": self._burn(st.freshness_window),
                })
            return rows

    def gauges(self) -> dict:
        out = {}
        for row in self.snapshot():
            t = _metric_name(row["tenant"])
            out[f"slo.latency_burn_rate.{t}"] = row["latency_burn_rate"]
            out[f"slo.freshness_burn_rate.{t}"] = row["freshness_burn_rate"]
        return out


# ---------------------------------------------------------------------------
# anomaly watchdog
# ---------------------------------------------------------------------------

#: every constructed monitor, weakly held — ``live_monitors()`` is the
#: conftest thread-leak guard's view
_MONITORS: "weakref.WeakSet" = weakref.WeakSet()


def live_monitors() -> "list[HealthMonitor]":
    """Monitors whose watchdog thread is still running (tests assert
    this is empty after each test)."""
    return [m for m in list(_MONITORS) if m.running()]


class HealthMonitor:
    """Background anomaly watchdog over one session's serving state.

    ``sample()`` is the whole cadence step — collect one snapshot,
    ring-buffer it, compare against the trailing baseline, fire on
    breach — and is public so tests (and the tier-1 gate) can drive
    detection deterministically without the thread.

    Breach semantics: a latch arms on a clean sample and a breach
    disarms it, so one sustained incident produces exactly one
    ``health_breach`` (plus a cooldown guarding re-arm flapping).
    On breach the worst in-flight query (longest elapsed, from the
    lifecycle's in-flight registry) is captured into the flight
    recorder under the ``health_breach`` trigger with its own live
    tracer — the slow query's post-mortem, not the watchdog's.
    """

    def __init__(self, session, scheduler=None, subscriptions=None,
                 interval_s: float = 0.25, ring: int = 128,
                 baseline_window: int = 8, min_samples: int = 3,
                 p99_factor: float = 3.0, queue_limit: int = 64,
                 burn_limit: float = 0.5, stale_lag_s: float = 30.0,
                 cooldown_s: float = 5.0,
                 on_breach: "Optional[Callable[[dict], None]]" = None):
        self.session = session
        self.scheduler = scheduler
        self.subscriptions = subscriptions
        self.interval_s = max(0.01, float(interval_s))
        self.baseline_window = max(1, int(baseline_window))
        self.min_samples = max(1, int(min_samples))
        self.p99_factor = float(p99_factor)
        self.queue_limit = int(queue_limit)
        self.burn_limit = float(burn_limit)
        self.stale_lag_s = float(stale_lag_s)
        self.cooldown_s = float(cooldown_s)
        self.on_breach = on_breach
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=max(4, int(ring)))
        self._breaches: "deque[dict]" = deque(maxlen=32)
        self._armed = True
        self._last_breach_mono: Optional[float] = None
        self._last_query_count = 0.0
        self._last_sample_mono: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _MONITORS.add(self)

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "HealthMonitor":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="presto-tpu-health", daemon=True)
                self._thread.start()
        return self

    def close(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout_s)
        with self._lock:
            self._thread = None

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — the watchdog must not die
                REGISTRY.counter("health.sample_errors").add()

    # ---- collection ------------------------------------------------------
    def _collect(self) -> dict:
        now = time.monotonic()
        snap = REGISTRY.snapshot()
        completed = float(snap.get("query.execution_s.count", 0.0))
        dt = (None if self._last_sample_mono is None
              else max(1e-9, now - self._last_sample_mono))
        qps = 0.0 if dt is None else max(
            0.0, completed - self._last_query_count) / dt
        self._last_query_count = completed
        self._last_sample_mono = now

        laten = [i.execution_s for i in self.session.history.infos()[-64:]
                 if i.execution_s > 0]
        pool = self.session.pool().snapshot()
        cap = pool.get("capacity_bytes") or 0
        occ = (pool.get("reserved_bytes", 0) / cap) if cap else 0.0
        hits = float(snap.get("exec_cache.hit", 0.0))
        misses = float(snap.get("exec_cache.miss", 0.0))
        hit_rate = hits / (hits + misses) if (hits + misses) else 0.0
        depth = 0
        if self.scheduler is not None:
            try:
                depth = int(self.scheduler.queue_depth())
            except Exception:  # noqa: BLE001
                depth = 0
        lag = 0.0
        if self.subscriptions is not None:
            try:
                lag = float(self.subscriptions.max_lag_s())
            except Exception:  # noqa: BLE001
                lag = 0.0
        slo = getattr(self.session, "slo", None)
        burn = slo.burn_rate() if slo is not None else 0.0
        return {
            "ts": time.time(),
            "qps": qps,
            "p50_s": _pctl(laten, 0.50),
            "p99_s": _pctl(laten, 0.99),
            "queue_depth": depth,
            "pool_occupancy": occ,
            "cache_hit_rate": hit_rate,
            "freshness_lag_s": lag,
            "slo_burn": burn,
            "breach": 0,
            "reason": "",
        }

    # ---- detection -------------------------------------------------------
    def _baseline_p99_locked(self) -> "tuple[float, int]":
        """Median p99 over the trailing ``baseline_window`` ring
        entries that actually observed latencies (>0), plus how many
        such entries back it."""
        recent = [r["p99_s"] for r in list(self._ring)[-self.baseline_window:]
                  if r["p99_s"] > 0]
        if not recent:
            return 0.0, 0
        return _pctl(recent, 0.5), len(recent)

    def _reasons(self, cur: dict, baseline_p99: float, support: int) -> list:
        reasons = []
        if (support >= self.min_samples and baseline_p99 > 0
                and cur["p99_s"] > self.p99_factor * baseline_p99):
            reasons.append("p99")
        if cur["queue_depth"] > self.queue_limit:
            reasons.append("queue")
        if cur["slo_burn"] > self.burn_limit:
            reasons.append("burn")
        if cur["freshness_lag_s"] > self.stale_lag_s:
            reasons.append("stale")
        return reasons

    def sample(self) -> dict:
        """One watchdog cadence step; returns the recorded snapshot."""
        cur = self._collect()
        with self._lock:
            baseline_p99, support = self._baseline_p99_locked()
            reasons = self._reasons(cur, baseline_p99, support)
            fire = False
            now = time.monotonic()
            if reasons:
                cooled = (self._last_breach_mono is None
                          or now - self._last_breach_mono >= self.cooldown_s)
                if self._armed and cooled:
                    fire = True
                    self._armed = False
                    self._last_breach_mono = now
                    cur["breach"] = 1
                    cur["reason"] = ",".join(reasons)
            else:
                # a clean sample re-arms the latch: the NEXT incident
                # is a new breach, the same one never double-fires
                self._armed = True
            self._ring.append(cur)
            if fire:
                event = dict(cur)
                event["baseline_p99_s"] = baseline_p99
                self._breaches.append(event)
        if fire:
            REGISTRY.counter("health.breach").add()
            for r in reasons:
                REGISTRY.counter(f"health.breach.{r}").add()
            self._capture_worst_inflight(event)
            if self.on_breach is not None:
                try:
                    self.on_breach(event)
                except Exception:  # noqa: BLE001
                    REGISTRY.counter("health.sample_errors").add()
        return cur

    def _capture_worst_inflight(self, event: dict) -> None:
        """Flight-record the longest-running in-flight query under the
        ``health_breach`` trigger — the post-mortem a slow-but-green
        incident would otherwise never leave."""
        manager = getattr(self.session, "query_manager", None)
        inflight = manager.inflight_snapshot() if manager is not None else []
        if not inflight:
            REGISTRY.counter("health.breach_no_inflight").add()
            return
        worst = max(inflight, key=lambda e: e["info"].elapsed_s)
        event["query_id"] = worst["info"].query_id
        try:
            self.session.flight.capture(
                worst["info"], worst["plan"], self.session,
                executor=worst["executor"], err=None,
                triggers=("health_breach",), tracer=worst["tracer"])
        except Exception:  # noqa: BLE001 — capture is best-effort
            REGISTRY.counter("flight.capture_errors").add()

    # ---- observability ---------------------------------------------------
    def snapshot(self) -> "list[dict]":
        """Ring contents, oldest first (the ``system.health`` backing
        store)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def breaches(self) -> "list[dict]":
        with self._lock:
            return [dict(b) for b in self._breaches]

    def gauges(self) -> dict:
        with self._lock:
            last = self._ring[-1] if self._ring else None
            n_breach = len(self._breaches)
        out = {"health.ring_depth": float(len(self._ring)),
               "health.breaches": float(n_breach)}
        if last is not None:
            out["health.qps"] = last["qps"]
            out["health.p99_s"] = last["p99_s"]
            out["health.queue_depth"] = float(last["queue_depth"])
            out["health.freshness_lag_s"] = last["freshness_lag_s"]
            out["health.slo_burn"] = last["slo_burn"]
        return out
