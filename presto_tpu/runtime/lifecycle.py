"""Query lifecycle management: deadlines, admission, retry, degradation.

Reference parity: ``QueryManager`` + ``SqlStageExecution`` — the tier
that treats failure as a first-class state: ``query.max-run-time``
deadlines enforced by the coordinator, memory-pool admission before a
query may start, and per-stage retry policy [SURVEY §3.1, §5.3;
reference tree unavailable, paths reconstructed]. The robust-hash-join
design argument (PAPERS.md) applies verbatim: the static estimates in
``plan/bounds.py`` WILL be wrong sometimes, so the lifecycle layer —
not the operators — must own what happens when they are.

Single-controller mapping:

- **Deadline** (``query_max_run_time``): there is no watchdog thread to
  cancel a running XLA program, so the deadline is checked at the
  host-side *boundaries* — every fragment dispatch in both executors
  and every driver-loop push in ``exec/pipeline.py``. A single compiled
  step runs to completion; the check fires before the next one starts.
- **Admission** (``query_max_memory_bytes``): the peak stats-estimated
  node materialization (``runtime/memory.estimate_node_bytes``) is
  compared against the limit BEFORE launch, rejecting with
  ``ResourceExhausted`` instead of OOMing mid-flight. The default limit
  is a loose multiple of the device budget: estimates are sound-ish,
  not exact, and the grouped/streaming tiers bound true residency well
  below the naive estimate — admission is the backstop for queries no
  tier can save.
- **Fragment retry** (``retry_count`` / ``retry_backoff_s``): a
  fragment dispatch failing with a *retryable* error re-runs after
  exponential backoff. Re-running a fragment re-executes its subtree —
  the engine is deterministic and side-effect-free below the sink, so
  a replay is safe (same property the capacity-overflow retries rely
  on). Exhausted retries mark the error so ancestor dispatches don't
  multiply the retry budget.
- **Degradation**: a distributed query whose retries are exhausted on a
  retryable error re-plans onto the single-device local pipeline
  (``degrade_to_local``) — the last resort when the mesh itself is the
  unreliable component.

The active :class:`QueryContext` travels via a ``ContextVar`` so the
driver loop and both executors see it without threading a parameter
through every operator signature (and nested queries from event
listeners get their own context).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Callable, Optional

from presto_tpu.runtime.errors import (
    DeviceOutOfMemory,
    ExceededTimeLimit,
    ResourceExhausted,
    is_backend_oom,
    is_retryable,
)
from presto_tpu.runtime.devices import timed_dispatch
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.overload import CancelScope, RetryBudget
from presto_tpu.runtime.trace import current as trace_current
from presto_tpu.runtime.trace import span as trace_span

#: cap on one exponential-backoff sleep (a retry loop must never turn
#: a deadline miss into a multi-minute hang)
MAX_BACKOFF_S = 5.0

_CURRENT: ContextVar[Optional["QueryContext"]] = ContextVar(
    "presto_tpu_query_context", default=None
)

#: absolute ``time.monotonic()`` deadline the CURRENT REQUEST carries
#: (the serving layer's ``X-Presto-Deadline`` header); ``_context``
#: folds it into the query deadline — the TIGHTER of the two wins
REQUEST_DEADLINE: ContextVar[Optional[float]] = ContextVar(
    "presto_tpu_request_deadline", default=None
)


@dataclass(frozen=True)
class RetryPolicy:
    count: int = 0
    backoff_s: float = 0.01


class QueryContext:
    """Per-query lifecycle state visible at every execution boundary."""

    def __init__(
        self,
        deadline_s: float | None = None,
        retry: RetryPolicy = RetryPolicy(),
        on_retry: Callable[[str, BaseException], None] | None = None,
        cancel_scope: "CancelScope | None" = None,
        retry_budget: "RetryBudget | None" = None,
    ):
        self.deadline = (
            None if deadline_s is None else time.monotonic() + deadline_s
        )
        self.deadline_s = deadline_s
        self.retry = retry
        self.on_retry = on_retry
        self.fragment_retries = 0
        #: cooperative cancellation flag (runtime/overload.py); every
        #: deadline checkpoint doubles as a cancel checkpoint, so the
        #: existing choke points (fragment entry, morsel loop, scan
        #: loops) observe a cancel within one boundary
        self.cancel_scope = cancel_scope
        #: session-wide retry token bucket + circuit breaker; None in
        #: bare contexts (tests constructing QueryContext directly)
        self.retry_budget = retry_budget

    def check_deadline(self, where: str = "driver") -> None:
        if self.cancel_scope is not None:
            self.cancel_scope.check(where)
        if self.deadline is not None and time.monotonic() > self.deadline:
            REGISTRY.counter("query.deadline_exceeded").add()
            raise ExceededTimeLimit(
                f"query exceeded query_max_run_time="
                f"{self.deadline_s}s (checked at {where})"
            )

    def record_retry(self, site: str, exc: BaseException) -> None:
        self.fragment_retries += 1
        REGISTRY.counter("fragment.retried").add()
        if self.on_retry is not None:
            self.on_retry(site, exc)


def current_context() -> QueryContext | None:
    return _CURRENT.get()


def check_deadline(where: str = "driver") -> None:
    """Boundary hook: enforce the active query deadline, if any."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.check_deadline(where)


def _map_backend_oom(e: BaseException, where: str):
    """Classify a backend RESOURCE_EXHAUSTED / allocator OOM raised at
    a dispatch boundary into the taxonomy. Returns the typed
    ``DeviceOutOfMemory`` to raise, or None when ``e`` is not an OOM.
    Every dispatch in both executors funnels through
    :func:`run_fragment`, so this single choke point covers all jitted
    -step sites — including lazy streams drained by an ancestor."""
    if not is_backend_oom(e):
        return None
    REGISTRY.counter("query.backend_oom").add()
    return DeviceOutOfMemory(
        f"backend out of memory at {where}: {type(e).__name__}: {e}"
    )


def run_fragment(label: str, fn: Callable[[], object]):
    """Execute one fragment dispatch under the active lifecycle: the
    deadline is checked at entry and between attempts, and retryable
    failures re-run with exponential backoff up to ``retry.count``
    times. Exceptions that exhausted their retries here are tagged
    (``_presto_retries_exhausted``) so every ancestor dispatch — whose
    body re-invokes this fragment — re-raises instead of multiplying
    the retry budget by the plan depth. Backend OOMs (real XLA
    RESOURCE_EXHAUSTED or the injected ``oom`` fault kind) map into
    ``DeviceOutOfMemory`` here — non-retryable at the fragment level,
    recoverable by the query-level degradation ladder."""
    ctx = _CURRENT.get()
    if ctx is None:
        with trace_span(label, "fragment"):
            try:
                # the dispatch ledger (runtime/devices.py) attributes
                # wall time to devices from this choke point
                return timed_dispatch(fn)
            except Exception as e:
                oom = _map_backend_oom(e, label)
                if oom is not None:
                    raise oom from e
                raise
    ctx.check_deadline(label)
    attempts = max(0, ctx.retry.count)
    dispatch_h = REGISTRY.histogram("fragment.dispatch_s")
    budget = ctx.retry_budget
    for attempt in range(attempts + 1):
        try:
            with trace_span(
                label, "fragment",
                {"attempt": attempt} if attempt else None,
            ), dispatch_h.time():
                result = timed_dispatch(fn)
            if attempt > 0 and budget is not None:
                # a spent retry paid off — a half-open probe's success
                # closes the breaker and refills the bucket
                budget.record_success()
            return result
        except Exception as e:
            if attempt > 0 and budget is not None:
                budget.record_failure()
            oom = _map_backend_oom(e, label)
            if oom is not None:
                raise oom from e
            exhausted = getattr(e, "_presto_retries_exhausted", False)
            if not is_retryable(e) or exhausted or attempt == attempts:
                if is_retryable(e):
                    e._presto_retries_exhausted = True
                raise
            if budget is not None and not budget.try_spend(label):
                # budget drained / breaker open: correlated failures
                # degrade to fail-fast with the ORIGINAL error instead
                # of a retry storm that multiplies offered load
                e._presto_retries_exhausted = True
                raise
            ctx.record_retry(label, e)
            sleep_s = min(ctx.retry.backoff_s * (2**attempt), MAX_BACKOFF_S)
            if ctx.deadline is not None:
                # never sleep past the deadline: the backoff must not
                # extend the query beyond query_max_run_time
                sleep_s = min(
                    sleep_s, max(0.0, ctx.deadline - time.monotonic())
                )
            with trace_span(
                f"backoff:{label}", "retry",
                {"attempt": attempt, "error": type(e).__name__},
            ):
                time.sleep(sleep_s)
            ctx.check_deadline(label)
    raise AssertionError("unreachable")  # pragma: no cover


def peak_estimate_bytes(plan, catalog) -> tuple[int, str]:
    """Max stats-estimated materialized bytes over all plan nodes (the
    admission-control operand) and the offending node's type name."""
    from presto_tpu.runtime.memory import estimate_node_bytes

    worst, worst_node = 0, "?"

    def walk(node):
        nonlocal worst, worst_node
        try:
            est = estimate_node_bytes(node, catalog)
        except Exception:  # noqa: BLE001 — stats gaps never block a query
            est = 0
        if est > worst:
            worst, worst_node = est, type(node).__name__
        for c in node.children:
            walk(c)

    walk(plan)
    return worst, worst_node


class _InflightEntry:
    """One in-flight execution other submissions can coalesce onto."""

    __slots__ = ("event", "df", "ok", "waiters")

    def __init__(self):
        self.event = threading.Event()
        self.df = None
        self.ok = False
        self.waiters = 0


class InflightCoalescer:
    """Cross-query batching, first rung: concurrent IDENTICAL queries
    (same binding fingerprint) coalesce onto one execution — followers
    wait for the leader's result instead of racing N duplicate device
    dispatches — and concurrent same-TEMPLATE different-literal queries
    serialize behind the single warm executable (one trace+compile,
    then back-to-back signature-cache hits) instead of racing N
    identical traces through jit's internal locks.

    The Session gates entry exactly like result-cache admission
    (deterministic plans, no fault injector, no stats recorder), so a
    follower's answer is always what its own execution would have
    produced. Leaders publish in a ``finally``: a failed leader wakes
    followers with no result and each falls through to executing
    itself — coalescing can batch work, never failures."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: dict[str, _InflightEntry] = {}
        #: template fingerprint -> [lock, refcount]
        self._tlocks: dict[str, list] = {}

    def lead_or_wait(self, key: str, timeout_s: float | None = None):
        """Returns ``(True, entry)`` for the leader (MUST ``publish``
        the entry in a finally), or ``(False, df_or_None)`` for a
        follower — the leader's result, or None when the leader failed
        / the wait timed out (the caller then executes itself)."""
        with self._lock:
            entry = self._inflight.get(key)
            if entry is None:
                entry = _InflightEntry()
                self._inflight[key] = entry
                return True, entry
            entry.waiters += 1
        try:
            served = entry.event.wait(timeout_s)
        finally:
            with self._lock:
                entry.waiters -= 1
        if served and entry.ok:
            # per-follower defensive copy: N coalesced submissions must
            # not alias one frame (mutating one result would corrupt
            # the others — the result-cache convention applies here too)
            return False, entry.df.copy(deep=True)
        return False, None

    def publish(self, key: str, entry: _InflightEntry, df) -> None:
        """Finish an in-flight execution: store a defensive copy of the
        result (None on failure) and wake every waiter. The key is
        retired first, so late arrivals lead a fresh execution instead
        of reading a result whose table versions may have moved."""
        with self._lock:
            self._inflight.pop(key, None)
        if df is not None:
            entry.df = df.copy(deep=True)
            entry.ok = True
        entry.event.set()

    def waiters(self, key: str) -> int:
        """Current follower count for an in-flight key (tests/metrics)."""
        with self._lock:
            entry = self._inflight.get(key)
            return 0 if entry is None else entry.waiters

    @contextmanager
    def template_slot(self, template_key: str):
        """Serialize executions of one plan template: the first binding
        traces+compiles, queued bindings then run warm. Slots are
        refcounted so the map stays bounded by in-flight templates."""
        with self._lock:
            slot = self._tlocks.get(template_key)
            if slot is None:
                slot = self._tlocks[template_key] = [threading.Lock(), 0]
            slot[1] += 1
        queued = not slot[0].acquire(blocking=False)
        if queued:
            REGISTRY.counter("prepare.template_queued").add()
            slot[0].acquire()
        try:
            yield
        finally:
            slot[0].release()
            with self._lock:
                slot[1] -= 1
                if slot[1] == 0:
                    self._tlocks.pop(template_key, None)


class QueryManager:
    """Owns one session's query lifecycle mechanics (the Session keeps
    the client surface and the QUEUED/RUNNING/FINISHED state machine;
    this class owns admission, deadline scope, and degradation)."""

    def __init__(self, session):
        self.session = session
        #: in-flight query coalescing (plan-template parameterization's
        #: cross-query batching rung; see InflightCoalescer)
        self.coalescer = InflightCoalescer()
        #: cross-query BATCHED dispatch (server/batcher.py): concurrent
        #: same-template different-literal queries meet here and fuse
        #: into one vmapped dispatch when the ``batched_dispatch``
        #: session property is on (the serving layer's default)
        from presto_tpu.server.batcher import TemplateBatchGate

        self.batch_gate = TemplateBatchGate()
        #: live executions, query_id -> {info, executor, plan, tracer}
        #: — the health watchdog's view of what is running RIGHT NOW
        #: (it flight-records the worst entry on a breach; the tracer
        #: is carried because trace.current() is context-local and the
        #: watchdog samples from its own thread)
        self._inflight_lock = threading.Lock()
        self._inflight_queries: dict = {}
        #: query_id -> CancelScope for the WHOLE tracked execution —
        #: registered by Session._run_tracked before the batch-gate /
        #: coalescer waits, so a cancel reaches a query that has not
        #: entered run_plan yet
        self._scopes: dict = {}
        #: lazily-built per-session retry token bucket (overload
        #: control rung 3); lazy because session properties are not
        #: validated yet when the Session constructs its manager
        self._retry_budget: RetryBudget | None = None

    def retry_budget(self) -> RetryBudget:
        """The session's shared :class:`RetryBudget` (fragment retries
        AND OOM-ladder rungs draw from one bucket — correlated
        failures are correlated across both)."""
        with self._inflight_lock:
            if self._retry_budget is None:
                self._retry_budget = RetryBudget(
                    capacity=self.session.prop("retry_budget_tokens"),
                    refill_per_s=self.session.prop(
                        "retry_budget_refill_per_s"),
                    probe_cooldown_s=self.session.prop(
                        "retry_breaker_cooldown_s"),
                )
            return self._retry_budget

    def open_scope(self, query_id: str) -> "CancelScope":
        """Register the query's CancelScope for the whole tracked
        execution (Session._run_tracked pairs this with
        :meth:`close_scope` in a finally)."""
        scope = CancelScope(query_id)
        with self._inflight_lock:
            self._scopes[query_id] = scope
        return scope

    def close_scope(self, query_id: str) -> None:
        with self._inflight_lock:
            self._scopes.pop(query_id, None)

    def scope_of(self, query_id: str) -> "CancelScope | None":
        with self._inflight_lock:
            return self._scopes.get(query_id)

    def cancel(self, query_id: str, reason: str = "cancelled") -> bool:
        """Flip a live query's :class:`CancelScope`; its next
        cooperative checkpoint raises ``QueryCancelled`` and the
        ordinary ``finally`` paths release every reservation. Returns
        False when the query is not in flight (already terminal) or
        was already cancelled."""
        with self._inflight_lock:
            scope = self._scopes.get(query_id)
            if scope is None:
                entry = self._inflight_queries.get(query_id)
                scope = None if entry is None else entry.get("cancel")
        if scope is None:
            return False
        return scope.cancel(reason)

    # -- admission ------------------------------------------------------
    def admission_limit(self) -> int:
        limit = self.session.prop("query_max_memory_bytes")
        if limit is not None:
            return int(limit)
        # the SAME headroom constant sizes the default shared pool, so
        # the per-query backstop and the pool capacity cannot drift
        from presto_tpu.runtime.memory import (
            DEFAULT_POOL_HEADROOM,
            device_budget_bytes,
        )

        return device_budget_bytes() * DEFAULT_POOL_HEADROOM

    def admit(self, plan, info, pool, scale: int = 1) -> int:
        """Admission in two stages: the per-query limit rejects
        (ResourceExhausted) before launch when the plan's peak
        estimated materialization exceeds it; then the shared memory
        pool takes a byte reservation for that peak, QUEUING (bounded
        FIFO, ``admission_queue_timeout_s``) while concurrent queries
        hold the pool — block-then-run instead of reject-or-nothing.
        Rejection/timeout messages carry the estimate, the limit, the
        offending node type, and the live pool reservations."""
        limit = self.admission_limit()
        peak, node = peak_estimate_bytes(plan, self.session.catalog)
        # a cross-query batch leader executes `scale` fused lanes in
        # one dispatch: its reservation should cover them all (loose —
        # lanes share the scan — but admission estimates are loose
        # upper shapes everywhere). The scale is CLAMPED so it can
        # never fail a query the serial path would have admitted:
        # batching multiplies work, never failures — the reject below
        # keeps its serial (scale=1) semantics.
        scale = max(1, int(scale))
        if scale > 1 and peak > 0:
            scale = min(scale,
                        max(1, limit // peak),
                        max(1, pool.capacity_bytes // peak))
        if peak > limit:
            REGISTRY.counter("query.admission_rejected").add()
            raise ResourceExhausted(
                f"admission control: {node} is estimated to materialize "
                f"{peak} bytes, over the limit of {limit} bytes "
                f"({pool.describe()}; set the query_max_memory_bytes "
                "session property to raise it)"
            )
        timeout_s = self.session.prop("admission_queue_timeout_s")
        deadline_s = self.session.prop("query_max_run_time")
        if deadline_s is not None:
            # the run-time deadline's clock starts AFTER admission, so
            # cap the queue wait by it — a 5s-deadline query must not
            # sit 30s in the pool queue and still look on-time
            timeout_s = (
                deadline_s if timeout_s is None
                else min(timeout_s, deadline_s)
            )
        t0 = time.monotonic()
        try:
            queued_s = pool.reserve(
                info.query_id, peak * scale,
                timeout_s=timeout_s,
                detail=f"peak estimate {peak} bytes at {node}"
                       + (f" x{scale} batch lanes" if scale > 1 else ""),
                # serving-layer attribution: the reservation carries the
                # query's tenant so the fairness scheduler's byte quotas
                # (server/scheduler.py) gate on REAL pool residency
                tenant=info.tenant or None,
            )
        except ResourceExhausted:
            # a timed-out query queued the LONGEST — record its wait
            info.memory_queued_s = time.monotonic() - t0
            raise
        info.memory_reserved_bytes = peak * scale
        info.memory_queued_s = queued_s
        # the GRANTED width: when the clamp shrank it, the batch leader
        # must trim its dispatch to the lanes this reservation covers
        return scale

    # -- execution scope ------------------------------------------------
    def _context(self, info, scope: "CancelScope | None" = None
                 ) -> QueryContext:
        events = self.session.events
        deadline_s = self.session.prop("query_max_run_time")
        request_deadline = REQUEST_DEADLINE.get()
        if request_deadline is not None:
            # the serving layer's X-Presto-Deadline (absolute
            # monotonic) propagates into the query scope; the TIGHTER
            # of the request and session deadlines wins
            remaining = max(0.0, request_deadline - time.monotonic())
            deadline_s = (remaining if deadline_s is None
                          else min(deadline_s, remaining))
        ctx = QueryContext(
            deadline_s=deadline_s,
            retry=RetryPolicy(
                count=self.session.prop("retry_count"),
                backoff_s=self.session.prop("retry_backoff_s"),
            ),
            cancel_scope=scope,
            retry_budget=self.retry_budget(),
        )

        def on_retry(site: str, exc: BaseException):
            # ctx.fragment_retries is the single writer (record_retry
            # increments it before calling here); info only mirrors it,
            # so listeners see the up-to-date count on the QueryInfo
            info.fragment_retries = ctx.fragment_retries
            # flight-recorder evidence: WHICH dispatch failed, with
            # what — the retry count alone can't answer a post-mortem
            info.retry_events.append(
                {"site": site, "error": type(exc).__name__})
            events.fragment_retried(info)

        ctx.on_retry = on_retry
        return ctx

    def run_plan(self, executor, plan, info, recorder):
        """Run a plan under the full lifecycle: queued admission
        against the shared memory pool, deadline scope, fragment retry
        (enforced at the executors' dispatch boundaries via the
        context), the adaptive OOM degradation ladder, and
        distributed->local degradation as the last resort. The pool
        reservation is released on EVERY terminal state.

        This is also the per-query metric-attribution choke point: a
        ``QueryMetricsDelta`` collector rides the context for the whole
        admission+execution scope, so every process-global counter the
        run moves (``join.strategy.*``, ``exec.*``, ``memory.*``,
        cache and exchange stats) is ALSO captured as this query's
        delta — ``info.metrics`` / ``info.join_strategy`` /
        ``info.filter_selectivity`` / ``info.oom_rung`` — without any
        cross-query bleed under concurrency (runtime/metrics.py)."""
        from presto_tpu.runtime.metrics import (
            QueryMetricsDelta,
            install_delta,
            uninstall_delta,
        )

        pool = self.session.pool()
        delta = QueryMetricsDelta()
        delta_token = install_delta(delta)
        # reuse the scope _run_tracked registered (a cancel issued
        # during the gate wait must stay flipped here); direct callers
        # (batch leaders, subscriptions) get a fresh one
        scope = self.scope_of(info.query_id) or CancelScope(info.query_id)
        with self._inflight_lock:
            self._inflight_queries[info.query_id] = {
                "info": info, "executor": executor, "plan": plan,
                "tracer": trace_current(), "cancel": scope,
            }
        err = None
        try:
            return self._run_admitted(executor, plan, info, recorder, pool,
                                      scope)
        except BaseException as e:
            err = e
            raise
        finally:
            with self._inflight_lock:
                self._inflight_queries.pop(info.query_id, None)
            uninstall_delta(delta_token)
            info.attribute_metrics(delta.snapshot())
            self._stamp_device_peak(info)
            self._observe_slo(info, err)
            # flight recorder (runtime/flight.py): this is the ONE
            # choke point every executed query passes with its full
            # evidence in hand — attributed metrics, rung/retry
            # history, the live trace recorder — and with the pool
            # reservation already released (_run_admitted's finally),
            # so a post-mortem can never hold memory capacity
            self._maybe_flight_record(executor, plan, info, err)

    def inflight_snapshot(self) -> "list[dict]":
        """Shallow copies of the live execution entries (watchdog +
        ``system.health`` consumers read outside the lock)."""
        with self._inflight_lock:
            return [dict(e) for e in self._inflight_queries.values()]

    def _stamp_device_peak(self, info) -> None:
        """Record the device HBM watermark on the finished query
        (``device_telemetry`` property; zeros on CPU backends)."""
        if not self.session.prop("device_telemetry"):
            return
        try:
            from presto_tpu.runtime.devices import peak_bytes

            info.device_peak_bytes = peak_bytes()
        except Exception:  # noqa: BLE001 — telemetry never fails a query
            pass

    def _observe_slo(self, info, err) -> None:
        """Feed the tenant SLO tracker (attached by the serving layer;
        plain sessions have none). Failures count as latency breaches —
        an erroring tenant is not meeting its objective."""
        slo = getattr(self.session, "slo", None)
        if slo is None:
            return
        try:
            latency = (float("inf") if err is not None
                       else info.execution_s)
            slo.observe_latency(info.tenant or "default", latency)
        except Exception:  # noqa: BLE001 — observability never fails a query
            pass

    def _maybe_flight_record(self, executor, plan, info, err) -> None:
        """Capture a post-mortem when the run FAILED, DEGRADED (OOM
        rung or distributed->local), RETRIED a fragment, or blew its
        deadline; successes only under ``flight_record_successes``.
        Best-effort: observability never fails (or retries) a query."""
        try:
            triggers = []
            if err is not None:
                triggers.append("failed")
                if isinstance(err, ExceededTimeLimit):
                    triggers.append("deadline")
            if info.oom_retries > 0 or info.degraded:
                triggers.append("degraded")
            if info.fragment_retries > 0:
                triggers.append("retried")
            if not triggers:
                if not self.session.prop("flight_record_successes"):
                    return
                triggers.append("requested")
            self.session.flight.capture(
                info, plan, self.session, executor=executor, err=err,
                triggers=triggers,
            )
        except Exception:  # noqa: BLE001 — see docstring
            REGISTRY.counter("flight.capture_errors").add()

    def _run_admitted(self, executor, plan, info, recorder, pool,
                      scope: "CancelScope | None" = None):
        try:
            with trace_span("admission", "lifecycle"):
                granted = self.admit(
                    plan, info, pool,
                    scale=getattr(executor, "admission_scale", 1))
                if granted != getattr(executor, "admission_scale", 1):
                    # a clamped batch leader may only dispatch the
                    # lanes its reservation covers; the rest re-queue
                    # at the gate (server/batcher.BatchRunner.run)
                    executor.admission_scale_granted = granted
        finally:
            # admission — including any time blocked in the pool's
            # FIFO queue — is QUEUED time, not execution: re-stamp the
            # RUNNING transition on success AND failure so
            # queued_s/execution_s split at the true run start, never
            # double-counting the wait as execution (the cache-hit
            # path does not reach here and keeps its original stamp)
            info.started_at = time.time()
            info.started_mono = time.monotonic()
        try:
            ctx = self._context(info, scope)
            token = _CURRENT.set(ctx)
            try:
                # timed post-admission, so the execution histogram
                # agrees with QueryInfo.execution_s (pool wait is
                # QUEUED)
                with REGISTRY.histogram("query.execution_s").time():
                    return self._run_with_oom_ladder(executor, plan, info,
                                                     recorder, ctx)
            finally:
                info.fragment_retries = ctx.fragment_retries
                _CURRENT.reset(token)
        finally:
            # the release guard covers EVERYTHING after a successful
            # reservation — even an async exception before the inner
            # scope installs would otherwise leak pool capacity for
            # the life of the process
            pool.release(info.query_id)

    def _run_with_oom_ladder(self, executor, plan, info, recorder, ctx):
        """The adaptive OOM recovery loop (robust-hash-join posture,
        PAPERS.md arXiv:2112.02480): a runtime ``DeviceOutOfMemory`` —
        a WRONG low estimate the static spill decision trusted — does
        not kill the query; the executor steps one rung down its
        degradation ladder (force grouped execution, then double
        buckets / halve probe chunks) and the plan re-runs, up to
        ``oom_ladder_max`` rungs. Deterministic re-planning, not a
        blind replay: each rung strictly shrinks per-step residency, so
        wrong estimates degrade throughput, never correctness."""
        ladder_max = self.session.prop("oom_ladder_max")
        budget = ctx.retry_budget
        rung = 0
        while True:
            try:
                if rung > 0:
                    # between-rung cancel/deadline checkpoint, INSIDE
                    # the try: the cancel scope doubles as the
                    # step.cancel_checkpoint fault site, and an
                    # injected OOM here must consume a rung like any
                    # step OOM, not escape the ladder
                    ctx.check_deadline("oom_ladder")
                result = executor.run(plan)
                if rung > 0 and budget is not None:
                    budget.record_success()
                # approximate-join visibility: the executor records
                # whether this run published a sketch (Bloom) probe —
                # QueryInfo must flag possibly-approximate results so
                # exactness is never silently degraded (ISSUE-7)
                info.approximate = bool(
                    getattr(executor, "used_approx", False))
                self._note_planned_spills(executor, info)
                return result
            except DeviceOutOfMemory as e:
                if rung > 0 and budget is not None:
                    budget.record_failure()
                degrade = getattr(executor, "degrade_for_oom", None)
                if rung >= ladder_max or degrade is None or not degrade():
                    raise
                if budget is not None and not budget.try_spend("oom_ladder"):
                    # ladder rungs draw from the SAME bucket as
                    # fragment retries: an OOM storm fails fast once
                    # the breaker opens instead of re-planning forever
                    raise
                rung += 1
                # additive: a degraded-to-local run's ladder continues
                # the count the distributed attempt started
                info.oom_retries += 1
                # the ladder's walk, preserved for the post-mortem:
                # rung ordinals are QUERY-level (they keep counting
                # across a distributed->local degradation)
                info.rung_history.append(
                    {"kind": "ladder", "rung": info.oom_retries,
                     "error": str(e)[:200]})
                with trace_span(
                    "oom_degrade", "lifecycle",
                    {"rung": rung, "error": str(e)[:120]},
                ):
                    REGISTRY.counter("query.oom_degraded").add()
                    self.session.events.query_degraded(info)
                    if recorder is not None:
                        # stats from the OOMed attempt must not leak
                        # into (or double-count in) the re-run's
                        # QueryInfo
                        recorder.nodes.clear()
            except Exception as e:
                if (
                    is_retryable(e)
                    and getattr(executor, "mesh", None) is not None
                    and self.session.prop("degrade_to_local")
                ):
                    return self._degrade(plan, info, recorder, ctx,
                                         getattr(executor, "params", ()))
                raise

    @staticmethod
    def _note_planned_spills(executor, info) -> None:
        """Append the run's PLANNED out-of-core decisions to the rung
        history with ``kind: "planned_hybrid"`` / ``"planned_grouped"``
        — distinguishable from ``kind: "ladder"`` entries, so the
        post-mortem separates 'the plan chose out-of-core up front'
        from 'a runtime OOM forced a re-plan'. Ladder rung counting
        (``oom_retries``) never includes these."""
        for ev in getattr(executor, "spill_events", ()) or ():
            if ev.get("mode") in ("hybrid", "grouped"):
                info.rung_history.append(
                    {"kind": f"planned_{ev['mode']}", **ev})

    def _degrade(self, plan, info, recorder, ctx, params=()):
        """Re-plan a failed distributed query onto the single-device
        local pipeline (graceful degradation; the deadline keeps
        running — the retry context stays installed, and if the local
        run fails too, implicit ``__context__`` chaining preserves the
        original distributed failure). The degraded run gets its OWN
        OOM ladder: one device now holds mesh-size times the data, so
        an in-memory build that fit distributed may genuinely OOM here
        — exactly the case the ladder recovers."""
        from presto_tpu.exec.local_planner import LocalExecutor

        REGISTRY.counter("query.degraded_to_local").add()
        info.degraded = True
        local = LocalExecutor(
            self.session.catalog,
            join_build_budget=self.session.prop("join_build_budget_bytes"),
            direct_group_limit=self.session.prop("direct_group_limit"),
            runtime_join_filters=self.session.prop("runtime_join_filters"),
            pallas_join_enabled=self.session.prop("pallas_join"),
            approx_join=self.session.prop("approx_join"),
            spill_host_budget=self.session.prop("spill_host_budget_bytes"),
        )
        if recorder is not None:
            # stats from the failed distributed attempt must not leak
            # into (or double-count in) the degraded run's QueryInfo —
            # the same invariant query-level retries keep by making a
            # fresh recorder per attempt
            recorder.nodes.clear()
        local.recorder = recorder
        # the literal-slot binding travels with the plan: the degraded
        # run evaluates the same Param slots the distributed one did
        local.params = tuple(params)
        with trace_span("degrade_to_local", "lifecycle"):
            return self._run_with_oom_ladder(local, plan, info, recorder,
                                             ctx)
