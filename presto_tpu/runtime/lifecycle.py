"""Query lifecycle management: deadlines, admission, retry, degradation.

Reference parity: ``QueryManager`` + ``SqlStageExecution`` — the tier
that treats failure as a first-class state: ``query.max-run-time``
deadlines enforced by the coordinator, memory-pool admission before a
query may start, and per-stage retry policy [SURVEY §3.1, §5.3;
reference tree unavailable, paths reconstructed]. The robust-hash-join
design argument (PAPERS.md) applies verbatim: the static estimates in
``plan/bounds.py`` WILL be wrong sometimes, so the lifecycle layer —
not the operators — must own what happens when they are.

Single-controller mapping:

- **Deadline** (``query_max_run_time``): there is no watchdog thread to
  cancel a running XLA program, so the deadline is checked at the
  host-side *boundaries* — every fragment dispatch in both executors
  and every driver-loop push in ``exec/pipeline.py``. A single compiled
  step runs to completion; the check fires before the next one starts.
- **Admission** (``query_max_memory_bytes``): the peak stats-estimated
  node materialization (``runtime/memory.estimate_node_bytes``) is
  compared against the limit BEFORE launch, rejecting with
  ``ResourceExhausted`` instead of OOMing mid-flight. The default limit
  is a loose multiple of the device budget: estimates are sound-ish,
  not exact, and the grouped/streaming tiers bound true residency well
  below the naive estimate — admission is the backstop for queries no
  tier can save.
- **Fragment retry** (``retry_count`` / ``retry_backoff_s``): a
  fragment dispatch failing with a *retryable* error re-runs after
  exponential backoff. Re-running a fragment re-executes its subtree —
  the engine is deterministic and side-effect-free below the sink, so
  a replay is safe (same property the capacity-overflow retries rely
  on). Exhausted retries mark the error so ancestor dispatches don't
  multiply the retry budget.
- **Degradation**: a distributed query whose retries are exhausted on a
  retryable error re-plans onto the single-device local pipeline
  (``degrade_to_local``) — the last resort when the mesh itself is the
  unreliable component.

The active :class:`QueryContext` travels via a ``ContextVar`` so the
driver loop and both executors see it without threading a parameter
through every operator signature (and nested queries from event
listeners get their own context).
"""

from __future__ import annotations

import time
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Callable, Optional

from presto_tpu.runtime.errors import (
    ExceededTimeLimit,
    ResourceExhausted,
    is_retryable,
)
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.trace import span as trace_span

#: admission headroom over the device budget when no explicit
#: ``query_max_memory_bytes`` is set: node estimates are loose upper
#: shapes, and the grouped/streaming tiers keep true residency far
#: below them — the default only rejects queries that would dwarf the
#: device by any execution strategy
DEFAULT_ADMISSION_HEADROOM = 64

#: cap on one exponential-backoff sleep (a retry loop must never turn
#: a deadline miss into a multi-minute hang)
MAX_BACKOFF_S = 5.0

_CURRENT: ContextVar[Optional["QueryContext"]] = ContextVar(
    "presto_tpu_query_context", default=None
)


@dataclass(frozen=True)
class RetryPolicy:
    count: int = 0
    backoff_s: float = 0.01


class QueryContext:
    """Per-query lifecycle state visible at every execution boundary."""

    def __init__(
        self,
        deadline_s: float | None = None,
        retry: RetryPolicy = RetryPolicy(),
        on_retry: Callable[[str, BaseException], None] | None = None,
    ):
        self.deadline = (
            None if deadline_s is None else time.monotonic() + deadline_s
        )
        self.deadline_s = deadline_s
        self.retry = retry
        self.on_retry = on_retry
        self.fragment_retries = 0

    def check_deadline(self, where: str = "driver") -> None:
        if self.deadline is not None and time.monotonic() > self.deadline:
            REGISTRY.counter("query.deadline_exceeded").add()
            raise ExceededTimeLimit(
                f"query exceeded query_max_run_time="
                f"{self.deadline_s}s (checked at {where})"
            )

    def record_retry(self, site: str, exc: BaseException) -> None:
        self.fragment_retries += 1
        REGISTRY.counter("fragment.retried").add()
        if self.on_retry is not None:
            self.on_retry(site, exc)


def current_context() -> QueryContext | None:
    return _CURRENT.get()


def check_deadline(where: str = "driver") -> None:
    """Boundary hook: enforce the active query deadline, if any."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.check_deadline(where)


def run_fragment(label: str, fn: Callable[[], object]):
    """Execute one fragment dispatch under the active lifecycle: the
    deadline is checked at entry and between attempts, and retryable
    failures re-run with exponential backoff up to ``retry.count``
    times. Exceptions that exhausted their retries here are tagged
    (``_presto_retries_exhausted``) so every ancestor dispatch — whose
    body re-invokes this fragment — re-raises instead of multiplying
    the retry budget by the plan depth."""
    ctx = _CURRENT.get()
    if ctx is None:
        with trace_span(label, "fragment"):
            return fn()
    ctx.check_deadline(label)
    attempts = max(0, ctx.retry.count)
    dispatch_h = REGISTRY.histogram("fragment.dispatch_s")
    for attempt in range(attempts + 1):
        try:
            with trace_span(
                label, "fragment",
                {"attempt": attempt} if attempt else None,
            ), dispatch_h.time():
                return fn()
        except Exception as e:
            exhausted = getattr(e, "_presto_retries_exhausted", False)
            if not is_retryable(e) or exhausted or attempt == attempts:
                if is_retryable(e):
                    e._presto_retries_exhausted = True
                raise
            ctx.record_retry(label, e)
            sleep_s = min(ctx.retry.backoff_s * (2**attempt), MAX_BACKOFF_S)
            if ctx.deadline is not None:
                # never sleep past the deadline: the backoff must not
                # extend the query beyond query_max_run_time
                sleep_s = min(
                    sleep_s, max(0.0, ctx.deadline - time.monotonic())
                )
            with trace_span(
                f"backoff:{label}", "retry",
                {"attempt": attempt, "error": type(e).__name__},
            ):
                time.sleep(sleep_s)
            ctx.check_deadline(label)
    raise AssertionError("unreachable")  # pragma: no cover


def peak_estimate_bytes(plan, catalog) -> tuple[int, str]:
    """Max stats-estimated materialized bytes over all plan nodes (the
    admission-control operand) and the offending node's type name."""
    from presto_tpu.runtime.memory import estimate_node_bytes

    worst, worst_node = 0, "?"

    def walk(node):
        nonlocal worst, worst_node
        try:
            est = estimate_node_bytes(node, catalog)
        except Exception:  # noqa: BLE001 — stats gaps never block a query
            est = 0
        if est > worst:
            worst, worst_node = est, type(node).__name__
        for c in node.children:
            walk(c)

    walk(plan)
    return worst, worst_node


class QueryManager:
    """Owns one session's query lifecycle mechanics (the Session keeps
    the client surface and the QUEUED/RUNNING/FINISHED state machine;
    this class owns admission, deadline scope, and degradation)."""

    def __init__(self, session):
        self.session = session

    # -- admission ------------------------------------------------------
    def admission_limit(self) -> int:
        limit = self.session.prop("query_max_memory_bytes")
        if limit is not None:
            return int(limit)
        from presto_tpu.runtime.memory import device_budget_bytes

        return device_budget_bytes() * DEFAULT_ADMISSION_HEADROOM

    def admit(self, plan) -> None:
        """Reject (ResourceExhausted) before launch when the plan's
        peak estimated materialization exceeds the admission limit."""
        limit = self.admission_limit()
        peak, node = peak_estimate_bytes(plan, self.session.catalog)
        if peak > limit:
            REGISTRY.counter("query.admission_rejected").add()
            raise ResourceExhausted(
                f"admission control: {node} is estimated to materialize "
                f"{peak} bytes, over the limit of {limit} bytes (set the "
                "query_max_memory_bytes session property to raise it)"
            )

    # -- execution scope ------------------------------------------------
    def _context(self, info) -> QueryContext:
        events = self.session.events
        ctx = QueryContext(
            deadline_s=self.session.prop("query_max_run_time"),
            retry=RetryPolicy(
                count=self.session.prop("retry_count"),
                backoff_s=self.session.prop("retry_backoff_s"),
            ),
        )

        def on_retry(site: str, exc: BaseException):
            # ctx.fragment_retries is the single writer (record_retry
            # increments it before calling here); info only mirrors it,
            # so listeners see the up-to-date count on the QueryInfo
            info.fragment_retries = ctx.fragment_retries
            events.fragment_retried(info)

        ctx.on_retry = on_retry
        return ctx

    def run_plan(self, executor, plan, info, recorder):
        """Run a plan under the full lifecycle: admission, deadline
        scope, fragment retry (enforced at the executors' dispatch
        boundaries via the context), and distributed->local
        degradation as the last resort."""
        with trace_span("admission", "lifecycle"):
            self.admit(plan)
        ctx = self._context(info)
        token = _CURRENT.set(ctx)
        try:
            try:
                return executor.run(plan)
            except Exception as e:
                if (
                    is_retryable(e)
                    and getattr(executor, "mesh", None) is not None
                    and self.session.prop("degrade_to_local")
                ):
                    return self._degrade(plan, info, recorder)
                raise
        finally:
            info.fragment_retries = ctx.fragment_retries
            _CURRENT.reset(token)

    def _degrade(self, plan, info, recorder):
        """Re-plan a failed distributed query onto the single-device
        local pipeline (graceful degradation; the deadline keeps
        running — the retry context stays installed, and if the local
        run fails too, implicit ``__context__`` chaining preserves the
        original distributed failure)."""
        from presto_tpu.exec.local_planner import LocalExecutor

        REGISTRY.counter("query.degraded_to_local").add()
        info.degraded = True
        local = LocalExecutor(
            self.session.catalog,
            join_build_budget=self.session.prop("join_build_budget_bytes"),
            direct_group_limit=self.session.prop("direct_group_limit"),
        )
        if recorder is not None:
            # stats from the failed distributed attempt must not leak
            # into (or double-count in) the degraded run's QueryInfo —
            # the same invariant query-level retries keep by making a
            # fresh recorder per attempt
            recorder.nodes.clear()
        local.recorder = recorder
        with trace_span("degrade_to_local", "lifecycle"):
            return local.run(plan)
