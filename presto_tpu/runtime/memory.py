"""Device-memory budgeting — the L9 capacity planner.

Reference parity: ``MemoryPool`` / ``QueryContext`` / the
``MemoryRevokingScheduler``-triggered spill decision [SURVEY §2.1 L9
rows, §7.4 #5]. TPU-first: there is no mid-operator revocation — XLA
allocations are planned at compile time — so budgeting happens at PLAN
time: the executor estimates a fragment's device-resident bytes from
connector stats and chooses grouped (bucketed) execution with host-RAM
offload BEFORE compiling, instead of reacting to pressure mid-flight.
"""

from __future__ import annotations

from presto_tpu.plan import nodes as N
from presto_tpu.types import DataType, TypeKind

#: conservative default when the backend exposes no memory stats
#: (v5e chip = 16 GB HBM; leave headroom for XLA scratch + outputs)
DEFAULT_BUDGET_BYTES = 8 << 30


def device_budget_bytes(device=None) -> int:
    """Usable device memory for resident operator state."""
    import jax

    dev = device or jax.devices()[0]
    try:
        stats = dev.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"] * 0.5)
    except Exception:  # noqa: BLE001 — CPU/interpret backends
        pass
    return DEFAULT_BUDGET_BYTES


def column_bytes(dtype: DataType) -> int:
    """Per-row device bytes of a column (data + validity mask)."""
    if dtype.kind is TypeKind.BYTES:
        return dtype.width + 1
    return dtype.np_dtype.itemsize + 1


def node_row_bytes(node: N.PlanNode) -> int:
    """Per-row device bytes of a node's output (+1 for the live mask)."""
    return sum(column_bytes(f.dtype) for f in node.fields) + 1


def estimate_node_bytes(node: N.PlanNode, catalog) -> int:
    """Estimated device-resident bytes if the node's output were fully
    materialized (stats-based; the grouped-execution trigger)."""
    from presto_tpu.plan.bounds import estimate_rows

    return estimate_rows(node, catalog) * node_row_bytes(node)
