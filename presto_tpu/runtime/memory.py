"""Device-memory budgeting and arbitration — the L9 capacity planner.

Reference parity: ``MemoryPool`` / ``QueryContext`` / the
``MemoryRevokingScheduler``-triggered spill decision [SURVEY §2.1 L9
rows, §7.4 #5]. TPU-first: there is no mid-operator revocation — XLA
allocations are planned at compile time — so budgeting happens at PLAN
time: the executor estimates a fragment's device-resident bytes from
connector stats and chooses grouped (bucketed) execution with host-RAM
offload BEFORE compiling, instead of reacting to pressure mid-flight.

Arbitration (:class:`MemoryPool`): concurrent queries reserve their
peak stats-estimated bytes at admission from a shared pool and release
on every terminal state. A query that does not fit QUEUES (bounded
FIFO, ``admission_queue_timeout_s``) instead of failing — the
block-then-run behavior the reference gets from ``MemoryPool`` +
cluster admission. When the estimate is wrong *low* anyway, the
runtime OOM recovery ladder (runtime/lifecycle.py) takes over.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from presto_tpu.plan import nodes as N
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.types import DataType, TypeKind

#: conservative default when the backend exposes no memory stats
#: (v5e chip = 16 GB HBM; leave headroom for XLA scratch + outputs)
DEFAULT_BUDGET_BYTES = 8 << 30

#: floor on the computed budget: a warm process whose allocator already
#: holds most of the device must still be able to run *small* queries
#: (the grouped/streaming tiers bound true residency far below the
#: budget, and XLA reuses the held buffers)
MIN_BUDGET_BYTES = 256 << 20

#: headroom over the device budget shared by the default admission
#: limit (runtime/lifecycle.py imports this) AND the default pool
#: capacity: node estimates are loose upper shapes and the grouped/
#: streaming tiers keep true residency far below them, so both
#: backstops only reject queries that would dwarf the device under any
#: execution strategy
DEFAULT_POOL_HEADROOM = 64


#: default-device budget, snapshotted at FIRST use: budget-derived
#: compiled-step capacities (nbuckets, probe chunks) feed the
#: content-keyed executable cache, so the budget must not drift with
#: the allocator's live bytes_in_use between queries — that would
#: recompile warm steps every run. The snapshot still reflects what
#: was already held when the engine started (the warm-process case the
#: subtraction exists for).
_DEFAULT_BUDGET: int | None = None


def device_budget_bytes(device=None) -> int:
    """Usable device memory for resident operator state: half the
    backend's byte limit MINUS what the allocator already held at
    first call (a warm process must not over-admit against memory it
    cannot get back), floored at :data:`MIN_BUDGET_BYTES`. The default
    -device value is computed once per process; passing an explicit
    ``device`` always measures fresh."""
    global _DEFAULT_BUDGET
    if device is None and _DEFAULT_BUDGET is not None:
        return _DEFAULT_BUDGET
    import jax

    dev = device or jax.devices()[0]
    budget = DEFAULT_BUDGET_BYTES
    try:
        stats = dev.memory_stats()
        if stats and "bytes_limit" in stats:
            budget = int(stats["bytes_limit"] * 0.5)
            budget -= int(stats.get("bytes_in_use", 0))
            budget = max(budget, MIN_BUDGET_BYTES)
    except Exception:  # noqa: BLE001 — CPU/interpret backends
        pass
    if device is None:
        _DEFAULT_BUDGET = budget
    return budget


class MemoryPool:
    """Byte-reservation arbiter shared by concurrent queries.

    ``reserve`` blocks in strict FIFO order (head-of-line: a large
    query cannot be starved by a stream of small ones) until the
    reservation fits or ``timeout_s`` expires; ``release`` is
    idempotent per query id and wakes every waiter. Reservations are
    *estimates* — the pool bounds concurrent admission, the grouped
    tiers bound true residency.
    """

    def __init__(self, capacity_bytes: int, name: str = "pool"):
        self.capacity_bytes = int(capacity_bytes)
        self.name = name
        self._cv = threading.Condition()
        self._reservations: dict[str, int] = {}
        self._queue: deque = deque()  # FIFO waiter tickets
        #: serving-layer attribution: query_id -> tenant, plus the
        #: per-tenant byte rollup the fairness scheduler's byte quotas
        #: read (server/scheduler.py)
        self._tenant_of: dict[str, str] = {}
        self._tenant_bytes: dict[str, int] = {}
        #: callbacks fired (outside the lock) after every release —
        #: lets the fairness scheduler re-check byte-quota-blocked
        #: waiters the moment capacity frees
        self._release_listeners: list = []

    # ---- observability ---------------------------------------------------
    @property
    def reserved_bytes(self) -> int:
        with self._cv:
            return sum(self._reservations.values())

    @property
    def free_bytes(self) -> int:
        return self.capacity_bytes - self.reserved_bytes

    @property
    def active_count(self) -> int:
        with self._cv:
            return len(self._reservations)

    @property
    def queued_count(self) -> int:
        with self._cv:
            return len(self._queue)

    def reservations(self) -> "dict[str, int]":
        with self._cv:
            return dict(self._reservations)

    def snapshot(self) -> "dict[str, int]":
        """One internally-consistent reading of the pool gauges (a
        single lock acquisition — the ``system.memory_pool`` row must
        not mix states from before and after a concurrent release)."""
        with self._cv:
            reserved = sum(self._reservations.values())
            return {
                "capacity_bytes": self.capacity_bytes,
                "reserved_bytes": reserved,
                "free_bytes": self.capacity_bytes - reserved,
                "active_queries": len(self._reservations),
                "queued_queries": len(self._queue),
            }

    def describe(self) -> str:
        """One-line pool state for admission error messages."""
        with self._cv:
            reserved = sum(self._reservations.values())
            return (
                f"pool {self.name!r}: {reserved}/{self.capacity_bytes} "
                f"bytes reserved by {len(self._reservations)} queries, "
                f"{len(self._queue)} queued"
            )

    def add_release_listener(self, fn) -> None:
        """Register a callback invoked (with no arguments, outside the
        pool lock) after every release."""
        with self._cv:
            self._release_listeners.append(fn)

    def remove_release_listener(self, fn) -> None:
        """Unregister (idempotent) — a scheduler detaching from the
        process-global pool must not stay pinned by its listener."""
        with self._cv:
            try:
                self._release_listeners.remove(fn)
            except ValueError:
                pass

    def tenant_reserved_bytes(self, tenant: str) -> int:
        """Live bytes reserved by queries tagged with ``tenant`` (the
        fairness scheduler's byte-quota operand)."""
        with self._cv:
            return self._tenant_bytes.get(tenant, 0)

    # ---- reserve / release ----------------------------------------------
    def reserve(self, query_id: str, nbytes: int,
                timeout_s: float | None = None, detail: str = "",
                tenant: str | None = None) -> float:
        """Reserve ``nbytes`` for ``query_id``, blocking FIFO until the
        pool has room. Returns the seconds spent queued. Raises
        ``ResourceExhausted`` immediately when the reservation can
        NEVER fit, or after ``timeout_s`` in the queue."""
        from presto_tpu.runtime.errors import ResourceExhausted

        nbytes = max(0, int(nbytes))
        ctx = f" ({detail})" if detail else ""
        if nbytes > self.capacity_bytes:
            REGISTRY.counter("memory.rejected").add()
            raise ResourceExhausted(
                f"admission control: reservation of {nbytes} bytes{ctx} "
                f"exceeds the whole memory pool capacity of "
                f"{self.capacity_bytes} bytes ({self.describe()}; set the "
                "memory_pool_bytes session property to raise it)"
            )
        t0 = time.monotonic()
        deadline = None if timeout_s is None else t0 + timeout_s
        ticket = object()
        waited = False
        with self._cv:
            self._queue.append(ticket)
            try:
                while not (
                    self._queue[0] is ticket
                    and sum(self._reservations.values()) + nbytes
                    <= self.capacity_bytes
                ):
                    remaining = (
                        None if deadline is None
                        else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        REGISTRY.counter("memory.queue_timeouts").add()
                        REGISTRY.counter("memory.queued").add()
                        # the longest waits are exactly the ones that
                        # time out — they must show in the histogram
                        REGISTRY.histogram("memory.queued_s").add(
                            time.monotonic() - t0
                        )
                        raise ResourceExhausted(
                            f"admission queue timeout: {query_id} waited "
                            f"{timeout_s}s to reserve {nbytes} bytes{ctx} "
                            f"({self.describe()}; raise "
                            "admission_queue_timeout_s or "
                            "memory_pool_bytes)"
                        )
                    waited = True
                    self._cv.wait(remaining)
                self._reservations[query_id] = (
                    self._reservations.get(query_id, 0) + nbytes
                )
                if tenant:
                    self._tenant_of[query_id] = tenant
                    self._tenant_bytes[tenant] = (
                        self._tenant_bytes.get(tenant, 0) + nbytes
                    )
            finally:
                self._queue.remove(ticket)
                self._cv.notify_all()
        queued_s = time.monotonic() - t0
        REGISTRY.counter("memory.reserved").add()
        if waited:
            REGISTRY.counter("memory.queued").add()
            REGISTRY.histogram("memory.queued_s").add(queued_s)
        return queued_s

    def release(self, query_id: str) -> int:
        """Drop ``query_id``'s reservation (idempotent; every terminal
        state calls this). Returns the bytes freed."""
        with self._cv:
            freed = self._reservations.pop(query_id, None)
            if freed is not None:
                tenant = self._tenant_of.pop(query_id, None)
                if tenant is not None:
                    left = self._tenant_bytes.get(tenant, 0) - freed
                    if left > 0:
                        self._tenant_bytes[tenant] = left
                    else:
                        self._tenant_bytes.pop(tenant, None)
            listeners = list(self._release_listeners)
            self._cv.notify_all()
        if freed is None:
            return 0
        REGISTRY.counter("memory.released").add()
        for fn in listeners:
            try:
                fn()
            except Exception:  # noqa: BLE001 — listeners never leak back
                pass
        return freed


#: default host-side spill capacity as a multiple of the device budget
#: (host RAM plays the spill-disk role; the ratio mirrors a typical
#: host:HBM memory ratio, overridable per session via the
#: ``spill_host_budget_bytes`` property)
DEFAULT_HOST_SPILL_FACTOR = 16


class HostSpillBudget:
    """Byte budget over HOST-side spill state (exec/grouped.HostSpill).

    The out-of-core tier's "disk" is host RAM, which before this class
    grew invisibly: every spilled partition chunk now reserves its
    bytes here under a per-store TAG (the tenant-tag discipline of
    :class:`MemoryPool`), and overflow raises the typed
    ``SpillBudgetExceeded`` instead of silently eating the host.
    Reservations are additive per tag; ``release`` clamps and is
    idempotent (success and fault paths both release in ``finally``)."""

    def __init__(self, capacity_bytes: int, name: str = "host-spill"):
        self.capacity_bytes = int(capacity_bytes)
        self.name = name
        self._lock = threading.Lock()
        self._tags: dict[str, int] = {}
        self.peak_bytes = 0

    @property
    def reserved_bytes(self) -> int:
        with self._lock:
            return sum(self._tags.values())

    def snapshot(self) -> "dict":
        with self._lock:
            reserved = sum(self._tags.values())
            return {
                "capacity_bytes": self.capacity_bytes,
                "reserved_bytes": reserved,
                "free_bytes": self.capacity_bytes - reserved,
                "tags": dict(self._tags),
                "peak_bytes": self.peak_bytes,
            }

    def reserve(self, tag: str, nbytes: int) -> None:
        """Add ``nbytes`` to ``tag``'s reservation, or fail typed and
        loud when the total would exceed capacity."""
        from presto_tpu.runtime.errors import SpillBudgetExceeded

        nbytes = max(0, int(nbytes))
        with self._lock:
            total = sum(self._tags.values()) + nbytes
            if total > self.capacity_bytes:
                REGISTRY.counter("spill.host_rejected").add()
                raise SpillBudgetExceeded(
                    f"host spill budget {self.name!r}: reserving {nbytes} "
                    f"more bytes for {tag!r} would hold {total} of "
                    f"{self.capacity_bytes} capacity (raise the "
                    "spill_host_budget_bytes session property)"
                )
            self._tags[tag] = self._tags.get(tag, 0) + nbytes
            self.peak_bytes = max(self.peak_bytes, total)

    def release(self, tag: str, nbytes: int | None = None) -> int:
        """Drop ``nbytes`` of ``tag``'s reservation (all of it when
        None). Clamped and idempotent; returns the bytes freed."""
        with self._lock:
            held = self._tags.get(tag, 0)
            freed = held if nbytes is None else min(held, max(0, int(nbytes)))
            left = held - freed
            if left > 0:
                self._tags[tag] = left
            else:
                self._tags.pop(tag, None)
            return freed


_GLOBAL_HOST_SPILL: HostSpillBudget | None = None

_GLOBAL_POOL: MemoryPool | None = None
_GLOBAL_POOL_LOCK = threading.Lock()


def global_host_spill_budget() -> HostSpillBudget:
    """The process-wide default host-spill budget (sessions without a
    ``spill_host_budget_bytes`` override account against it). Sized
    lazily so the device-budget snapshot rule holds."""
    global _GLOBAL_HOST_SPILL
    with _GLOBAL_POOL_LOCK:
        if _GLOBAL_HOST_SPILL is None:
            _GLOBAL_HOST_SPILL = HostSpillBudget(
                device_budget_bytes() * DEFAULT_HOST_SPILL_FACTOR,
                name="global-host-spill",
            )
        return _GLOBAL_HOST_SPILL


def global_pool() -> MemoryPool:
    """The process-wide default pool every Session without an explicit
    pool (or ``memory_pool_bytes`` override) arbitrates through —
    concurrent sessions in one process share the device, so they share
    the pool. Sized lazily at first use."""
    global _GLOBAL_POOL
    with _GLOBAL_POOL_LOCK:
        if _GLOBAL_POOL is None:
            _GLOBAL_POOL = MemoryPool(
                device_budget_bytes() * DEFAULT_POOL_HEADROOM, name="global"
            )
        return _GLOBAL_POOL


def pool_leaks() -> "dict[str, int]":
    """Reservations still held in the global pool (the test-suite
    leak-check: every terminal query state must have released)."""
    return {} if _GLOBAL_POOL is None else _GLOBAL_POOL.reservations()


def column_bytes(dtype: DataType) -> int:
    """Per-row device bytes of a column (data + validity mask)."""
    if dtype.kind is TypeKind.BYTES:
        return dtype.width + 1
    return dtype.np_dtype.itemsize + 1


def node_row_bytes(node: N.PlanNode, catalog=None) -> int:
    """Per-row device bytes of a node's output (+1 for the live mask).

    With a ``catalog``, columns that resolve to a source scan column
    count at their narrowed PHYSICAL width (the storage the scan
    actually materializes), so admission estimates and join-build
    budget decisions track real device bytes instead of canonical
    widths; computed columns stay canonical (arithmetic widens)."""
    total = 1
    for f in node.fields:
        dt = f.dtype
        if catalog is not None and not dt.is_narrowed:
            dt = _physical_field_type(node, f.name, dt, catalog)
        total += column_bytes(dt)
    return total


def _physical_field_type(node, name: str, dtype: DataType, catalog) -> DataType:
    from presto_tpu.plan.bounds import resolve_source_column

    src = resolve_source_column(node, name)
    if src is None:
        return dtype
    conn = catalog.connectors.get(src[0])
    if conn is None or not hasattr(conn, "physical_schema"):
        return dtype
    try:
        return conn.physical_schema(src[1], [src[2]])[src[2]]
    except KeyError:
        return dtype


def estimate_node_bytes(node: N.PlanNode, catalog, memo=None) -> int:
    """Estimated device-resident bytes if the node's output were fully
    materialized (stats-based, physical-width-aware; the
    grouped-execution trigger). ``memo``: optional per-walk estimate
    cache (plan/bounds.estimate_rows)."""
    from presto_tpu.plan.bounds import estimate_rows

    return estimate_rows(node, catalog, memo) * node_row_bytes(node, catalog)
