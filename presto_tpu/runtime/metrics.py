"""Process-wide metrics registry.

Reference parity: Airlift's ``@Managed`` JMX beans — ``CounterStat``,
``TimeStat``, ``DistributionStat`` — exported by every subsystem and
queryable live through the JMX connector [SURVEY §5.5; reference tree
unavailable]. Single-process, single-controller: a flat registry of
named counters/timers/histograms, exposed as the
``system.runtime_metrics`` table, snapshot-able as JSON, and
exportable as OpenMetrics/Prometheus text (:func:`to_openmetrics`,
surfaced by ``Session.export_metrics`` and ``python -m presto_tpu
metrics``).

Thread safety: event listeners and prefetch workers may bump stats off
the driver thread, so every ``add`` is atomic under a per-stat lock
(the registry lock only guards map creation). ``HistogramStat`` is the
``DistributionStat`` role on fixed buckets — p50/p95/p99 appear in
snapshots — and hot timers (query execution, fragment dispatch,
exchange dispatch, cache lookups) record onto it.

Per-query attribution: the registry is process-global, so a raw
before/after snapshot diff cannot attribute a counter move to a query
once queries run concurrently. :class:`QueryMetricsDelta` closes that
gap at the ``add`` site: the lifecycle layer installs a delta
collector in a ``ContextVar`` around each query's ``run_plan`` scope,
and every stat ``add`` ALSO lands in the collector of the context it
ran under. Concurrent queries on separate driver threads carry
separate contexts, so their deltas never bleed — the global totals
stay the union. Adds from threads outside any query context (prefetch
workers, like trace spans) update only the global stat; attribution is
driver-thread-observed by design.
"""

from __future__ import annotations

import bisect
import threading
import time
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Optional


_DELTA: ContextVar[Optional["QueryMetricsDelta"]] = ContextVar(
    "presto_tpu_metrics_delta", default=None
)


class QueryMetricsDelta:
    """A query-scoped view of every stat moved while this collector was
    installed (``install_delta``/``uninstall_delta``). Counters land
    under their plain name; timers under ``name.count``/``name.total_s``;
    histograms under ``name.count``/``name.total`` — the same key shapes
    ``MetricsRegistry.snapshot`` uses, so delta dicts and snapshot
    diffs read identically. Locked: event listeners may add from a
    thread that inherited the query's context."""

    __slots__ = ("_vals", "_lock")

    def __init__(self):
        self._vals: dict[str, float] = {}
        self._lock = threading.Lock()

    def add(self, name: str, v: float) -> None:
        with self._lock:
            self._vals[name] = self._vals.get(name, 0.0) + v

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._vals)


def install_delta(collector: Optional[QueryMetricsDelta]):
    """Install ``collector`` as the context's delta sink; returns the
    reset token (nested queries from event listeners install their own
    and restore the outer one on exit)."""
    return _DELTA.set(collector)


def uninstall_delta(token) -> None:
    _DELTA.reset(token)


def current_delta() -> Optional[QueryMetricsDelta]:
    return _DELTA.get()


@dataclass
class CounterStat:
    name: str
    total: float = 0.0
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def add(self, v: float = 1.0):
        with self._lock:
            self.total += v
        d = _DELTA.get()
        if d is not None:
            d.add(self.name, v)


@dataclass
class TimeStat:
    """Wall-time accumulator with count/total/min/max (the digest role
    of Airlift's TimeStat, without decaying percentiles)."""

    name: str
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def add(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.min_s = min(self.min_s, seconds)
            self.max_s = max(self.max_s, seconds)
        d = _DELTA.get()
        if d is not None:
            d.add(self.name + ".count", 1.0)
            d.add(self.name + ".total_s", seconds)

    def time(self):
        return _Timer(self)


#: default histogram bucket upper bounds: geometric, 10us..100s in
#: quarter-decade steps (wall times of everything from a span append to
#: a cold distributed compile land inside; the last bucket is +inf)
DEFAULT_BOUNDS = tuple(10.0 ** (-5 + i * 0.25) for i in range(29))

#: ratio-shaped bounds for fraction metrics (selectivities, hit rates):
#: values live on [0, 1], where the latency buckets would dump
#: everything below 1.0 into two cells and destroy the percentiles
SELECTIVITY_BOUNDS = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0)

#: ratio-shaped bounds for the exchange-skew histogram: max/mean
#: delivered rows per destination lives on [1, mesh size] (1 =
#: balanced, P = one hot partition owns everything) — latency buckets
#: would crush the whole range into two cells
SKEW_BOUNDS = (1.0, 1.1, 1.25, 1.5, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0,
               16.0, 32.0)

#: per-metric bucket shapes — THE place a histogram's boundary choice
#: lives. ``MetricsRegistry.histogram(name)`` resolves bounds here, so
#: every call site of a named metric agrees by construction (bounds are
#: fixed at first creation; a second caller passing different explicit
#: bounds would silently get the first shape). Latency-shaped
#: DEFAULT_BOUNDS is the fallback for everything unlisted.
HISTOGRAM_BOUNDS: dict[str, tuple] = {
    "join.filter_selectivity": SELECTIVITY_BOUNDS,
    "exchange.skew": SKEW_BOUNDS,
    "spill.resident_fraction": SELECTIVITY_BOUNDS,
}


class HistogramStat:
    """Fixed-bucket histogram with percentile snapshots.

    Values land in the first bucket whose upper bound is >= v (the last
    bucket is unbounded). Percentiles report the matched bucket's upper
    bound — a conservative (never under-reporting) estimate; the exact
    observed max is tracked separately.
    """

    __slots__ = ("name", "bounds", "counts", "count", "total", "max",
                 "_lock")

    def __init__(self, name: str, bounds: tuple = DEFAULT_BOUNDS):
        self.name = name
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self._lock = threading.Lock()

    def add(self, v: float):
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.total += v
            if v > self.max:
                self.max = v
        d = _DELTA.get()
        if d is not None:
            d.add(self.name + ".count", 1.0)
            d.add(self.name + ".total", v)

    def time(self):
        return _Timer(self)

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile (0 when
        empty; the exact max for the overflow bucket)."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                return self.bounds[i] if i < len(self.bounds) else self.max
        return self.max

    def snapshot_into(self, out: dict) -> None:
        out[self.name + ".count"] = float(self.count)
        out[self.name + ".total"] = self.total
        if self.count:
            out[self.name + ".p50"] = self.quantile(0.50)
            out[self.name + ".p95"] = self.quantile(0.95)
            out[self.name + ".p99"] = self.quantile(0.99)
            out[self.name + ".max"] = self.max


class _Timer:
    def __init__(self, stat):
        self.stat = stat

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.stat.add(time.perf_counter() - self.t0)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, CounterStat] = {}
        self.timers: dict[str, TimeStat] = {}
        self.histograms: dict[str, HistogramStat] = {}

    def counter(self, name: str) -> CounterStat:
        with self._lock:
            if name not in self.counters:
                self.counters[name] = CounterStat(name)
            return self.counters[name]

    def timer(self, name: str) -> TimeStat:
        with self._lock:
            if name not in self.timers:
                self.timers[name] = TimeStat(name)
            return self.timers[name]

    def histogram(self, name: str,
                  bounds: Optional[tuple] = None) -> HistogramStat:
        """``bounds=None`` resolves the metric's registered shape from
        ``HISTOGRAM_BOUNDS`` (latency-shaped default) — call sites of a
        named metric need not, and should not, repeat its boundaries."""
        with self._lock:
            if name not in self.histograms:
                if bounds is None:
                    bounds = HISTOGRAM_BOUNDS.get(name, DEFAULT_BOUNDS)
                self.histograms[name] = HistogramStat(name, bounds)
            return self.histograms[name]

    def reset(self) -> None:
        """Drop every stat (test isolation; live handles from before a
        reset keep counting into detached objects, so re-fetch by name
        after resetting)."""
        with self._lock:
            self.counters.clear()
            self.timers.clear()
            self.histograms.clear()

    def snapshot(self) -> dict:
        out: dict[str, float] = {}
        for c in self.counters.values():
            out[c.name] = c.total
        for t in self.timers.values():
            out[t.name + ".count"] = float(t.count)
            out[t.name + ".total_s"] = t.total_s
            if t.count:
                out[t.name + ".min_s"] = t.min_s
                out[t.name + ".max_s"] = t.max_s
        for h in self.histograms.values():
            h.snapshot_into(out)
        return out


#: the process registry (reference: the JMX MBean server)
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# OpenMetrics / Prometheus text exposition
# ---------------------------------------------------------------------------

#: metric-name prefix in the exposition (the reference's JMX beans map
#: to a prometheus-jmx namespace the same way)
EXPOSITION_PREFIX = "presto_tpu_"


def _metric_name(name: str) -> str:
    """Engine metric name -> exposition family name: dots and dashes
    become underscores (the only characters our names use outside
    ``[a-zA-Z0-9_]``)."""
    return EXPOSITION_PREFIX + name.replace(".", "_").replace("-", "_")


def _fmt(v: float) -> str:
    """Canonical sample value: integral floats print as integers
    (OpenMetrics allows either; stable text diffs nicely)."""
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


#: ``# HELP`` text per exposition family (post-prefix engine names).
#: EVERY literal family the engine fires has an entry — enforced by
#: tests/test_health.py's completeness check, which greps the source
#: for literal ``REGISTRY.counter/timer/histogram("...")`` names.
#: Dynamically-suffixed families (f-string names: per-tenant, per-
#: trigger, per-reason, per-device) stay HELP-less (OpenMetrics
#: allows it) — their prefix documents them here via the base family.
METRIC_HELP: dict[str, str] = {
    # ---- aggregation strategy picks
    "agg.strategy.bypass": (
        "aggregations answered straight from incremental table stats "
        "(no scan dispatched)"),
    "agg.strategy.fused": "aggregations fused into the scan kernel",
    "agg.strategy.partial": (
        "aggregations executed partial-per-fragment then merged"),
    "agg.strategy.single": (
        "aggregations executed single-stage on gathered rows"),
    # ---- cross-query batched dispatch (server/batcher.py)
    "batch.dispatched": "vmapped cross-query batch dispatches",
    "batch.fallback": (
        "batch members served by per-query fallback instead of the "
        "vmapped program (reasons: batch.fallback.*)"),
    "batch.fallback.distributed": (
        "batch fallbacks because the template planned distributed"),
    "batch.fallback.error": (
        "batch fallbacks because the vmapped dispatch raised"),
    "batch.gate_timeout": (
        "batch-gate waits that timed out and ran solo"),
    "batch.queries": "queries that entered the template batch gate",
    "batch.served": (
        "queries served a result from a cross-query batched dispatch"),
    "batch.size": "lanes per dispatched cross-query batch",
    "batch.trimmed": (
        "batch members trimmed because the gate filled past the "
        "vmap width"),
    # ---- caches
    "cache.result_lookup_s": "result-cache lookup latency",
    "exec_cache.evicted": "compiled-executable cache evictions",
    "exec_cache.hit": "compiled-executable cache hits",
    "exec_cache.miss": "compiled-executable cache misses",
    "exec_cache.uncacheable": (
        "executables not cached (non-hashable or oversized keys)"),
    "result_cache.evicted": "result-cache evictions",
    "result_cache.hit": "result-cache hits (no execution dispatched)",
    "result_cache.invalidated": (
        "result-cache entries dropped by DDL/version invalidation"),
    "result_cache.miss": "result-cache misses",
    "result_cache.populated": "result-cache entries populated",
    "result_cache.skipped": (
        "result-cache lookups skipped (volatile scans or caching off)"),
    "result_cache.uncacheable": (
        "results not cached (oversized or non-deterministic)"),
    "stats_cache.hit": "incremental table-stats cache hits",
    "stats_cache.miss": "incremental table-stats cache misses",
    "joinkeys.minmax_memo_hits": (
        "join-key min/max pruning memo hits (plan_stats-backed)"),
    # ---- events / listeners
    "events.listener_errors": (
        "query-event listener callbacks that raised (isolated; the "
        "query is unaffected)"),
    # ---- exchange
    "exchange.bytes": "bytes moved through partitioned exchanges",
    "exchange.dispatch_s": "partitioned-exchange dispatch latency",
    "exchange.dispatches": "partitioned-exchange dispatches",
    "exchange.rounds": "exchange rounds executed",
    "exchange.skew": (
        "max/mean delivered-rows-per-destination ratio of each "
        "partitioned exchange (1 = balanced)"),
    "exchange.quota_overflow": (
        "exchanges whose receive capacity overflowed (the hot "
        "partition id rides the trace span and flight record)"),
    # ---- executor routes
    "exec.leaf_fused_route": (
        "leaf fragments routed through the fused scan kernel"),
    "exec.leaf_route_fallback": (
        "leaf fused-route bailouts to the general path (reasons: "
        "exec.leaf_route_fallback.*)"),
    "exec.pallas_join_route": "joins routed through the Pallas kernel",
    "exec.q1_fused_route": (
        "aggregation queries routed through the fused Q1-shape kernel"),
    "exec.q1_route_fallback": (
        "Q1-shape route bailouts to the general aggregation path"),
    "exec.traces": "actual jit traces executed (the no-retrace probe)",
    "exec.trace_errors": (
        "best-effort trace/observability plumbing failures (the "
        "query is unaffected)"),
    # ---- flight recorder
    "flight.captured": "flight-recorder post-mortems captured",
    "flight.capture_errors": (
        "flight-recorder captures that failed (capture is best-effort; "
        "the query is unaffected)"),
    # ---- fragments / lifecycle
    "fragment.dispatch_s": "per-fragment dispatch latency",
    "fragment.retried": "fragment dispatches retried after failure",
    "query.admission_rejected": (
        "queries rejected at memory-pool admission"),
    "query.backend_oom": "backend out-of-memory errors observed",
    "query.completed": "queries reaching a terminal state",
    "query.deadline_exceeded": (
        "queries killed by query_max_run_time"),
    "query.degraded_to_local": (
        "distributed plans degraded to local execution"),
    "query.execution_s": "query execution latency (admitted -> done)",
    "query.failed": "queries reaching FAILED",
    "query.oom_degraded": (
        "queries that finished only after OOM-ladder degradation"),
    "query.retried": "whole-query retries",
    "query.started": "queries admitted to execution",
    # ---- health watchdog / SLOs (runtime/health.py)
    "health.breach": (
        "health-watchdog breaches fired (each arms the flight "
        "recorder; reasons: health.breach.*)"),
    "health.breach_no_inflight": (
        "health breaches with no in-flight query to capture"),
    "health.sample_errors": (
        "health-watchdog sampling passes that raised (isolated)"),
    "slo.good": "SLO observations within objective (all tenants)",
    "slo.breach": "SLO observations over objective (all tenants)",
    # ---- join strategy
    "join.filter_rows_in": (
        "probe rows entering join-pushdown filters"),
    "join.filter_rows_pruned": (
        "probe rows pruned by join-pushdown filters"),
    "join.filter_selectivity": (
        "observed selectivity of join-pushdown filters"),
    "join.pallas_fallback": (
        "Pallas join routes that fell back to the general kernel"),
    # ---- memory pool
    "memory.queue_timeouts": (
        "pool admissions that timed out waiting for capacity"),
    "memory.queued": "pool admissions that had to queue",
    "memory.queued_s": "time spent queued for pool capacity",
    "memory.rejected": "pool reservations rejected outright",
    "memory.released": "pool reservations released",
    "memory.reserved": "pool reservations granted",
    # ---- adaptive execution (plan/adaptive.py)
    "adaptive.salted": (
        "repartition joins rewritten with skew salting (hot "
        "destination split across S salted partitions, matching "
        "build rows replicated)"),
    "adaptive.join_flip": (
        "join builds re-sized from recorded actuals (grouped vs "
        "in-memory re-decided from history, not the static estimate)"),
    "adaptive.bucket_override": (
        "grouped aggregations re-sized from recorded actuals "
        "(bucket counts from history, not the static estimate)"),
    "adaptive.route_disabled": (
        "fused (Pallas) join routes disabled because the "
        "fingerprint's route fell back at runtime (lying stats)"),
    "adaptive.compile_budget_refused": (
        "adaptive re-specializations refused because predicted "
        "compile cost exceeded predicted win at the observed "
        "recurrence rate"),
    "adaptive.stand_down": (
        "adaptive decision passes suppressed under an active fault "
        "injector or success-capture recorder (baseline plans only)"),
    "adaptive.warmed": (
        "top-K templates background-warmed by the serving layer so "
        "adaptivity never injects a cold compile into steady state"),
    # ---- plan stats
    "plan_stats.evicted": "plan-stats fingerprints evicted",
    "plan_stats.invalidated": (
        "plan-stats fingerprints dropped by DDL/version invalidation"),
    "plan_stats.record_errors": (
        "plan-stats recording failures (isolated)"),
    "plan_stats.recorded": "plan-stats runs recorded",
    "plan_stats.imported": (
        "plan-stats entries imported from a previous run's export "
        "(Session.import_plan_stats — adaptivity warm restart)"),
    "plan_stats.import_stale": (
        "imported plan-stats entries skipped because their recorded "
        "table versions no longer match the catalog"),
    # ---- prepared statements / templates
    "prepare.coalesced": (
        "executions coalesced onto an identical in-flight run"),
    "prepare.slot_ineligible": (
        "literals not auto-templated into binding slots (reasons: "
        "prepare.slot_ineligible.*)"),
    "prepare.slots_bound": "template binding slots bound per execution",
    "prepare.template_hit": (
        "executions whose plan template was already compiled-warm"),
    "prepare.template_queued": (
        "executions that waited at the template batch gate"),
    # ---- scan
    "scan.splits_sampled_out": (
        "table-scan splits skipped by approx-mode sampled scans "
        "(approx_scan_fraction < 1; results flagged approximate)"),
    # ---- serving front-end
    "server.failed": "submitted statements reaching FAILED",
    "server.shutdowns": "server shutdown/drain sequences run",
    "server.started": "HTTP front-ends started",
    "server.submit_rejected": (
        "statement submissions rejected by the submit_limit "
        "backpressure bound"),
    "server.submitted": "statements accepted via submit()",
    "tenant.admitted": "fair-scheduler slot admissions (all tenants)",
    "tenant.over_quota_blocked": (
        "admissions blocked on a tenant byte/concurrency quota"),
    "tenant.overflow": (
        "walk-in tenant names pooled into the __overflow__ lane "
        "(max_tenants cardinality bound)"),
    "tenant.queue_timeouts": "fair-queue waits that timed out",
    "tenant.queued": "admissions that had to queue (all tenants)",
    "tenant.queued_s": "time spent queued in the fair scheduler",
    # ---- trace
    "trace.spans_dropped": (
        "spans dropped by per-query recorder ring bounds"),
    # ---- live gauges (exported via Session.export_metrics)
    "memory_pool_reserved_bytes": (
        "bytes currently reserved from the session's memory pool"),
    "memory_pool_capacity_bytes": "capacity of the session's memory pool",
    "memory_pool_occupancy": (
        "reserved/capacity fraction of the session's memory pool"),
    "exec_cache_entries": (
        "entries in the process-wide compiled-executable cache "
        "(ledger: system.exec_cache)"),
    "flight_recorder_depth": (
        "post-mortem records currently retained in the session's "
        "flight-recorder ring"),
    "health.ring_depth": "samples in the health watchdog's vitals ring",
    "health.breaches": "breach events retained by the health watchdog",
    "health.qps": "last-sampled completed-queries-per-second",
    "health.p99_s": "last-sampled p99 execution latency",
    "health.queue_depth": "last-sampled admission-queue depth",
    "health.freshness_lag_s": (
        "last-sampled worst subscription delivery lag"),
    "health.slo_burn": "last-sampled worst tenant SLO burn rate",
    "spill.planned_hybrid": (
        "joins/aggregations planned as hybrid spill (hot partitions "
        "device-resident, cold ones streamed from host)"),
    "spill.planned_grouped": (
        "joins/aggregations planned as fully-grouped spill (no "
        "resident partitions)"),
    "spill.partitions_resident": (
        "build partitions kept device-resident by hybrid spill plans"),
    "spill.partitions_streamed": (
        "build partitions streamed host->device by spill plans"),
    "spill.resident_fraction": (
        "resident/total partition fraction of each hybrid spill plan"),
    "spill.partition_overflow": (
        "cold spill partitions recursively re-partitioned because "
        "they exceeded the per-unit byte budget"),
    "spill.transfer_bytes": (
        "host->device bytes moved by the spill transfer pipeline"),
    "spill.host_rejected": (
        "host-spill reservations refused by spill_host_budget_bytes "
        "(typed SPILL_BUDGET_EXCEEDED failures)"),
    "stream.appends": (
        "micro-batch appends landed on streaming tables (each bumps "
        "the table's version epoch)"),
    "stream.rows": "rows ingested by micro-batch appends",
    "stream.dict_rebuilds": (
        "VARCHAR dictionary merges forced by appends introducing "
        "unseen values (old codes remapped in place)"),
    "stream.append_s": (
        "append latency: encode + incremental stats merge + publish"),
    "stream.tables_created": "streaming tables created",
    "subscription.fired": (
        "continuous-query refreshes delivered (initial, epoch-driven, "
        "and interval ticks — see subscription.trigger.*)"),
    "subscription.refresh_failed": (
        "continuous-query refreshes that failed (typed failures "
        "re-arm the fire; untyped ones fail the subscription)"),
    "subscription.stale_blocked": (
        "refresh results DROPPED because the executing session read a "
        "table version older than the fire-time epoch floor"),
    "subscription.drain_blocked": (
        "refreshes dropped because the server was draining "
        "(subscriptions stay active for a restarted server)"),
    "subscription.refresh_s": (
        "continuous-query refresh latency: fire decision -> result "
        "delivered to the subscription's ring"),
    "subscription.created": "continuous queries registered",
    "subscription.cancelled": "continuous queries cancelled",
    # ---- overload control (runtime/overload.py) ----
    "overload.shed": (
        "submissions refused at admission with the retryable "
        "SERVER_OVERLOADED (queue ceilings, EWMA drain estimate, or "
        "brown-out shed policy; per-cause split in overload."
        "shed_reason.*, per-tenant in overload.shed_tenant.*)"),
    "overload.shed_reason.brownout": (
        "submissions shed because the brown-out latch was engaged and "
        "the tenant's brownout policy is 'shed'"),
    "overload.retry_budget_exhausted": (
        "retries denied by the per-session retry token bucket / open "
        "circuit breaker (the caller fails fast with its original "
        "error instead of retrying)"),
    "overload.breaker_open": (
        "retry circuit breaker OPEN transitions (the token bucket "
        "drained — correlated failures outpaced the refill)"),
    "overload.breaker_probe": (
        "half-open probe retries granted after the breaker cooldown "
        "(exactly one in-flight probe at a time)"),
    "overload.breaker_rearm": (
        "breaker CLOSED transitions: a half-open probe succeeded, the "
        "token bucket refilled"),
    "cancel.requested": (
        "CancelScope flips (DELETE /v1/statement, Session.cancel, or "
        "the overload controller) — first flip per query only"),
    "cancel.observed": (
        "cancelled queries that reached a cooperative checkpoint and "
        "raised the typed QUERY_CANCELLED (first observation per "
        "query)"),
    "server.cancel_requests": (
        "cancel requests accepted by the serving layer for non-"
        "terminal submitted queries"),
    "brownout.engaged": (
        "brown-out latch engagements (health breach or operator "
        "force): eligible tenants' NEW traffic degrades per their "
        "TenantSpec.brownout policy"),
    "brownout.recovered": (
        "brown-out latch releases after a breach-free cooldown (or "
        "the operator clearing brownout_force)"),
    "brownout.approx_routed": (
        "submissions routed to the approximate tier by an engaged "
        "brown-out (flagged approximate on every poll page)"),
}


def _help_line(lines: list, engine_name: str, family: str) -> None:
    text = METRIC_HELP.get(engine_name)
    if text:
        lines.append(f"# HELP {family} {text}")


def to_openmetrics(registry: MetricsRegistry = None,
                   gauges: Optional[dict] = None) -> str:
    """The registry as OpenMetrics/Prometheus text exposition.

    - counters -> ``# TYPE f counter`` with one ``f_total`` sample;
    - timers -> ``# TYPE f_seconds summary`` (``_count``/``_sum``) plus
      ``f_seconds_min``/``_max`` gauges (TimeStat keeps no quantiles);
    - histograms -> ``# TYPE f summary`` with ``quantile`` labels
      (p50/p95/p99 — bucket upper bounds, conservative) plus
      ``_count``/``_sum`` and an ``f_max`` gauge;
    - ``gauges`` (name -> live value, e.g. memory-pool occupancy or
      cache entry counts — state a monotone counter cannot express)
      -> ``# TYPE f gauge`` with one sample each.

    Known families also carry a ``# HELP`` line (:data:`METRIC_HELP`).
    Families are emitted in sorted name order and the text ends with
    ``# EOF`` (the OpenMetrics terminator), so the output is both
    scrape-able and deterministic for golden tests.
    """
    reg = REGISTRY if registry is None else registry
    lines: list[str] = []
    for c in sorted(reg.counters.values(), key=lambda s: s.name):
        f = _metric_name(c.name)
        _help_line(lines, c.name, f)
        lines.append(f"# TYPE {f} counter")
        lines.append(f"{f}_total {_fmt(c.total)}")
    for t in sorted(reg.timers.values(), key=lambda s: s.name):
        f = _metric_name(t.name) + "_seconds"
        _help_line(lines, t.name, f)
        lines.append(f"# TYPE {f} summary")
        lines.append(f"{f}_count {_fmt(t.count)}")
        lines.append(f"{f}_sum {_fmt(t.total_s)}")
        if t.count:
            lines.append(f"# TYPE {f}_min gauge")
            lines.append(f"{f}_min {_fmt(t.min_s)}")
            lines.append(f"# TYPE {f}_max gauge")
            lines.append(f"{f}_max {_fmt(t.max_s)}")
    for h in sorted(reg.histograms.values(), key=lambda s: s.name):
        f = _metric_name(h.name)
        _help_line(lines, h.name, f)
        lines.append(f"# TYPE {f} summary")
        for q in (0.5, 0.95, 0.99):
            lines.append(f'{f}{{quantile="{q}"}} {_fmt(h.quantile(q))}')
        lines.append(f"{f}_count {_fmt(h.count)}")
        lines.append(f"{f}_sum {_fmt(h.total)}")
        if h.count:
            lines.append(f"# TYPE {f}_max gauge")
            lines.append(f"{f}_max {_fmt(h.max)}")
    for name in sorted(gauges or ()):
        f = _metric_name(name)
        _help_line(lines, name, f)
        lines.append(f"# TYPE {f} gauge")
        lines.append(f"{f} {_fmt(gauges[name])}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"
