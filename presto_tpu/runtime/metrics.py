"""Process-wide metrics registry.

Reference parity: Airlift's ``@Managed`` JMX beans — ``CounterStat``,
``TimeStat``, ``DistributionStat`` — exported by every subsystem and
queryable live through the JMX connector [SURVEY §5.5; reference tree
unavailable]. Single-process, single-controller: a flat registry of
named counters/timers/histograms, exposed as the
``system.runtime_metrics`` table and snapshot-able as JSON.

Thread safety: event listeners and prefetch workers may bump stats off
the driver thread, so every ``add`` is atomic under a per-stat lock
(the registry lock only guards map creation). ``HistogramStat`` is the
``DistributionStat`` role on fixed buckets — p50/p95/p99 appear in
snapshots — and hot timers (query execution, fragment dispatch,
exchange dispatch, cache lookups) record onto it.
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass, field


@dataclass
class CounterStat:
    name: str
    total: float = 0.0
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def add(self, v: float = 1.0):
        with self._lock:
            self.total += v


@dataclass
class TimeStat:
    """Wall-time accumulator with count/total/min/max (the digest role
    of Airlift's TimeStat, without decaying percentiles)."""

    name: str
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def add(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.min_s = min(self.min_s, seconds)
            self.max_s = max(self.max_s, seconds)

    def time(self):
        return _Timer(self)


#: default histogram bucket upper bounds: geometric, 10us..100s in
#: quarter-decade steps (wall times of everything from a span append to
#: a cold distributed compile land inside; the last bucket is +inf)
DEFAULT_BOUNDS = tuple(10.0 ** (-5 + i * 0.25) for i in range(29))


class HistogramStat:
    """Fixed-bucket histogram with percentile snapshots.

    Values land in the first bucket whose upper bound is >= v (the last
    bucket is unbounded). Percentiles report the matched bucket's upper
    bound — a conservative (never under-reporting) estimate; the exact
    observed max is tracked separately.
    """

    __slots__ = ("name", "bounds", "counts", "count", "total", "max",
                 "_lock")

    def __init__(self, name: str, bounds: tuple = DEFAULT_BOUNDS):
        self.name = name
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self._lock = threading.Lock()

    def add(self, v: float):
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.total += v
            if v > self.max:
                self.max = v

    def time(self):
        return _Timer(self)

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile (0 when
        empty; the exact max for the overflow bucket)."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                return self.bounds[i] if i < len(self.bounds) else self.max
        return self.max

    def snapshot_into(self, out: dict) -> None:
        out[self.name + ".count"] = float(self.count)
        out[self.name + ".total"] = self.total
        if self.count:
            out[self.name + ".p50"] = self.quantile(0.50)
            out[self.name + ".p95"] = self.quantile(0.95)
            out[self.name + ".p99"] = self.quantile(0.99)
            out[self.name + ".max"] = self.max


class _Timer:
    def __init__(self, stat):
        self.stat = stat

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.stat.add(time.perf_counter() - self.t0)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, CounterStat] = {}
        self.timers: dict[str, TimeStat] = {}
        self.histograms: dict[str, HistogramStat] = {}

    def counter(self, name: str) -> CounterStat:
        with self._lock:
            if name not in self.counters:
                self.counters[name] = CounterStat(name)
            return self.counters[name]

    def timer(self, name: str) -> TimeStat:
        with self._lock:
            if name not in self.timers:
                self.timers[name] = TimeStat(name)
            return self.timers[name]

    def histogram(self, name: str,
                  bounds: tuple = DEFAULT_BOUNDS) -> HistogramStat:
        with self._lock:
            if name not in self.histograms:
                self.histograms[name] = HistogramStat(name, bounds)
            return self.histograms[name]

    def reset(self) -> None:
        """Drop every stat (test isolation; live handles from before a
        reset keep counting into detached objects, so re-fetch by name
        after resetting)."""
        with self._lock:
            self.counters.clear()
            self.timers.clear()
            self.histograms.clear()

    def snapshot(self) -> dict:
        out: dict[str, float] = {}
        for c in self.counters.values():
            out[c.name] = c.total
        for t in self.timers.values():
            out[t.name + ".count"] = float(t.count)
            out[t.name + ".total_s"] = t.total_s
            if t.count:
                out[t.name + ".min_s"] = t.min_s
                out[t.name + ".max_s"] = t.max_s
        for h in self.histograms.values():
            h.snapshot_into(out)
        return out


#: the process registry (reference: the JMX MBean server)
REGISTRY = MetricsRegistry()
