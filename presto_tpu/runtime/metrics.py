"""Process-wide metrics registry.

Reference parity: Airlift's ``@Managed`` JMX beans — ``CounterStat``,
``TimeStat``, ``DistributionStat`` — exported by every subsystem and
queryable live through the JMX connector [SURVEY §5.5; reference tree
unavailable]. Single-process, single-controller: a flat registry of
named counters/timers, exposed as the ``system.runtime_metrics`` table
and snapshot-able as JSON.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class CounterStat:
    name: str
    total: float = 0.0

    def add(self, v: float = 1.0):
        self.total += v


@dataclass
class TimeStat:
    """Wall-time accumulator with count/total/min/max (the digest role
    of Airlift's TimeStat, without decaying percentiles)."""

    name: str
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0

    def add(self, seconds: float):
        self.count += 1
        self.total_s += seconds
        self.min_s = min(self.min_s, seconds)
        self.max_s = max(self.max_s, seconds)

    def time(self):
        return _Timer(self)


class _Timer:
    def __init__(self, stat: TimeStat):
        self.stat = stat

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.stat.add(time.perf_counter() - self.t0)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, CounterStat] = {}
        self.timers: dict[str, TimeStat] = {}

    def counter(self, name: str) -> CounterStat:
        with self._lock:
            if name not in self.counters:
                self.counters[name] = CounterStat(name)
            return self.counters[name]

    def timer(self, name: str) -> TimeStat:
        with self._lock:
            if name not in self.timers:
                self.timers[name] = TimeStat(name)
            return self.timers[name]

    def snapshot(self) -> dict:
        out: dict[str, float] = {}
        for c in self.counters.values():
            out[c.name] = c.total
        for t in self.timers.values():
            out[t.name + ".count"] = float(t.count)
            out[t.name + ".total_s"] = t.total_s
            if t.count:
                out[t.name + ".min_s"] = t.min_s
                out[t.name + ".max_s"] = t.max_s
        return out


#: the process registry (reference: the JMX MBean server)
REGISTRY = MetricsRegistry()
