"""Closed-loop overload control: shed, cancel, budget, brown-out.

Reference parity: the coordinator's admission-time load shedding
(``QueryManager`` queue caps + ``TOO_MANY_REQUESTS_FAILED``), client
cancellation (``DELETE /v1/statement``), and resource-group CPU-burn
throttling — the layer that turns telemetry into *action* [SURVEY
§2.1 resource-group row, §5.3]. PR 18 gave the engine eyes (the
health watchdog detects a p99 regression and files a post-mortem);
this module gives it hands. Four rungs, ordered by how much each one
costs the client:

1. **Load shedding** (cheapest, at admission): queue ceilings plus an
   EWMA-cost controller in ``server/scheduler.py`` fail a submission
   fast with the retryable :class:`~presto_tpu.runtime.errors
   .ServerOverloaded` — HTTP 429 + a Retry-After hint monotone in
   queue depth — instead of letting the backlog grow past what the
   engine can drain. A shed query never enqueues, so it leaves no
   waiter, no vtime burn, and no submit record.
2. **Cooperative cancellation** (mid-flight): every query carries a
   :class:`CancelScope`, checked at the existing choke points (the
   fragment boundary, the morsel loop, spill transfer slots, the
   batch-gate wait). ``DELETE /v1/statement/<id>`` or
   ``Session.cancel`` flips it; the next checkpoint raises the typed
   ``QueryCancelled`` and the ordinary ``finally`` paths release pool
   and host-spill reservations — cancellation reuses the failure
   plumbing instead of duplicating it.
3. **Retry budget + circuit breaker** (correlated-failure damping):
   fragment retries and OOM-ladder rungs draw from a per-session
   :class:`RetryBudget` token bucket. A storm of correlated failures
   drains it, the breaker opens, and further failures fail fast
   instead of multiplying load 1+retries times; a half-open probe
   re-arms it once one retry succeeds.
4. **Brown-out** (last rung before refusing everyone): a health-breach
   event latches :class:`OverloadController`, and tenants that opted
   in via ``TenantSpec.brownout`` have NEW traffic routed to the
   approx tier (flagged honestly via ``QueryInfo.approximate``) or
   shed outright — fidelity is spent before availability, per the
   approximate-join degradation argument in PAPERS.md. Recovery
   latches back after a breach-free cooldown.

Everything here is mechanism; policy lives in session properties
(``shed_*``, ``retry_budget_*``, ``brownout_*``) and per-tenant specs.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from presto_tpu.runtime.errors import (
    DeviceOutOfMemory,
    QueryCancelled,
    is_backend_oom,
)
from presto_tpu.runtime.faults import fault_point
from presto_tpu.runtime.metrics import REGISTRY


class CancelScope:
    """One query's cooperative-cancellation flag.

    ``cancel(reason)`` is safe from any thread and idempotent (the
    first reason wins); ``check(where)`` is called by the query's OWN
    thread at choke points and raises the typed ``QueryCancelled``
    once flipped. There is no preemption — a compiled XLA step runs to
    completion — so "within one checkpoint" is the cancellation
    latency contract, same as every other lifecycle control here.
    """

    __slots__ = ("_event", "_reason", "_observed", "query_id")

    def __init__(self, query_id: str = ""):
        self._event = threading.Event()
        self._reason: Optional[str] = None
        self._observed = False
        self.query_id = query_id

    def cancel(self, reason: str = "cancelled") -> bool:
        """Flip the scope; returns True on the first flip only."""
        if self._event.is_set():
            return False
        self._reason = reason
        self._event.set()
        REGISTRY.counter("cancel.requested").add()
        return True

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    def check(self, where: str) -> None:
        """Cooperative checkpoint: a no-op until cancelled, then a
        typed raise. Doubles as the ``step.cancel_checkpoint`` fault
        site so chaos can storm the checkpoint itself. Checkpoints
        run OUTSIDE the fragment boundary (gate waits, driver loop),
        so a backend-shaped injection (an ``oom`` fault armed at the
        ``step`` prefix) is mapped to the typed ``DeviceOutOfMemory``
        HERE — the correct-or-typed contract holds at every site."""
        try:
            fault_point("step.cancel_checkpoint")
        except Exception as e:
            if not is_backend_oom(e):
                raise
            REGISTRY.counter("query.backend_oom").add()
            raise DeviceOutOfMemory(
                f"backend out of memory at cancel checkpoint {where!r}: "
                f"{type(e).__name__}: {e}"
            ) from e
        if self._event.is_set():
            if not self._observed:
                self._observed = True
                REGISTRY.counter("cancel.observed").add()
            raise QueryCancelled(
                f"query {self.query_id or '?'} cancelled at {where!r}"
                f" ({self._reason or 'cancelled'})"
            )


def shed_retry_after(queued: int, *, base_s: float = 0.1,
                     cap_s: float = 30.0) -> float:
    """Retry-After hint for a shed: strictly monotone in queue depth
    (each queued query adds drain time), capped so a melted server
    never tells a client to go away for minutes."""
    return min(cap_s, base_s * (1.0 + max(0, queued)))


class CostEwma:
    """Exponentially-weighted moving average of per-query cost
    (seconds of slot occupancy) — the admission controller's estimate
    of how long one more queued query takes to drain. Thread-safe;
    starts at ``initial`` so an idle server never sheds its first
    query on a cold estimate."""

    def __init__(self, alpha: float = 0.2, initial: float = 0.0):
        self._alpha = float(alpha)
        self._value = float(initial)
        self._samples = 0
        self._lock = threading.Lock()

    def update(self, cost_s: float) -> float:
        with self._lock:
            if self._samples == 0:
                self._value = float(cost_s)
            else:
                self._value += self._alpha * (float(cost_s) - self._value)
            self._samples += 1
            return self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples


class RetryBudget:
    """Per-session token bucket over ALL retry-shaped work (fragment
    retries, OOM-ladder rungs) with a circuit breaker on top.

    Independent faults sip from the bucket and the time-based refill
    keeps pace. Correlated failures — a storm where every fragment
    fails the same way — drain it; then the breaker OPENS and every
    subsequent ``try_spend`` is denied instantly (fail-fast instead of
    a retry storm that multiplies offered load). After
    ``probe_cooldown_s`` the breaker goes HALF-OPEN: exactly one
    caller gets a probe token; its ``record_success`` closes the
    breaker and refills the bucket, its ``record_failure`` re-opens
    and the cooldown restarts.
    """

    def __init__(self, capacity: float = 16.0, refill_per_s: float = 2.0,
                 probe_cooldown_s: float = 1.0):
        self.capacity = max(1.0, float(capacity))
        self.refill_per_s = max(0.0, float(refill_per_s))
        self.probe_cooldown_s = max(0.0, float(probe_cooldown_s))
        self._tokens = self.capacity
        self._last = time.monotonic()
        self._state = "closed"  # closed | open | half-open
        self._opened_at = 0.0
        self._probing = False
        self._lock = threading.Lock()

    def _refill_locked(self, now: float) -> None:
        if self.refill_per_s > 0.0 and now > self._last:
            self._tokens = min(self.capacity,
                               self._tokens
                               + (now - self._last) * self.refill_per_s)
        self._last = now

    def try_spend(self, label: str = "") -> bool:
        """May this retry proceed? Denials are terminal for the caller
        (fail fast with the ORIGINAL error); they are counted under
        ``overload.retry_budget_exhausted``."""
        now = time.monotonic()
        with self._lock:
            self._refill_locked(now)
            if self._state == "open":
                if now - self._opened_at >= self.probe_cooldown_s:
                    self._state = "half-open"
                else:
                    REGISTRY.counter("overload.retry_budget_exhausted").add()
                    return False
            if self._state == "half-open":
                if self._probing:
                    REGISTRY.counter("overload.retry_budget_exhausted").add()
                    return False
                self._probing = True
                REGISTRY.counter("overload.breaker_probe").add()
                return True
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            self._state = "open"
            self._opened_at = now
            REGISTRY.counter("overload.breaker_open").add()
            REGISTRY.counter("overload.retry_budget_exhausted").add()
            return False

    def record_success(self) -> None:
        """A spent retry succeeded: a half-open probe's success closes
        the breaker and refills the bucket (the storm has passed)."""
        with self._lock:
            if self._state == "half-open" and self._probing:
                self._state = "closed"
                self._probing = False
                self._tokens = self.capacity
                REGISTRY.counter("overload.breaker_rearm").add()

    def record_failure(self) -> None:
        """A spent retry failed: a half-open probe's failure re-opens
        the breaker and the cooldown restarts."""
        with self._lock:
            if self._state == "half-open" and self._probing:
                self._state = "open"
                self._probing = False
                self._opened_at = time.monotonic()

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "tokens": round(self._tokens, 3),
                    "capacity": self.capacity}


class OverloadController:
    """The brown-out latch: health breaches flip it, a breach-free
    cooldown flips it back, and an operator can force either way.

    The serving tier consults :meth:`mode_for` per NEW submission —
    in-flight queries are never re-routed (results must match the tier
    they were admitted to) — and routes ``brownout="approx"`` tenants
    through the approx session (flagged via ``QueryInfo.approximate``)
    or sheds ``brownout="shed"`` tenants with ``ServerOverloaded``.
    Tenants with no brown-out policy are untouched: degradation is
    opt-in per the fairness contract.
    """

    def __init__(self, cooldown_s: float = 5.0):
        self.cooldown_s = max(0.0, float(cooldown_s))
        self._lock = threading.Lock()
        self._engaged = False
        self._forced = False
        self._last_breach = 0.0
        self._engagements = 0
        self._last_event: Optional[dict] = None

    def on_breach(self, event: Optional[dict] = None) -> None:
        """HealthMonitor ``on_breach`` callback: engage (or extend)
        the brown-out."""
        with self._lock:
            self._last_breach = time.monotonic()
            self._last_event = dict(event) if event else None
            if not self._engaged:
                self._engaged = True
                self._engagements += 1
                REGISTRY.counter("brownout.engaged").add()

    def force(self, on: bool) -> None:
        """Operator override (``brownout_force`` session property or a
        direct call): ``True`` engages and pins the brown-out past any
        cooldown; ``False`` releases the pin and disengages now."""
        with self._lock:
            if on:
                self._forced = True
                if not self._engaged:
                    self._engaged = True
                    self._engagements += 1
                    REGISTRY.counter("brownout.engaged").add()
            else:
                self._forced = False
                if self._engaged:
                    self._engaged = False
                    REGISTRY.counter("brownout.recovered").add()

    def _maybe_recover_locked(self, now: float) -> None:
        if (self._engaged and not self._forced
                and now - self._last_breach >= self.cooldown_s):
            self._engaged = False
            REGISTRY.counter("brownout.recovered").add()

    @property
    def engaged(self) -> bool:
        with self._lock:
            self._maybe_recover_locked(time.monotonic())
            return self._engaged

    def mode_for(self, spec) -> Optional[str]:
        """Routing verdict for one NEW submission under ``spec``:
        ``None`` (serve normally), ``"approx"`` (route to the approx
        tier), or ``"shed"`` (refuse with ServerOverloaded). Checks
        recovery first so a quiet server disengages lazily without a
        background thread."""
        with self._lock:
            self._maybe_recover_locked(time.monotonic())
            if not self._engaged:
                return None
        return getattr(spec, "brownout", None)

    @property
    def forced(self) -> bool:
        with self._lock:
            return self._forced

    @property
    def engagements(self) -> int:
        with self._lock:
            return self._engagements

    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_recover_locked(time.monotonic())
            return {"engaged": self._engaged, "forced": self._forced,
                    "engagements": self._engagements,
                    "cooldown_s": self.cooldown_s,
                    "last_event": self._last_event}
