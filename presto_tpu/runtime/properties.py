"""Session property registry: every engine knob, typed and validated.

Reference parity: ``SystemSessionProperties`` — the rule that every
perf-relevant config default is also a per-query/session overridable
property, with typed validation and unknown-property rejection at the
door (Airlift config binding fails startup on unknown keys)
[SURVEY §2.1 session/config row, §5.6].

The registry is the single source of truth: ``Session`` validates its
``properties`` mapping against it, the REPL's ``SET SESSION`` /
``SHOW SESSION`` statements read it, and executors pull their knobs
through ``Session.prop()``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from presto_tpu.exec.local_planner import DIRECT_LIMIT
from presto_tpu.runtime.errors import UserError


@dataclass(frozen=True)
class PropertyDef:
    name: str
    py_type: type
    default: Any
    description: str
    #: extra constraint beyond the type (returns problem string or None)
    check: Optional[Callable[[Any], Optional[str]]] = None

    def coerce(self, value):
        """Coerce a user-supplied value (possibly a SQL literal string)
        to the property's type; raises ValueError with the property
        name on any mismatch."""
        if value is None:
            return None
        try:
            if self.py_type is bool:
                if isinstance(value, bool):
                    v = value
                elif isinstance(value, str):
                    s = value.strip().lower()
                    if s in ("true", "1", "on", "yes"):
                        v = True
                    elif s in ("false", "0", "off", "no"):
                        v = False
                    else:
                        raise UserError(s)
                else:
                    v = bool(value)
            elif self.py_type is int:
                v = int(value)
            elif self.py_type is float:
                v = float(value)
            else:
                v = self.py_type(value)
        except (TypeError, ValueError):
            raise UserError(
                f"session property {self.name}: cannot interpret "
                f"{value!r} as {self.py_type.__name__}"
            ) from None
        if self.check is not None:
            problem = self.check(v)
            if problem:
                raise UserError(f"session property {self.name}: {problem}")
        return v


def _positive(v):
    return None if v > 0 else f"must be positive, got {v}"


def _non_negative(v):
    return None if v >= 0 else f"must be >= 0, got {v}"


SESSION_PROPERTIES: dict[str, PropertyDef] = {
    p.name: p
    for p in [
        PropertyDef(
            "broadcast_join_row_limit", int, 1 << 21,
            "Build sides with at most this many rows use the broadcast "
            "(all_gather REPLICATED) join distribution; larger builds "
            "repartition both sides (FIXED_HASH all_to_all). 0 disables "
            "broadcast joins entirely.",
            _non_negative,
        ),
        PropertyDef(
            "gather_row_limit", int, 1 << 22,
            "Guard on replicate-everything fallbacks (global-partition "
            "windows, degenerate-key sorts, unsharded build sides): "
            "replicating more rows than this to every device fails fast "
            "instead of multiplying HBM use by the mesh size.",
            _positive,
        ),
        PropertyDef(
            "join_build_budget_bytes", int, None,
            "L9 capacity planner: estimated join build sides above this "
            "byte budget run as grouped (bucketed) execution with "
            "host-RAM offload. Default: device HBM / 4.",
            _positive,
        ),
        PropertyDef(
            "spill_host_budget_bytes", int, None,
            "Host-RAM byte budget for spilled partitions "
            "(exec/grouped.HostSpill): grouped/hybrid execution reserves "
            "its host-side partition bytes against this budget and fails "
            "loud (SPILL_BUDGET_EXCEEDED) instead of growing host memory "
            "silently. Default: the process-wide host-spill budget "
            "(device HBM x 16).",
            _positive,
        ),
        PropertyDef(
            "direct_group_limit", int, DIRECT_LIMIT,
            "Grouped aggregation uses dense direct addressing when the "
            "product of the key dictionary domains is at most this; "
            "larger domains use the bounded sort-based strategy.",
            _positive,
        ),
        PropertyDef(
            "partial_agg_bypass", bool, True,
            "Adaptive aggregation strategy: bypass per-morsel partial "
            "aggregation (stream rows straight to one final aggregation "
            "pass) when the estimated — or plan-stats-observed — group "
            "cardinality approaches the input cardinality. Identical "
            "results for integer/decimal aggregates (exact arithmetic); "
            "floating-point sums agree to rounding (the one-pass shape "
            "changes summation order). Off pins keyed aggregations to "
            "agg_strategy=partial.",
        ),
        PropertyDef(
            "plan_templates", bool, True,
            "Plan-template parameterization: eligible literals are "
            "lifted out of traced programs into runtime scalar slots, "
            "so queries differing only in constants share ONE compiled "
            "executable (zero warm re-traces across bindings), and "
            "concurrent identical queries coalesce onto one in-flight "
            "execution. Bit-identical results on or off — NOT a "
            "codegen property; the result cache keys on the full "
            "literal binding either way. Literals that prove kernel "
            "admission (leaf-route spec bounds, LIMIT shapes) stay "
            "baked, counted under prepare.slot_ineligible.*.",
        ),
        PropertyDef(
            "batched_dispatch", bool, False,
            "Cross-query batched dispatch (server/batcher.py): "
            "concurrent same-template different-literal queries stack "
            "their literal-slot bindings on a leading axis and execute "
            "as ONE vmapped device dispatch (one scan, one fused "
            "program, N results) instead of N serialized warm calls. "
            "Results are bit-identical to serial execution — the "
            "batched replay traces the same compiled step bodies — and "
            "the result cache stays keyed per binding. Templates "
            "outside the pure scan/filter/project/global-agg/sort/topN "
            "whitelist fall back to the serialized template slot, "
            "counted under batch.fallback.*. Off by default for "
            "embedded sessions (a batch dispatch compiles one extra "
            "vmapped signature per width); the serving layer "
            "(presto_tpu.server) turns it on.",
        ),
        PropertyDef(
            "batch_max_size", int, 8,
            "Most bindings one cross-query batched dispatch may fuse "
            "(also the bound on distinct compiled batch widths — jit "
            "caches one signature per width).",
            _positive,
        ),
        PropertyDef(
            "tenant", str, None,
            "Default tenant identity stamped on this session's "
            "QueryInfo records (system.query_history attribution). The "
            "serving front-end overrides it per request via the "
            "request-scoped tenant context.",
        ),
        PropertyDef(
            "collect_node_stats", bool, False,
            "Record per-plan-node wall time and output rows on every "
            "query (the EXPLAIN ANALYZE recorder, always on).",
        ),
        PropertyDef(
            "query_retries", int, 0,
            "Transparent query-level retries on execution failure — the "
            "engine's whole failure-recovery posture (like the "
            "reference, there is no mid-query recovery; see README "
            "'Failure posture').",
            _non_negative,
        ),
        PropertyDef(
            "query_max_run_time", float, None,
            "Per-query wall-clock deadline in seconds. Checked at every "
            "fragment-dispatch and driver-loop boundary (a single "
            "compiled XLA step runs to completion; the check fires "
            "before the next one starts). Expiry raises "
            "ExceededTimeLimit, recorded as error_code "
            "EXCEEDED_TIME_LIMIT on the QueryInfo. None: no deadline.",
            _positive,
        ),
        PropertyDef(
            "query_max_memory_bytes", int, None,
            "Admission-control limit: a query whose peak stats-"
            "estimated node materialization "
            "(runtime/memory.estimate_node_bytes) exceeds this is "
            "rejected with ResourceExhausted BEFORE launch instead of "
            "OOMing mid-flight. None: 64x the device budget (a loose "
            "backstop — estimates are coarse and the grouped/streaming "
            "tiers keep true residency far below them).",
            _positive,
        ),
        PropertyDef(
            "memory_pool_bytes", int, None,
            "Capacity of the memory pool this session arbitrates "
            "admission through. None (default): the PROCESS-wide shared "
            "pool (64x the device budget) — concurrent sessions share "
            "the device, so they share the pool. Setting it gives the "
            "session a private pool of that size (tests, tenant "
            "isolation); passing Session(memory_pool=...) shares an "
            "explicit pool object across sessions.",
            _positive,
        ),
        PropertyDef(
            "admission_queue_timeout_s", float, 30.0,
            "How long a query may wait in the memory pool's FIFO "
            "admission queue for its byte reservation before failing "
            "with ResourceExhausted. Concurrent queries that together "
            "exceed the pool block-then-run instead of failing; the "
            "timeout bounds the wait. 0 restores reject-or-nothing.",
            _non_negative,
        ),
        PropertyDef(
            "oom_ladder_max", int, 4,
            "Rungs of the adaptive runtime-OOM degradation ladder: a "
            "backend RESOURCE_EXHAUSTED at a jitted-step dispatch "
            "re-plans the query with grouped (bucketed) execution, then "
            "doubled bucket counts / halved probe chunks, and re-runs — "
            "up to this many times before the DeviceOutOfMemory "
            "surfaces. 0 disables runtime OOM recovery.",
            _non_negative,
        ),
        PropertyDef(
            "retry_count", int, 0,
            "Fragment-level retries for RETRYABLE failures (injected "
            "faults, transient device loss — see runtime/errors.py): a "
            "failing fragment dispatch re-runs its subtree up to this "
            "many extra times with exponential backoff. Deterministic "
            "failures (user errors, resource walls, deadline expiry) "
            "are never retried. 0 disables fragment retry.",
            _non_negative,
        ),
        PropertyDef(
            "retry_backoff_s", float, 0.01,
            "Base of the exponential fragment-retry backoff: attempt k "
            "sleeps retry_backoff_s * 2^k seconds (capped at 5s).",
            _non_negative,
        ),
        PropertyDef(
            "degrade_to_local", bool, True,
            "Graceful degradation: a distributed query that fails with "
            "a retryable error after its fragment retries are exhausted "
            "re-plans onto the single-device local pipeline as a last "
            "resort (QueryInfo.degraded marks it).",
        ),
        PropertyDef(
            "result_cache_enabled", bool, True,
            "Serve a repeated identical query from the session's "
            "versioned result cache (keyed by plan fingerprint + "
            "referenced-table catalog versions; see README 'Caching'). "
            "Volatile plans (system tables, nondeterministic "
            "functions), fault-injected runs, and failed queries never "
            "populate or hit regardless of this switch.",
        ),
        PropertyDef(
            "result_cache_max_bytes", int, 256 << 20,
            "Byte budget of the per-session result cache (pandas deep "
            "memory usage); eviction is LRU-first, and a single result "
            "larger than the whole budget is skipped, not stored.",
            _positive,
        ),
        PropertyDef(
            "exec_cache_max_entries", int, 256,
            "Entry bound of the compiled-executable cache (jitted "
            "operator step functions keyed by step-config fingerprint); "
            "a repeated identical query skips XLA trace+compile "
            "entirely. LRU eviction. The cache is PROCESS-wide: setting "
            "this explicitly resizes it for every session; leaving it "
            "unset leaves the process bound untouched.",
            _positive,
        ),
        PropertyDef(
            "trace_enabled", bool, True,
            "Record a structured span trace (query -> fragment -> plan "
            "node -> jitted-step dispatch, plus cache/retry/exchange "
            "spans) for every query. Traces are retained in a "
            "per-session ring, exportable as Chrome trace JSON via "
            "Session.export_trace(path) and queryable as "
            "system.trace_spans.",
        ),
        PropertyDef(
            "trace_max_spans", int, 8192,
            "Span cap per traced query; spans beyond it are dropped "
            "(counted in the trace.spans_dropped metric), never an "
            "error.",
            _positive,
        ),
        PropertyDef(
            "query_history_limit", int, 256,
            "Entries retained in the session's query-history ring (the "
            "system.query_history table, fed by the built-in "
            "query_completed listener).",
            _positive,
        ),
        PropertyDef(
            "flight_recorder_limit", int, 64,
            "Post-mortem records retained in the session's flight-"
            "recorder ring (runtime/flight.py; the "
            "system.flight_recorder table). A record is captured "
            "automatically whenever a query fails, degrades down the "
            "OOM ladder, retries a fragment, or exceeds its deadline; "
            "export via Session.export_flight_record or `python -m "
            "presto_tpu flightrec`.",
            _positive,
        ),
        PropertyDef(
            "flight_record_successes", bool, False,
            "Also capture a flight record for every SUCCESSFUL query "
            "(plan render + spans + metric delta + pool state) — the "
            "on-demand post-mortem mode for profiling a healthy run; "
            "off by default to keep the ring for failures.",
        ),
        PropertyDef(
            "plan_stats_limit", int, 512,
            "Plan fingerprints retained in the session's "
            "estimate-vs-actual history store (the system.plan_stats "
            "table; LRU by fingerprint, invalidated on DDL through the "
            "catalog version listeners).",
            _positive,
        ),
        PropertyDef(
            "adaptive_execution", bool, True,
            "Let plan-stats history STEER recurring plans "
            "(plan/adaptive.py): skew-salted repartitioning, "
            "history-corrected join/aggregate sizing, fused-route "
            "disable after a runtime fallback — all compile-budget "
            "gated against the exec-cache ledger and logged to "
            "system.adaptive. Off = telemetry only (the pre-adaptive "
            "baseline, also the A/B control in bench.py).",
        ),
        PropertyDef(
            "adaptive_salt_max", int, 8,
            "Upper bound on the skew-salt partition count S "
            "(plan/adaptive.salt_factor): a hot destination splits "
            "across at most this many salted partitions; build-row "
            "replication cost grows linearly in S.",
            _positive,
        ),
        PropertyDef(
            "profile_annotations", bool, False,
            "Wrap every trace span in a jax.profiler.TraceAnnotation "
            "named '<span>#<trace_token>' so xprof/TensorBoard device "
            "timelines (see profile_dir) correlate with engine spans "
            "by trace token.",
        ),
        PropertyDef(
            "profile_dir", str, None,
            "When set, every query executes under jax.profiler.trace "
            "writing an XLA op-level timeline (TensorBoard/xprof) to "
            "this directory — the device-side complement to EXPLAIN "
            "ANALYZE's host-level per-operator stats.",
        ),
        PropertyDef(
            "narrow_storage", bool, None,
            "Stats-driven narrow physical column storage: scans "
            "materialize int8/int16/int32 device columns wherever "
            "connector value bounds permit (HBM-bandwidth lever, "
            "~4x on bandwidth-bound aggregation — notes/PERF.md §6). "
            "Process-wide, mirrors the PRESTO_TPU_NARROW environment "
            "variable; default: on. Turn off to bisect narrowing "
            "against canonical int64 storage — results must be "
            "bit-identical either way.",
        ),
        PropertyDef(
            "runtime_join_filters", bool, True,
            "Sideways information passing: when a join build side "
            "finishes, its key min/max plus a Bloom membership bitmask "
            "are pushed into the probe-side table scan, pruning rows "
            "that cannot join before downstream operators see them "
            "(inner and semi joins only — outer/anti joins keep "
            "unmatched probe rows). Semantics-preserving: results are "
            "bit-identical on or off; observable via the "
            "join.filter_rows_pruned / join.filter_selectivity "
            "metrics and the join_filter trace span.",
        ),
        PropertyDef(
            "pallas_join", bool, True,
            "Prefer the fused Pallas VMEM-table probe for equi-joins "
            "on narrow stats-bounded keys (build->probe->project in "
            "one kernel; ops/pallas_join.py). Ineligible joins — wide "
            "keys, over-budget domains, unblockable capacities — fall "
            "back to the dense/sorted/expansion XLA probes with a "
            "join.pallas_fallback counter; results are bit-identical "
            "either way.",
        ),
        PropertyDef(
            "approx_join", bool, False,
            "APPROXIMATE semi joins: when the exact fused table cannot "
            "fit VMEM, probe a two-hash Bloom sketch instead — false "
            "positives possible (extra rows at roughly "
            "(1-exp(-2n/m))^2 for n build keys in m=2^19 bits), never "
            "false negatives, never row loss (anti joins are excluded "
            "by construction). Changes results: the plan fingerprint "
            "folds this property, so cached results never leak across "
            "the exact/approximate boundary.",
        ),
        PropertyDef(
            "approx_scan_fraction", float, 1.0,
            "APPROXIMATE scans: execute only this deterministic "
            "fraction of each table's splits (evenly strided, so the "
            "sample is stable per split layout). 1.0 scans "
            "everything; below 1.0 the query is flagged "
            "QueryInfo.approximate — the dashboard tier of "
            "presto_tpu/stream/ subscriptions. Changes results: the "
            "plan fingerprint folds this property, so sampled and "
            "exact runs never share cached results.",
            check=lambda v: (None if 0.0 < v <= 1.0
                             else f"must be in (0, 1], got {v}"),
        ),
        PropertyDef(
            "pallas_strings", bool, None,
            "Force the Pallas string-predicate kernels on or off "
            "(process-wide; default: on when running on TPU). Mirrors "
            "the PRESTO_TPU_PALLAS environment variable.",
        ),
        PropertyDef(
            "device_telemetry", bool, True,
            "Sample per-device allocator stats (runtime/devices.py) at "
            "query completion: stamps QueryInfo.device_peak_bytes and "
            "feeds the system.device_stats table and device.* gauges. "
            "Backends without memory_stats() (CPU) report zeros.",
        ),
        PropertyDef(
            "slo_latency_objective_s", float, 1.0,
            "Default per-tenant latency objective (seconds): a query "
            "finishing slower counts against the tenant's SLO burn "
            "rate (system.slo). Per-tenant overrides ride "
            "TenantSpec.slo_latency_s.",
            _positive,
        ),
        PropertyDef(
            "slo_freshness_objective_s", float, 10.0,
            "Default per-tenant subscription freshness objective "
            "(seconds): a continuous-query refresh delivering staler "
            "than this counts against the tenant's freshness burn "
            "rate. Per-tenant overrides ride TenantSpec.slo_freshness_s.",
            _positive,
        ),
        PropertyDef(
            "slo_window", int, 256,
            "Rolling observation window (per tenant, per objective "
            "kind) over which SLO burn rates are computed.",
            _positive,
        ),
        PropertyDef(
            "health_monitor", bool, True,
            "Arm the serving-tier anomaly watchdog "
            "(runtime/health.py) when a QueryServer starts: a "
            "background thread samples qps/p99/queue/pool/cache/"
            "freshness into system.health and fires health_breach "
            "events (plus a flight-recorder capture of the worst "
            "in-flight query) on regressions.",
        ),
        PropertyDef(
            "health_interval_s", float, 0.25,
            "Watchdog sampling cadence (seconds).",
            _positive,
        ),
        PropertyDef(
            "health_ring", int, 128,
            "Bounded ring of health snapshots retained (the "
            "system.health table depth).",
            _positive,
        ),
        PropertyDef(
            "health_baseline_window", int, 8,
            "Trailing samples forming the watchdog's baseline (median "
            "p99 over this window is the regression reference).",
            _positive,
        ),
        PropertyDef(
            "health_min_samples", int, 3,
            "Baseline samples (with observed latencies) required "
            "before the p99 regression detector may fire — a cold "
            "start must not breach on its first slow query.",
            _positive,
        ),
        PropertyDef(
            "health_p99_factor", float, 3.0,
            "Breach when the current p99 exceeds this multiple of the "
            "trailing-baseline p99.",
            _positive,
        ),
        PropertyDef(
            "health_queue_limit", int, 64,
            "Breach when the admission queue holds more waiters than "
            "this.",
            _positive,
        ),
        PropertyDef(
            "health_burn_limit", float, 0.5,
            "Breach when any tenant's rolling SLO burn rate (breach "
            "fraction) exceeds this.",
            _positive,
        ),
        PropertyDef(
            "health_stale_lag_s", float, 30.0,
            "Breach when the worst subscription freshness lag exceeds "
            "this many seconds.",
            _positive,
        ),
        PropertyDef(
            "health_cooldown_s", float, 5.0,
            "Minimum seconds between health_breach firings (with the "
            "clean-sample re-arm latch, one sustained incident fires "
            "once, not once per sample).",
            _non_negative,
        ),
        PropertyDef(
            "retry_budget_tokens", float, 16.0,
            "Capacity of the per-session retry token bucket "
            "(runtime/overload.RetryBudget): fragment retries and "
            "OOM-ladder rungs each spend one token; a drained bucket "
            "opens the circuit breaker and failures fail fast instead "
            "of retry-storming.",
            _positive,
        ),
        PropertyDef(
            "retry_budget_refill_per_s", float, 2.0,
            "Retry tokens refilled per second — the sustainable "
            "independent-failure rate; correlated failures outpace it "
            "and trip the breaker. 0 disables refill (tokens only "
            "return via the half-open probe's success).",
            _non_negative,
        ),
        PropertyDef(
            "retry_breaker_cooldown_s", float, 1.0,
            "Seconds an OPEN retry circuit breaker waits before going "
            "half-open and granting exactly one probe retry; the "
            "probe's success re-closes the breaker and refills the "
            "bucket.",
            _non_negative,
        ),
        PropertyDef(
            "brownout_cooldown_s", float, 5.0,
            "Breach-free seconds after which an engaged brown-out "
            "(runtime/overload.OverloadController) disengages and "
            "eligible tenants' traffic returns to the exact tier.",
            _non_negative,
        ),
        PropertyDef(
            "brownout_force", bool, False,
            "Operator override: pin the brown-out latch ON (eligible "
            "tenants degrade per TenantSpec.brownout regardless of "
            "health). Setting it back to false disengages immediately.",
        ),
    ]
}


def validate_properties(props: dict) -> dict:
    """Coerce + validate a property mapping; unknown names are errors
    (the reference fails startup on unknown config keys)."""
    out = {}
    for name, value in props.items():
        d = SESSION_PROPERTIES.get(name)
        if d is None:
            known = ", ".join(sorted(SESSION_PROPERTIES))
            raise UserError(
                f"unknown session property {name!r} (known: {known})"
            )
        out[name] = d.coerce(value)
    return out


def effective(props: dict, name: str):
    """Value of a property under the session overrides."""
    d = SESSION_PROPERTIES[name]
    return props.get(name, d.default)
