"""Session: the client-facing query surface.

Reference parity: ``Session`` + the statement execution path
(``SqlQueryExecution``: parse -> analyze -> plan -> execute), the
``QueryTracker``/``QueryStateMachine`` lifecycle (QUEUED -> RUNNING ->
FINISHED/FAILED), ``QueryMonitor`` events, and EXPLAIN / EXPLAIN
ANALYZE [SURVEY §2.1, §3.1, §5.1, §5.5; reference tree unavailable,
paths reconstructed]. Single-controller: there is no dispatch/queueing
tier; ``sql()`` drives the full pipeline synchronously and returns a
DataFrame.

Every session auto-registers the ``system`` catalog
(system.runtime_queries / runtime_metrics / runtime_nodes) backed by
its own query history and the process metrics registry.
"""

from __future__ import annotations

import itertools
import time
import uuid
from typing import Mapping, Optional

from presto_tpu.exec.local_planner import LocalExecutor
from presto_tpu.plan.catalog import Catalog
from presto_tpu.plan.nodes import PlanNode, plan_tree_str
from presto_tpu.plan.prune import prune
from presto_tpu.runtime.events import EventDispatcher
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.stats import (
    QueryInfo,
    StatsRecorder,
    render_analyzed_plan,
)
from presto_tpu.sql.analyzer import Analyzer
from presto_tpu.sql.parser import parse

_query_seq = itertools.count(1)


class Session:
    def __init__(self, connectors: Mapping[str, object], properties=None,
                 mesh=None, trace_token: Optional[str] = None):
        """``mesh=None`` runs single-device (the LocalQueryRunner shape);
        passing a ``jax.sharding.Mesh`` runs every query distributed
        over its ``workers`` axis (the DistributedQueryRunner shape).
        Session properties override engine defaults per query, the
        reference's SystemSessionProperties rule [SURVEY §5.6]."""
        from presto_tpu.connectors.system import SystemConnector

        conns = dict(connectors)
        conns.setdefault("system", SystemConnector(self))
        self.catalog = Catalog(conns)
        self.analyzer = Analyzer(self.catalog)
        self.properties = dict(properties or {})
        self.mesh = mesh
        self.trace_token = trace_token
        self.events = EventDispatcher()
        self.query_history: list[QueryInfo] = []
    @property
    def executor(self):
        """A freshly-configured executor reflecting current session
        properties. Queries never share one: ``_run_tracked`` builds its
        own per query (this accessor exists for introspection)."""
        return self._make_executor()

    def _make_executor(self):
        """A fresh executor per query: per-query state (the stats
        recorder) must never live on a shared object, or concurrent /
        nested queries cross-contaminate each other's stats
        (reference parity: per-query SqlQueryExecution objects)."""
        if self.mesh is None:
            budget = self.properties.get("join_build_budget_bytes")
            return LocalExecutor(
                self.catalog,
                join_build_budget=int(budget) if budget is not None else None,
            )
        from presto_tpu.exec.distributed import DistributedExecutor

        return DistributedExecutor(
            self.catalog,
            self.mesh,
            broadcast_limit=int(
                self.properties.get("broadcast_join_row_limit", 1 << 21)
            ),
            gather_limit=int(
                self.properties.get("gather_row_limit", 1 << 22)
            ),
        )

    # ------------------------------------------------------------------
    def add_event_listener(self, listener):
        """Register an EventListener (reference: EventListener SPI)."""
        self.events.add(listener)

    def plan(self, sql: str) -> PlanNode:
        ast = parse(sql)
        logical = self.analyzer.analyze(ast)
        return prune(logical)

    def explain(self, sql: str) -> str:
        return plan_tree_str(self.plan(sql))

    def explain_analyze(self, sql: str) -> str:
        """Execute and render the plan annotated with actuals
        (reference: EXPLAIN ANALYZE)."""
        recorder = StatsRecorder()
        plan = self.plan(sql)
        self._run_tracked(sql, plan, recorder)
        return render_analyzed_plan(plan, recorder)

    def sql(self, sql: str):
        """Execute and return a pandas DataFrame."""
        recorder = (
            StatsRecorder()
            if self.properties.get("collect_node_stats")
            else None
        )
        df, _info = self._run_tracked(sql, self.plan(sql), recorder)
        return df

    def execute(self, sql: str):
        """Execute returning (DataFrame, QueryInfo)."""
        recorder = StatsRecorder()
        return self._run_tracked(sql, self.plan(sql), recorder)

    # ------------------------------------------------------------------
    def _run_tracked(self, sql: str, plan: PlanNode, recorder):
        info = QueryInfo(
            query_id=f"q_{next(_query_seq)}_{uuid.uuid4().hex[:8]}",
            sql=sql,
            state="QUEUED",
            created_at=time.time(),
            trace_token=self.trace_token,
        )
        self.query_history.append(info)
        REGISTRY.counter("query.started").add()
        self.events.query_created(info)
        info.state = "RUNNING"
        info.started_at = time.time()
        executor = self._make_executor()
        executor.recorder = recorder
        try:
            with REGISTRY.timer("query.execution").time():
                df = executor.run(plan)
            info.state = "FINISHED"
            info.output_rows = len(df)
            REGISTRY.counter("query.completed").add()
        except Exception as e:
            info.state = "FAILED"
            info.error = f"{type(e).__name__}: {e}"
            REGISTRY.counter("query.failed").add()
            raise
        finally:
            info.finished_at = time.time()
            if recorder is not None:
                info.node_stats = [
                    s.to_dict() for s in recorder.nodes.values()
                ]
            self.events.query_completed(info)
        return df, info
