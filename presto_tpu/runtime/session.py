"""Session: the client-facing query surface.

Reference parity: ``Session`` + the statement execution path
(``SqlQueryExecution``: parse -> analyze -> plan -> execute)
[SURVEY §2.1, §3.1; reference tree unavailable, paths reconstructed].
Single-controller: there is no dispatch/queueing tier; ``sql()`` drives
the full pipeline synchronously and returns a DataFrame.
"""

from __future__ import annotations

from typing import Mapping

from presto_tpu.exec.local_planner import LocalExecutor
from presto_tpu.plan.catalog import Catalog
from presto_tpu.plan.nodes import PlanNode, plan_tree_str
from presto_tpu.plan.prune import prune
from presto_tpu.sql.analyzer import Analyzer
from presto_tpu.sql.parser import parse


class Session:
    def __init__(self, connectors: Mapping[str, object], properties=None, mesh=None):
        """``mesh=None`` runs single-device (the LocalQueryRunner shape);
        passing a ``jax.sharding.Mesh`` runs every query distributed
        over its ``workers`` axis (the DistributedQueryRunner shape).
        Session properties override engine defaults per query, the
        reference's SystemSessionProperties rule [SURVEY §5.6]."""
        self.catalog = Catalog(connectors)
        self.analyzer = Analyzer(self.catalog)
        self.properties = dict(properties or {})
        self.mesh = mesh
        if mesh is None:
            self.executor = LocalExecutor(self.catalog)
        else:
            from presto_tpu.exec.distributed import DistributedExecutor

            self.executor = DistributedExecutor(
                self.catalog,
                mesh,
                broadcast_limit=int(
                    self.properties.get("broadcast_join_row_limit", 1 << 21)
                ),
            )

    def plan(self, sql: str) -> PlanNode:
        ast = parse(sql)
        logical = self.analyzer.analyze(ast)
        return prune(logical)

    def explain(self, sql: str) -> str:
        return plan_tree_str(self.plan(sql))

    def sql(self, sql: str):
        """Execute and return a pandas DataFrame."""
        return self.executor.run(self.plan(sql))
