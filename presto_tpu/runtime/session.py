"""Session: the client-facing query surface.

Reference parity: ``Session`` + the statement execution path
(``SqlQueryExecution``: parse -> analyze -> plan -> execute), the
``QueryTracker``/``QueryStateMachine`` lifecycle (QUEUED -> RUNNING ->
FINISHED/FAILED), ``QueryMonitor`` events, and EXPLAIN / EXPLAIN
ANALYZE [SURVEY §2.1, §3.1, §5.1, §5.5; reference tree unavailable,
paths reconstructed]. Single-controller: there is no dispatch/queueing
tier; ``sql()`` drives the full pipeline synchronously and returns a
DataFrame.

Every session auto-registers the ``system`` catalog
(system.runtime_queries / runtime_metrics / runtime_nodes) backed by
its own query history and the process metrics registry.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from typing import Mapping, Optional

from presto_tpu.exec.local_planner import LocalExecutor
from presto_tpu.plan.catalog import Catalog
from presto_tpu.plan.nodes import PlanNode, plan_tree_str
from presto_tpu.plan.prune import prune
from presto_tpu.runtime import trace
from presto_tpu.runtime.errors import UserError, error_code, is_retryable
from presto_tpu.runtime.events import EventDispatcher, QueryHistoryBuffer
from presto_tpu.runtime.lifecycle import QueryManager
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.stats import (
    QueryInfo,
    StatsRecorder,
    render_analyzed_plan,
)
from presto_tpu.runtime.trace import TraceRecorder, TraceStore
from presto_tpu.sql.analyzer import Analyzer
from presto_tpu.sql.parser import parse

_query_seq = itertools.count(1)

#: request-scoped tenant identity, set by the serving front-end
#: (presto_tpu/server/frontend.py) around each tenant's execution so
#: QueryInfo attribution works through one shared session without
#: threading a parameter into every sql()/execute() signature. Falls
#: back to the ``tenant`` session property, then "".
from contextvars import ContextVar

CURRENT_TENANT: ContextVar[Optional[str]] = ContextVar(
    "presto_tpu_current_tenant", default=None
)

#: request-scoped trace context, set by the serving front-end around a
#: submitted query's execution and by the subscription manager around
#: each refresh fire. A mutable dict: {"token": trace token for the
#: query's TraceRecorder (client-supplied X-Presto-Trace / W3C
#: traceparent trace-id, or a subscription-scoped token),
#: "subscription_id": continuous-query id ("" for ad-hoc),
#: "force_trace": record spans even when the session-level
#: trace_enabled property is off (a client that sent a traceparent
#: asked to be traced), "query_id": written BACK by _run_tracked so
#: the front-end can stitch its submit/poll spans onto the query's
#: recorder after the fact}.
REQUEST_TRACE: ContextVar[Optional[dict]] = ContextVar(
    "presto_tpu_request_trace", default=None
)


def _ast_literal_value(node):
    """EXECUTE ... USING argument -> logical Python value (literals
    only — parameters are values, not expressions)."""
    from presto_tpu.sql import ast as A

    if isinstance(node, A.NumberLit):
        return float(node.text) if "." in node.text else int(node.text)
    if isinstance(node, A.StringLit):
        return node.value
    if isinstance(node, (A.DateLit, A.TimestampLit)):
        return node.value  # ISO strings; DataType.to_physical parses
    if isinstance(node, A.UnaryOp) and node.op == "-":
        return -_ast_literal_value(node.operand)
    raise UserError(
        "EXECUTE ... USING arguments must be literals"
    )


class Session:
    def __init__(self, connectors: Mapping[str, object], properties=None,
                 mesh=None, trace_token: Optional[str] = None,
                 memory_pool=None):
        """``mesh=None`` runs single-device (the LocalQueryRunner shape);
        passing a ``jax.sharding.Mesh`` runs every query distributed
        over its ``workers`` axis (the DistributedQueryRunner shape).
        Session properties override engine defaults per query, the
        reference's SystemSessionProperties rule [SURVEY §5.6].
        ``memory_pool`` shares an explicit ``runtime.memory.MemoryPool``
        across sessions (default: the process-wide pool, or a private
        one when ``memory_pool_bytes`` is set)."""
        from presto_tpu.connectors.memory import MemoryConnector
        from presto_tpu.connectors.system import SystemConnector
        from presto_tpu.runtime.properties import validate_properties

        conns = dict(connectors)
        conns.setdefault("system", SystemConnector(self))
        # the writable catalog: CREATE TABLE AS / INSERT INTO land here
        # (reference: presto-memory as the default test/CTAS target)
        conns.setdefault("memory", MemoryConnector())
        self.catalog = Catalog(conns)
        self.analyzer = Analyzer(self.catalog)
        self.properties = validate_properties(dict(properties or {}))
        self.mesh = mesh
        self.trace_token = trace_token
        self.events = EventDispatcher()
        self.query_history: list[QueryInfo] = []
        #: ring of recent completed QueryInfos behind system.query_history
        #: (a built-in EventListener — the reference's history-store
        #: EventListener plugin shape)
        self.history = QueryHistoryBuffer(self.prop("query_history_limit"))
        self.events.add(self.history)
        #: ring of recent span traces (Session.export_trace /
        #: system.trace_spans); populated when trace_enabled
        self.traces = TraceStore()
        #: flight recorder: bounded ring of failure post-mortems
        #: (runtime/flight.py), auto-captured at run_plan's choke point
        #: whenever a query fails/degrades/retries/overruns; queryable
        #: as system.flight_recorder, exportable via
        #: export_flight_record / `python -m presto_tpu flightrec`
        from presto_tpu.runtime.flight import FlightRecorder

        self.flight = FlightRecorder(self.prop("flight_recorder_limit"))
        #: lifecycle mechanics: admission control, deadlines, fragment
        #: retry, distributed->local degradation (runtime/lifecycle.py)
        self.query_manager = QueryManager(self)
        #: explicit shared memory pool (None: ``pool()`` resolves to
        #: the private pool below or the process-wide one). The private
        #: pool is built EAGERLY — lazy creation would race concurrent
        #: first queries into two pools, doubling the admission bound
        self._memory_pool = memory_pool
        self._private_pool = None
        cap = self.prop("memory_pool_bytes")
        if cap is not None:
            from presto_tpu.runtime.memory import MemoryPool

            self._private_pool = MemoryPool(cap, name="session")
        #: versioned result cache (cache/result_cache.py) — per session:
        #: sessions own private memory catalogs, so equal fingerprints
        #: across sessions do not imply equal data. DDL drops entries
        #: eagerly through the catalog's invalidation listener.
        from presto_tpu.cache.result_cache import ResultCache

        self.result_cache = ResultCache(self.prop("result_cache_max_bytes"))
        self.catalog.add_invalidation_listener(
            self.result_cache.invalidate_table
        )
        #: estimate-vs-actual history keyed by plan fingerprint
        #: (cache/plan_stats.py; system.plan_stats) — invalidated
        #: through the same catalog DDL listeners as the result cache,
        #: so stale history never survives a version bump
        from presto_tpu.cache.plan_stats import PlanStatsStore

        self.plan_stats = PlanStatsStore(self.prop("plan_stats_limit"))
        #: adaptive-execution feedback controller (plan/adaptive.py):
        #: turns plan-stats history into sticky per-(fingerprint, node)
        #: plan decisions, budget-gated against the exec-cache ledger;
        #: its decision ring is queryable as ``system.adaptive``
        from presto_tpu.plan.adaptive import AdaptiveController

        self.adaptive = AdaptiveController()
        self.catalog.add_invalidation_listener(
            self.plan_stats.invalidate_table
        )
        #: serving-layer tenant registry (server/scheduler.FairScheduler
        #: when a QueryServer fronts this session) — the backing store
        #: of system.tenants; None outside the serving layer
        self.tenants = None
        #: tenant SLO tracker (runtime/health.SloTracker, attached by
        #: the serving layer) — the backing store of system.slo; None
        #: outside the serving layer
        self.slo = None
        #: anomaly watchdog (runtime/health.HealthMonitor, armed by the
        #: serving layer) — the backing store of system.health; None
        #: outside the serving layer
        self.health = None
        #: prepared statements (PREPARE name FROM ... / Session.prepare)
        self._prepared: dict[str, object] = {}
        #: plan templates this session has executed at least once —
        #: the query_history ``template_hit`` column's ground truth.
        #: LRU-bounded: a long-lived serving session over unbounded
        #: distinct statements must not grow it forever (evicting a
        #: template only re-marks its NEXT run a miss — observability,
        #: never correctness)
        from collections import OrderedDict

        self._seen_templates: "OrderedDict[str, None]" = OrderedDict()
        self._seen_templates_limit = 4096
        self._tmpl_lock = threading.Lock()
        # every memory-connector write (CTAS store / INSERT commit /
        # DROP) bumps the catalog version even when issued through the
        # Python API rather than SQL DDL — stale metadata or cached
        # results after a direct write are structurally impossible
        mem = conns["memory"]
        self._mem_ddl_hooked = hasattr(mem, "add_ddl_listener")
        if self._mem_ddl_hooked:
            mem.add_ddl_listener(self.catalog.invalidate)

    # ------------------------------------------------------------------
    def prop(self, name: str):
        """Effective value of a session property (override or default)."""
        from presto_tpu.runtime.properties import effective

        return effective(self.properties, name)

    def set_property(self, name: str, value):
        """SET SESSION name = value (typed + validated; unknown names
        rejected, the reference's config-binding rule [SURVEY §5.6])."""
        from presto_tpu.runtime.properties import validate_properties

        self.properties.update(validate_properties({name: value}))
        if name == "query_history_limit":
            # the history ring is sized at construction; a changed
            # limit must take effect, not silently keep the old bound
            self.history.resize(self.prop(name))
        if name == "plan_stats_limit":
            # like the history ring above: a lowered bound must evict
            # immediately, not silently keep the old size until the
            # next recorded query
            self.plan_stats.resize(self.prop(name))
        if name == "flight_recorder_limit":
            # same take-effect rule as the rings above
            self.flight.resize(self.prop(name))
        if name == "memory_pool_bytes":
            # rebuild the private pool here — not lazily in pool() —
            # so concurrent queries always see exactly one pool
            from presto_tpu.runtime.memory import MemoryPool

            cap = self.prop(name)
            self._private_pool = (
                None if cap is None else MemoryPool(cap, name="session")
            )

    def show_session(self) -> "list[tuple[str, object, str]]":
        """(name, effective value, description) rows, SHOW SESSION."""
        from presto_tpu.runtime.properties import SESSION_PROPERTIES

        return [
            (d.name, self.prop(d.name), d.description)
            for d in SESSION_PROPERTIES.values()
        ]
    def pool(self):
        """The memory pool this session's queries reserve from: an
        explicit shared pool if one was passed, else the private pool
        built from ``memory_pool_bytes``, else the process-wide pool
        (``runtime.memory.global_pool``). Read-only — pools are built
        in ``__init__``/``set_property``, never here, so concurrent
        queries can race this accessor safely."""
        from presto_tpu.runtime.memory import global_pool

        if self._memory_pool is not None:
            return self._memory_pool
        if self._private_pool is not None:
            return self._private_pool
        return global_pool()

    @property
    def executor(self):
        """A freshly-configured executor reflecting current session
        properties. Queries never share one: ``_run_tracked`` builds its
        own per query (this accessor exists for introspection)."""
        return self._make_executor()

    def _make_executor(self):
        """A fresh executor per query: per-query state (the stats
        recorder) must never live on a shared object, or concurrent /
        nested queries cross-contaminate each other's stats
        (reference parity: per-query SqlQueryExecution objects)."""
        import os

        from presto_tpu.cache.exec_cache import EXEC_CACHE

        # the executable cache is PROCESS-wide: only an explicit
        # per-session override mutates its bound — a session that never
        # touched the knob must not evict other sessions' compiled steps
        if "exec_cache_max_entries" in self.properties:
            EXEC_CACHE.set_max_entries(self.prop("exec_cache_max_entries"))
        pallas = self.prop("pallas_strings")
        if pallas is not None:
            # the string-kernel probe reads the env at trace time;
            # mirror the property there (documented as process-wide)
            # presto-lint: ignore[PT401] -- deliberate documented mirror: the property IS the process-wide env switch (properties.py documents it); tests restore via the conftest guard
            os.environ["PRESTO_TPU_PALLAS"] = "1" if pallas else "0"
        narrow = self.prop("narrow_storage")
        if narrow is not None:
            # connectors read the switch at scan time (spi.narrow_enabled);
            # mirror the property there (documented as process-wide)
            # presto-lint: ignore[PT401] -- deliberate documented mirror: the property IS the process-wide env switch (properties.py documents it); tests restore via the conftest guard
            os.environ["PRESTO_TPU_NARROW"] = "1" if narrow else "0"
        if self.mesh is None:
            budget = self.prop("join_build_budget_bytes")
            return LocalExecutor(
                self.catalog,
                join_build_budget=budget,
                direct_group_limit=self.prop("direct_group_limit"),
                runtime_join_filters=self.prop("runtime_join_filters"),
                pallas_join_enabled=self.prop("pallas_join"),
                approx_join=self.prop("approx_join"),
                scan_sample_fraction=self.prop("approx_scan_fraction"),
                spill_host_budget=self.prop("spill_host_budget_bytes"),
            )
        from presto_tpu.exec.distributed import DistributedExecutor

        return DistributedExecutor(
            self.catalog,
            self.mesh,
            broadcast_limit=self.prop("broadcast_join_row_limit"),
            gather_limit=self.prop("gather_row_limit"),
            direct_group_limit=self.prop("direct_group_limit"),
            join_build_budget=self.prop("join_build_budget_bytes"),
            spill_host_budget=self.prop("spill_host_budget_bytes"),
        )

    def _profiled(self):
        """XLA op-level profiling per query when ``profile_dir`` is set
        (jax.profiler trace -> TensorBoard/xprof), the device-side
        complement to the host-level EXPLAIN ANALYZE node stats
        [SURVEY §5.1 TPU-mapping row]."""
        import contextlib

        d = self.prop("profile_dir")
        if not d:
            return contextlib.nullcontext()
        import jax

        return jax.profiler.trace(d)

    # ------------------------------------------------------------------
    def add_event_listener(self, listener):
        """Register an EventListener (reference: EventListener SPI)."""
        self.events.add(listener)

    def plan(self, sql: str) -> PlanNode:
        from presto_tpu.sql import ast as A

        ast = parse(sql)
        if isinstance(ast, (A.CreateTableAs, A.InsertInto, A.DropTable)):
            raise UserError(
                "DDL statements execute via Session.sql(), not plan()/explain()"
            )
        logical = self.analyzer.analyze(ast)
        if self.analyzer.param_types:
            # catch the unbindable plan at PLAN time: executing it would
            # surface as a KeyError deep inside a traced step (and then
            # be pointlessly retried)
            raise UserError(
                "query contains ? parameters; PREPARE it and EXECUTE "
                "... USING (or Session.prepare/execute)"
            )
        return prune(logical)

    def explain(self, sql: str) -> str:
        """EXPLAIN rendering. With ``plan_templates`` on, the plan is
        rendered as its TEMPLATE — exprs show ``?N`` slots — followed by
        a ``params=[...]`` line binding each slot to this statement's
        literal (the prepared-statement view of the query)."""
        from presto_tpu.sql import ast as A

        stmt = parse(sql)
        if isinstance(stmt, (A.CreateTableAs, A.InsertInto, A.DropTable,
                             A.Prepare, A.ExecuteStmt, A.Deallocate)):
            raise UserError(
                "DDL statements execute via Session.sql(), not plan()/explain()"
            )
        plan, bound = self._plan_binding(stmt)
        hints = self._plan_hints(plan)
        out = plan_tree_str(plan, catalog=self.catalog,
                            approx_join=bool(self.prop("approx_join")),
                            plan_hints=hints,
                            agg_bypass=bool(self.prop("partial_agg_bypass")),
                            join_build_budget=self.prop(
                                "join_build_budget_bytes"),
                            adaptive=self._explain_adaptive(plan, hints))
        if bound:
            rendered = ", ".join(
                f"?{i}={dt}:{v!r}" for i, (dt, v) in enumerate(bound)
            )
            out += f"params=[{rendered}]\n"
        return out

    def explain_distributed(self, sql: str) -> str:
        """Fragment/exchange rendering (reference: EXPLAIN (TYPE
        DISTRIBUTED) via PlanFragmenter + PlanPrinter). Fragment
        headers carry observed exchange-partition skew from plan-stats
        history when this plan's fingerprint has recurred — a hot
        partition seen in past runs is plan-visible, not buried in a
        finished query's trace."""
        from presto_tpu.plan.fragmenter import fragment_plan

        ex = self.executor
        plan = self.plan(sql)
        # local sessions render with the same session-property defaults
        # a distributed executor would be built with — no duplicated
        # literals that could drift from execution
        fp = fragment_plan(
            plan, self.catalog,
            getattr(ex, "broadcast_limit",
                    self.prop("broadcast_join_row_limit")),
            getattr(ex, "join_build_budget",
                    self.prop("join_build_budget_bytes")),
        )
        skew = {
            nid: rec.get("skew", 0.0)
            for nid, rec in self._plan_hints(plan).items()
            if rec.get("skew", 0.0) > 1.0
        }
        return fp.render(skew_history=skew or None)

    def explain_analyze(self, sql: str) -> str:
        """Execute and render the plan annotated with actuals
        (reference: EXPLAIN ANALYZE), plus the exchange/cache span
        rollups from the query's trace. A result-cache hit is reported
        in a header line — no execution happened, so node actuals
        render as not-executed."""
        recorder = StatsRecorder()
        t0 = time.perf_counter()
        plan = self.plan(sql)
        planning_s = time.perf_counter() - t0
        _df, info = self._run_tracked(sql, plan, recorder,
                                      planning_s=planning_s)
        rendered = render_analyzed_plan(
            plan, recorder, tracer=self.traces.for_query(info.query_id)
        )
        if info.cache_hit:
            return "result cache: HIT (no execution)\n" + rendered
        return rendered

    def sql(self, sql: str):
        """Execute and return a pandas DataFrame. DDL/DML statements
        (CREATE TABLE AS / INSERT INTO / DROP TABLE) return a one-row
        summary frame; PREPARE / EXECUTE ... USING / DEALLOCATE PREPARE
        drive the prepared-statement surface."""
        import pandas as pd

        from presto_tpu.sql import ast as A

        t0 = time.perf_counter()
        stmt = parse(sql)
        if isinstance(stmt, A.Prepare):
            self._prepared[stmt.name] = self._prepare_ast(
                stmt.name, sql, stmt.statement)
            return pd.DataFrame({"prepared": [stmt.name]})
        if isinstance(stmt, A.ExecuteStmt):
            df, _info = self.execute_prepared(
                stmt.name, [_ast_literal_value(a) for a in stmt.args],
                planning_s=time.perf_counter() - t0,
            )
            return df
        if isinstance(stmt, A.Deallocate):
            if self._prepared.pop(stmt.name, None) is None:
                raise UserError(f"prepared statement not found: {stmt.name}")
            return pd.DataFrame({"deallocated": [stmt.name]})
        if isinstance(stmt, (A.CreateTableAs, A.InsertInto, A.DropTable)):
            return self._run_ddl(sql, stmt)
        want = bool(self.prop("collect_node_stats"))
        plan, bound = self._plan_binding(stmt, parameterize=not want)
        planning_s = time.perf_counter() - t0
        df, _info = self._run_with_retries(
            sql, plan, (lambda: StatsRecorder()) if want else (lambda: None),
            planning_s=planning_s, bound=bound,
        )
        return df

    def cancel(self, query_id: str, reason: str = "cancelled") -> bool:
        """Cooperatively cancel a live query by ENGINE query id
        (``QueryInfo.query_id``): flips its CancelScope so the next
        checkpoint — fragment entry, morsel push, spill transfer slot,
        batch-gate wake — raises the typed ``QueryCancelled`` and the
        ordinary ``finally`` paths release its pool and host-spill
        reservations. Returns False for unknown/terminal/already-
        cancelled ids; there is nothing to interrupt preemptively — a
        compiled XLA step runs to completion, like every other
        lifecycle control here."""
        return self.query_manager.cancel(query_id, reason)

    # ---- prepared statements / plan templates ------------------------
    def _plan_binding(self, stmt, parameterize: bool = True):
        """Analyze + prune + (when ``plan_templates`` is on)
        auto-parameterize one statement: returns ``(plan, bound)``
        where ``bound`` is the slot-ordered (dtype, logical value)
        binding the statement's own literals supply. A raw statement
        containing explicit ``?`` placeholders has no values to bind —
        PREPARE it instead."""
        plan = prune(self.analyzer.analyze(stmt))
        if self.analyzer.param_types:
            raise UserError(
                "query contains ? parameters; PREPARE it and EXECUTE "
                "... USING (or Session.prepare/execute)"
            )
        if not (parameterize and self.prop("plan_templates")):
            return plan, ()
        from presto_tpu.plan.templates import parameterize_plan

        plan, slots = parameterize_plan(plan, self.catalog)
        return plan, tuple((s.dtype, s.value) for s in slots)

    def _prepare_ast(self, name: str, sql: str, stmt):
        from presto_tpu.plan.templates import (
            PreparedStatement,
            parameterize_plan,
        )
        from presto_tpu.sql import ast as A

        if not isinstance(stmt, (A.Query, A.SetQuery)):
            raise UserError("only queries can be prepared")
        plan = prune(self.analyzer.analyze(stmt))
        user = tuple(sorted(self.analyzer.param_types.items()))
        auto = ()
        if self.prop("plan_templates"):
            plan, auto = parameterize_plan(plan, self.catalog,
                                           start_slot=len(user))
        return PreparedStatement(name, sql, plan, user, auto)

    def prepare(self, sql: str, name: Optional[str] = None):
        """Prepare a query into a plan-template handle: eligible
        literals (and explicit ``?`` placeholders) become typed slots,
        and every ``execute(handle, params)`` binding reuses ONE
        compiled executable — zero re-traces across bindings."""
        stmt = parse(sql)
        handle = self._prepare_ast(name or f"stmt_{len(self._prepared)}",
                                   sql, stmt)
        self._prepared[handle.name] = handle
        return handle

    def execute_prepared(self, handle, params=(), planning_s: float = 0.0):
        """Execute a prepared handle (or its registered name) with
        positional ``?`` bindings; returns (DataFrame, QueryInfo)."""
        from presto_tpu.plan.templates import PreparedStatement

        if not isinstance(handle, PreparedStatement):
            h = self._prepared.get(handle)
            if h is None:
                raise UserError(f"prepared statement not found: {handle}")
            handle = h
        bound = handle.bind(list(params))
        return self._run_with_retries(
            handle.sql, handle.plan, lambda: None,
            planning_s=planning_s, bound=bound,
        )

    def _owning_catalog(self, table: str):
        for cname, conn in self.catalog.connectors.items():
            if table in conn.tables():
                return cname
        return None

    def _run_ddl(self, sql: str, stmt):
        """Write-path statements against the memory catalog
        (reference: ConnectorPageSink + the coordinator's
        finishInsert — all-or-nothing visibility [SURVEY §5.4]).
        Target names must not shadow tables in read-only catalogs:
        name resolution prefers user connectors, so a shadowed memory
        table would be unreachable."""
        import pandas as pd

        from presto_tpu.sql import ast as A

        mem = self.catalog.connector("memory")
        owner = self._owning_catalog(stmt.name)
        if isinstance(stmt, A.DropTable):
            if owner == "memory":
                mem.drop_table(stmt.name)
            elif owner is not None:
                raise UserError(
                    f"cannot drop {stmt.name}: it belongs to the read-only "
                    f"{owner!r} catalog"
                )
            elif not stmt.if_exists:
                raise UserError(f"table not found in memory catalog: {stmt.name}")
            if not self._mem_ddl_hooked:
                # connectors with the DDL-listener API already bumped
                # the version from inside drop_table — invalidating
                # again would double-count versions and listener fires
                self.catalog.invalidate(stmt.name)
            return pd.DataFrame({"dropped": [stmt.name]})
        # existence checks BEFORE running the (possibly expensive) query
        if isinstance(stmt, A.CreateTableAs) and owner is not None:
            raise UserError(
                f"table already exists in catalog {owner!r}: {stmt.name}"
            )
        if isinstance(stmt, A.InsertInto):
            if owner is None:
                raise UserError(f"table not found: {stmt.name}")
            if owner != "memory":
                raise UserError(
                    f"cannot insert into {stmt.name}: the {owner!r} catalog "
                    "is read-only"
                )
        t0 = time.perf_counter()
        plan, bound = self._plan_binding(stmt.query)
        planning_s = time.perf_counter() - t0
        df, _info = self._run_with_retries(sql, plan, lambda: None,
                                           planning_s=planning_s, bound=bound)
        if isinstance(stmt, A.CreateTableAs):
            rows = mem.create_table(stmt.name, df)
        else:
            rows = mem.insert(stmt.name, df)
        if not self._mem_ddl_hooked:
            self.catalog.invalidate(stmt.name)  # see the drop path
        return pd.DataFrame({"rows": [rows]})

    def execute(self, sql, params=None):
        """Execute returning (DataFrame, QueryInfo). With a
        ``PreparedStatement`` handle (or a registered name) plus
        ``params``, runs the prepared template with those bindings."""
        from presto_tpu.plan.templates import PreparedStatement

        if isinstance(sql, PreparedStatement) or params is not None:
            return self.execute_prepared(sql, params or ())
        t0 = time.perf_counter()
        plan = self.plan(sql)
        planning_s = time.perf_counter() - t0
        return self._run_with_retries(sql, plan, StatsRecorder,
                                      planning_s=planning_s)

    def _run_with_retries(self, sql: str, plan, make_recorder,
                          planning_s: float = 0.0, bound=()):
        """The engine's whole failure-recovery posture, like the
        reference's: no mid-query recovery — a failed attempt fails the
        query, and recovery is re-running it from the top
        (``query_retries`` session property). Each attempt is tracked
        as its own query with its own fresh recorder — stats from a
        failed attempt must not leak into the retry's QueryInfo."""
        retries = self.prop("query_retries")
        for attempt in range(retries + 1):
            try:
                return self._run_tracked(sql, plan, make_recorder(),
                                         planning_s=planning_s, bound=bound)
            except Exception:
                if attempt == retries:
                    raise
                REGISTRY.counter("query.retried").add()

    # ------------------------------------------------------------------
    def _run_tracked(self, sql: str, plan: PlanNode, recorder,
                     planning_s: float = 0.0, bound=()):
        """Track one execution attempt: QueryInfo lifecycle, span trace
        (when ``trace_enabled``), result-cache lookup, events.
        ``bound`` is the plan template's slot-ordered (dtype, value)
        literal binding (empty for unparameterized plans)."""
        # request-scoped trace context (serving front-end / subscription
        # manager): the client's trace token overrides the session's,
        # the subscription id rides into history attribution, and the
        # query id flows BACK so the caller can stitch frontend spans
        # onto this query's recorder post-hoc
        rctx = REQUEST_TRACE.get()
        info = QueryInfo(
            query_id=f"q_{next(_query_seq)}_{uuid.uuid4().hex[:8]}",
            sql=sql,
            state="QUEUED",
            created_at=time.time(),
            created_mono=time.monotonic(),
            planning_s=planning_s,
            trace_token=(rctx.get("token") if rctx else None)
            or self.trace_token,
            # serving-layer attribution: request-scoped tenant first
            # (the front-end sets it around each client's execution),
            # then the session-level default property
            tenant=(CURRENT_TENANT.get() or self.prop("tenant") or ""),
            subscription_id=(rctx.get("subscription_id", "")
                             if rctx else ""),
        )
        if rctx is not None:
            rctx["query_id"] = info.query_id
        tracer = None
        token = None
        if self.prop("trace_enabled") or (rctx is not None
                                          and rctx.get("force_trace")):
            tracer = TraceRecorder(
                info.query_id, info.trace_token,
                max_spans=self.prop("trace_max_spans"),
                annotate=bool(self.prop("profile_annotations")),
            )
            token = trace.install(tracer)
        # the cancel scope covers the WHOLE tracked execution — cache
        # lookup, coalescer and batch-gate waits included — so
        # Session.cancel reaches a query before run_plan installs it
        # in the in-flight registry
        self.query_manager.open_scope(info.query_id)
        try:
            with trace.span("query", "query", {"query_id": info.query_id}):
                return self._run_tracked_inner(sql, plan, recorder, info,
                                               bound=bound)
        finally:
            self.query_manager.close_scope(info.query_id)
            if tracer is not None:
                trace.uninstall(token)
                self.traces.add(tracer)

    def _run_tracked_inner(self, sql: str, plan: PlanNode, recorder, info,
                           bound=()):
        self.query_history.append(info)
        REGISTRY.counter("query.started").add()
        self.events.query_created(info)
        info.state = "RUNNING"
        info.started_at = time.time()
        info.started_mono = time.monotonic()
        if recorder is not None:
            # deterministic pre-order plan-node ids (trace spans and
            # NodeStats correlate on them)
            recorder.attach_plan(plan)
        from presto_tpu.cache.fingerprint import (
            plan_fingerprint,
            table_versions,
            try_fingerprint,
        )
        from presto_tpu.cache.result_cache import ResultCache
        from presto_tpu.plan.templates import device_params, logical_values

        # ---- binding identity (plan/templates.py) --------------------
        # Two fingerprints with distinct jobs: the plan TEMPLATE's
        # fingerprint (Param slots hash by id + type, never value) is
        # the trace/compile identity — template-hit tracking and the
        # in-flight coalescer's serialization key; the full BINDING
        # fingerprint (template + this query's literal values) keys the
        # result cache and plan stats. Compile work is shared across
        # bindings; results never are.
        values = logical_values(bound) if bound else ()
        admissible = ResultCache.admissible(plan, self.catalog)
        cache_ok = bool(self.prop("result_cache_enabled")) and admissible
        templates_on = bool(self.prop("plan_templates")) and recorder is None
        base_fp = None
        if cache_ok or templates_on:
            base_fp = plan_fingerprint(plan, self.catalog, self.properties,
                                       self.mesh)
        fp = None
        if base_fp is not None:
            fp = (try_fingerprint(("binding", base_fp, values))
                  if bound else base_fp)
        if templates_on and base_fp is not None:
            with self._tmpl_lock:
                info.template_hit = base_fp in self._seen_templates
                self._seen_templates[base_fp] = None
                self._seen_templates.move_to_end(base_fp)
                while len(self._seen_templates) > self._seen_templates_limit:
                    self._seen_templates.popitem(last=False)
            REGISTRY.counter(
                "prepare.template_hit" if info.template_hit
                else "prepare.template_miss").add()
        # ---- versioned result cache (cache/result_cache.py) ----------
        # the binding fingerprint folds in plan-template content,
        # referenced-table catalog versions, mesh shape, codegen
        # session properties, AND the full literal values; admission
        # excludes volatile plans and fault-injected runs. Failed
        # queries never populate: the put sits on the FINISHED path.
        if cache_ok and fp is not None:
            with trace.span("result_cache:lookup", "cache") as sp, \
                    REGISTRY.histogram("cache.result_lookup_s").time():
                hit = self.result_cache.get_entry(fp, self.catalog)
                cached = None if hit is None else hit[0]
                if sp is not None:
                    sp.args["hit"] = cached is not None
            if cached is not None:
                info.state = "FINISHED"
                info.cache_hit = True
                # restore the flag the POPULATING run recorded — an
                # approx-enabled session still produces exact results
                # when no sketch fired, and the hit must not re-label
                # them (the fingerprint folds approx_join, so exact
                # and approximate sessions can never share entries)
                info.approximate = hit[1].approximate
                info.output_rows = len(cached)
                info.finished_at = time.time()
                info.finished_mono = time.monotonic()
                REGISTRY.counter("query.completed").add()
                self.events.query_cached(info)
                self.events.query_completed(info)
                return cached, info
        # plan-stats history hints for recurring fingerprints (runs>=2):
        # the adaptive aggregation-strategy inputs, shared by the
        # estimate snapshot, EXPLAIN, and the executors
        hints = self._plan_hints(plan, fp)
        if recorder is not None:
            # snapshot the planner's per-node predictions BEFORE
            # execution (estimate-vs-actual telemetry: estimated rows,
            # sound upper bound + exactness, chosen join/agg strategy,
            # physical widths), keyed by the same stable node ids.
            # AFTER the cache lookup deliberately: a hit skips
            # execution entirely, so paying the per-node estimate walk
            # there would slow exactly the path the cache speeds up
            with trace.span("plan_estimates", "stats"):
                recorder.attach_estimates(
                    plan, self.catalog,
                    join_build_budget=self.prop("join_build_budget_bytes"),
                    approx_join=bool(self.prop("approx_join")),
                    plan_hints=hints,
                    agg_bypass=bool(self.prop("partial_agg_bypass")),
                )
        # ---- in-flight coalescing (lifecycle.InflightCoalescer) ------
        # identical concurrent queries (same binding fp) dedupe onto
        # one execution; same-template different-literal queries queue
        # behind the single warm executable via the template slot.
        # Gated by the result-cache admission rules (deterministic
        # plans, no fault injector): a follower's answer is always what
        # its own execution would have produced.
        entry = None
        if templates_on and admissible and fp is not None:
            wait_s = (self.prop("query_max_run_time")
                      or self.prop("admission_queue_timeout_s"))
            lead, payload = self.query_manager.coalescer.lead_or_wait(
                fp, wait_s)
            if lead:
                entry = payload
            elif payload is not None:
                info.state = "FINISHED"
                info.coalesced = True
                info.output_rows = len(payload)
                info.finished_at = time.time()
                info.finished_mono = time.monotonic()
                REGISTRY.counter("prepare.coalesced").add()
                REGISTRY.counter("query.completed").add()
                self.events.query_completed(info)
                return payload, info
            # else: the leader failed or the wait timed out — fall
            # through and execute this query ourselves (uncoalesced)
        try:
            executor = self._make_executor()
            executor.recorder = recorder
            executor.plan_hints = hints
            executor.agg_bypass = bool(self.prop("partial_agg_bypass"))
            # adaptive-execution decisions for THIS query (guarded:
            # property, runs>=2 via hints, fault injector, success
            # recorder, compile budget — plan/adaptive.py)
            executor.adaptive = self._adaptive_decisions(
                plan, fp, hints, executor)
            #: the literal binding as device scalars, threaded through
            #: every jitted step (plan/templates.py; expr.param_scope)
            executor.params = device_params(bound) if bound else ()
            # counters bumped AFTER run_plan returns (query.completed,
            # result-cache populate, plan-stats record, completion
            # events) land in an explicit ``post_run.`` metric bucket —
            # closing the attribution gap run_plan's delta scope cannot
            # see
            import contextlib

            from presto_tpu.runtime.metrics import (
                QueryMetricsDelta,
                install_delta,
                uninstall_delta,
            )

            post = QueryMetricsDelta()
        except BaseException:
            # a failure BEFORE the publishing try/finally below (e.g.
            # executor construction) must still retire the in-flight
            # entry, or every later identical query blocks the full
            # coalesce wait on a key nobody will ever publish
            if entry is not None:
                self.query_manager.coalescer.publish(fp, entry, None)
            raise
        published = None  # the leader's successful result, for waiters
        try:
            # cross-query BATCHED dispatch (server/batcher.py): the
            # bindings queued on this template fuse into one vmapped
            # device dispatch when the template is batchable; falls
            # back to (and interoperates with) the serialized template
            # slot below via the same per-template executor lock
            gate_on = (
                entry is not None and bound and base_fp is not None
                and bool(self.prop("batched_dispatch"))
            )
            if gate_on and not getattr(executor,
                                       "supports_batched_dispatch", False):
                # mesh sessions can't stack a binding axis onto
                # shard_map fragments — loud, then the classic path
                REGISTRY.counter("batch.fallback").add()
                REGISTRY.counter("batch.fallback.distributed").add()
                gate_on = False
            if gate_on:
                with self._profiled():
                    df = self._run_template_batched(
                        executor, plan, info, recorder, base_fp, bound)
                published = df
            else:
                # same-template serialization: first binding compiles,
                # the rest run warm back to back (leaders only;
                # identical-fp followers wait on the entry event, not
                # this lock)
                slot_cm = (
                    self.query_manager.coalescer.template_slot(base_fp)
                    if entry is not None and bound and base_fp is not None
                    else contextlib.nullcontext()
                )
                # the query.execution_s histogram is timed inside
                # run_plan AFTER admission, so pool queue wait lands in
                # queued_s / memory.queued_s, never in execution
                # percentiles
                with self._profiled(), slot_cm:
                    df = self.query_manager.run_plan(executor, plan, info,
                                                     recorder)
                published = df
            token = install_delta(post)
            try:
                info.state = "FINISHED"
                info.output_rows = len(df)
                REGISTRY.counter("query.completed").add()
                if cache_ok and fp is not None:
                    with trace.span("result_cache:populate", "cache"):
                        self.result_cache.put(
                            fp, df, table_versions(plan, self.catalog),
                            max_bytes=self.prop("result_cache_max_bytes"),
                            approximate=info.approximate,
                        )
            finally:
                uninstall_delta(token)
        except Exception as e:
            info.state = "FAILED"
            info.error = f"{type(e).__name__}: {e}"
            info.error_code = error_code(e)
            info.retryable = is_retryable(e)
            token = install_delta(post)
            try:
                REGISTRY.counter("query.failed").add()
                self.events.query_failed(info)
            finally:
                uninstall_delta(token)
            raise
        finally:
            if entry is not None:
                # wake identical-query followers with the result (or,
                # on failure, with nothing — each then runs itself:
                # coalescing batches work, never failures)
                self.query_manager.coalescer.publish(fp, entry, published)
            info.finished_at = time.time()
            info.finished_mono = time.monotonic()
            token = install_delta(post)
            try:
                if recorder is not None:
                    recorder.finalize(plan)
                    info.node_stats = [
                        s.to_dict() for s in recorder.nodes.values()
                    ]
                    if info.state == "FINISHED":
                        self._record_plan_stats(plan, info, recorder, fp)
                # stitch applied adaptive decisions into the session
                # decision log (system.adaptive) — failed runs too: a
                # post-mortem needs to know what adaptivity changed
                ev = getattr(executor, "adaptive_events", None)
                if ev:
                    self.adaptive.note_applied(
                        getattr(executor, "adaptive_fp", None) or fp or "",
                        info.query_id, ev)
                self.events.query_completed(info)
            finally:
                uninstall_delta(token)
            for k, v in post.snapshot().items():
                if v:
                    info.metrics["post_run." + k] = v
        return df, info

    def _run_template_batched(self, executor, plan, info, recorder,
                              base_fp, bound):
        """Run one bound template through the batch gate
        (server/batcher.TemplateBatchGate): enqueue the binding, then
        either get SERVED by a concurrent leader's fused dispatch, or
        LEAD — draining the queued bindings into one vmapped dispatch
        when the template is batchable (``batch.dispatched``), else
        running serially under the template executor lock (the PR 9
        serialization, with the unbatchable reason counted). Patience
        is bounded like the coalescer's wait; on timeout the query
        executes itself unserialized (correct, just unbatched)."""
        gate = self.query_manager.batch_gate
        wait_s = (self.prop("query_max_run_time")
                  or self.prop("admission_queue_timeout_s"))
        max_batch = int(self.prop("batch_max_size"))
        member = gate.enqueue(base_fp, bound)
        # lane provenance: the leader's fused dispatch stamps one
        # batch:lane span per member, carrying this origin — linking
        # every vmapped lane back to the submission that enqueued it
        member.origin = info.trace_token or info.query_id
        deadline = (None if wait_s is None
                    else time.monotonic() + float(wait_s))
        gate_t0 = time.perf_counter()
        scope = self.query_manager.scope_of(info.query_id)
        while True:
            if scope is not None:
                # batch-gate cancel checkpoint: a cancelled waiter must
                # abandon its lane (dequeue + deref) on the way out, or
                # a later leader would burn a lane on a departed thread
                try:
                    scope.check("batch-gate-wait")
                except BaseException:
                    gate.abandon(base_fp, member)
                    raise
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            role, payload = gate.lead_or_wait(base_fp, member, remaining,
                                              max_batch=max_batch)
            if role != "retry":
                # the batch-gate wait, visible in the trace between
                # submit and dispatch (the serving-tier span chain)
                trace.add_complete(
                    "batch:gate_wait", "driver", gate_t0,
                    time.perf_counter() - gate_t0, {"verdict": role})
            if role == "serve":
                # a leader's batched dispatch computed this binding —
                # same skip-the-lifecycle shape as a coalesced follower
                # (the caller's FINISHED path still populates the
                # result cache under THIS binding's fingerprint)
                info.batched = True
                info.batch_size = int(getattr(member, "batch_size", 0))
                REGISTRY.counter("batch.served").add()
                return payload
            if role == "timeout":
                REGISTRY.counter("batch.gate_timeout").add()
                return self.query_manager.run_plan(executor, plan, info,
                                                   recorder)
            if role == "retry":
                if deadline is not None and time.monotonic() >= deadline:
                    # leaving the gate without a verdict: abandon the
                    # member first, or a later leader would burn a
                    # lane on (and pin a ref for) a departed thread
                    gate.abandon(base_fp, member)
                    REGISTRY.counter("batch.gate_timeout").add()
                    return self.query_manager.run_plan(executor, plan,
                                                       info, recorder)
                continue
            # lead: this thread holds the template executor lock
            members = payload
            try:
                runner = executor
                if len(members) > 1:
                    reason = gate.template_reason(base_fp, plan,
                                                  self.catalog)
                    if reason is None:
                        from presto_tpu.server.batcher import BatchRunner

                        runner = BatchRunner(executor, gate, members,
                                             member, template_key=base_fp)
                    else:
                        REGISTRY.counter("batch.fallback").add()
                        REGISTRY.counter(f"batch.fallback.{reason}").add()
                df = self.query_manager.run_plan(runner, plan, info,
                                                 recorder)
                if runner is not executor:
                    info.batched = bool(
                        getattr(runner, "dispatched_batch", False))
                    if info.batched:
                        info.batch_size = int(
                            getattr(runner, "batch_size", 0))
                return df
            finally:
                gate.finish_lead(base_fp, member, members)

    def _plan_hints(self, plan, fp=None) -> dict:
        """Plan-stats history for this plan, keyed by the LIVE plan
        nodes: ``{id(node): estimate-vs-actual record}`` when the
        plan's fingerprint has recurred (``runs >= 2``), else empty.
        Record node_ids are the recorder's pre-order ids
        (``NodeIds.assign``), so a fresh pre-order walk of the
        shape-identical plan maps them back onto nodes. Best-effort:
        hints are advisory inputs to the adaptive aggregation strategy
        — a failure here must never fail (or even slow) a query."""
        try:
            if len(self.plan_stats) == 0:
                return {}
            from presto_tpu.cache.fingerprint import (
                plan_fingerprint,
                plan_is_deterministic,
            )

            if fp is None:
                if not plan_is_deterministic(plan, self.catalog):
                    return {}
                fp = plan_fingerprint(plan, self.catalog, self.properties,
                                      self.mesh)
            entry = self.plan_stats.get(fp, self.catalog)
            if entry is None or entry.runs < 2:
                return {}
            from presto_tpu.runtime.stats import NodeIds

            ids = NodeIds()
            ids.assign(plan)
            by_id = {}

            def walk(n):
                by_id[ids.of(n)] = n
                for c in n.children:
                    walk(c)

            walk(plan)
            # fresh copies, with the entry's recurrence count attached:
            # consumers (adaptive controller, EXPLAIN) must never
            # mutate — or observe mutation of — the store's records
            return {
                id(by_id[r["node_id"]]): {**r, "runs": entry.runs}
                for r in entry.records if r["node_id"] in by_id
            }
        except Exception:  # noqa: BLE001 — advisory only
            return {}

    def _adaptive_decisions(self, plan, fp, hints, executor,
                            for_render: bool = False) -> dict:
        """Adaptive-execution decision pass for one query (or for an
        EXPLAIN render): plan/adaptive.AdaptiveController over the
        plan-hints history. Best-effort and guarded — the
        ``adaptive_execution`` property, a missing fingerprint, or any
        internal failure yields the baseline (empty) decision map."""
        try:
            if not hints:
                return {}
            if not bool(self.prop("adaptive_execution")):
                return {}
            if not fp:
                # the caller ran without a binding fingerprint (result
                # cache off / stats run): decisions still need the
                # history key, so derive it the way _plan_hints does
                from presto_tpu.cache.fingerprint import (
                    plan_fingerprint,
                    plan_is_deterministic,
                )

                if not plan_is_deterministic(plan, self.catalog):
                    return {}
                fp = plan_fingerprint(plan, self.catalog, self.properties,
                                      self.mesh)
            if not for_render:
                # the stitch in _run_tracked_inner logs applied events
                # under the same history key the decisions used
                executor.adaptive_fp = fp
            return self.adaptive.decide(
                plan, hints, self.catalog, fingerprint=fp,
                nworkers=getattr(executor, "nworkers", 1),
                salt_max=int(self.prop("adaptive_salt_max")),
                for_render=for_render,
                recording=bool(self.prop("flight_record_successes")),
            )
        except Exception:  # noqa: BLE001 — adaptivity never fails a query
            return {}

    def _explain_adaptive(self, plan, hints) -> dict:
        """WOULD-BE adaptive decisions for EXPLAIN rendering (no
        logging, no stickiness, no runtime stand-down guards — the
        steady-state plan a recurring query will get)."""
        try:
            if not hints:
                return {}
            from presto_tpu.cache.fingerprint import (
                plan_fingerprint,
                plan_is_deterministic,
            )

            if not plan_is_deterministic(plan, self.catalog):
                return {}
            fp = plan_fingerprint(plan, self.catalog, self.properties,
                                  self.mesh)
            return self._adaptive_decisions(plan, fp, hints, self.executor,
                                            for_render=True)
        except Exception:  # noqa: BLE001 — EXPLAIN renders partial plans
            return {}

    def _record_plan_stats(self, plan, info, recorder, fp) -> None:
        """Persist the run's estimate-vs-actual records into the
        fingerprint-keyed history store (system.plan_stats). Reuses the
        result-cache lookup's fingerprint when one was computed;
        volatile plans (system-table scans) are never recorded — their
        cardinalities describe engine state, not data. Best-effort: a
        recording failure must never fail a FINISHED query."""
        from presto_tpu.cache.fingerprint import (
            plan_fingerprint,
            plan_is_deterministic,
            table_versions,
        )

        try:
            if not recorder.estimates:
                return
            if fp is None:
                if not plan_is_deterministic(plan, self.catalog):
                    return
                fp = plan_fingerprint(plan, self.catalog, self.properties,
                                      self.mesh)
            with trace.span("plan_stats:record", "stats"):
                self.plan_stats.put(
                    fp, info.query_id, table_versions(plan, self.catalog),
                    recorder.estimate_vs_actual(),
                )
        except Exception:  # noqa: BLE001 — observability never fails a query
            REGISTRY.counter("plan_stats.record_errors").add()

    # ------------------------------------------------------------------
    def export_metrics(self, path: Optional[str] = None) -> str:
        """The process metrics registry as OpenMetrics/Prometheus text
        exposition (counters, timers, histogram quantiles — see
        ``runtime.metrics.to_openmetrics``), plus live state gauges the
        counter registry cannot carry: memory-pool occupancy, compiled-
        executable cache entries, and this session's flight-recorder
        ring depth. Returns the text; with ``path``, also writes it
        there (the scrape-file shape; ``python -m presto_tpu metrics``
        is the CLI surface)."""
        from presto_tpu.cache.exec_cache import EXEC_CACHE
        from presto_tpu.runtime.metrics import to_openmetrics

        snap = self.pool().snapshot()
        gauges = {
            "memory_pool_capacity_bytes": snap["capacity_bytes"],
            "memory_pool_reserved_bytes": snap["reserved_bytes"],
            "memory_pool_occupancy": (
                snap["reserved_bytes"] / snap["capacity_bytes"]
                if snap["capacity_bytes"] else 0.0),
            "exec_cache_entries": len(EXEC_CACHE),
            "flight_recorder_depth": len(self.flight),
        }
        # serving-tier health gauges (ISSUE 18): per-device allocator
        # state, tenant SLO burn rates, and the watchdog's latest
        # sample — each best-effort, none may fail the scrape
        if self.prop("device_telemetry"):
            try:
                from presto_tpu.runtime import devices

                gauges.update(devices.gauges())
            except Exception:  # noqa: BLE001
                pass
        for layer in (self.slo, self.health):
            if layer is not None:
                try:
                    gauges.update(layer.gauges())
                except Exception:  # noqa: BLE001
                    pass
        text = to_openmetrics(gauges=gauges)
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def export_flight_record(self, path: Optional[str] = None,
                             query_id: Optional[str] = None) -> str:
        """Flight-recorder post-mortems as JSON (runtime/flight.py):
        one record with ``query_id``, else the whole ring (newest
        last). Returns the JSON text; with ``path``, also writes it
        there (``python -m presto_tpu flightrec`` is the CLI surface —
        the dump-on-failure workflow)."""
        text = self.flight.to_json(query_id)
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def export_plan_stats(self, path: Optional[str] = None) -> str:
        """The plan-stats history (system.plan_stats) as JSON — the
        warm-restart half of adaptive execution. A server about to
        restart exports; its successor imports
        (:meth:`import_plan_stats`) and history-driven decisions
        resume at full recurrence counts instead of starting cold.
        Returns the JSON text; with ``path``, also writes it there."""
        text = self.plan_stats.to_json()
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def import_plan_stats(self, path: str) -> int:
        """Merge a previously exported plan-stats history from
        ``path``, returning the number of entries imported. Entries
        are version-checked against the CURRENT catalog's table epochs
        — history recorded against data that has since changed is
        skipped (``plan_stats.import_stale``), and a document in an
        unknown format is refused (UserError)."""
        with open(path) as f:
            text = f.read()
        try:
            return self.plan_stats.load_json(text, catalog=self.catalog)
        except ValueError as e:
            raise UserError(str(e)) from e

    def export_trace(self, path: str, query_id: Optional[str] = None) -> str:
        """Write retained span traces as Chrome ``trace_event`` JSON
        (load in Perfetto / chrome://tracing). ``query_id`` narrows the
        export to one query; default exports every retained trace, one
        pid per query. Returns ``path``."""
        from presto_tpu.runtime.trace import export_chrome_trace

        if query_id is None:
            recorders = self.traces.recorders()
        else:
            rec = self.traces.for_query(query_id)
            if rec is None:
                raise UserError(f"no retained trace for query {query_id!r} "
                                "(trace_enabled off, or evicted)")
            recorders = [rec]
        if not recorders:
            raise UserError(
                "no traces retained (is trace_enabled set to false?)"
            )
        return export_chrome_trace(path, recorders)
