"""Per-query execution statistics.

Reference parity: ``OperatorStats`` accumulated in ``OperatorContext``,
rolled up Driver->Pipeline->Task->``QueryStats`` and shipped in
``QueryInfo`` JSON; rendered by EXPLAIN ANALYZE [SURVEY §5.1;
reference tree unavailable, paths reconstructed].

TPU-first shape: the single-controller executors have one dispatch
choke point per plan node, so stats attach to *plan nodes* (the logical
operators) rather than worker-side operator instances. Device-compute
inside a fused step is opaque to host timers by design — XLA owns the
schedule; per-node wall time measures the host-observed latency of the
node's dispatch including its device work (jax profiler traces cover
the intra-step timeline, SURVEY §5.1 TPU mapping).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class NodeStats:
    """Actuals for one plan node (reference: OperatorStats)."""

    node_type: str
    detail: str = ""
    wall_s: float = 0.0
    output_rows: int = -1  # -1: not measured
    invocations: int = 0

    def to_dict(self):
        return {
            "node": self.node_type,
            "detail": self.detail,
            "wall_s": round(self.wall_s, 6),
            "output_rows": self.output_rows,
            "invocations": self.invocations,
        }


class StatsRecorder:
    """Collects NodeStats keyed by plan-node identity during one query."""

    def __init__(self, measure_rows: bool = True):
        self.nodes: dict[int, NodeStats] = {}
        self.measure_rows = measure_rows

    def record(self, node, wall_s: float, output_rows: int = -1):
        key = id(node)
        st = self.nodes.get(key)
        if st is None:
            st = NodeStats(type(node).__name__)
            self.nodes[key] = st
        st.wall_s += wall_s
        st.invocations += 1
        if output_rows >= 0:
            st.output_rows = output_rows

    def stats_for(self, node) -> Optional[NodeStats]:
        return self.nodes.get(id(node))


@dataclass
class QueryInfo:
    """One executed query's full record (reference: QueryInfo JSON).

    ``trace_token`` propagates from the session for cross-system
    correlation [SURVEY §5.1]."""

    query_id: str
    sql: str
    state: str  # QUEUED -> RUNNING -> FINISHED | FAILED
    created_at: float
    trace_token: Optional[str] = None
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    error: Optional[str] = None
    #: taxonomy code (runtime/errors.py), set on FAILED transitions
    error_code: Optional[str] = None
    #: retry class of the failure (None while not failed)
    retryable: Optional[bool] = None
    #: fragment-level retries performed during execution
    fragment_retries: int = 0
    #: True when a failed distributed run degraded to the local pipeline
    degraded: bool = False
    #: True when the result was served from the versioned result cache
    #: (no execution happened; node_stats stay empty)
    cache_hit: bool = False
    output_rows: int = -1
    node_stats: list = field(default_factory=list)  # list[NodeStats.to_dict()]

    @property
    def elapsed_s(self) -> float:
        if self.started_at is None:
            return 0.0
        end = self.finished_at if self.finished_at is not None else time.time()
        return end - self.started_at

    def to_json(self) -> str:
        return json.dumps(
            {
                "queryId": self.query_id,
                "sql": self.sql,
                "state": self.state,
                "traceToken": self.trace_token,
                "createdAt": self.created_at,
                "startedAt": self.started_at,
                "finishedAt": self.finished_at,
                "elapsedS": round(self.elapsed_s, 6),
                "error": self.error,
                "errorCode": self.error_code,
                "retryable": self.retryable,
                "fragmentRetries": self.fragment_retries,
                "degraded": self.degraded,
                "cacheHit": self.cache_hit,
                "outputRows": self.output_rows,
                "nodeStats": self.node_stats,
            }
        )


def render_analyzed_plan(plan, recorder: StatsRecorder) -> str:
    """EXPLAIN ANALYZE rendering: the plan tree annotated with actuals
    (reference: PlanPrinter.textDistributedPlan with stats)."""
    from presto_tpu.plan.nodes import plan_tree_str

    lines = []

    def walk(node, indent):
        pad = "  " * indent
        name = type(node).__name__
        st = recorder.stats_for(node)
        if st is not None:
            rows = "?" if st.output_rows < 0 else f"{st.output_rows:,}"
            lines.append(
                f"{pad}{name}  [wall {st.wall_s * 1e3:.1f}ms, rows {rows}, "
                f"calls {st.invocations}]"
            )
        else:
            lines.append(f"{pad}{name}  [not executed]")
        for c in node.children:
            walk(c, indent + 1)

    walk(plan, 0)
    return "\n".join(lines) + "\n"
