"""Per-query execution statistics.

Reference parity: ``OperatorStats`` accumulated in ``OperatorContext``,
rolled up Driver->Pipeline->Task->``QueryStats`` and shipped in
``QueryInfo`` JSON; rendered by EXPLAIN ANALYZE [SURVEY §5.1;
reference tree unavailable, paths reconstructed].

TPU-first shape: the single-controller executors have one dispatch
choke point per plan node, so stats attach to *plan nodes* (the logical
operators) rather than worker-side operator instances. Device-compute
inside a fused step is opaque to host timers by design — XLA owns the
schedule; per-node wall time measures the host-observed latency of the
node's dispatch including its device work (jax profiler traces cover
the intra-step timeline, SURVEY §5.1 TPU mapping).

Node identity: stats key on *stable per-query plan-node ids* assigned
by :class:`NodeIds` (pre-order over the plan, dispatch order for
synthetic nodes) — never on raw ``id(node)``. A bare ``id()`` key is
the same bug class as the ``id()``-keyed minmax cache removed in PR 2:
CPython reuses addresses after GC, which could silently merge two
distinct nodes' stats. ``NodeIds`` pins a strong reference to every
node it names, so an id can never be reused while the map lives.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Optional


class NodeIds:
    """Stable per-query plan-node ids (shared by StatsRecorder and the
    trace layer so spans and stats correlate on ``plan_node_id``)."""

    __slots__ = ("_ids", "_pinned", "_next")

    def __init__(self):
        self._ids: dict[int, int] = {}
        #: strong refs: an id(node) key stays unique for our lifetime
        self._pinned: list = []
        self._next = 0

    def assign(self, plan) -> None:
        """Pre-order id assignment over a plan tree (deterministic ids
        for EXPLAIN/export; idempotent per node)."""
        self.of(plan)
        for c in plan.children:
            self.assign(c)

    def of(self, node) -> int:
        key = id(node)
        nid = self._ids.get(key)
        if nid is None:
            nid = self._next
            self._next += 1
            self._ids[key] = nid
            self._pinned.append(node)
        return nid

    def get(self, node) -> Optional[int]:
        return self._ids.get(id(node))


@dataclass
class NodeStats:
    """Actuals for one plan node (reference: OperatorStats)."""

    node_type: str
    detail: str = ""
    node_id: int = -1
    wall_s: float = 0.0
    input_rows: int = -1  # -1: not measured
    output_rows: int = -1  # -1: not measured
    output_bytes: int = -1  # live-row payload bytes of the node's output
    device_bytes: int = -1  # peak device-buffer (capacity) bytes observed
    invocations: int = 0

    def to_dict(self):
        return {
            "node": self.node_type,
            "detail": self.detail,
            "nodeId": self.node_id,
            "wall_s": round(self.wall_s, 6),
            "input_rows": self.input_rows,
            "output_rows": self.output_rows,
            "output_bytes": self.output_bytes,
            "device_bytes": self.device_bytes,
            "invocations": self.invocations,
        }


class StatsRecorder:
    """Collects NodeStats keyed by stable per-query node id."""

    def __init__(self, measure_rows: bool = True):
        self.ids = NodeIds()
        self.nodes: dict[int, NodeStats] = {}
        self.measure_rows = measure_rows

    def attach_plan(self, plan) -> None:
        """Pre-assign deterministic pre-order ids for a plan about to
        execute (synthetic nodes dispatched later extend the space)."""
        self.ids.assign(plan)

    def node_id(self, node) -> int:
        return self.ids.of(node)

    def record(self, node, wall_s: float, output_rows: int = -1,
               output_bytes: int = -1, device_bytes: int = -1):
        key = self.ids.of(node)
        st = self.nodes.get(key)
        if st is None:
            st = NodeStats(type(node).__name__, node_id=key)
            self.nodes[key] = st
        st.wall_s += wall_s
        st.invocations += 1
        if output_rows >= 0:
            st.output_rows = output_rows
        if output_bytes >= 0:
            st.output_bytes = (
                output_bytes if st.output_bytes < 0
                else st.output_bytes + output_bytes
            )
        if device_bytes >= 0:
            st.device_bytes = max(st.device_bytes, device_bytes)

    def stats_for(self, node) -> Optional[NodeStats]:
        nid = self.ids.get(node)
        return None if nid is None else self.nodes.get(nid)

    def finalize(self, plan) -> None:
        """Derive each node's input_rows from its children's measured
        output_rows (the Driver->Pipeline rollup direction)."""

        def walk(node):
            st = self.stats_for(node)
            if st is not None and node.children:
                total, known = 0, False
                for c in node.children:
                    cst = self.stats_for(c)
                    if cst is not None and cst.output_rows >= 0:
                        total += cst.output_rows
                        known = True
                if known:
                    st.input_rows = total
            for c in node.children:
                walk(c)

        walk(plan)


@dataclass
class QueryInfo:
    """One executed query's full record (reference: QueryInfo JSON).

    ``trace_token`` propagates from the session for cross-system
    correlation [SURVEY §5.1]. Wall-clock fields (``created_at`` etc.)
    are for display; *durations* come from the monotonic mirror fields
    (``*_mono``) — a wall-clock step (NTP, DST) must never produce a
    negative or inflated elapsed time."""

    query_id: str
    sql: str
    state: str  # QUEUED -> RUNNING -> FINISHED | FAILED
    created_at: float
    trace_token: Optional[str] = None
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    #: monotonic mirrors of the lifecycle timestamps (duration source)
    created_mono: Optional[float] = None
    started_mono: Optional[float] = None
    finished_mono: Optional[float] = None
    #: host time spent in parse/analyze/prune before tracking started
    planning_s: float = 0.0
    error: Optional[str] = None
    #: taxonomy code (runtime/errors.py), set on FAILED transitions
    error_code: Optional[str] = None
    #: retry class of the failure (None while not failed)
    retryable: Optional[bool] = None
    #: fragment-level retries performed during execution
    fragment_retries: int = 0
    #: True when a failed distributed run degraded to the local pipeline
    degraded: bool = False
    #: rungs taken down the runtime-OOM degradation ladder (0 = none)
    oom_retries: int = 0
    #: seconds spent queued on the shared memory pool at admission
    memory_queued_s: float = 0.0
    #: bytes reserved from the pool (the peak stats estimate)
    memory_reserved_bytes: int = 0
    #: True when the result was served from the versioned result cache
    #: (no execution happened; node_stats stay empty)
    cache_hit: bool = False
    #: True when the run probed an APPROXIMATE join sketch (the
    #: ``approx_join`` session property routed a semi join through the
    #: Bloom sketch): the result may contain false-positive rows.
    #: Exact results are NEVER silently degraded — this flag (and the
    #: EXPLAIN ``strategy=sketch(approx)`` rendering) is the contract
    approximate: bool = False
    output_rows: int = -1
    node_stats: list = field(default_factory=list)  # list[NodeStats.to_dict()]

    @property
    def queued_s(self) -> float:
        """QUEUED -> RUNNING (monotonic; 0 while still queued)."""
        if self.created_mono is None or self.started_mono is None:
            return 0.0
        return max(0.0, self.started_mono - self.created_mono)

    @property
    def execution_s(self) -> float:
        """RUNNING -> terminal (monotonic; live queries read 'so far')."""
        if self.started_mono is None:
            return 0.0
        end = (
            self.finished_mono if self.finished_mono is not None
            else time.monotonic()
        )
        return max(0.0, end - self.started_mono)

    @property
    def elapsed_s(self) -> float:
        if self.started_mono is not None:
            return self.execution_s
        # legacy construction without monotonic mirrors: wall fallback
        if self.started_at is None:
            return 0.0
        end = self.finished_at if self.finished_at is not None else time.time()
        return end - self.started_at

    def to_json(self) -> str:
        return json.dumps(
            {
                "queryId": self.query_id,
                "sql": self.sql,
                "state": self.state,
                "traceToken": self.trace_token,
                "createdAt": self.created_at,
                "startedAt": self.started_at,
                "finishedAt": self.finished_at,
                "elapsedS": round(self.elapsed_s, 6),
                "queuedS": round(self.queued_s, 6),
                "planningS": round(self.planning_s, 6),
                "executionS": round(self.execution_s, 6),
                "error": self.error,
                "errorCode": self.error_code,
                "retryable": self.retryable,
                "fragmentRetries": self.fragment_retries,
                "degraded": self.degraded,
                "oomRetries": self.oom_retries,
                "memoryQueuedS": round(self.memory_queued_s, 6),
                "memoryReservedBytes": self.memory_reserved_bytes,
                "cacheHit": self.cache_hit,
                "approximate": self.approximate,
                "outputRows": self.output_rows,
                "nodeStats": self.node_stats,
            }
        )


def _fmt_bytes(n: int) -> str:
    if n < 0:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"  # pragma: no cover


def render_analyzed_plan(plan, recorder: StatsRecorder,
                         tracer=None) -> str:
    """EXPLAIN ANALYZE rendering: the plan tree annotated with actuals
    (reference: PlanPrinter.textDistributedPlan with stats), followed
    by the query's exchange and cache span rollups when a trace
    recorder is supplied."""
    lines = []

    def walk(node, indent):
        pad = "  " * indent
        name = type(node).__name__
        st = recorder.stats_for(node)
        if st is not None:
            rows = "?" if st.output_rows < 0 else f"{st.output_rows:,}"
            in_rows = "?" if st.input_rows < 0 else f"{st.input_rows:,}"
            lines.append(
                f"{pad}{name}  [wall {st.wall_s * 1e3:.1f}ms, "
                f"rows {in_rows}->{rows}, "
                f"bytes {_fmt_bytes(st.output_bytes)}, "
                f"calls {st.invocations}]"
            )
        else:
            lines.append(f"{pad}{name}  [not executed]")
        for c in node.children:
            walk(c, indent + 1)

    walk(plan, 0)
    if tracer is not None:
        ex = tracer.spans_by_cat("exchange")
        if ex:
            total = sum(int(s.args.get("bytes", 0)) for s in ex)
            rounds = sum(int(s.args.get("rounds", 0)) for s in ex)
            wall = sum(max(s.t1 - s.t0, 0.0) for s in ex)
            lines.append(
                f"exchanges: {len(ex)} dispatches, {_fmt_bytes(total)} "
                f"moved, {rounds} rounds, wall {wall * 1e3:.1f}ms"
            )
        for s in tracer.spans_by_cat("cache"):
            extra = ", ".join(f"{k}={v}" for k, v in sorted(s.args.items()))
            lines.append(
                f"cache: {s.name} {max(s.t1 - s.t0, 0.0) * 1e3:.2f}ms"
                + (f" ({extra})" if extra else "")
            )
    return "\n".join(lines) + "\n"
