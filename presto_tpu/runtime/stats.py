"""Per-query execution statistics.

Reference parity: ``OperatorStats`` accumulated in ``OperatorContext``,
rolled up Driver->Pipeline->Task->``QueryStats`` and shipped in
``QueryInfo`` JSON; rendered by EXPLAIN ANALYZE [SURVEY §5.1;
reference tree unavailable, paths reconstructed].

TPU-first shape: the single-controller executors have one dispatch
choke point per plan node, so stats attach to *plan nodes* (the logical
operators) rather than worker-side operator instances. Device-compute
inside a fused step is opaque to host timers by design — XLA owns the
schedule; per-node wall time measures the host-observed latency of the
node's dispatch including its device work (jax profiler traces cover
the intra-step timeline, SURVEY §5.1 TPU mapping).

Node identity: stats key on *stable per-query plan-node ids* assigned
by :class:`NodeIds` (pre-order over the plan, dispatch order for
synthetic nodes) — never on raw ``id(node)``. A bare ``id()`` key is
the same bug class as the ``id()``-keyed minmax cache removed in PR 2:
CPython reuses addresses after GC, which could silently merge two
distinct nodes' stats. ``NodeIds`` pins a strong reference to every
node it names, so an id can never be reused while the map lives.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Optional


class NodeIds:
    """Stable per-query plan-node ids (shared by StatsRecorder and the
    trace layer so spans and stats correlate on ``plan_node_id``)."""

    __slots__ = ("_ids", "_pinned", "_next")

    def __init__(self):
        self._ids: dict[int, int] = {}
        #: strong refs: an id(node) key stays unique for our lifetime
        self._pinned: list = []
        self._next = 0

    def assign(self, plan) -> None:
        """Pre-order id assignment over a plan tree (deterministic ids
        for EXPLAIN/export; idempotent per node)."""
        self.of(plan)
        for c in plan.children:
            self.assign(c)

    def of(self, node) -> int:
        key = id(node)
        nid = self._ids.get(key)
        if nid is None:
            nid = self._next
            self._next += 1
            self._ids[key] = nid
            self._pinned.append(node)
        return nid

    def get(self, node) -> Optional[int]:
        return self._ids.get(id(node))


#: symmetric misestimate factor at which EXPLAIN ANALYZE flags a node
#: loudly: estimate and actual disagree by >= this in either direction.
#: 4x is past any capacity-retry slack the executors absorb silently —
#: the point where the adaptive decisions (ROADMAP item 2) would have
#: chosen differently with the truth.
MISEST_FACTOR = 4.0


def misestimate_ratio(est_rows: int, actual_rows: int) -> float:
    """Symmetric est-vs-actual factor: ``max(actual/est, est/actual)``
    (always >= 1 when both measured; 0.0 when either side is unknown).
    ``actual == 0`` reports the estimate itself — predicting N rows and
    seeing none is an N-fold miss, not a divide-by-zero."""
    if est_rows is None or est_rows <= 0 or actual_rows < 0:
        return 0.0
    if actual_rows == 0:
        return float(est_rows)
    return max(actual_rows / est_rows, est_rows / actual_rows)


@dataclass
class NodeEstimate:
    """Plan-time snapshot of what the planner PREDICTED for one node —
    frozen before execution so the finalize-time comparison against
    :class:`NodeStats` actuals can never be contaminated by runtime
    state (the estimate-vs-actual telemetry's left-hand side)."""

    node_id: int
    node_type: str
    #: bounds.estimate_rows — the selectivity-guessing estimate that
    #: sizes group capacities and admission
    est_rows: int
    #: fragmenter.upper_bound_rows — the SOUND bound (None: unprovable)
    upper_bound_rows: Optional[int] = None
    #: True when the sound bound is EXACT (no predicate below — the
    #: fragmenter's proven-broadcast condition)
    exact: bool = False
    #: joinfilters.planned_join_strategy for Join/SemiJoin nodes
    strategy: str = ""
    #: physical (narrowed) per-row output bytes the planner assumed
    row_bytes: int = -1

    def to_dict(self):
        return {
            "nodeId": self.node_id,
            "node": self.node_type,
            "est_rows": self.est_rows,
            "upper_bound_rows": self.upper_bound_rows,
            "exact": self.exact,
            "strategy": self.strategy,
            "row_bytes": self.row_bytes,
        }


@dataclass
class NodeStats:
    """Actuals for one plan node (reference: OperatorStats)."""

    node_type: str
    detail: str = ""
    node_id: int = -1
    wall_s: float = 0.0
    input_rows: int = -1  # -1: not measured
    output_rows: int = -1  # -1: not measured
    output_bytes: int = -1  # live-row payload bytes of the node's output
    device_bytes: int = -1  # peak device-buffer (capacity) bytes observed
    invocations: int = 0
    #: plan-time predicted rows (copied from NodeEstimate at finalize;
    #: -1 when no estimate snapshot was taken)
    est_rows: int = -1
    #: planner-chosen join strategy for Join/SemiJoin nodes ("" else)
    strategy: str = ""
    #: worst observed exchange-partition skew (max/mean delivered-row
    #: ratio across destinations) of the exchanges this node drove;
    #: 0.0 = no partitioned exchange measured, 1.0 = balanced
    skew: float = 0.0
    #: live rows those exchanges delivered (the skew's weight)
    exchange_rows: int = 0
    #: hottest partition id of the worst-skew exchange (-1: none seen)
    hot_partition: int = -1
    #: True when a planner-chosen fused (Pallas) route fell back at
    #: runtime — advisory stats lied; adaptive execution reads this to
    #: stop re-attempting the route for recurring fingerprints
    route_fallback: bool = False
    #: executed out-of-core mode ("" = resident / no spill tier ran)
    spill_mode: str = ""
    #: spill partition count (0 outside the spill tier)
    spill_partitions: int = 0
    #: partitions kept device-resident by a hybrid plan
    spill_resident: int = 0
    #: peak host-RAM bytes this node's spill stores held
    spill_host_bytes: int = 0

    @property
    def misest(self) -> float:
        """Symmetric est-vs-actual factor (0.0 when unmeasured)."""
        if self.est_rows < 0 or self.output_rows < 0:
            return 0.0
        return misestimate_ratio(self.est_rows, self.output_rows)

    def to_dict(self):
        return {
            "node": self.node_type,
            "detail": self.detail,
            "nodeId": self.node_id,
            "wall_s": round(self.wall_s, 6),
            "input_rows": self.input_rows,
            "output_rows": self.output_rows,
            "output_bytes": self.output_bytes,
            "device_bytes": self.device_bytes,
            "invocations": self.invocations,
            "est_rows": self.est_rows,
            "strategy": self.strategy,
            "misest": round(self.misest, 3),
            "skew": round(self.skew, 3),
            "exchange_rows": self.exchange_rows,
            "hot_partition": self.hot_partition,
            "route_fallback": self.route_fallback,
            "spill_mode": self.spill_mode,
            "spill_partitions": self.spill_partitions,
            "spill_resident": self.spill_resident,
            "spill_host_bytes": self.spill_host_bytes,
        }


class StatsRecorder:
    """Collects NodeStats keyed by stable per-query node id."""

    def __init__(self, measure_rows: bool = True):
        self.ids = NodeIds()
        self.nodes: dict[int, NodeStats] = {}
        #: plan-time estimate snapshot, same node-id key space
        self.estimates: dict[int, NodeEstimate] = {}
        self.measure_rows = measure_rows

    def attach_plan(self, plan) -> None:
        """Pre-assign deterministic pre-order ids for a plan about to
        execute (synthetic nodes dispatched later extend the space)."""
        self.ids.assign(plan)

    def attach_estimates(self, plan, catalog,
                         join_build_budget: Optional[int] = None,
                         approx_join: bool = False,
                         plan_hints: Optional[dict] = None,
                         agg_bypass: bool = True) -> None:
        """Snapshot the planner's per-node predictions BEFORE execution,
        keyed by the same stable node ids the actuals use: estimated
        rows (bounds.estimate_rows), the sound upper bound + exactness
        (fragmenter.upper_bound_rows / is_unfiltered), the chosen join
        strategy (joinfilters.planned_join_strategy) or aggregation
        strategy (leaf_route.agg_strategy_for, fed by ``plan_hints`` —
        plan-stats history for recurring fingerprints), and the
        physical row width. A per-node stats gap degrades that node's
        snapshot, never the query (the admission-control posture).

        One ``memo`` dict rides the whole walk: ``estimate_rows`` /
        ``node_intervals`` are memoized per node id, so the snapshot is
        linear in plan size instead of quadratic (pure memoization —
        every rendered estimate is unchanged)."""
        from presto_tpu.plan import nodes as N
        from presto_tpu.plan.bounds import estimate_record
        from presto_tpu.plan.joinfilters import planned_join_strategy
        from presto_tpu.runtime.memory import node_row_bytes

        memo: dict = {}

        def walk(node):
            nid = self.ids.of(node)
            est, ub, exact = 1, None, False
            try:
                rec = estimate_record(node, catalog, memo=memo)
                est, ub, exact = (rec["est_rows"],
                                  rec["upper_bound_rows"], rec["exact"])
            except Exception:  # noqa: BLE001 — stats gaps never block
                pass
            strategy = ""
            if isinstance(node, (N.Join, N.SemiJoin)):
                try:
                    strategy = planned_join_strategy(
                        node, catalog, join_build_budget=join_build_budget,
                        approx_join=approx_join, memo=memo)
                except Exception:  # noqa: BLE001
                    strategy = ""
            elif isinstance(node, N.Aggregate):
                try:
                    from presto_tpu.exec.leaf_route import agg_strategy_for

                    # fused_enabled=False: recorder runs take the
                    # generic tiers (the executors skip the leaf route
                    # so per-node actuals stay true), so the snapshot
                    # records the strategy THIS run uses
                    strategy = agg_strategy_for(
                        node, catalog, hints=plan_hints, memo=memo,
                        bypass_enabled=agg_bypass, fused_enabled=False)
                except Exception:  # noqa: BLE001
                    strategy = ""
            try:
                rb = node_row_bytes(node, catalog)
            except Exception:  # noqa: BLE001
                rb = -1
            self.estimates[nid] = NodeEstimate(
                nid, type(node).__name__, int(est), ub, bool(exact),
                strategy, rb)
            for c in node.children:
                walk(c)

        walk(plan)

    def node_id(self, node) -> int:
        return self.ids.of(node)

    def record(self, node, wall_s: float, output_rows: int = -1,
               output_bytes: int = -1, device_bytes: int = -1):
        key = self.ids.of(node)
        st = self.nodes.get(key)
        if st is None:
            st = NodeStats(type(node).__name__, node_id=key)
            self.nodes[key] = st
        st.wall_s += wall_s
        st.invocations += 1
        if output_rows >= 0:
            # accumulate like wall_s/output_bytes: a node invoked once
            # per batch/bucket must report its TOTAL rows, not the last
            # invocation's (the last-write-wins bug under-reported
            # multi-batch nodes in EXPLAIN ANALYZE and the finalize
            # input_rows rollup). Known trade-off shared with the
            # bytes/wall accumulators: a fragment RETRY re-dispatches
            # its subtree into the same recorder, so retried queries
            # over-count (invocations says by how much); OOM-ladder
            # re-runs don't — the lifecycle clears nodes per rung
            st.output_rows = (
                output_rows if st.output_rows < 0
                else st.output_rows + output_rows
            )
        if output_bytes >= 0:
            st.output_bytes = (
                output_bytes if st.output_bytes < 0
                else st.output_bytes + output_bytes
            )
        if device_bytes >= 0:
            st.device_bytes = max(st.device_bytes, device_bytes)

    def record_skew(self, node, ratio: float, rows: int = 0,
                    hot: Optional[int] = None) -> None:
        """Attach an exchange-skew observation to the node that drove
        the exchange (distributed executor flush path): the WORST ratio
        wins — a post-mortem wants the hottest imbalance, and a
        capacity-retried exchange reports once per dispatch. ``hot``
        names the hottest destination of that worst exchange; it rides
        the plan-stats history so a recurring fingerprint's hybrid
        spill plan can seed its resident set from it."""
        key = self.ids.of(node)
        st = self.nodes.get(key)
        if st is None:
            st = NodeStats(type(node).__name__, node_id=key)
            self.nodes[key] = st
        if float(ratio) >= st.skew and hot is not None:
            st.hot_partition = int(hot)
        st.skew = max(st.skew, float(ratio))
        st.exchange_rows += int(rows)

    def record_route_fallback(self, node) -> None:
        """Mark a node whose planner-chosen fused (Pallas) route fell
        back at runtime — the build's advisory stats were violated.
        Rides the plan-stats history so adaptive execution stops
        re-attempting the route for this fingerprint (the lying-stats
        posture: degrade once, remember, stay on the generic tier)."""
        key = self.ids.of(node)
        st = self.nodes.get(key)
        if st is None:
            st = NodeStats(type(node).__name__, node_id=key)
            self.nodes[key] = st
        st.route_fallback = True

    def record_spill(self, node, mode: str, partitions: int,
                     resident: int, host_bytes: int) -> None:
        """Attach the executed out-of-core decision to a node (both
        executors' spill strategy points): what mode actually ran, how
        many partitions, how many stayed device-resident, and the peak
        host bytes its spill stores held."""
        key = self.ids.of(node)
        st = self.nodes.get(key)
        if st is None:
            st = NodeStats(type(node).__name__, node_id=key)
            self.nodes[key] = st
        st.spill_mode = mode
        st.spill_partitions = int(partitions)
        st.spill_resident = int(resident)
        st.spill_host_bytes = max(st.spill_host_bytes, int(host_bytes))

    def stats_for(self, node) -> Optional[NodeStats]:
        nid = self.ids.get(node)
        return None if nid is None else self.nodes.get(nid)

    def estimate_for(self, node) -> Optional[NodeEstimate]:
        nid = self.ids.get(node)
        return None if nid is None else self.estimates.get(nid)

    def finalize(self, plan) -> None:
        """Derive each node's input_rows from its children's measured
        output_rows (the Driver->Pipeline rollup direction), and close
        the estimate-vs-actual loop: executed nodes with a plan-time
        snapshot get ``est_rows``/``strategy`` copied onto their
        NodeStats so QueryInfo JSON and EXPLAIN ANALYZE carry both
        sides plus the misestimate ratio."""

        def walk(node):
            st = self.stats_for(node)
            if st is not None and node.children:
                total, known = 0, False
                for c in node.children:
                    cst = self.stats_for(c)
                    if cst is not None and cst.output_rows >= 0:
                        total += cst.output_rows
                        known = True
                if known:
                    st.input_rows = total
            for c in node.children:
                walk(c)

        walk(plan)
        for nid, est in self.estimates.items():
            st = self.nodes.get(nid)
            if st is not None:
                st.est_rows = est.est_rows
                st.strategy = est.strategy

    def estimate_vs_actual(self) -> list:
        """Per-node (node_id, node_type, est, actual, selectivity,
        strategy, misest) records — the rows the plan-stats history
        store persists under the query's plan fingerprint. Selectivity
        is the node's measured output/input row ratio (-1.0 when either
        side is unmeasured)."""
        out = []
        for nid in sorted(self.estimates):
            est = self.estimates[nid]
            st = self.nodes.get(nid)
            actual = -1 if st is None else st.output_rows
            sel = -1.0
            if (st is not None and st.input_rows > 0
                    and st.output_rows >= 0):
                sel = st.output_rows / st.input_rows
            out.append({
                "node_id": nid,
                "node_type": est.node_type,
                "est_rows": est.est_rows,
                "actual_rows": actual,
                "selectivity": sel,
                "strategy": est.strategy,
                "misest": misestimate_ratio(est.est_rows, actual),
                # observed exchange-partition skew rides the history
                # beside est/actual: recurring skew becomes visible at
                # PLAN time (EXPLAIN (TYPE DISTRIBUTED) headers)
                "skew": 0.0 if st is None else round(st.skew, 3),
                # hottest partition + executed spill mode ride along so
                # a recurring fingerprint's NEXT run can seed its
                # hybrid resident set from measured skew
                "hot_partition": -1 if st is None else st.hot_partition,
                "spill_mode": "" if st is None else st.spill_mode,
                # measured node wall + runtime route fallback ride the
                # history for the adaptive controller: wall_s prices
                # the compile-budget gate's predicted win, and a lying
                # fused-route fragment stops being re-attempted
                "wall_s": 0.0 if st is None else round(st.wall_s, 6),
                "route_fallback": (False if st is None
                                   else bool(st.route_fallback)),
            })
        return out


@dataclass
class QueryInfo:
    """One executed query's full record (reference: QueryInfo JSON).

    ``trace_token`` propagates from the session for cross-system
    correlation [SURVEY §5.1]. Wall-clock fields (``created_at`` etc.)
    are for display; *durations* come from the monotonic mirror fields
    (``*_mono``) — a wall-clock step (NTP, DST) must never produce a
    negative or inflated elapsed time."""

    query_id: str
    sql: str
    state: str  # QUEUED -> RUNNING -> FINISHED | FAILED
    created_at: float
    trace_token: Optional[str] = None
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    #: monotonic mirrors of the lifecycle timestamps (duration source)
    created_mono: Optional[float] = None
    started_mono: Optional[float] = None
    finished_mono: Optional[float] = None
    #: host time spent in parse/analyze/prune before tracking started
    planning_s: float = 0.0
    error: Optional[str] = None
    #: taxonomy code (runtime/errors.py), set on FAILED transitions
    error_code: Optional[str] = None
    #: retry class of the failure (None while not failed)
    retryable: Optional[bool] = None
    #: fragment-level retries performed during execution
    fragment_retries: int = 0
    #: True when a failed distributed run degraded to the local pipeline
    degraded: bool = False
    #: rungs taken down the runtime-OOM degradation ladder (0 = none)
    oom_retries: int = 0
    #: per-rung history of the ladder walk ({"rung", "error"} dicts in
    #: descent order) — the flight recorder's post-mortem evidence for
    #: WHY a run degraded, not just how far
    rung_history: list = field(default_factory=list)
    #: fragment retry events ({"site", "error"} dicts in occurrence
    #: order) — which dispatch failed retryably, with what
    retry_events: list = field(default_factory=list)
    #: seconds spent queued on the shared memory pool at admission
    memory_queued_s: float = 0.0
    #: bytes reserved from the pool (the peak stats estimate)
    memory_reserved_bytes: int = 0
    #: True when the result was served from the versioned result cache
    #: (no execution happened; node_stats stay empty)
    cache_hit: bool = False
    #: True when this query's plan TEMPLATE (literal slots in place of
    #: values) had already executed in this session — the compiled
    #: executable was warm regardless of the literal binding
    template_hit: bool = False
    #: True when this query coalesced onto a concurrent identical
    #: in-flight execution (one device dispatch served N submissions)
    coalesced: bool = False
    #: True when this query rode a cross-query BATCHED dispatch: its
    #: literal binding was stacked with concurrent same-template
    #: bindings and computed by one vmapped device program
    #: (server/batcher.py) — as the leader or as a served member
    batched: bool = False
    #: serving-layer tenant identity ("" outside the serving front-end
    #: unless the ``tenant`` session property is set) — the per-tenant
    #: attribution column of system.query_history
    tenant: str = ""
    #: True when the run probed an APPROXIMATE join sketch (the
    #: ``approx_join`` session property routed a semi join through the
    #: Bloom sketch): the result may contain false-positive rows.
    #: Exact results are NEVER silently degraded — this flag (and the
    #: EXPLAIN ``strategy=sketch(approx)`` rendering) is the contract
    approximate: bool = False
    output_rows: int = -1
    node_stats: list = field(default_factory=list)  # list[NodeStats.to_dict()]
    #: per-query metric deltas (runtime/metrics.QueryMetricsDelta
    #: snapshot captured at the run_plan choke point): every counter /
    #: timer / histogram the query moved, attributed to THIS query even
    #: under concurrency — cache hits skip run_plan and stay empty
    metrics: dict = field(default_factory=dict)
    #: strategies of the joins this run actually executed (comma-joined
    #: ``join.strategy.*`` delta names, e.g. "grouped,pallas"; "")
    join_strategy: str = ""
    #: mean runtime-join-filter selectivity observed (fraction of probe
    #: scan rows KEPT; -1.0 when no filter fired)
    filter_selectivity: float = -1.0
    #: final OOM-ladder rung the successful attempt ran at, derived
    #: from the query's own ``query.oom_degraded`` delta (0 = no OOM)
    oom_rung: int = 0
    #: max device HBM watermark observed at query completion
    #: (runtime/devices.py; 0 on backends without allocator stats)
    device_peak_bytes: int = 0
    #: continuous-query id when this run was a subscription refresh
    #: fire ("" for ad-hoc queries) — makes refreshes distinguishable
    #: in system.query_history
    subscription_id: str = ""
    #: lanes in the vmapped batch this query rode (leader or served
    #: member; 0 = not batched)
    batch_size: int = 0

    def attribute_metrics(self, deltas: dict) -> None:
        """Fold a per-query metric-delta snapshot into this record:
        the raw deltas land in ``metrics`` (zero-valued entries
        dropped), and the derived columns ``system.query_history``
        exposes — executed join strategies, mean filter selectivity,
        final OOM rung — are computed here so every consumer (to_json,
        history table, listeners) reads one attribution."""
        self.metrics = {k: v for k, v in deltas.items() if v}
        prefix = "join.strategy."
        self.join_strategy = ",".join(sorted(
            k[len(prefix):] for k, v in deltas.items()
            if k.startswith(prefix) and v > 0
        ))
        n = deltas.get("join.filter_selectivity.count", 0.0)
        self.filter_selectivity = (
            deltas.get("join.filter_selectivity.total", 0.0) / n
            if n else -1.0
        )
        self.oom_rung = int(deltas.get("query.oom_degraded", 0))

    @property
    def queued_s(self) -> float:
        """QUEUED -> RUNNING (monotonic; 0 while still queued)."""
        if self.created_mono is None or self.started_mono is None:
            return 0.0
        return max(0.0, self.started_mono - self.created_mono)

    @property
    def execution_s(self) -> float:
        """RUNNING -> terminal (monotonic; live queries read 'so far')."""
        if self.started_mono is None:
            return 0.0
        end = (
            self.finished_mono if self.finished_mono is not None
            else time.monotonic()
        )
        return max(0.0, end - self.started_mono)

    @property
    def elapsed_s(self) -> float:
        if self.started_mono is not None:
            return self.execution_s
        # legacy construction without monotonic mirrors: wall fallback
        if self.started_at is None:
            return 0.0
        end = self.finished_at if self.finished_at is not None else time.time()
        return end - self.started_at

    def to_json(self) -> str:
        return json.dumps(
            {
                "queryId": self.query_id,
                "sql": self.sql,
                "state": self.state,
                "traceToken": self.trace_token,
                "createdAt": self.created_at,
                "startedAt": self.started_at,
                "finishedAt": self.finished_at,
                "elapsedS": round(self.elapsed_s, 6),
                "queuedS": round(self.queued_s, 6),
                "planningS": round(self.planning_s, 6),
                "executionS": round(self.execution_s, 6),
                "error": self.error,
                "errorCode": self.error_code,
                "retryable": self.retryable,
                "fragmentRetries": self.fragment_retries,
                "degraded": self.degraded,
                "oomRetries": self.oom_retries,
                "rungHistory": self.rung_history,
                "retryEvents": self.retry_events,
                "memoryQueuedS": round(self.memory_queued_s, 6),
                "memoryReservedBytes": self.memory_reserved_bytes,
                "cacheHit": self.cache_hit,
                "templateHit": self.template_hit,
                "coalesced": self.coalesced,
                "batched": self.batched,
                "tenant": self.tenant,
                "approximate": self.approximate,
                "outputRows": self.output_rows,
                "nodeStats": self.node_stats,
                "metrics": {k: self.metrics[k] for k in sorted(self.metrics)},
                "joinStrategy": self.join_strategy,
                "filterSelectivity": round(self.filter_selectivity, 6),
                "oomRung": self.oom_rung,
                "devicePeakBytes": self.device_peak_bytes,
                "subscriptionId": self.subscription_id,
                "batchSize": self.batch_size,
            }
        )


def _fmt_bytes(n: int) -> str:
    if n < 0:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"  # pragma: no cover


def render_analyzed_plan(plan, recorder: StatsRecorder,
                         tracer=None) -> str:
    """EXPLAIN ANALYZE rendering: the plan tree annotated with actuals
    (reference: PlanPrinter.textDistributedPlan with stats), the
    planner's row estimate against what actually happened — ``est
    E->A (Nx)``, flagged ``MISEST`` past :data:`MISEST_FACTOR` — plus
    the chosen join strategy, followed by the query's exchange and
    cache span rollups when a trace recorder is supplied."""
    lines = []

    def est_part(node, st) -> str:
        est = recorder.estimate_for(node)
        if est is None:
            return ""
        actual = -1 if st is None else st.output_rows
        if actual < 0:
            return f", est {est.est_rows:,}->?"
        ratio = misestimate_ratio(est.est_rows, actual)
        flag = " MISEST" if ratio >= MISEST_FACTOR else ""
        return (f", est {est.est_rows:,}->{actual:,} "
                f"({ratio:.1f}x{flag})")

    def walk(node, indent):
        pad = "  " * indent
        name = type(node).__name__
        st = recorder.stats_for(node)
        est = recorder.estimate_for(node)
        strat = (f"  strategy={est.strategy}"
                 if est is not None and est.strategy else "")
        if st is not None:
            rows = "?" if st.output_rows < 0 else f"{st.output_rows:,}"
            in_rows = "?" if st.input_rows < 0 else f"{st.input_rows:,}"
            # exchange-partition skew of the exchanges this node drove
            # (distributed runs only): max/mean delivered-row ratio
            skew = f", skew {st.skew:.1f}x" if st.skew > 0 else ""
            spill = ""
            if st.spill_mode:
                spill = (f", spill {st.spill_mode}"
                         f"({st.spill_resident}/{st.spill_partitions} "
                         f"resident, host "
                         f"{_fmt_bytes(st.spill_host_bytes)})")
            lines.append(
                f"{pad}{name}  [wall {st.wall_s * 1e3:.1f}ms, "
                f"rows {in_rows}->{rows}"
                f"{est_part(node, st)}, "
                f"bytes {_fmt_bytes(st.output_bytes)}, "
                f"calls {st.invocations}{skew}{spill}]" + strat
            )
        else:
            lines.append(
                f"{pad}{name}  [not executed{est_part(node, st)}]" + strat
            )
        for c in node.children:
            walk(c, indent + 1)

    walk(plan, 0)
    if tracer is not None:
        ex = tracer.spans_by_cat("exchange")
        if ex:
            total = sum(int(s.args.get("bytes", 0)) for s in ex)
            rounds = sum(int(s.args.get("rounds", 0)) for s in ex)
            wall = sum(max(s.t1 - s.t0, 0.0) for s in ex)
            lines.append(
                f"exchanges: {len(ex)} dispatches, {_fmt_bytes(total)} "
                f"moved, {rounds} rounds, wall {wall * 1e3:.1f}ms"
            )
        for s in tracer.spans_by_cat("cache"):
            extra = ", ".join(f"{k}={v}" for k, v in sorted(s.args.items()))
            lines.append(
                f"cache: {s.name} {max(s.t1 - s.t0, 0.0) * 1e3:.2f}ms"
                + (f" ({extra})" if extra else "")
            )
    return "\n".join(lines) + "\n"
