"""Structured query tracing: nested spans, exportable as Chrome trace JSON.

Reference parity: the reference's observability stack is three-tiered —
``OperatorStats`` rollups (host timings), the EventListener SPI (query
history), and external tracing hooks; this module is the tracing tier
[SURVEY §5.1, §5.5]. A :class:`TraceRecorder` collects one query's span
tree — query -> fragment dispatch -> plan node -> jitted-step dispatch,
plus cache / retry / exchange / degradation spans — and the session's
ring of recent recorders backs ``Session.export_trace`` (Chrome
``trace_event`` JSON, loadable in Perfetto / chrome://tracing) and the
``system.trace_spans`` table.

Design constraints:

- **Cheap when off, cheap when on.** The recorder rides a ContextVar;
  with none installed, :func:`span` costs one ContextVar read and
  returns a shared no-op context manager. With one installed, a span is
  two ``perf_counter`` reads and one list append — recording is
  per-query and single-writer (the driver thread), so there are no
  locks on the hot path. The acceptance bound (<5% overhead on the
  warm-cache Q1 path) is asserted in tests/test_trace.py.
- **Host-observed times.** A span around a jitted-step call measures
  the host-side dispatch latency including the device work the host
  waited on; XLA owns the intra-step schedule (SURVEY §5.1). The
  optional ``profile_annotations`` hook wraps each span in a
  ``jax.profiler.TraceAnnotation`` named ``<span>#<trace_token>`` so
  xprof device timelines correlate with engine spans by trace token.
- **Bounded.** Spans per query cap at ``max_spans`` (overflow counts
  into the ``trace.spans_dropped`` metric, never errors); the
  per-session :class:`TraceStore` is a fixed-size ring.
"""

from __future__ import annotations

import json
import time
from collections import deque
from contextlib import nullcontext
from contextvars import ContextVar
from typing import Any, Optional

from presto_tpu.runtime.metrics import REGISTRY

#: span categories (the ``cat`` field of exported events)
CATEGORIES = (
    "query",      # the root span of one tracked query
    "fragment",   # a lifecycle fragment dispatch (run_fragment attempt)
    "node",       # one plan node's execution (inclusive of children)
    "step",       # one jitted-step / operator dispatch
    "exchange",   # a collective exchange (bytes/partitions/rounds in args)
    "cache",      # exec/result/stats cache lookups
    "retry",      # a fragment-retry backoff window
    "lifecycle",  # admission / degradation
    "driver",     # the local driver push loop
    "stats",      # estimate snapshot / plan-stats history recording
    "frontend",   # HTTP serving-tier spans (submit / poll round-trips)
    "subscription",  # a continuous-query refresh fire (child of its sub)
)

_TRACE: ContextVar[Optional["TraceRecorder"]] = ContextVar(
    "presto_tpu_trace", default=None
)

#: shared reusable no-op context manager (``nullcontext`` keeps no
#: per-use state); its ``__enter__`` returns None, so callers that
#: annotate span args must guard ``if sp is not None``
_NOOP = nullcontext()


class Span:
    """One recorded span. ``args`` is live-mutable until export —
    callers may attach results (bytes moved, hit/miss) after the
    timed region closes."""

    __slots__ = ("span_id", "parent_id", "name", "cat", "t0", "t1", "args")

    def __init__(self, span_id: int, parent_id: int, name: str, cat: str):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.cat = cat
        self.t0 = 0.0
        self.t1 = 0.0
        self.args: dict[str, Any] = {}


class _SpanCtx:
    __slots__ = ("rec", "span", "_ann")

    def __init__(self, rec: "TraceRecorder", span: Span):
        self.rec = rec
        self.span = span
        self._ann = None

    def __enter__(self) -> Span:
        rec = self.rec
        rec._stack.append(self.span.span_id)
        if rec.annotate:
            self._ann = _annotation(self.span.name, rec.trace_token)
            if self._ann is not None:
                self._ann.__enter__()
        self.span.t0 = time.perf_counter()
        return self.span

    def __exit__(self, *exc):
        self.span.t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self.rec._stack.pop()
        return False


def _annotation(name: str, token: Optional[str]):
    """A jax.profiler.TraceAnnotation carrying the trace token, or None
    when the profiler is unavailable (annotation is best-effort)."""
    try:
        from jax.profiler import TraceAnnotation
    except Exception:  # pragma: no cover - ancient jax
        return None
    return TraceAnnotation(f"{name}#{token}" if token else name)


class TraceRecorder:
    """One query's span tree. Single-writer (the driver thread owns the
    query synchronously); reads happen after the query finishes."""

    __slots__ = (
        "query_id", "trace_token", "max_spans", "annotate",
        "spans", "dropped", "created_wall", "_stack", "_seq",
    )

    def __init__(self, query_id: str, trace_token: Optional[str] = None,
                 max_spans: int = 8192, annotate: bool = False):
        self.query_id = query_id
        self.trace_token = trace_token
        self.max_spans = max_spans
        self.annotate = annotate
        self.spans: list[Span] = []
        self.dropped = 0
        self.created_wall = time.time()
        self._stack: list[int] = []  # open span ids (parents)
        self._seq = 0

    # -- recording ---------------------------------------------------------
    def span(self, name: str, cat: str = "step",
             args: Optional[dict] = None):
        if len(self.spans) >= self.max_spans:
            self.dropped += 1
            REGISTRY.counter("trace.spans_dropped").add()
            return _NOOP
        parent = self._stack[-1] if self._stack else -1
        s = Span(self._seq, parent, name, cat)
        self._seq += 1
        if args:
            s.args.update(args)
        self.spans.append(s)
        return _SpanCtx(self, s)

    def add_complete(self, name: str, cat: str, t0: float, dur_s: float,
                     args: Optional[dict] = None) -> Optional[Span]:
        """Record an already-timed span (explicit perf_counter start +
        duration) under the currently open span."""
        if len(self.spans) >= self.max_spans:
            self.dropped += 1
            REGISTRY.counter("trace.spans_dropped").add()
            return None
        parent = self._stack[-1] if self._stack else -1
        s = Span(self._seq, parent, name, cat)
        self._seq += 1
        s.t0 = t0
        s.t1 = t0 + dur_s
        if args:
            s.args.update(args)
        self.spans.append(s)
        return s

    # -- introspection -----------------------------------------------------
    @property
    def t0(self) -> float:
        return self.spans[0].t0 if self.spans else 0.0

    def to_span_dicts(self) -> list[dict]:
        """The span tree as plain dicts with query-relative timestamps
        (args shared by reference — callers that persist them, like
        the flight recorder, must deep-copy/coerce). The flattening
        the ``system.trace_spans`` scan and post-mortem capture share."""
        t0 = self.t0
        return [
            {
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "name": s.name,
                "cat": s.cat,
                "start_s": round(max(s.t0 - t0, 0.0), 6),
                "duration_s": round(max(s.t1 - s.t0, 0.0), 6),
                "args": s.args,
            }
            for s in self.spans
        ]

    def spans_by_cat(self, cat: str) -> list[Span]:
        return [s for s in self.spans if s.cat == cat]

    def children_of(self, span_id: int) -> list[Span]:
        return [s for s in self.spans if s.parent_id == span_id]

    # -- export ------------------------------------------------------------
    def to_events(self, pid: int) -> list[dict]:
        """Chrome trace_event entries for this query (one pid per
        query; ts in microseconds on the process perf_counter epoch)."""
        events: list[dict] = [
            {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"query {self.query_id}"},
            },
            {
                "name": "process_labels", "ph": "M", "pid": pid, "tid": 0,
                "args": {"labels": f"trace_token={self.trace_token}"},
            },
        ]
        for s in self.spans:
            args = {"span_id": s.span_id, "parent_id": s.parent_id}
            args.update(s.args)
            if self.trace_token is not None:
                args["trace_token"] = self.trace_token
            events.append({
                "name": s.name,
                "cat": s.cat,
                "ph": "X",
                "pid": pid,
                "tid": 0,
                "ts": round(s.t0 * 1e6, 3),
                "dur": round(max(s.t1 - s.t0, 0.0) * 1e6, 3),
                "args": args,
            })
        return events


# ---------------------------------------------------------------------------
# Module-level recording surface (the instrumentation points' API)
# ---------------------------------------------------------------------------


def install(rec: Optional[TraceRecorder]):
    """Install ``rec`` as the active recorder; returns the reset token
    (nested queries from event listeners get their own recorder and
    restore the outer one on exit)."""
    return _TRACE.set(rec)


def uninstall(token) -> None:
    _TRACE.reset(token)


def current() -> Optional[TraceRecorder]:
    return _TRACE.get()


def span(name: str, cat: str = "step", args: Optional[dict] = None):
    """The one instrumentation hook: a context manager timing a span
    under the active recorder, or a shared no-op when tracing is off.
    ``with span(...) as sp:`` — ``sp`` is the live Span (mutate
    ``sp.args`` freely) or None on the no-op path."""
    rec = _TRACE.get()
    if rec is None:
        return _NOOP
    return rec.span(name, cat, args)


def add_complete(name: str, cat: str, t0: float, dur_s: float,
                 args: Optional[dict] = None) -> None:
    rec = _TRACE.get()
    if rec is not None:
        rec.add_complete(name, cat, t0, dur_s, args)


# ---------------------------------------------------------------------------
# Byte accounting helpers (observability-side batch sizing; capacity
# arithmetic only — never a device sync)
# ---------------------------------------------------------------------------


def batch_row_bytes(batch) -> int:
    """Per-row device bytes of a Batch: column payload widths + the
    validity and live masks (1 byte each as moved on the wire — bools
    ride as uint8 through the collectives)."""
    total = 1  # live mask
    for c in batch.columns.values():
        width = 1
        for d in c.data.shape[1:]:
            width *= int(d)
        total += width * c.data.dtype.itemsize + 1  # + valid mask
    return total


def batch_device_bytes(batch) -> int:
    """Capacity-based device residency of a Batch (live rows and
    padding both occupy HBM)."""
    return batch_row_bytes(batch) * int(batch.capacity)


# ---------------------------------------------------------------------------
# Per-session trace retention + Chrome export
# ---------------------------------------------------------------------------

#: recorders retained per session (spans are memory-heavy relative to
#: QueryInfo, so this ring is deliberately smaller than query history)
TRACE_RING = 64


class TraceStore:
    """Ring buffer of the session's most recent TraceRecorders."""

    def __init__(self, maxlen: int = TRACE_RING):
        self._ring: deque[TraceRecorder] = deque(maxlen=maxlen)

    def add(self, rec: TraceRecorder) -> None:
        self._ring.append(rec)

    def recorders(self) -> list[TraceRecorder]:
        return list(self._ring)

    def latest(self) -> Optional[TraceRecorder]:
        return self._ring[-1] if self._ring else None

    def for_query(self, query_id: str) -> Optional[TraceRecorder]:
        for rec in reversed(self._ring):
            if rec.query_id == query_id:
                return rec
        return None

    def __len__(self) -> int:
        return len(self._ring)


def to_chrome_trace(recorders: list[TraceRecorder]) -> dict:
    """The Chrome ``trace_event`` JSON object for a set of recorders
    (one pid per query, ts on the shared perf_counter epoch)."""
    events: list[dict] = []
    tokens = []
    for pid, rec in enumerate(recorders, start=1):
        events.extend(rec.to_events(pid))
        if rec.trace_token is not None:
            tokens.append(rec.trace_token)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "engine": "presto_tpu",
            "trace_tokens": sorted(set(tokens)),
            "queries": [rec.query_id for rec in recorders],
        },
    }


def export_chrome_trace(path: str, recorders: list[TraceRecorder]) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(recorders), f)
    return path
