"""Serving layer: multi-tenant front-end, fairness scheduler, and
cross-query batched dispatch.

Reference parity: the coordinator tier presto-main wraps around query
execution — ``NodeScheduler`` / resource groups multiplexing many
clients onto shared workers, and the HTTP ``/v1/statement`` protocol
[SURVEY §2.1 protocol + resource-group rows]. Single-controller
mapping: the "cluster" is one process, so the serving layer is three
cooperating pieces over the existing ``Session``/``QueryManager``
substrate:

- :mod:`presto_tpu.server.scheduler` — weighted-fair admission with
  per-tenant quotas between the front-end and the memory pool's strict
  FIFO.
- :mod:`presto_tpu.server.batcher` — the throughput multiplier that
  comes from *load shape*: concurrent same-template different-literal
  queries stack their param bindings into ONE vmapped device dispatch.
- :mod:`presto_tpu.server.frontend` — the HTTP/JSON surface
  (``/v1/statement``, ``/v1/prepared``, ``/metrics``) plus the
  in-process ``ServerClient`` tests and the bench harness drive
  without sockets.

Imports are lazy (PEP 562): the runtime imports
``presto_tpu.server.batcher`` from ``QueryManager`` without dragging
the HTTP front-end (and its ``Session`` import) into every query.
"""

from __future__ import annotations

_EXPORTS = {
    "TenantSpec": "presto_tpu.server.scheduler",
    "FairScheduler": "presto_tpu.server.scheduler",
    "TemplateBatchGate": "presto_tpu.server.batcher",
    "run_batched": "presto_tpu.server.batcher",
    "QueryServer": "presto_tpu.server.frontend",
    "ServerClient": "presto_tpu.server.frontend",
    "HttpFrontend": "presto_tpu.server.frontend",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
