"""Cross-query batched dispatch: N same-template bindings, ONE device
dispatch.

PR 9's ``InflightCoalescer.template_slot`` serializes concurrent
same-template different-literal queries behind one warm executable —
N queries still pay N dispatches, N scans, N driver loops. This module
turns that serialization rung into a throughput multiplier: because
the plan template threads every literal as a runtime ``params=`` scalar
(plan/templates.py), the bindings queued on a template slot differ
ONLY in those scalars — so stack them on a leading axis, ``jax.vmap``
the template's execution over that axis, and one fused dispatch
computes every queued query's result. The scan (host generation +
H2D transfer — the dominant per-query cost of a warm template) happens
once per batch instead of once per query.

Bit-identity contract: the batched replay reuses the *same* compiled
step bodies the serial path runs — ``FilterProjectOperator._step``,
``GlobalAggregationOperator._update`` + ``result_batch``,
``TopNOperator/OrderByOperator.result_batch`` — traced under ``vmap``
rather than re-implemented, so each lane computes the exact program
the serial run would (the test suite asserts frame equality with
``check_exact``). Templates outside the pure whitelist
(plan/templates.unbatchable_reason) fall back to the PR 9 serialized
path, counted per reason under ``batch.fallback.*``; a failing batched
dispatch falls back the same way (``batch.fallback.error``) — batching
multiplies work, never failures.

Two pieces:

- :func:`run_batched` — lower a whitelisted template once (cached in
  the process executable cache, keyed by the template fingerprint),
  scan once, dispatch once, split per binding.
- :class:`TemplateBatchGate` — the meeting point: concurrent bindings
  enqueue per template; whoever acquires the template's executor lock
  drains the whole queue (bounded by ``batch_max_size``, so distinct
  compiled batch widths stay bounded too) and leads one batched
  dispatch, serving every drained member. Unserved members re-contend,
  so failure semantics mirror the coalescer's.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from presto_tpu.plan import nodes as N
from presto_tpu.runtime import trace
from presto_tpu.runtime.metrics import REGISTRY

_UNSET = object()


# ---------------------------------------------------------------------------
# the vmapped template runner
# ---------------------------------------------------------------------------


def _lower(node: N.PlanNode, catalog):
    """Recursively lower a whitelisted plan node to a traceable
    ``fn(batches, params) -> [Batch]`` built from the SAME operator
    step bodies the serial executor dispatches. Callers must have
    vetted the plan with ``plan.templates.unbatchable_reason`` first —
    an unexpected node here is an internal error, not a fallback."""
    from presto_tpu.exec.operators import (
        AggSpec,
        FilterProjectOperator,
        GlobalAggregationOperator,
        OrderByOperator,
        SortKey,
        TopNOperator,
        concat_batches,
    )
    from presto_tpu.runtime.errors import InternalError

    if isinstance(node, N.TableScan):
        pred_op = (FilterProjectOperator(node.predicate, None)
                   if node.predicate is not None else None)

        def scan_fn(batches, params):
            if pred_op is None:
                return list(batches)
            return [pred_op._step(b, params) for b in batches]

        return scan_fn
    if isinstance(node, N.Filter):
        child = _lower(node.child, catalog)
        op = FilterProjectOperator(node.predicate, None)
        return lambda bs, params: [op._step(b, params)
                                   for b in child(bs, params)]
    if isinstance(node, N.Project):
        child = _lower(node.child, catalog)
        op = FilterProjectOperator(None, dict(node.exprs))
        return lambda bs, params: [op._step(b, params)
                                   for b in child(bs, params)]
    if isinstance(node, N.Aggregate):
        from presto_tpu.plan.bounds import agg_value_bits

        child = _lower(node.child, catalog)
        bits = agg_value_bits(node, catalog)
        aggs = [AggSpec(a.kind, a.input, a.name, a.dtype, value_bits=b)
                for a, b in zip(node.aggs, bits)]
        op = GlobalAggregationOperator(aggs)

        def agg_fn(bs, params):
            state = op._init()
            for b in child(bs, params):
                state = op._update(state, b, params)
            return [op.result_batch(state)]

        return agg_fn
    if isinstance(node, (N.TopN, N.Sort)):
        child = _lower(node.child, catalog)
        keys = [SortKey(k.expr, k.descending, k.nulls_first)
                for k in node.keys]
        op = (TopNOperator(keys, node.count) if isinstance(node, N.TopN)
              else OrderByOperator(keys))

        def sort_fn(bs, params):
            out = child(bs, params)
            if not out:
                return []
            return [op.result_batch(concat_batches(out))]

        return sort_fn
    raise InternalError(
        f"unbatchable node reached the batched runner: {type(node).__name__}"
    )


def _find_scan(node: N.PlanNode) -> N.TableScan:
    if isinstance(node, N.TableScan):
        return node
    return _find_scan(node.children[0])


def _build_batched(plan: N.Output, catalog):
    """Lower ``plan`` once: returns ``(scan_batches, vmapped_fn,
    names, catalog)``. ``scan_batches`` re-scans fresh host batches per
    dispatch (data is never cached — the executable cache entry holds
    only the compiled callable); the vmapped fn maps bindings over the
    params axis while the scan batches stay unmapped (shared across
    every lane). The catalog rides in the tuple to pin its identity
    for the cache key (see run_batched)."""
    from presto_tpu.expr import param_scope

    scan = _find_scan(plan.child)
    conn = catalog.connector(scan.connector)
    src_cols = [s for _, s in scan.columns]
    rename = {s: n for n, s in scan.columns}
    root = _lower(plan.child, catalog)
    sources, names = list(plan.sources), list(plan.names)
    out_rename = dict(zip(sources, names))

    def one(batches, params):
        # the traced-body convention of every jitted step: the params
        # argument shadows the executor's ambient scope so eager
        # evaluation sites (sort keys) read the traced values
        with param_scope(params):
            out = root(batches, params)
            return [b.select(sources).rename(out_rename) for b in out]

    vf = jax.jit(jax.vmap(one, in_axes=(None, 0)))

    def scan_batches():
        from presto_tpu.runtime.faults import fault_point
        from presto_tpu.runtime.lifecycle import check_deadline
        from presto_tpu.spi import batch_capacity

        splits = list(conn.splits(scan.table))
        cap = batch_capacity(max(s.row_hint for s in splits))
        out = []
        for split in splits:
            fault_point("scan")
            check_deadline("scan")
            out.append(conn.scan(split, src_cols, cap).rename(rename))
        return out

    return scan_batches, vf, names, catalog


def run_batched(catalog, plan: N.Output, bounds: Sequence[tuple],
                template_key: Optional[str] = None):
    """Execute one whitelisted template for every binding in ``bounds``
    (slot-ordered ``(dtype, logical value)`` tuples) in ONE vmapped
    device dispatch; returns one DataFrame per binding, in order. The
    lowered callable is cached in the process executable cache keyed by
    the template fingerprint (catalog versions and codegen properties
    are folded in upstream), so repeat batches pay zero re-lowering and
    jit's signature cache makes repeat widths zero re-traces."""
    import pandas as pd

    from presto_tpu.batch import live_count
    from presto_tpu.cache.exec_cache import EXEC_CACHE
    from presto_tpu.plan.templates import device_params
    from presto_tpu.runtime.lifecycle import run_fragment

    # the key folds the LIVE catalog's identity beside the template
    # fingerprint: the lowered entry captures the connector (its scan
    # closure) and catalog-derived spec constants (agg value-bit
    # bounds), and two same-schema catalogs over different data would
    # otherwise collide on the fingerprint alone and serve one
    # session's table to the other. The cached tuple pins the catalog,
    # so its id cannot be recycled while the entry lives (entries are
    # LRU-bounded, so short-lived sessions' entries age out).
    key = (EXEC_CACHE.key_of("batched_dispatch", template_key,
                             str(id(catalog)))
           if template_key else None)
    scan_batches, vf, names, _catalog_pin = EXEC_CACHE.get_or_build(
        key, lambda: _build_batched(plan, catalog))
    per = [device_params(b) for b in bounds]
    n_slots = len(per[0])
    stacked = tuple(
        jnp.stack([p[i] for p in per]) for i in range(n_slots)
    )
    scans = scan_batches()
    outs = run_fragment("fragment:batched_dispatch",
                        lambda: vf(scans, stacked))
    dfs = []
    for i in range(len(bounds)):
        batches = [jax.tree_util.tree_map(lambda x, i=i: x[i], b)
                   for b in outs]
        frames = [b.to_pandas() for b in batches if live_count(b) > 0]
        if not frames:
            dfs.append(pd.DataFrame(columns=names))
        else:
            dfs.append(
                pd.concat(frames, ignore_index=True)[list(names)])
    return dfs


# ---------------------------------------------------------------------------
# the batch gate
# ---------------------------------------------------------------------------


class _BatchMember:
    """One query waiting at a template's batch gate."""

    __slots__ = ("bound", "event", "df", "served", "abandoned",
                 "origin", "batch_size")

    def __init__(self, bound: tuple):
        self.bound = bound
        self.event = threading.Event()
        self.df = None
        self.served = False
        self.abandoned = False
        #: trace provenance of the enqueuing submission (its trace
        #: token or query id, stamped by the session) — the leader's
        #: batch:lane spans carry it so every vmapped lane links back
        #: to the query that enqueued it
        self.origin = ""
        #: lanes in the dispatch that served this member (stamped by
        #: the leader; 0 until served) — QueryInfo.batch_size's source
        #: for served members
        self.batch_size = 0


class TemplateBatchGate:
    """Per-template meeting point for concurrent bindings.

    Protocol (driven by ``Session._run_template_batched``): a query
    ``enqueue``s its binding, then loops on ``lead_or_wait``:

    - ``("serve", df)`` — a leader's batched dispatch computed this
      binding's result; done.
    - ``("lead", members)`` — this query holds the template's executor
      lock and drained ``members`` (itself included, up to
      ``max_batch``). It must run them — batched when the template
      allows, else serially for itself — and call ``finish_lead`` in a
      finally.
    - ``("retry", None)`` — woken without a result (leader fell back
      or served others); contend again.
    - ``("timeout", None)`` — patience exhausted; the caller executes
      itself unserialized (correct, just uncoalesced — counted).

    The executor lock doubles as PR 9's template serializer: an
    unbatchable template degrades to exactly the old behavior, one
    warm execution at a time.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._templates: dict[str, dict] = {}

    # ---- membership ------------------------------------------------------
    def enqueue(self, template_key: str, bound: tuple) -> _BatchMember:
        m = _BatchMember(tuple(bound))
        with self._lock:
            t = self._templates.get(template_key)
            if t is None:
                t = self._templates[template_key] = {
                    "exec": threading.Lock(), "queue": [], "refs": 0,
                    "reason": _UNSET,
                }
            t["queue"].append(m)
            t["refs"] += 1
        return m

    def _drop_locked(self, template_key: str, n: int = 1) -> None:
        t = self._templates.get(template_key)
        if t is None:
            return
        t["refs"] -= n
        if t["refs"] <= 0:
            self._templates.pop(template_key, None)

    def lead_or_wait(self, template_key: str, member: _BatchMember,
                     timeout_s: Optional[float], max_batch: int = 8):
        with self._lock:
            t = self._templates.get(template_key)
            if t is None:
                # defensive: a refcount invariant slip must degrade to
                # an unserialized (still correct) serial run, never a
                # KeyError out of the session
                return "timeout", None
            if member.served:
                self._drop_locked(template_key)
                return "serve", member.df
            if t["exec"].acquire(blocking=False):
                q = t["queue"]
                # drain everything waiting (bounded): every member
                # fused here is a scan + dispatch the engine never
                # pays again, and jit caches one signature per width
                # so the cost of a new width amortizes across the
                # serving session
                size = min(len(q), max(1, max_batch))
                others = [m for m in q if m is not member][: size - 1]
                members = [member] + others
                for m in members:
                    q.remove(m)
                return "lead", members
        served = member.event.wait(timeout_s)
        with self._lock:
            t = self._templates.get(template_key)
            if t is None:
                return "timeout", None
            member.event.clear()
            if member.served:
                self._drop_locked(template_key)
                return "serve", member.df
            if not served:
                member.abandoned = True
                if member in t["queue"]:
                    t["queue"].remove(member)
                self._drop_locked(template_key)
                return "timeout", None
        return "retry", None

    def abandon(self, template_key: str, member: _BatchMember) -> None:
        """A member's thread is leaving WITHOUT a leader's verdict
        (e.g. its overall gate deadline expired on a retry wake): mark
        it so a leader never wastes a lane on it, dequeue it, and drop
        its ref — the exact bookkeeping the in-gate timeout branch
        does. Idempotent."""
        with self._lock:
            t = self._templates.get(template_key)
            if t is None or member.abandoned:
                return
            member.abandoned = True
            if member in t["queue"]:
                t["queue"].remove(member)
            self._drop_locked(template_key)

    def serve(self, member: _BatchMember, df) -> bool:
        """Leader-side result delivery; returns False when the member
        gave up waiting (its thread runs serially; the frame drops)."""
        with self._lock:
            if member.abandoned:
                return False
            member.df = df
            member.served = True
        member.event.set()
        return True

    def finish_lead(self, template_key: str, leader: _BatchMember,
                    members: "list[_BatchMember]") -> None:
        """Release the template executor lock; members the leader could
        not serve re-queue at the FRONT (they were first in line) and
        every waiter wakes to contend for the lock."""
        with self._lock:
            t = self._templates.get(template_key)
            if t is None:  # refs can't hit 0 while the leader is live
                return
            requeue = [m for m in members
                       if m is not leader and not m.served
                       and not m.abandoned]
            t["queue"][:0] = requeue
            # ONLY the leader's ref drops here: served members' own
            # threads drop theirs on pickup, and abandoned members
            # already dropped theirs in the timeout branch — dropping
            # them again would pop the template out from under members
            # still queued (stranding them with a held exec lock)
            self._drop_locked(template_key)
            t = self._templates.get(template_key)
            if t is not None:
                t["exec"].release()
                for m in t["queue"]:
                    m.event.set()

    # ---- batchability ----------------------------------------------------
    def template_reason(self, template_key: str, plan, catalog):
        """Memoized ``plan.templates.unbatchable_reason`` per template
        (None = batchable). The walk — including the leaf-route matcher
        probe — runs once per template, not per burst."""
        with self._lock:
            t = self._templates.get(template_key)
            cached = t["reason"] if t is not None else _UNSET
        if cached is not _UNSET:
            return cached
        from presto_tpu.plan.templates import unbatchable_reason

        reason = unbatchable_reason(plan, catalog)
        with self._lock:
            t = self._templates.get(template_key)
            if t is not None:
                t["reason"] = reason
        return reason

    def queue_depth(self, template_key: str) -> int:
        """Current queued member count for one template (tests)."""
        with self._lock:
            t = self._templates.get(template_key)
            return 0 if t is None else len(t["queue"])


class BatchRunner:
    """Executor adapter the batch leader hands to ``run_plan``: its
    ``run`` executes ONE batched dispatch for every drained member,
    serves the others, and returns the leader's own frame. Any failure
    in the batched path falls back to the wrapped executor's serial
    ``run`` (``batch.fallback.error``) — unserved members re-contend at
    the gate, exactly the coalescer's failure semantics. Every other
    attribute (catalog, params, degradation hooks, approx flags)
    delegates to the real executor, so the lifecycle ladder keeps
    working on the serial fallback."""

    def __init__(self, executor, gate: TemplateBatchGate,
                 members: "list[_BatchMember]", me: _BatchMember,
                 template_key: Optional[str] = None):
        self._executor = executor
        self._gate = gate
        self._members = members
        self._me = me
        self._template_key = template_key
        self._attempted = False
        self.dispatched_batch = False
        #: lanes in the dispatched batch (0 until a batch dispatches)
        self.batch_size = 0
        #: admission-control multiplier (runtime/lifecycle.admit): the
        #: leader's pool reservation must cover every fused lane's
        #: state, not just its own binding's — conservative (lanes
        #: share the dominant scan node), which is the admission
        #: posture everywhere else
        self.admission_scale = len(members)

    def run(self, plan):
        if self._attempted:
            # an OOM-ladder (or retry) re-entry after a fallback: the
            # batch has already been attempted once; stay serial
            return self._executor.run(plan)
        self._attempted = True
        # admission may have GRANTED fewer lanes than were drained
        # (the reservation clamp in runtime/lifecycle.admit): dispatch
        # only the covered prefix — the leader is members[0], so it is
        # always included — and let finish_lead re-queue the rest
        granted = self.__dict__.get("admission_scale_granted")
        batch = self._members
        if granted is not None and granted < len(batch):
            REGISTRY.counter("batch.trimmed").add()
            batch = batch[: max(1, int(granted))]
        t0 = time.perf_counter()
        try:
            dfs = run_batched(self._executor.catalog, plan,
                              [m.bound for m in batch],
                              template_key=self._template_key)
        except Exception:  # noqa: BLE001 — batching never fails a query
            REGISTRY.counter("batch.fallback").add()
            REGISTRY.counter("batch.fallback.error").add()
            return self._executor.run(plan)
        dur = time.perf_counter() - t0
        self.dispatched_batch = True
        self.batch_size = len(batch)
        REGISTRY.counter("batch.dispatched").add()
        REGISTRY.counter("batch.queries").add(len(batch))
        REGISTRY.histogram("batch.size").add(len(batch))
        out = None
        for i, (m, df) in enumerate(zip(batch, dfs)):
            # lane provenance on the leader's trace: the fused dispatch
            # covered the full batch window, and each lane names the
            # submission (trace token / query id) whose binding it
            # computed — the end-to-end linkage from a vmapped lane
            # back to its originating HTTP submit or subscription fire
            trace.add_complete(
                "batch:lane", "driver", t0, dur,
                {"lane": i, "origin": m.origin, "batch_size": len(batch)})
            if m is self._me:
                out = df
            else:
                m.batch_size = len(batch)
                self._gate.serve(m, df)
        return out

    def __getattr__(self, name):
        return getattr(self.__dict__["_executor"], name)
