"""Multi-client front-end over the Session/QueryManager substrate.

Reference parity: the coordinator's statement protocol —
``POST /v1/statement`` returning a poll URI, clients following it to
``QUEUED -> RUNNING -> FINISHED`` with results in the terminal page
[SURVEY §2.1 protocol row] — plus ``PREPARE``/``EXECUTE`` riding the
session's prepared-statement surface and a ``/metrics`` scrape of the
existing OpenMetrics exposition. Two surfaces over ONE core:

- :class:`QueryServer` — the in-process serving core (tenant identity,
  fairness slots, submit/poll bookkeeping, graceful drain). Tests and
  the bench harness drive it directly as the ``ServerClient`` — no
  sockets, same code path.
- :class:`HttpFrontend` — a stdlib ``ThreadingHTTPServer`` speaking
  HTTP/JSON on top (no new dependencies). Tenant identity rides the
  ``X-Presto-Tenant`` header, one tenant per connection/request.

All tenants share one ``Session`` (so ``system.query_history``,
``system.tenants``, and the flight recorder see the whole serving
process) and therefore one memory pool; per-tenant isolation is the
scheduler's job, attribution is ``QueryInfo.tenant``'s.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import uuid
from typing import Mapping, Optional

from presto_tpu.runtime.errors import (
    PrestoError,
    QueryCancelled,
    ServerOverloaded,
    UserError,
    error_code,
)
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.overload import OverloadController, shed_retry_after
from presto_tpu.server.scheduler import FairScheduler, TenantSpec

_submit_seq = itertools.count(1)

_HEX = frozenset("0123456789abcdef")


def _df_payload(df) -> dict:
    """DataFrame -> the JSON result page shape ({columns, data})."""
    return {
        "columns": [str(c) for c in df.columns],
        "data": json.loads(
            df.to_json(orient="values", date_format="iso")),
    }


def _parse_traceparent(header: Optional[str]) -> Optional[str]:
    """W3C ``traceparent`` -> its 32-hex trace-id, or None when the
    header is absent or malformed. A bad header degrades to a
    server-generated trace — it never rejects the statement (trace
    plumbing must not be able to 400 a query)."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if (len(version) == 2 and set(version) <= _HEX
            and len(trace_id) == 32 and set(trace_id) <= _HEX
            and len(span_id) == 16 and set(span_id) <= _HEX
            and trace_id != "0" * 32):
        return trace_id
    return None


def _trace_context(token: Optional[str] = None,
                   traceparent_id: Optional[str] = None,
                   subscription_id: str = "",
                   force: bool = False) -> dict:
    """Build one REQUEST_TRACE context dict (runtime/session.py).

    Token precedence: an explicit ``X-Presto-Trace`` token, then the
    client traceparent's trace-id, then a fresh server-side id — a
    client that supplied EITHER header gets its identifier honored end
    to end. ``trace_id`` is what outgoing ``traceparent`` headers
    carry: the client's trace-id when one arrived, else the token
    itself when it happens to be 32-hex, else a new id."""
    tok = token or traceparent_id or uuid.uuid4().hex
    trace_id = traceparent_id
    if trace_id is None:
        low = tok.lower()
        trace_id = (low if len(low) == 32 and set(low) <= _HEX
                    else uuid.uuid4().hex)
    return {"token": tok, "trace_id": trace_id,
            "subscription_id": subscription_id,
            "force_trace": bool(force)}


class QueryServer:
    """The in-process serving core: tenant-scoped execution over one
    shared Session, gated by a :class:`FairScheduler`.

    ``connectors`` builds a fresh session (with ``batched_dispatch``
    ON — the serving layer exists to exploit load shape); passing an
    explicit ``session`` serves through it unchanged. Tests and the
    bench drive this class directly — the HTTP front-end adds only
    transport."""

    def __init__(self, connectors: Optional[Mapping[str, object]] = None,
                 *, session=None, tenants=None,
                 total_slots: Optional[int] = None,
                 properties: Optional[dict] = None,
                 approx_properties: Optional[dict] = None,
                 default_tenant: str = "default",
                 query_record_limit: int = 256,
                 submit_limit: int = 128,
                 submit_timeout_s: float = 300.0,
                 shed_queue_limit: Optional[int] = None,
                 shed_tenant_queue_limit: Optional[int] = None,
                 shed_drain_limit_s: Optional[float] = None,
                 warm_top_k: int = 0,
                 warm_interval_s: float = 1.0):
        from presto_tpu.runtime.health import HealthMonitor, SloTracker
        from presto_tpu.runtime.session import Session
        from presto_tpu.stream.subscriptions import SubscriptionManager

        if session is None:
            props = {"batched_dispatch": True}
            props.update(properties or {})
            session = Session(dict(connectors or {}), properties=props)
        self.session = session
        self.default_tenant = default_tenant
        self.scheduler = FairScheduler(
            tenants, total_slots=total_slots, pool=session.pool(),
            global_queue_limit=shed_queue_limit,
            tenant_queue_limit=shed_tenant_queue_limit,
            shed_drain_limit_s=shed_drain_limit_s)
        #: the brown-out latch (overload rung 4): health breaches
        #: engage it, a breach-free cooldown disengages it, and
        #: eligible tenants' NEW traffic degrades per TenantSpec
        #: .brownout while it is engaged
        self.overload = OverloadController(
            cooldown_s=float(session.prop("brownout_cooldown_s")))
        #: the registry behind system.tenants (connectors/system.py)
        session.tenants = self.scheduler
        #: submit/poll records, RING-bounded: terminal records beyond
        #: the limit retire oldest-first (clients that still hold the
        #: id get "unknown query id" — the reference protocol's retired
        #: -query behavior). In-flight records are never evicted.
        self.query_record_limit = max(1, int(query_record_limit))
        #: backpressure on async submission: at most this many
        #: NON-terminal submitted queries (each owns one worker thread
        #: blocked in the fair scheduler) — beyond it, submit() rejects
        #: loudly instead of growing a thread per request
        self.submit_limit = max(1, int(submit_limit))
        #: fair-queue patience for ASYNC submissions: a worker thread
        #: must never block in the scheduler forever (a starved tenant
        #: flooding /v1/statement would otherwise pin threads and
        #: exhaust submit_limit for everyone); expiry surfaces as the
        #: typed admission-timeout failure on the poll page
        self.submit_timeout_s = submit_timeout_s
        self._queries: "dict[str, dict]" = {}
        self._qlock = threading.Lock()
        self._accepting = True
        self._inflight = 0
        self._drain_cv = threading.Condition()
        #: continuous-query subscriptions (presto_tpu/stream/): the
        #: manager's notifier thread starts on first subscribe, never
        #: for a server that serves only ad-hoc statements
        self.subscriptions = SubscriptionManager(self)
        #: extra session properties for the APPROXIMATE sibling
        #: session (mode="approx" subscriptions) — e.g. a tiny
        #: join_build_budget_bytes to force the sketch path, or
        #: approx_scan_fraction for sampled scans
        self._approx_properties = dict(approx_properties or {})
        self._approx_session = None
        self._approx_lock = threading.Lock()
        #: per-tenant SLO burn-rate tracking (runtime/health.py):
        #: defaults come from the slo_* session properties, per-tenant
        #: objectives from TenantSpec.slo_latency_s/slo_freshness_s;
        #: run_plan observes latency, subscription delivery observes
        #: freshness — both through ``session.slo``
        session.slo = SloTracker(
            latency_objective_s=float(
                session.prop("slo_latency_objective_s")),
            freshness_objective_s=float(
                session.prop("slo_freshness_objective_s")),
            window=int(session.prop("slo_window")),
            overrides=self.scheduler.slo_overrides())
        #: the anomaly watchdog (runtime/health.py): samples serving
        #: vitals on its own thread, and on a breach arms the flight
        #: recorder against the worst in-flight query. Built LAST so
        #: every structure it samples (scheduler, subscriptions, slo)
        #: already exists; ``health_monitor=False`` serves without it
        self.health = None
        if session.prop("health_monitor"):
            self.health = HealthMonitor(
                session, scheduler=self.scheduler,
                subscriptions=self.subscriptions,
                interval_s=float(session.prop("health_interval_s")),
                ring=int(session.prop("health_ring")),
                baseline_window=int(
                    session.prop("health_baseline_window")),
                min_samples=int(session.prop("health_min_samples")),
                p99_factor=float(session.prop("health_p99_factor")),
                queue_limit=int(session.prop("health_queue_limit")),
                burn_limit=float(session.prop("health_burn_limit")),
                stale_lag_s=float(session.prop("health_stale_lag_s")),
                cooldown_s=float(session.prop("health_cooldown_s")),
                on_breach=self.overload.on_breach)
            self.health.start()
        #: the registry behind system.health (connectors/system.py)
        session.health = self.health
        #: compile-budget warming (plan/adaptive.py tentpole (c)):
        #: adaptivity re-specializes recurring templates (salt /
        #: flip / route), and the FIRST run of a re-specialized
        #: template pays a cold compile. With ``warm_top_k > 0`` a
        #: background thread re-executes the top-K SELECT templates
        #: by observed traffic once each, off the serving path, so
        #: steady-state traffic only ever sees warm exec-cache hits.
        self._traffic: "dict[str, int]" = {}
        self._traffic_lock = threading.Lock()
        self._warmed: "set[str]" = set()
        self.warm_top_k = max(0, int(warm_top_k))
        self.warm_interval_s = max(0.05, float(warm_interval_s))
        self._warm_stop = threading.Event()
        self._warm_thread = None
        if self.warm_top_k > 0:
            self._warm_thread = threading.Thread(
                target=self._warm_loop, name="presto-warm", daemon=True)
            self._warm_thread.start()

    # ---- template warming ------------------------------------------------
    def _note_traffic(self, sql: str) -> None:
        """Count one arrival of ``sql`` toward warming priority.
        Traffic shape, not success, drives warming — a template that
        keeps arriving keeps deserving a warm cache."""
        if self.warm_top_k <= 0:
            return
        with self._traffic_lock:
            self._traffic[sql] = self._traffic.get(sql, 0) + 1

    def _warm_candidates(self) -> "list[str]":
        """Top-K recurring SELECT templates not yet warmed. Recurrence
        >= 2 mirrors the adaptivity corridor (plan-hints fire on runs
        >= 2): warming a one-shot statement buys nothing."""
        with self._traffic_lock:
            ranked = sorted(self._traffic.items(),
                            key=lambda kv: -kv[1])
        out = []
        for sql, count in ranked:
            if len(out) >= self.warm_top_k:
                break
            if count < 2 or sql in self._warmed:
                continue
            head = sql.lstrip().lower()
            if not (head.startswith("select") or head.startswith("with")):
                continue  # never re-execute DML/DDL in the background
            out.append(sql)
        return out

    def _warm_loop(self) -> None:
        """Daemon body: each interval, re-execute newly-hot templates
        once, paying any adaptivity-induced cold compile HERE instead
        of on a serving thread. Runs against the shared session (same
        exec cache the serving path hits) but outside the fair
        scheduler — warming must never consume a tenant's slot."""
        while not self._warm_stop.wait(self.warm_interval_s):
            for sql in self._warm_candidates():
                if self._warm_stop.is_set() or not self._accepting:
                    return
                self._warmed.add(sql)
                try:
                    self.session.sql(sql)
                    REGISTRY.counter("adaptive.warmed").add()
                except Exception:  # noqa: BLE001 — warming is advisory
                    pass

    # ---- lifecycle accounting -------------------------------------------
    def _enter(self, tenant: str):
        with self._drain_cv:
            if not self._accepting:
                raise UserError("server is draining: not accepting queries")
            self._inflight += 1
        return tenant

    def _leave(self):
        with self._drain_cv:
            self._inflight -= 1
            self._drain_cv.notify_all()

    # ---- synchronous execution ------------------------------------------
    def _execute_admitted(self, fn, tenant: str,
                          timeout_s: Optional[float] = None,
                          on_start=None):
        """The ONE admission wrapper AFTER in-flight accounting: fair
        slot, tenant attribution, then ``fn()`` against the shared
        session. ``on_start`` fires once the slot is held (the
        QUEUED->RUNNING transition submit/poll reports — a query
        starved at the scheduler must poll as QUEUED, not RUNNING).
        Callers own ``_enter``/``_leave`` (submit() enters at accept
        time so a drain never drops an already-accepted query)."""
        from presto_tpu.runtime.session import CURRENT_TENANT

        with self.scheduler.slot(tenant, timeout_s):
            if on_start is not None:
                on_start()
            token = CURRENT_TENANT.set(tenant)
            try:
                return fn()
            finally:
                CURRENT_TENANT.reset(token)

    def _brownout_mode(self, tenant: str) -> Optional[str]:
        """Routing verdict for one NEW submission: None (serve
        normally), "approx" (serve through the approx sibling
        session), or "shed" (refuse with ServerOverloaded). The
        ``brownout_force`` session property is the operator override —
        it pins the latch on regardless of health."""
        forced = bool(self.session.prop("brownout_force"))
        if forced != self.overload.forced:
            self.overload.force(forced)
        return self.overload.mode_for(self.scheduler.spec(tenant))

    def _route_session(self, tenant: str):
        """The session one NEW statement from ``tenant`` runs against,
        after the brown-out verdict. Raises ServerOverloaded for
        ``brownout="shed"`` tenants while the latch is engaged."""
        mode = self._brownout_mode(tenant)
        if mode == "shed":
            REGISTRY.counter("overload.shed").add()
            REGISTRY.counter("overload.shed_reason.brownout").add()
            raise ServerOverloaded(
                f"tenant {tenant!r} shed: brown-out engaged and the "
                f"tenant's brownout policy is 'shed'",
                retry_after_s=shed_retry_after(self.scheduler.queue_depth()))
        if mode == "approx":
            REGISTRY.counter("brownout.approx_routed").add()
            return self.approx_session(), True
        return self.session, False

    def execute(self, sql: str, tenant: Optional[str] = None,
                timeout_s: Optional[float] = None,
                deadline_s: Optional[float] = None):
        """Run one statement as ``tenant`` (fair slot + attribution);
        returns the DataFrame. ``deadline_s`` bounds the WHOLE request
        — queue time included — and propagates into the query's
        cancel/deadline scope."""
        from presto_tpu.runtime.lifecycle import REQUEST_DEADLINE

        tenant = tenant or self.default_tenant
        sess, _ = self._route_session(tenant)
        self._note_traffic(sql)
        self._enter(tenant)
        dl_token = (None if deadline_s is None else
                    REQUEST_DEADLINE.set(time.monotonic() + deadline_s))
        try:
            return self._execute_admitted(lambda: sess.sql(sql),
                                          tenant, timeout_s)
        finally:
            if dl_token is not None:
                REQUEST_DEADLINE.reset(dl_token)
            self._leave()

    def _prepared_key(self, tenant: str, name: str) -> str:
        """Per-tenant prepared-statement namespace: handles register
        in the shared session under ``tenant::name``, so one tenant
        can never overwrite, execute, or deallocate another's
        statement through the shared-session design."""
        return f"{tenant}::{name}"

    def prepare(self, sql: str, name: Optional[str] = None,
                tenant: Optional[str] = None):
        """PREPARE (no slot needed: planning only); returns the
        client-visible handle name (scoped to ``tenant``) to pass to
        :meth:`execute_prepared` / :meth:`deallocate`."""
        tenant = tenant or self.default_tenant
        if name is None:
            name = f"stmt_{next(_submit_seq)}"
        self.session.prepare(sql, self._prepared_key(tenant, name))
        return name

    def execute_prepared(self, name: str, params=(),
                         tenant: Optional[str] = None,
                         timeout_s: Optional[float] = None):
        tenant = tenant or self.default_tenant
        key = self._prepared_key(tenant, name)
        prep = self.session._prepared.get(key)
        if prep is not None:
            self._note_traffic(getattr(prep, "sql", "") or "")
        self._enter(tenant)
        try:
            return self._execute_admitted(
                lambda: self.session.execute_prepared(key,
                                                      list(params))[0],
                tenant, timeout_s)
        finally:
            self._leave()

    def deallocate(self, name: str, tenant: Optional[str] = None) -> None:
        from presto_tpu.runtime.errors import UserError as _UE

        tenant = tenant or self.default_tenant
        key = self._prepared_key(tenant, name)
        if self.session._prepared.pop(key, None) is None:
            raise _UE(f"prepared statement not found: {name}")

    # ---- submit / poll (the /v1/statement shape) ------------------------
    def _retire_records_locked(self) -> None:
        """Evict oldest TERMINAL records beyond the ring bound (under
        ``_qlock``): a long-running server must not hold every result
        frame it ever produced."""
        over = len(self._queries) - self.query_record_limit
        if over <= 0:
            return
        for qid in [q for q, r in self._queries.items()
                    if r["state"] in ("FINISHED", "FAILED")][:over]:
            del self._queries[qid]

    def submit(self, sql: str, tenant: Optional[str] = None,
               trace: Optional[dict] = None,
               deadline_s: Optional[float] = None) -> str:
        """Asynchronous submission; returns a server query id to poll.
        In-flight accounting happens HERE (not on the worker thread):
        an accepted query is part of the drain set immediately, so a
        shutdown between the accept and the worker's first instruction
        still waits for it. Submission is bounded by ``submit_limit``
        pending queries — beyond it, reject loudly instead of growing
        one blocked thread per request.

        ``trace`` is a REQUEST_TRACE context dict (a client-supplied
        ``traceparent``/``X-Presto-Trace``, parsed by the HTTP layer);
        every submission gets one — a server-generated context when the
        client sent none — so the engine-side trace token always links
        back to the submission that caused it."""
        tenant = tenant or self.default_tenant
        with self._qlock:
            pending = sum(1 for r in self._queries.values()
                          if r["state"] in ("QUEUED", "RUNNING"))
        if pending >= self.submit_limit:
            REGISTRY.counter("server.submit_rejected").add()
            raise ServerOverloaded(
                f"server busy: {pending} submitted queries pending "
                f"(submit_limit={self.submit_limit})",
                retry_after_s=shed_retry_after(pending))
        # the scheduler's shed verdict, taken SYNCHRONOUSLY at accept
        # time: an over-ceiling submission must 429 on /v1/statement
        # itself, never spend a worker thread to fail on the poll page
        # — and a shed submission leaves no submit record behind
        self.scheduler.check_shed(tenant)
        sess, approximate = self._route_session(tenant)
        self._enter(tenant)  # raises while draining; worker leaves
        if trace is None:
            trace = _trace_context()
        trace["t0"] = time.perf_counter()
        qid = f"srv_{next(_submit_seq)}"
        rec = {"id": qid, "tenant": tenant, "sql": sql, "state": "QUEUED",
               "df": None, "error": None, "error_code": None,
               "submitted_at": time.time(), "done": threading.Event(),
               "trace": trace, "cancel_requested": False,
               "approximate": approximate,
               "deadline_mono": (None if deadline_s is None
                                 else time.monotonic() + deadline_s)}
        with self._qlock:
            self._queries[qid] = rec
            self._retire_records_locked()
        REGISTRY.counter("server.submitted").add()

        def on_start():
            # QUEUED until the fair slot is actually held: scheduler
            # starvation must be observable as QUEUED, not mislabeled
            # RUNNING; the stamp also bounds the frontend:submit span
            # (submit accept -> slot held = admission wait)
            trace["started_pc"] = time.perf_counter()
            if rec["cancel_requested"]:
                # cancelled while QUEUED: observe it at the slot
                # boundary — the slot releases on the way out and no
                # engine-side state was ever created
                raise QueryCancelled(
                    f"query {qid} cancelled while queued")
            rec["state"] = "RUNNING"

        def work():
            from presto_tpu.runtime.lifecycle import REQUEST_DEADLINE
            from presto_tpu.runtime.session import REQUEST_TRACE

            token = REQUEST_TRACE.set(trace)
            dl_token = (None if rec["deadline_mono"] is None else
                        REQUEST_DEADLINE.set(rec["deadline_mono"]))
            try:
                rec["df"] = self._execute_admitted(
                    lambda: sess.sql(sql), tenant,
                    timeout_s=self.submit_timeout_s,
                    on_start=on_start)
                rec["state"] = "FINISHED"
            except Exception as e:  # noqa: BLE001 — reported to the client
                rec["state"] = "FAILED"
                rec["error"] = f"{type(e).__name__}: {e}"
                rec["error_code"] = (error_code(e)
                                     if isinstance(e, PrestoError)
                                     else "INTERNAL")
                if isinstance(e, ServerOverloaded):
                    rec["retry_after_s"] = e.retry_after_s
                REGISTRY.counter("server.failed").add()
            finally:
                if dl_token is not None:
                    REQUEST_DEADLINE.reset(dl_token)
                REQUEST_TRACE.reset(token)
                rec["done"].set()
                self._leave()

        t = threading.Thread(target=work, daemon=True,
                             name=f"presto-tpu-{qid}")
        rec["thread"] = t
        try:
            t.start()
        except BaseException:
            self._leave()  # thread never ran; balance the accounting
            raise
        return qid

    def poll(self, qid: str) -> dict:
        """Current state page for a submitted query (terminal pages
        carry results or the typed error). The first terminal poll
        stitches the frontend spans (submit wait, this poll) onto the
        query's own trace recorder — the end-to-end export then reads
        submit -> admission -> gate wait -> dispatch -> poll as one
        linked trace."""
        poll_t0 = time.perf_counter()
        with self._qlock:
            rec = self._queries.get(qid)
        if rec is None:
            raise UserError(f"unknown query id: {qid}")
        page = {"id": qid, "tenant": rec["tenant"], "state": rec["state"]}
        if rec.get("approximate"):
            # brown-out honesty: a query served through the approx
            # tier is flagged on every page, not just the result
            page["approximate"] = True
        if rec["state"] == "FINISHED":
            payload = rec.get("payload")
            if payload is None:
                # serialized once, on first poll of the terminal page —
                # repeat polls (or several clients sharing the id) must
                # not re-pay O(rows) JSON encoding per request
                payload = rec["payload"] = _df_payload(rec["df"])
            page.update(payload)
        elif rec["state"] == "FAILED":
            page["error"] = rec["error"]
            page["errorCode"] = rec["error_code"]
            if rec.get("retry_after_s") is not None:
                page["retryAfterS"] = rec["retry_after_s"]
        if rec["state"] in ("FINISHED", "FAILED"):
            self._stitch_frontend_spans(rec, poll_t0)
        return page

    def _stitch_frontend_spans(self, rec: dict, poll_t0: float) -> None:
        """Append the frontend-side spans to the query's trace recorder
        (once, on the first terminal poll). Post-hoc by design: the
        engine-side recorder exists only after the worker ran, and the
        submit wait is only known once the slot was held. Best-effort —
        trace plumbing must never fail a poll."""
        trace_ctx = rec.get("trace")
        if not trace_ctx or trace_ctx.get("frontend_spans_done"):
            return
        engine_qid = trace_ctx.get("query_id")
        if not engine_qid:  # worker never reached the session
            return
        try:
            tracer = self.session.traces.for_query(engine_qid)
        except Exception:  # noqa: BLE001 — observability-only path
            tracer = None
        if tracer is None:  # tracing off for this query
            return
        trace_ctx["frontend_spans_done"] = True
        try:
            t0 = trace_ctx["t0"]
            started = trace_ctx.get("started_pc", t0)
            tracer.add_complete(
                "frontend:submit", "frontend", t0,
                max(0.0, started - t0),
                {"queryId": rec["id"], "tenant": rec["tenant"],
                 "traceToken": trace_ctx["token"]})
            tracer.add_complete(
                "frontend:poll", "frontend", poll_t0,
                time.perf_counter() - poll_t0,
                {"queryId": rec["id"], "state": rec["state"]})
        except Exception:  # noqa: BLE001 — observability-only path
            REGISTRY.counter("exec.trace_errors").add()

    def trace_info(self, qid: str) -> dict:
        """Outgoing trace headers for a submitted query: the honored
        (or server-assigned) ``X-Presto-Trace`` token plus a W3C
        ``traceparent`` carrying the query's trace-id under a fresh
        server span-id — what the HTTP layer echoes on the 201 and on
        every poll page."""
        with self._qlock:
            rec = self._queries.get(qid)
        trace_ctx = (rec or {}).get("trace")
        if not trace_ctx:
            return {}
        span_id = uuid.uuid4().hex[:16]
        return {"X-Presto-Trace": trace_ctx["token"],
                "traceparent": f"00-{trace_ctx['trace_id']}-{span_id}-01"}

    def cancel(self, qid: str, reason: str = "cancelled by client") -> dict:
        """Cooperatively cancel a submitted query (the ``DELETE
        /v1/statement/<id>`` verb). RUNNING queries get their engine
        CancelScope flipped — the next checkpoint raises the typed
        ``QueryCancelled`` and releases every pool/host-spill
        reservation; QUEUED queries are marked and observed at the
        slot boundary (a waiter blocked in the fair queue drains at
        its next wake). Terminal queries are left untouched."""
        with self._qlock:
            rec = self._queries.get(qid)
        if rec is None:
            raise UserError(f"unknown query id: {qid}")
        if rec["state"] in ("FINISHED", "FAILED"):
            return {"id": qid, "state": rec["state"], "cancelled": False}
        REGISTRY.counter("server.cancel_requests").add()
        rec["cancel_requested"] = True
        flipped = False
        engine_qid = (rec.get("trace") or {}).get("query_id")
        if engine_qid:
            flipped = self.session.cancel(engine_qid, reason)
            if not flipped and self._approx_session is not None:
                flipped = self._approx_session.cancel(engine_qid, reason)
        # wake fair-queue waiters so a QUEUED cancel is observed at
        # the next scheduling pass instead of the admission timeout
        self.scheduler.kick()
        return {"id": qid, "state": rec["state"], "cancelled": True,
                "observed_running": flipped}

    def result(self, qid: str, timeout_s: Optional[float] = None):
        """Block until a submitted query finishes; returns the frame
        (raises UserError with the captured failure on FAILED)."""
        with self._qlock:
            rec = self._queries.get(qid)
        if rec is None:
            raise UserError(f"unknown query id: {qid}")
        if not rec["done"].wait(timeout_s):
            raise UserError(f"query {qid} still running")
        if rec["state"] == "FAILED":
            raise UserError(f"query {qid} failed: {rec['error']}")
        return rec["df"]

    # ---- continuous queries (presto_tpu/stream/) ------------------------
    def approx_session(self):
        """The APPROXIMATE sibling session (built lazily): same
        connectors and memory pool as the main session, but with
        ``approx_join`` on (Bloom-sketch semi joins) plus any
        ``approx_properties`` overrides. Its plan fingerprints fold
        the approx knobs, so exact and approximate executions never
        share cached results — and its own catalog hooks the shared
        memory connector's DDL listeners, so appends invalidate both
        sessions' caches scoped per table."""
        with self._approx_lock:
            if self._approx_session is None:
                from presto_tpu.runtime.session import Session

                conns = {n: c for n, c in
                         self.session.catalog.connectors.items()
                         if n != "system"}
                props = {"batched_dispatch": True, "approx_join": True}
                props.update(self._approx_properties)
                self._approx_session = Session(
                    conns, memory_pool=self.session.pool(),
                    properties=props)
            return self._approx_session

    def subscribe(self, sql: str, tenant: Optional[str] = None,
                  mode: str = "exact",
                  interval_s: Optional[float] = None, keep: int = 8):
        """Register a continuous query: ``sql`` is prepared into a
        plan template and re-executed (through the fair scheduler and
        the batch gate) whenever a referenced table's version epoch
        advances, or every ``interval_s`` seconds. Returns the
        :class:`~presto_tpu.stream.subscriptions.ContinuousQuery`
        handle; ``mode="approx"`` serves the dashboard tier through
        the approx sibling session, flagged ``approximate``."""
        with self._drain_cv:
            if not self._accepting:
                raise UserError("server is draining: not accepting "
                                "subscriptions")
        return self.subscriptions.subscribe(
            sql, tenant or self.default_tenant, mode=mode,
            interval_s=interval_s, keep=keep)

    def unsubscribe(self, sub_id: str) -> None:
        self.subscriptions.unsubscribe(sub_id)

    def subscription_page(self, sub_id: str) -> dict:
        return self.subscriptions.get(sub_id).page()

    # ---- observability / shutdown ---------------------------------------
    def metrics_text(self) -> str:
        return self.session.export_metrics()

    def tenants_snapshot(self) -> "list[dict]":
        return self.scheduler.snapshot()

    def shutdown(self, drain_timeout_s: float = 30.0,
                 flight_path: Optional[str] = None) -> dict:
        """Graceful drain: stop accepting, wait for in-flight queries,
        then report pool state (reservations release on every terminal
        state, so a clean drain leaves the pool empty) and optionally
        flush the flight-recorder ring to ``flight_path``. Continuous
        queries cancel FIRST — their in-flight refreshes hold ordinary
        in-flight accounting, so the drain wait below covers them. The
        health watchdog stops before anything it samples is torn
        down."""
        deadline = time.monotonic() + drain_timeout_s
        self._warm_stop.set()
        if self._warm_thread is not None:
            self._warm_thread.join(timeout=drain_timeout_s)
        if self.health is not None:
            self.health.close()
        self.subscriptions.close()
        with self._drain_cv:
            self._accepting = False
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._drain_cv.wait(remaining)
            drained_clients = self._inflight == 0
        pool = self.session.pool()
        if flight_path is not None:
            try:
                self.session.export_flight_record(flight_path)
            except Exception:  # noqa: BLE001 — a drain must not fail
                REGISTRY.counter("flight.capture_errors").add()
        # detach the scheduler's pool listener: the process-global pool
        # must not keep a retired server's scheduler alive
        self.scheduler.close()
        REGISTRY.counter("server.shutdowns").add()
        return {
            "drained": drained_clients,
            "inflight": self._inflight,
            "pool_reserved_bytes": pool.snapshot()["reserved_bytes"],
            "flight_records": len(self.session.flight),
        }


#: the no-sockets client surface tests and the bench harness use; it
#: IS the server core — one name per role, one implementation
ServerClient = QueryServer


# ---------------------------------------------------------------------------
# HTTP transport
# ---------------------------------------------------------------------------


class HttpFrontend:
    """stdlib HTTP/JSON transport over a :class:`QueryServer`.

    Routes::

        POST /v1/statement           body = SQL text; 200 -> {id, state,
                                     nextUri}; tenant via X-Presto-Tenant;
                                     a client ``traceparent`` (W3C) or
                                     ``X-Presto-Trace`` token is honored
                                     end to end and echoed back on the
                                     response headers; an
                                     ``X-Presto-Deadline`` header (epoch
                                     seconds, or relative seconds)
                                     propagates into the query's cancel/
                                     deadline scope; a shed submission
                                     gets 429 + ``Retry-After``
        DELETE /v1/statement/<id>    cooperative cancel; 200 -> {id,
                                     state, cancelled}
        GET  /v1/statement/<id>      poll page (FINISHED pages carry
                                     {columns, data}); echoes the trace
                                     headers of the submission
        POST /v1/prepared            JSON {action: prepare|execute|
                                     deallocate, name, sql?, params?}
        POST /v1/subscribe           JSON {sql, mode?, intervalS?};
                                     201 -> {id, tables, mode,
                                     nextUri} (continuous query)
        GET  /v1/subscription/<id>   latest delivered page (epochs,
                                     seq, approximate, columns, data)
        POST /v1/subscription/<id>/cancel
        GET  /metrics                OpenMetrics text exposition
        GET  /v1/tenants             scheduler snapshot JSON

    ``port=0`` binds an ephemeral port (tests); ``.port`` reports it.
    """

    def __init__(self, server: QueryServer, host: str = "127.0.0.1",
                 port: int = 8080):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        qserver = server

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _send(self, code: int, payload, ctype="application/json",
                      headers=None):
                body = (payload if isinstance(payload, bytes)
                        else json.dumps(payload, default=str).encode())
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def _tenant(self) -> str:
                return (self.headers.get("X-Presto-Tenant")
                        or self.headers.get("X-Presto-User")
                        or qserver.default_tenant)

            def _trace_ctx(self):
                """REQUEST_TRACE context from the client's trace
                headers, or None when it sent none. A client that
                supplied either header opted into tracing — the query
                runs with a recorder even when the session-wide
                ``trace_enabled`` property is off."""
                token = self.headers.get("X-Presto-Trace")
                tp_id = _parse_traceparent(self.headers.get("traceparent"))
                if token is None and tp_id is None:
                    return None
                return _trace_context(token=token, traceparent_id=tp_id,
                                      force=True)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n)

            def _deadline_s(self):
                """``X-Presto-Deadline`` -> relative seconds remaining,
                or None. Values past 1e9 are absolute unix-epoch
                deadlines (the cross-service propagation shape); small
                values are relative budgets. Malformed or already-
                expired deadlines are the CLIENT's fault: UserError ->
                400, never a silent drop of a semantic header."""
                hdr = self.headers.get("X-Presto-Deadline")
                if hdr is None:
                    return None
                try:
                    v = float(hdr)
                except ValueError:
                    raise UserError(
                        f"X-Presto-Deadline: cannot parse {hdr!r} as "
                        "seconds") from None
                remaining = v - time.time() if v > 1e9 else v
                if remaining <= 0:
                    raise UserError(
                        f"X-Presto-Deadline already expired "
                        f"({remaining:.3f}s remaining)")
                return remaining

            def _overloaded(self, e: "ServerOverloaded"):
                """429 + Retry-After (integer seconds, ceil'd so a
                sub-second hint never rounds to 'retry now')."""
                after = max(1, int(e.retry_after_s + 0.999))
                self._send(429, {"error": str(e),
                                 "errorCode": e.error_code,
                                 "retryAfterS": e.retry_after_s},
                           headers={"Retry-After": str(after)})

            def do_GET(self):
                try:
                    if self.path == "/metrics":
                        self._send(200, qserver.metrics_text().encode(),
                                   ctype=("application/openmetrics-text; "
                                          "version=1.0.0"))
                        return
                    if self.path == "/v1/tenants":
                        self._send(200, qserver.tenants_snapshot())
                        return
                    if self.path.startswith("/v1/statement/"):
                        qid = self.path.rsplit("/", 1)[1]
                        page = qserver.poll(qid)
                        self._send(200, page,
                                   headers=qserver.trace_info(qid))
                        return
                    if self.path.startswith("/v1/subscription/"):
                        sid = self.path.rsplit("/", 1)[1]
                        self._send(200, qserver.subscription_page(sid))
                        return
                    self._send(404, {"error": f"no route {self.path}"})
                except ServerOverloaded as e:
                    self._overloaded(e)
                except UserError as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

            def do_DELETE(self):
                try:
                    if self.path.startswith("/v1/statement/"):
                        qid = self.path.rsplit("/", 1)[1]
                        self._send(200, qserver.cancel(qid))
                        return
                    self._send(404, {"error": f"no route {self.path}"})
                except UserError as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

            def do_POST(self):
                try:
                    if self.path == "/v1/statement":
                        sql = self._body().decode("utf-8")
                        qid = qserver.submit(sql, self._tenant(),
                                             trace=self._trace_ctx(),
                                             deadline_s=self._deadline_s())
                        self._send(201, {
                            "id": qid, "state": "QUEUED",
                            "nextUri": f"/v1/statement/{qid}",
                        }, headers=qserver.trace_info(qid))
                        return
                    if self.path == "/v1/prepared":
                        try:
                            req = json.loads(self._body().decode("utf-8"))
                            action = req.get("action")
                            if action in ("prepare", "execute",
                                          "deallocate"):
                                req["name"]  # required for all actions
                            if action == "prepare":
                                req["sql"]
                        except (ValueError, KeyError) as e:
                            # malformed CLIENT input is a 400, not a
                            # 500 (json.JSONDecodeError is ValueError)
                            self._send(400, {"error": "bad request: "
                                             f"{type(e).__name__}: {e}"})
                            return
                        if action == "prepare":
                            name = qserver.prepare(req["sql"],
                                                   req.get("name"),
                                                   self._tenant())
                            self._send(201, {"prepared": name})
                            return
                        if action == "execute":
                            df = qserver.execute_prepared(
                                req["name"], req.get("params", ()),
                                self._tenant())
                            self._send(200, _df_payload(df))
                            return
                        if action == "deallocate":
                            qserver.deallocate(req["name"],
                                               self._tenant())
                            self._send(200, {"deallocated": req["name"]})
                            return
                        self._send(400, {"error": "action must be "
                                         "prepare|execute|deallocate"})
                        return
                    if self.path == "/v1/subscribe":
                        try:
                            req = json.loads(self._body().decode("utf-8"))
                            sql = req["sql"]
                        except (ValueError, KeyError) as e:
                            self._send(400, {"error": "bad request: "
                                             f"{type(e).__name__}: {e}"})
                            return
                        sub = qserver.subscribe(
                            sql, self._tenant(),
                            mode=req.get("mode", "exact"),
                            interval_s=req.get("intervalS"))
                        self._send(201, {
                            "id": sub.id, "mode": sub.mode,
                            "tables": list(sub.tables),
                            "nextUri": f"/v1/subscription/{sub.id}",
                        })
                        return
                    if (self.path.startswith("/v1/subscription/")
                            and self.path.endswith("/cancel")):
                        sid = self.path.split("/")[3]
                        qserver.unsubscribe(sid)
                        self._send(200, {"cancelled": sid})
                        return
                    self._send(404, {"error": f"no route {self.path}"})
                except ServerOverloaded as e:
                    self._overloaded(e)
                except UserError as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

        self.server = server
        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def serve_forever(self):
        REGISTRY.counter("server.started").add()
        self.httpd.serve_forever()

    def start_background(self) -> "HttpFrontend":
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="presto-tpu-http")
        self._thread.start()
        return self

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(10)
